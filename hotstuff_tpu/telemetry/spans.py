"""Round-trace spans: follow a block through propose -> vote fan-in ->
QC formation -> commit and record per-stage durations.

One ``RoundTrace`` lives in each consensus core (created only when
telemetry is enabled; the core holds ``None`` otherwise, so the disabled
hot path pays a single ``is not None`` check per event). Marks are
keyed by round; a commit closes every round up to it (the 2-chain rule
commits round r while the core works on r+2), so the table stays bounded
even without commits via the ``max_rounds`` FIFO cap. Rounds that fall
out of that FIFO *without ever committing* are counted
(``consensus.span.evicted_rounds``) — chaos runs shed trace data there
and the loss must be visible, not silent.

Stage semantics (all durations in milliseconds, monotonic clock):

- ``propose -> first_vote``: proposal seen/created to the first vote for
  that round arriving. Only the round's vote collector (the NEXT leader)
  receives votes, so only it observes this and the following span.
- ``first_vote -> qc``: vote fan-in window — first vote to the assembled
  QC passing verification.
- ``qc -> commit``: certificate to 2-chain commit of that round's block
  (spans the two follow-on rounds by construction).
- ``propose -> commit``: the whole round trace end to end.

Cross-node causality: when constructed with an ``events`` sink (a
:class:`~.trace.TraceBuffer`) and a ``node`` label, every mark — plus the
per-node-only ``verified``/``vote_send``/``vote_rx``/``timeout`` marks
that have no local span — is ALSO recorded as a trace event, so
``benchmark/trace_assemble.py`` can merge all nodes' streams into one
causal timeline per block and attribute milliseconds to each cross-node
edge, and ``hotstuff_tpu/telemetry/watchtower.py`` can score per-peer
behavior (vote participation, commit-height lag, timeout emission,
conflicting-vote evidence) from the same stream while it is written.
"""

from __future__ import annotations

import time
from collections import OrderedDict

from . import profiler as pyprof
from .registry import DURATION_MS_BUCKETS, FINE_DURATION_MS_BUCKETS, Registry

_PROPOSE, _VOTE, _QC = 0, 1, 2


class RoundTrace:
    __slots__ = (
        "_rounds", "_max_rounds", "_h_pv", "_h_vq", "_h_qc", "_h_pc",
        "_h_pc_faulted", "_c_faulted", "_c_evicted", "node", "_events",
    )

    #: fault annotation hook: a zero-arg callable set by
    #: ``faultline.runtime.install`` that reports whether fault injection
    #: is currently active. Rounds whose commit closes under active
    #: faults are recorded into the ``...propose_to_commit_faulted_ms``
    #: histogram instead of the clean one (and counted), so chaos runs
    #: separate degraded-round latency from steady-state latency.
    fault_flag = None

    def __init__(
        self,
        registry: Registry,
        max_rounds: int = 512,
        node: str = "",
        events=None,
    ) -> None:
        # round -> [propose_ts, first_vote_ts, qc_ts] (None until marked)
        self._rounds: OrderedDict[int, list[float | None]] = OrderedDict()
        self._max_rounds = max_rounds
        self.node = node
        self._events = events  # TraceBuffer or None
        h = registry.histogram
        # The two sub-round spans use the fine (µs-resolving) buckets:
        # at small committees and on the native path they sit well under
        # the coarse scale's 0.1 ms floor.
        self._h_pv = h(
            "consensus.span.propose_to_first_vote_ms", FINE_DURATION_MS_BUCKETS
        )
        self._h_vq = h(
            "consensus.span.first_vote_to_qc_ms", FINE_DURATION_MS_BUCKETS
        )
        self._h_qc = h("consensus.span.qc_to_commit_ms", DURATION_MS_BUCKETS)
        self._h_pc = h("consensus.span.propose_to_commit_ms", DURATION_MS_BUCKETS)
        self._h_pc_faulted = h(
            "consensus.span.propose_to_commit_faulted_ms", DURATION_MS_BUCKETS
        )
        self._c_faulted = registry.counter("consensus.span.faulted_rounds")
        self._c_evicted = registry.counter("consensus.span.evicted_rounds")

    def _emit(
        self, round_: int, stage: str, t: float, detail: str | None = None
    ) -> None:
        if self._events is not None:
            self._events.record(self.node, round_, stage, t, detail)

    def _marks(self, round_: int) -> list[float | None]:
        marks = self._rounds.get(round_)
        if marks is None:
            if len(self._rounds) >= self._max_rounds:
                # FIFO overflow: the evicted round never committed (a
                # commit would have GC'd it below) — count the loss.
                self._rounds.popitem(last=False)
                self._c_evicted.inc()
            marks = self._rounds[round_] = [None, None, None]
        return marks

    # Each mark flips the sampling profiler's per-thread stage tag to the
    # edge whose work FOLLOWS the mark (profiler samples between two
    # marks get blamed on the edge between them — the join key
    # benchmark/profile_assemble.py uses against the trace edges). One
    # module-attribute read per mark when no profiler session is live.

    def mark_propose(self, round_: int, detail: str | None = None) -> None:
        if pyprof.TAGGING:
            pyprof.set_thread_stage("verify")
        marks = self._marks(round_)
        if marks[_PROPOSE] is None:
            marks[_PROPOSE] = t = time.perf_counter()
            self._emit(round_, "propose", t, detail)

    def mark_verified(self, round_: int) -> None:
        """The proposal's certificates passed verification on this node
        (event-only: the cross-node assembler attributes the
        receive→verified edge; there is no local histogram)."""
        if pyprof.TAGGING:
            pyprof.set_thread_stage("vote")
        self._emit(round_, "verified", time.perf_counter())

    def mark_vote_send(self, round_: int) -> None:
        """This node created and dispatched its vote (event-only)."""
        if pyprof.TAGGING:
            pyprof.set_thread_stage("idle")
        self._emit(round_, "vote_send", time.perf_counter())

    def mark_vote(self, round_: int) -> None:
        if pyprof.TAGGING:
            pyprof.set_thread_stage("fanin")
        marks = self._marks(round_)
        if marks[_VOTE] is None:
            marks[_VOTE] = t = time.perf_counter()
            self._emit(round_, "first_vote", t)

    def mark_vote_rx(self, round_: int, detail: str) -> None:
        """One admitted vote arrived at this collector (event-only).
        ``detail`` is ``"<author>|<block digest>"`` — the per-peer
        accountability evidence (vote participation, conflicting-vote
        detection) the watchtower scores from."""
        self._emit(round_, "vote_rx", time.perf_counter(), detail)

    def mark_timeout(self, round_: int) -> None:
        """This node fired a local timeout for ``round_`` (event-only:
        the watchtower's timeout-emission-rate and grind evidence)."""
        self._emit(round_, "timeout", time.perf_counter())

    def mark_qc(self, round_: int) -> None:
        if pyprof.TAGGING:
            pyprof.set_thread_stage("qc_to_commit")
        marks = self._marks(round_)
        if marks[_QC] is None:
            marks[_QC] = t = time.perf_counter()
            self._emit(round_, "qc", t)
            if marks[_VOTE] is not None:
                self._h_vq.observe((marks[_QC] - marks[_VOTE]) * 1e3)
            if marks[_PROPOSE] is not None and marks[_VOTE] is not None:
                self._h_pv.observe((marks[_VOTE] - marks[_PROPOSE]) * 1e3)

    def mark_commit(self, round_: int, detail: str | None = None) -> None:
        """Close round ``round_`` (and GC every older round: commits are
        monotone, so anything below the committed round is finished).
        ``detail`` carries the node's commit height as ``"h<round>"`` so
        stream analyzers read the frontier off the event itself."""
        if pyprof.TAGGING:
            pyprof.set_thread_stage("idle")
        now = time.perf_counter()
        marks = self._rounds.get(round_)
        self._emit(round_, "commit", now, detail)
        if marks is not None:
            if marks[_QC] is not None:
                self._h_qc.observe((now - marks[_QC]) * 1e3)
            if marks[_PROPOSE] is not None:
                flag = RoundTrace.fault_flag
                if flag is not None and flag():
                    self._c_faulted.inc()
                    self._h_pc_faulted.observe((now - marks[_PROPOSE]) * 1e3)
                else:
                    self._h_pc.observe((now - marks[_PROPOSE]) * 1e3)
        while self._rounds:
            oldest = next(iter(self._rounds))
            if oldest > round_:
                break
            del self._rounds[oldest]

    def open_rounds(self) -> int:
        return len(self._rounds)
