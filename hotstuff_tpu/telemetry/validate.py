"""Stream validation CLI: check any telemetry JSON-lines file against
every known record schema and print per-schema counts.

    python -m hotstuff_tpu.telemetry.validate PATH [PATH ...]

Before this existed, a malformed stream was only diagnosed deep inside
the assemble scripts (a ParseError three layers into trace_assemble with
no hint which line was bad). This walks the file line by line, validates
each record against the schema its ``schema`` field claims, and reports:

- counts per schema (snapshots / traces / profiles / meta / alerts);
- every invalid line with its line number and the validator's problems;
- unknown-schema and non-JSON lines (a trailing truncated line — a
  writer killed mid-append — is reported but does not fail the file);
- whether the stream self-describes (a ``hotstuff-meta-v1`` record
  first, the contract every emitter follows since the meta record).

Exit code 0 when every file is clean, 1 when any problem was found.
"""

from __future__ import annotations

import argparse
import json
import sys

from .dtrace import DTRACE_SCHEMA, validate_dtrace_record
from .emitter import META_SCHEMA, SCHEMA, validate_meta_record, validate_snapshot
from .profiler import PROFILE_SCHEMA, validate_profile_record
from .trace import TRACE_SCHEMA, validate_trace_record
from .watchtower import ALERT_SCHEMA, validate_alert_record

VALIDATORS = {
    SCHEMA: validate_snapshot,
    TRACE_SCHEMA: validate_trace_record,
    DTRACE_SCHEMA: validate_dtrace_record,
    PROFILE_SCHEMA: validate_profile_record,
    META_SCHEMA: validate_meta_record,
    ALERT_SCHEMA: validate_alert_record,
}


def validate_stream(path: str) -> dict:
    """Validate one stream file; returns the machine-readable report
    (``ok``, per-schema ``counts``, ``problems`` with line numbers)."""
    counts: dict[str, int] = {name: 0 for name in VALIDATORS}
    problems: list[dict] = []
    unknown = 0
    lines = 0
    truncated_tail = False
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        return {
            "path": path,
            "ok": False,
            "counts": counts,
            "lines": 0,
            "unknown_schema": 0,
            "truncated_tail": False,
            "problems": [{"line": 0, "problems": [str(e)]}],
        }
    payload = raw.split(b"\n")
    for i, line in enumerate(payload, 1):
        line = line.strip()
        if not line:
            continue
        lines += 1
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            if i == len(payload) and not raw.endswith(b"\n"):
                # The writer died mid-append: expected crash fallout.
                truncated_tail = True
                continue
            problems.append({"line": i, "problems": [f"bad JSON: {e}"]})
            continue
        schema = obj.get("schema") if isinstance(obj, dict) else None
        validator = VALIDATORS.get(schema)
        if validator is None:
            unknown += 1
            continue
        found = validator(obj)
        if found:
            problems.append({"line": i, "schema": schema, "problems": found})
        else:
            counts[schema] += 1
    return {
        "path": path,
        "ok": not problems,
        "lines": lines,
        "counts": counts,
        "unknown_schema": unknown,
        "truncated_tail": truncated_tail,
        "self_described": counts[META_SCHEMA] > 0,
        "problems": problems,
    }


def _human(report: dict) -> str:
    lines = [f"{report['path']}: {'ok' if report['ok'] else 'INVALID'}"]
    lines.append(
        "  "
        + "  ".join(
            f"{name.split('-')[1]}={n}"
            for name, n in sorted(report["counts"].items())
        )
        + f"  unknown={report['unknown_schema']}"
    )
    if not report.get("self_described"):
        lines.append(
            "  note: no hotstuff-meta-v1 record (pre-meta stream, or not "
            "written by a TelemetryEmitter)"
        )
    if report.get("truncated_tail"):
        lines.append("  note: truncated final line (writer died mid-append)")
    for p in report["problems"][:20]:
        lines.append(
            f"  line {p['line']}"
            + (f" [{p['schema']}]" if p.get("schema") else "")
            + ": " + "; ".join(p["problems"])
        )
    if len(report["problems"]) > 20:
        lines.append(f"  ... and {len(report['problems']) - 20} more")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m hotstuff_tpu.telemetry.validate",
        description=__doc__,
    )
    p.add_argument("paths", nargs="+", help="stream files to validate")
    p.add_argument(
        "--json", action="store_true", help="machine-readable reports"
    )
    args = p.parse_args(argv)
    ok = True
    for path in args.paths:
        report = validate_stream(path)
        ok &= report["ok"]
        if args.json:
            print(json.dumps(report, sort_keys=True))
        else:
            print(_human(report))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
