"""SLO engine: declarative service-level objectives evaluated over
telemetry snapshot streams, emitting machine verdicts.

An :class:`SloSpec` names a metric, an evaluation ``kind``, and a
threshold; :func:`evaluate` slides a time window over a node's snapshot
stream (cumulative counters/histograms → reset-aware window deltas) and
judges every window, so a long soak is gated on "p99 commit latency
stayed under X in every 30 s window", not on one end-of-run average that
a mid-run stall would vanish into. The verdict is plain JSON data —
benchmark harnesses and CI lanes gate on ``verdict["ok"]`` without
parsing human text (the same contract as faultline's checker).

Kinds:

- ``quantile``: histogram metric; the window's q-quantile (linear
  interpolation inside the bucket) must stay ≤ ``max``. Windows with no
  observations are skipped (no data ≠ violation — a rate SLO owns
  progress).
- ``ms_per_count``: ``window_ms / counter delta`` ≤ ``max`` (ms/round
  from ``consensus.rounds_advanced``). A window with zero delta is a
  stall: worst = +inf, violated.
- ``rate``: counter delta per second ≥ ``min`` and/or ≤ ``max``.
- ``ratio``: counter delta ÷ another counter delta (``per``) ≤ ``max``
  (timeouts per round). Zero denominator skips the window.
- ``gauge_max``: the gauge's value in every snapshot of the window ≤
  ``max`` (mempool queue depth).
- ``gauge_growth``: the gauge's per-second growth across the window,
  ``(after - before) / window_s`` ≤ ``max`` (RSS / store-size growth —
  the unbounded-growth failure mode long soaks exist to catch). Windows
  where the gauge is absent at either end are skipped; negative growth
  (GC, compaction) always passes a max bound.

Counter resets (node restart mid-stream) make a cumulative value go
DOWN; a reset-aware delta treats that as "counted from zero again" and
uses the after-value, so a crash/restart chaos run doesn't produce
negative rates or bogus violations.

``allow_violation_fraction`` (per spec) tolerates a bounded fraction of
bad windows — chaos soaks legitimately degrade while a partition is
open; the SLO bounds how much of the run may be degraded, rather than
flipping on the first bad window.
"""

from __future__ import annotations

import json
import math

SLO_VERDICT_SCHEMA = "hotstuff-slo-verdict-v1"


class SloSpec:
    """One declarative objective. See module docstring for kinds."""

    __slots__ = (
        "name", "kind", "metric", "q", "per", "max", "min",
        "allow_violation_fraction",
    )

    def __init__(
        self,
        name: str,
        kind: str,
        metric: str,
        *,
        q: float | None = None,
        per: str | None = None,
        max: float | None = None,  # noqa: A002 — spec field name
        min: float | None = None,  # noqa: A002
        allow_violation_fraction: float = 0.0,
    ) -> None:
        if kind not in (
            "quantile", "ms_per_count", "rate", "ratio", "gauge_max",
            "gauge_growth",
        ):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if kind == "quantile" and not (q and 0.0 < q < 1.0):
            raise ValueError(f"quantile SLO {name!r} needs 0 < q < 1")
        if kind == "ratio" and not per:
            raise ValueError(f"ratio SLO {name!r} needs a 'per' counter")
        if max is None and min is None:
            raise ValueError(f"SLO {name!r} needs a max and/or min threshold")
        self.name = name
        self.kind = kind
        self.metric = metric
        self.q = q
        self.per = per
        self.max = max
        self.min = min
        self.allow_violation_fraction = allow_violation_fraction

    @classmethod
    def from_dict(cls, d: dict) -> "SloSpec":
        return cls(
            d["name"], d["kind"], d["metric"],
            q=d.get("q"), per=d.get("per"), max=d.get("max"), min=d.get("min"),
            allow_violation_fraction=d.get("allow_violation_fraction", 0.0),
        )

    def to_dict(self) -> dict:
        d = {"name": self.name, "kind": self.kind, "metric": self.metric}
        for k in ("q", "per", "max", "min"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        if self.allow_violation_fraction:
            d["allow_violation_fraction"] = self.allow_violation_fraction
        return d


def load_specs(path: str) -> list[SloSpec]:
    """Read a JSON file: a list of spec objects (see ``SloSpec``)."""
    with open(path) as f:
        return [SloSpec.from_dict(d) for d in json.load(f)]


def default_slos(
    *,
    p99_commit_latency_ms: float = 5_000.0,
    ms_per_round: float = 2_000.0,
    mempool_queue_depth: float = 50_000.0,
    timeouts_per_round: float = 0.5,
    allow_violation_fraction: float = 0.0,
) -> list[SloSpec]:
    """The roadmap's gate set: p99 commit latency, round rate, mempool
    queue depth, timeout/view-change rate. Thresholds are per-deployment
    knobs, not universal truths — callers override per harness."""
    return [
        SloSpec(
            "p99_commit_latency_ms", "quantile",
            "consensus.commit_latency_ms", q=0.99, max=p99_commit_latency_ms,
            allow_violation_fraction=allow_violation_fraction,
        ),
        SloSpec(
            "ms_per_round", "ms_per_count",
            "consensus.rounds_advanced", max=ms_per_round,
            allow_violation_fraction=allow_violation_fraction,
        ),
        SloSpec(
            "mempool_queue_depth", "gauge_max",
            "mempool.tx_queue_depth", max=mempool_queue_depth,
            allow_violation_fraction=allow_violation_fraction,
        ),
        SloSpec(
            "timeouts_per_round", "ratio",
            "consensus.timeouts_fired", per="consensus.rounds_advanced",
            max=timeouts_per_round,
            allow_violation_fraction=allow_violation_fraction,
        ),
    ]


def dataplane_slos(
    *,
    worker_store_depth: float = 512.0,
    digest_queue_growth_per_s: float = 50.0,
    allow_violation_fraction: float = 0.0,
) -> list[SloSpec]:
    """The Conveyor data-plane gate set. Streams without the worker
    metrics (data plane off) skip these specs entirely.

    - ``worker_store_depth`` — sealed-but-uncommitted batches per node
      must stay bounded (the watermark should gate sealing well before
      this trips; a breach means back-pressure is broken, the
      queue-collapse failure mode this plane exists to prevent);
    - ``resolver_unresolved`` — the commit path must NEVER time out
      resolving a certified digest to its batch (max 0 per second: one
      occurrence is an availability violation, not degradation);
    - ``digest_queue_growth_per_s`` — the proposer's certified-digest
      queue must not GROW faster than the bound in any window (ROADMAP
      3b: ordering starving behind ingest). Growth, not depth: a deep
      queue that drains as fast as it fills is healthy pipelining; the
      watchtower's ``digest_queue_starvation`` detector judges the same
      gauge online.
    """
    return [
        SloSpec(
            "worker_store_depth", "gauge_max",
            "mempool.worker.store_depth", max=worker_store_depth,
            allow_violation_fraction=allow_violation_fraction,
        ),
        SloSpec(
            "resolver_unresolved", "rate",
            "mempool.resolver.unresolved", max=0.0,
            allow_violation_fraction=0.0,
        ),
        SloSpec(
            "digest_queue_growth_per_s", "gauge_growth",
            "consensus.proposer.digest_queue_depth",
            max=digest_queue_growth_per_s,
            allow_violation_fraction=allow_violation_fraction,
        ),
    ]


def memory_slos(
    *,
    rss_growth_bytes_per_s: float = 8 * 1024 * 1024,
    store_growth_bytes_per_s: float = 32 * 1024 * 1024,
    store_bytes_max: float | None = None,
    allow_violation_fraction: float = 0.0,
) -> list[SloSpec]:
    """The memory-growth gate (ROADMAP item 4's unbounded-growth failure
    mode): RSS and on-disk store size must grow slower than a bound in
    every window. The gauges come from ``telemetry/resources.py``
    (``resource.rss_bytes`` / ``resource.store_bytes``); streams without
    them (resource collector not installed) skip these specs. Store
    growth is workload-proportional — the default bound is a ceiling on
    runaway WAL/MetaLog growth, not a tight fit; soaks tune it to their
    input rate.

    ``store_bytes_max`` (None = off) adds an ABSOLUTE cap on on-disk
    store size — the gate retention-armed soaks use: with
    snapshot/truncate compaction live, store size must plateau at the
    retention depth's working set, so a cap is meaningful regardless of
    run length. Without compaction store size is unbounded by design and
    only the growth-rate bound applies."""
    specs = [
        SloSpec(
            "rss_growth_bytes_per_s", "gauge_growth",
            "resource.rss_bytes", max=rss_growth_bytes_per_s,
            allow_violation_fraction=allow_violation_fraction,
        ),
        SloSpec(
            "store_growth_bytes_per_s", "gauge_growth",
            "resource.store_bytes", max=store_growth_bytes_per_s,
            allow_violation_fraction=allow_violation_fraction,
        ),
    ]
    if store_bytes_max is not None:
        specs.append(
            SloSpec(
                "store_bytes_max", "gauge_max",
                "resource.store_bytes", max=store_bytes_max,
                allow_violation_fraction=allow_violation_fraction,
            )
        )
    return specs


# -- window arithmetic -------------------------------------------------------


_ZERO = {"counters": {}, "histograms": {}, "gauges": {}, "ts": None}


def counter_delta(before: dict | None, after: dict, name: str) -> int:
    """Reset-aware cumulative-counter delta over a window."""
    a = after.get("counters", {}).get(name, 0)
    b = (before or _ZERO).get("counters", {}).get(name, 0)
    return a if a < b else a - b  # a < b: the counter reset mid-window


def histogram_delta(before: dict | None, after: dict, name: str) -> dict | None:
    """Window delta of a cumulative histogram (per-bucket subtraction);
    falls back to the after-histogram on a mid-window reset. None when
    the metric is absent."""
    ha = after.get("histograms", {}).get(name)
    if ha is None:
        return None
    hb = (before or _ZERO).get("histograms", {}).get(name)
    if hb is None or list(hb.get("le", [])) != list(ha["le"]):
        return ha
    counts = [a - b for a, b in zip(ha["counts"], hb["counts"])]
    if any(c < 0 for c in counts):  # reset: count from zero again
        return ha
    return {
        "le": ha["le"],
        "counts": counts,
        "sum": ha["sum"] - hb["sum"],
        "count": ha["count"] - hb["count"],
    }


def histogram_quantile(hist: dict, q: float) -> float | None:
    """q-quantile from bucket counts, linearly interpolated inside the
    bucket (Prometheus ``histogram_quantile`` semantics; the overflow
    bucket resolves to its lower edge — a known-conservative answer).
    None when the histogram is empty."""
    le, counts = list(hist["le"]), list(hist["counts"])
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= rank:
            if i >= len(le):  # overflow bucket: unbounded above
                return le[-1] if le else math.inf
            lo = le[i - 1] if i > 0 else 0.0
            return lo + (le[i] - lo) * ((rank - cum) / c)
        cum += c
    return le[-1] if le else math.inf


def windows(snapshots: list[dict], window_s: float) -> list[tuple[dict | None, dict]]:
    """Sliding (before, after) snapshot pairs ~``window_s`` apart.

    Every snapshot past the first ends one window whose start is the
    latest snapshot at least ``window_s`` older (clamped to the stream
    head for the warm-up prefix). A single-snapshot stream yields one
    cumulative-from-zero window ``(None, snap)`` — counters are
    cumulative, so zero-state is a valid "before". An empty stream
    yields no windows."""
    if not snapshots:
        return []
    if len(snapshots) == 1:
        return [(None, snapshots[0])]
    out: list[tuple[dict | None, dict]] = []
    for i in range(1, len(snapshots)):
        end = snapshots[i]
        start_idx = 0
        for j in range(i - 1, -1, -1):
            if end["ts"] - snapshots[j]["ts"] >= window_s:
                start_idx = j
                break
        out.append((snapshots[start_idx], end))
    return out


# -- evaluation --------------------------------------------------------------


def _window_seconds(before: dict | None, after: dict) -> float:
    if before is None or before.get("ts") is None:
        return 0.0
    return max(0.0, after["ts"] - before["ts"])


def _counter_present(before: dict | None, after: dict, name: str) -> bool:
    """A counter that never appeared in the window is 'plane absent'
    (e.g. no mempool in a consensus-only bench) — no data, not a stall."""
    return name in after.get("counters", {}) or (
        before is not None and name in before.get("counters", {})
    )


def _eval_window(spec: SloSpec, before: dict | None, after: dict):
    """The spec's observed value over one window, or None (no data)."""
    if spec.kind == "quantile":
        hist = histogram_delta(before, after, spec.metric)
        if hist is None:
            return None
        return histogram_quantile(hist, spec.q)
    if spec.kind == "ms_per_count":
        secs = _window_seconds(before, after)
        if secs <= 0.0 or not _counter_present(before, after, spec.metric):
            return None
        delta = counter_delta(before, after, spec.metric)
        return math.inf if delta <= 0 else secs * 1e3 / delta
    if spec.kind == "rate":
        secs = _window_seconds(before, after)
        if secs <= 0.0 or not _counter_present(before, after, spec.metric):
            return None
        return counter_delta(before, after, spec.metric) / secs
    if spec.kind == "ratio":
        num = counter_delta(before, after, spec.metric)
        den = counter_delta(before, after, spec.per)
        return None if den <= 0 else num / den
    if spec.kind == "gauge_growth":
        secs = _window_seconds(before, after)
        if secs <= 0.0 or before is None:
            return None
        a = after.get("gauges", {}).get(spec.metric)
        b = before.get("gauges", {}).get(spec.metric)
        if a is None or b is None:
            return None
        return (a - b) / secs
    # gauge_max: worst value across the window's endpoints.
    values = [
        s.get("gauges", {}).get(spec.metric)
        for s in (before, after)
        if s is not None
    ]
    values = [v for v in values if v is not None]
    return max(values) if values else None


def _violates(spec: SloSpec, value: float) -> bool:
    if spec.max is not None and value > spec.max:
        return True
    return spec.min is not None and value < spec.min


def evaluate(
    snapshots: list[dict],
    specs: list[SloSpec],
    *,
    window_s: float = 30.0,
    source: str = "",
) -> dict:
    """Judge one snapshot stream against ``specs``; returns the verdict.

    ``ok`` is True only when every spec's violated-window fraction stays
    within its allowance AND the stream carried at least one window —
    an empty stream cannot certify anything, so it fails closed
    (``ok: False, reason: "no snapshots"``); specs whose metric never
    appeared report ``windows: 0`` and don't fail the verdict (absence
    of a plane ≠ violation — e.g. no mempool in a consensus-only bench).
    """
    snaps = sorted(snapshots, key=lambda s: (s.get("ts", 0), s.get("seq", 0)))
    wins = windows(snaps, window_s)
    results = []
    ok = True
    for spec in specs:
        evaluated = 0
        violated = 0
        worst = None
        worst_t = None
        for before, after in wins:
            value = _eval_window(spec, before, after)
            if value is None:
                continue
            evaluated += 1
            bad = _violates(spec, value)
            if bad:
                violated += 1
            # "worst" is the most-violating direction: max for max-bound
            # specs, min for min-bound ones.
            key = value if spec.max is not None else -value
            if worst is None or key > (worst if spec.max is not None else -worst):
                worst = value
                worst_t = after.get("ts")
        frac = (violated / evaluated) if evaluated else 0.0
        spec_ok = frac <= spec.allow_violation_fraction
        if evaluated and not spec_ok:
            ok = False
        results.append(
            {
                "spec": spec.to_dict(),
                "ok": spec_ok,
                "windows": evaluated,
                "violated_windows": violated,
                "violated_fraction": round(frac, 4),
                "worst": (
                    None if worst is None
                    else ("inf" if math.isinf(worst) else round(worst, 3))
                ),
                "worst_at": worst_t,
            }
        )
    verdict = {
        "schema": SLO_VERDICT_SCHEMA,
        "source": source,
        "window_s": window_s,
        "snapshots": len(snaps),
        "ok": ok and bool(wins),
        "slos": results,
    }
    if not wins:
        verdict["reason"] = "no snapshots"
    return verdict


def evaluate_streams(
    streams: dict[str, list[dict]],
    specs: list[SloSpec],
    *,
    window_s: float = 30.0,
) -> dict:
    """Per-stream (per-node) evaluation + one aggregate verdict: every
    node must individually meet its SLOs — a cluster average hides a
    wedged straggler."""
    per_node = {
        name: evaluate(snaps, specs, window_s=window_s, source=name)
        for name, snaps in sorted(streams.items())
    }
    return {
        "schema": SLO_VERDICT_SCHEMA,
        "window_s": window_s,
        "ok": bool(per_node) and all(v["ok"] for v in per_node.values()),
        "nodes": per_node,
    }
