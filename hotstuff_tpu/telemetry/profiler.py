"""Continuous in-process sampling profiler: function-level attribution
joined onto the round-trace stages.

PR 6's causal tracing attributes milliseconds to cross-node *edges*
(ingress, vote_wire, qc_to_commit, ...); this module answers the next
question — WHICH FUNCTIONS burn that time — without the tracing
overhead multiplying asyncio's per-event cost the way cProfile does
(a traced N=40 committee cannot even form its mesh inside a CI window;
a 2 ms sampler costs ~0.3%).

One :class:`SamplingProfiler` per process walks **every** thread's stack
via ``sys._current_frames()`` on a ~2 ms cadence, driven either by
``SIGPROF``/``ITIMER_PROF`` (CPU-time ticks, main thread only holds the
handler) or by a daemon sampler thread (the fallback when signals are
unavailable — non-main-thread start, Windows, nested samplers). Each
sample is tagged with the sampled thread's **currently-active
round-trace stage**: ``consensus/core.py``'s event dispatch and the
RoundTrace marks set a contextvar (task-correct for ``current_stage()``
queries) mirrored into a thread-keyed table (what the sampler, running
on a different thread, can actually read). Folded stacks accumulate per
(stage, stack) and drain into the telemetry JSON-lines streams as
``hotstuff-profile-v1`` records alongside snapshots and traces;
``benchmark/profile_assemble.py`` joins them onto the trace edges.

Two boundary accounts ride along:

- **ctypes accounting**: the native planes register their CDLLs here
  (``register_ctypes_lib``); while a profiler session is active every
  ``hs_net_*``/``hs_ed25519_*`` entry point is wrapped to count calls
  and cumulative wall nanoseconds (the call itself releases the GIL;
  the measured span includes the GIL reacquisition on return — exactly
  the per-call toll ROADMAP item 2's command ring wants to amortize).
  Zero cost when no session is active: the original function pointers
  are restored on ``stop()``.
- **GIL-delay proxy**: the sampler records how much later than
  scheduled each tick fired (``gil_delay_ns``). The handler/sampler
  thread can only run once it holds the GIL, so accumulated excess
  delay is a direct, if coarse, measure of how contended the GIL was —
  per-call ctypes wall time tells you *where*, this tells you *how
  much* overall.

Stage semantics on a shared event-loop thread (the one-process
committee): the thread-keyed tag is last-writer-wins across interleaved
engine tasks, so a sample taken during engine A's await may be tagged
by engine B's most recent mark. All engines do the same kind of work in
the same protocol phase, so per-stage attribution stays statistically
sound; per-task queries (``current_stage()``) use the contextvar and
are exact across await points.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from collections import Counter
from contextlib import contextmanager
from contextvars import ContextVar

PROFILE_SCHEMA = "hotstuff-profile-v1"

DEFAULT_INTERVAL_MS = 2.0
DEFAULT_MAX_DEPTH = 48
#: distinct (stage, folded-stack) keys kept between drains; past this the
#: sample lands in the per-stage ``truncated`` bucket (counted, never
#: silent) so a pathological stack explosion cannot eat the heap.
DEFAULT_MAX_STACKS = 16_384

# -- stage tagging -----------------------------------------------------------

#: task-correct stage (exact across await points — contextvars follow the
#: asyncio task). Readable only from the owning task/thread.
_STAGE_VAR: ContextVar[str] = ContextVar("hotstuff_profile_stage", default="")
#: thread-keyed mirror the sampler reads cross-thread. Plain dict writes
#: are GIL-atomic; stale entries for dead threads are pruned at sample
#: time against sys._current_frames()'s live set.
_THREAD_STAGE: dict[int, str] = {}

#: module-level fast flag: tagging call sites in hot paths read this ONE
#: attribute and skip the set entirely when no profiler session is live.
TAGGING = False


def set_thread_stage(stage: str) -> None:
    """Point-set the calling thread's stage (the run-loop/mark hot path:
    no token, no restore — the next set wins)."""
    _THREAD_STAGE[threading.get_ident()] = stage


def set_stage(stage: str):
    """Scoped set: updates both the contextvar (task-correct) and the
    thread mirror; returns a token for :func:`reset_stage`."""
    token = _STAGE_VAR.set(stage)
    _THREAD_STAGE[threading.get_ident()] = stage
    return token


def reset_stage(token) -> None:
    _STAGE_VAR.reset(token)
    _THREAD_STAGE[threading.get_ident()] = _STAGE_VAR.get()


def current_stage() -> str:
    """The calling task's stage (contextvar — survives await points and
    is isolated between concurrently-running tasks)."""
    return _STAGE_VAR.get()


@contextmanager
def stage(name: str):
    token = set_stage(name)
    try:
        yield
    finally:
        reset_stage(token)


# -- frame folding -----------------------------------------------------------


#: code object -> rendered frame id. Code objects are stable for loaded
#: code and hashable; caching skips the string formatting on every
#: sampled frame (the sampler's hottest inner loop). Bounded defensively
#: against pathological code churn (exec-generated functions).
_CODE_ID_CACHE: dict[object, str] = {}
_CODE_ID_CACHE_CAP = 65_536


def frame_id(frame) -> str:
    """Compact stable id: repo-relative (or stdlib basename) file, first
    line of the function, function name."""
    code = frame.f_code
    fid = _CODE_ID_CACHE.get(code)
    if fid is not None:
        return fid
    fn = code.co_filename
    for marker in ("/hotstuff_tpu/", "/benchmark/", "/tests/"):
        if marker in fn:
            fn = marker.strip("/") + "/" + fn.split(marker, 1)[1]
            break
    else:
        fn = os.path.basename(fn)
    fid = f"{fn}:{code.co_firstlineno}:{code.co_name}"
    if len(_CODE_ID_CACHE) < _CODE_ID_CACHE_CAP:
        _CODE_ID_CACHE[code] = fid
    return fid


def fold_stack(frame, max_depth: int = DEFAULT_MAX_DEPTH) -> str:
    """Root→leaf semicolon-folded stack (the flamegraph convention).
    Stacks deeper than ``max_depth`` keep the LEAF end (self-time blame
    must survive truncation) behind a ``...`` root marker."""
    names: list[str] = []
    f = frame
    while f is not None:
        names.append(frame_id(f))
        f = f.f_back
    # names is leaf→root; reverse to root→leaf.
    if len(names) > max_depth:
        return ";".join(["..."] + names[max_depth - 1 :: -1][-max_depth:])
    return ";".join(reversed(names))


# -- ctypes boundary accounting ---------------------------------------------

#: (lib, plane, names) registered by the native wrappers at load time.
_CTYPES_LIBS: list[tuple[object, str, tuple[str, ...]]] = []
#: name -> [calls, cumulative wall ns]; cells mutated GIL-atomically.
_CTYPES_STATS: dict[str, list[int]] = {}
_CTYPES_WRAPPED: list[tuple[object, str, object]] = []  # (lib, name, original)


def register_ctypes_lib(lib, plane: str, names: list[str]) -> None:
    """Called by the native wrappers (`network/native`, `crypto/
    native_ed25519`) after a CDLL loads: makes its entry points
    instrumentable. No wrapping happens here — only an active profiler
    session (``SamplingProfiler.start``) pays the per-call toll."""
    _CTYPES_LIBS.append((lib, plane, tuple(names)))
    if _ACTIVE is not None and _ACTIVE._ctypes:
        _wrap_lib(lib, plane, tuple(names))


def _make_ctypes_wrapper(name, fn, cell):
    def wrapper(*args):
        t0 = time.perf_counter_ns()
        try:
            return fn(*args)
        finally:
            cell[0] += 1
            cell[1] += time.perf_counter_ns() - t0

    # Rename the code object so stack samples taken INSIDE the native
    # call (C frames are invisible to the sampler; the wrapper is the
    # visible leaf) blame the named boundary — "ctypes:hs_net_send" —
    # instead of an anonymous "wrapper".
    wrapper.__code__ = wrapper.__code__.replace(co_name=f"ctypes:{name}")
    wrapper.__name__ = f"ctypes:{name}"
    wrapper.__wrapped__ = fn
    return wrapper


def _wrap_lib(lib, plane: str, names: tuple[str, ...]) -> None:
    for name in names:
        fn = getattr(lib, name, None)
        if fn is None or hasattr(fn, "__wrapped__"):
            continue
        cell = _CTYPES_STATS.setdefault(f"{plane}.{name}", [0, 0])
        setattr(lib, name, _make_ctypes_wrapper(name, fn, cell))
        _CTYPES_WRAPPED.append((lib, name, fn))


def _wrap_all_libs() -> None:
    for lib, plane, names in _CTYPES_LIBS:
        _wrap_lib(lib, plane, names)


def _unwrap_all_libs() -> None:
    while _CTYPES_WRAPPED:
        lib, name, fn = _CTYPES_WRAPPED.pop()
        setattr(lib, name, fn)


def ctypes_stats() -> dict[str, list[int]]:
    """``{plane.fn: [calls, wall_ns]}`` accumulated across sessions."""
    return {k: list(v) for k, v in _CTYPES_STATS.items() if v[0]}


# -- the sampler -------------------------------------------------------------

_ACTIVE: "SamplingProfiler | None" = None


def active() -> "SamplingProfiler | None":
    """The process's running profiler session, or None (what emitters
    attach to when asked to stream profile records)."""
    return _ACTIVE


def env_interval_ms() -> float:
    try:
        return float(os.environ.get("HOTSTUFF_PYPROF_INTERVAL_MS", ""))
    except ValueError:
        return DEFAULT_INTERVAL_MS


class SamplingProfiler:
    """All-thread sampling profiler with stage tagging. One instance may
    be active per process (``start`` raises otherwise)."""

    def __init__(
        self,
        interval_ms: float = DEFAULT_INTERVAL_MS,
        max_depth: int = DEFAULT_MAX_DEPTH,
        max_stacks: int = DEFAULT_MAX_STACKS,
    ) -> None:
        self.interval_ms = max(float(interval_ms), 0.1)
        self.max_depth = max_depth
        self.max_stacks = max_stacks
        self.mode: str | None = None
        # (stage, folded) -> samples, flushed to _drained on drain().
        self._counts: Counter[tuple[str, str]] = Counter()
        self._lock = threading.Lock()
        self.samples = 0
        self.truncated = 0  # samples folded into the overflow bucket
        self.contended = 0  # samples dropped: aggregation lock was held
        self.gil_delay_ns = 0
        self.threads_seen = 0  # thread count at the last sample
        self._last_tick_ns: int | None = None
        self._ctypes = False
        self._sampler_tid: int | None = None
        # tid -> (leaf frame object, folded stack). A frame's f_back
        # chain is fixed at creation, so an IDENTICAL leaf frame object
        # means an identical stack: blocked threads (crypto workers
        # parked on the fused-batch wait, the flusher between windows)
        # re-walk nothing — without this, sampling ~35 mostly-idle
        # threads per tick cost ~6% of an N=100 round instead of <1%.
        # Holding the frame ref is what makes the `is` check sound
        # (the object cannot be freed/reused while cached).
        self._frame_cache: dict[int, tuple] = {}
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self._prev_handler = None
        self._drain_seq = 0
        self.started_ts: float | None = None

    # -- lifecycle --

    def start(self, mode: str = "auto", ctypes_accounting: bool = True) -> "SamplingProfiler":
        """Begin sampling. ``mode``: ``signal`` (ITIMER_PROF — CPU-time
        ticks, needs the main thread), ``thread`` (wall-clock daemon
        thread), or ``auto`` (signal when possible, else thread)."""
        global _ACTIVE, TAGGING
        if _ACTIVE is not None:
            raise RuntimeError("a SamplingProfiler session is already active")
        if mode == "auto":
            mode = (
                "signal"
                if threading.current_thread() is threading.main_thread()
                and hasattr(signal, "setitimer")
                else "thread"
            )
        if mode not in ("signal", "thread"):
            raise ValueError(f"unknown profiler mode {mode!r}")
        self.mode = mode
        self.started_ts = time.time()
        self._stop_evt.clear()
        self._last_tick_ns = None
        _ACTIVE = self
        TAGGING = True
        self._ctypes = ctypes_accounting
        if ctypes_accounting:
            _wrap_all_libs()
        if mode == "signal":
            self._prev_handler = signal.signal(signal.SIGPROF, self._on_sigprof)
            signal.setitimer(
                signal.ITIMER_PROF, self.interval_ms / 1e3, self.interval_ms / 1e3
            )
        else:
            self._thread = threading.Thread(
                target=self._run_thread, name="hotstuff-pyprof", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        global _ACTIVE, TAGGING
        if _ACTIVE is not self:
            return
        if self.mode == "signal":
            signal.setitimer(signal.ITIMER_PROF, 0, 0)
            if self._prev_handler is not None:
                signal.signal(signal.SIGPROF, self._prev_handler)
                self._prev_handler = None
        elif self.mode == "thread" and self._thread is not None:
            self._stop_evt.set()
            self._thread.join(timeout=2.0)
            self._thread = None
        _unwrap_all_libs()
        self._frame_cache.clear()  # release the held frame refs
        _ACTIVE = None
        TAGGING = False

    # -- sampling --

    def _on_sigprof(self, signum, frame) -> None:
        # ITIMER_PROF ticks on process CPU time — the delay proxy must
        # measure on the same clock or idle wall time masquerades as
        # GIL contention.
        now = time.process_time_ns()
        frames = sys._current_frames()
        main_tid = threading.main_thread().ident
        if frame is not None and main_tid is not None:
            # The interrupted frame, not the handler's own frames.
            frames[main_tid] = frame
        elif main_tid is not None:
            # Signal delivered with no Python frame current on the main
            # thread (inside a C call): _current_frames would show the
            # handler itself — drop the main thread from this sample.
            frames.pop(main_tid, None)
        self.sample(frames, now_ns=now)

    def _run_thread(self) -> None:
        self._sampler_tid = threading.get_ident()
        interval_s = self.interval_ms / 1e3
        while not self._stop_evt.wait(interval_s):
            self.sample(sys._current_frames(), now_ns=time.perf_counter_ns())

    def sample(self, frames: dict[int, object], now_ns: int | None = None) -> None:
        """Record one sample from ``frames`` (thread id -> top frame).
        Public and deterministic: tests drive it with synthetic frames.
        ``now_ns`` feeds the GIL-delay account; None skips it."""
        if now_ns is not None:
            if self._last_tick_ns is not None:
                gap = now_ns - self._last_tick_ns
                expected = int(self.interval_ms * 1e6)
                if gap > expected:
                    self.gil_delay_ns += gap - expected
            self._last_tick_ns = now_ns
        own = self._sampler_tid
        live: list[tuple[str, str]] = []
        cache = self._frame_cache
        for tid, frame in frames.items():
            if tid == own:
                continue
            cached = cache.get(tid)
            # Identity reuse is only sound for plain-function leaves: a
            # generator/coroutine frame (CO_GENERATOR|CO_COROUTINE|
            # CO_ASYNC_GENERATOR) keeps its identity across suspensions
            # but gets a NEW f_back on every resume.
            if (
                cached is not None
                and cached[0] is frame
                and not (frame.f_code.co_flags & 0x2A0)
            ):
                folded = cached[1]
            else:
                folded = fold_stack(frame, self.max_depth)
                cache[tid] = (frame, folded)
            live.append((_THREAD_STAGE.get(tid, ""), folded))
        # Prune stage tags / frame cache of exited threads (bounded by
        # live thread ids).
        if len(_THREAD_STAGE) > 4 * max(1, len(frames)):
            for tid in list(_THREAD_STAGE):
                if tid not in frames:
                    _THREAD_STAGE.pop(tid, None)
        if len(cache) > 4 * max(1, len(frames)):
            for tid in list(cache):
                if tid not in frames:
                    del cache[tid]
        # NEVER block here: in signal mode this runs in a SIGPROF handler
        # on the main thread, and the main thread may hold the lock in
        # drain_record — a blocking acquire would deadlock the process.
        # A contended tick is dropped and counted instead.
        if not self._lock.acquire(blocking=False):
            self.contended += 1
            return
        try:
            self.samples += 1
            self.threads_seen = len(live)
            for key in live:
                if key not in self._counts and len(self._counts) >= self.max_stacks:
                    self.truncated += 1
                    key = (key[0], "...")
                self._counts[key] += 1
        finally:
            self._lock.release()

    # -- output --

    def drain_record(self, node: str = "") -> dict | None:
        """One ``hotstuff-profile-v1`` line: the folded stacks recorded
        since the previous drain (delta — stacks are large and
        append-only, like trace events) plus cumulative session gauges.
        None when nothing was sampled since the last drain."""
        with self._lock:
            if not self._counts:
                return None
            stacks = [[s, f, c] for (s, f), c in self._counts.items()]
            self._counts.clear()
            seq = self._drain_seq
            self._drain_seq += 1
            samples = self.samples
            truncated = self.truncated
            gil_delay = self.gil_delay_ns
            threads = self.threads_seen
        stacks.sort(key=lambda e: (-e[2], e[0], e[1]))
        return {
            "schema": PROFILE_SCHEMA,
            "node": node,
            "pid": os.getpid(),
            "seq": seq,
            "ts": time.time(),
            "mode": self.mode,
            "interval_ms": self.interval_ms,
            "samples": samples,
            "truncated": truncated,
            "threads": threads,
            "gil_delay_ns": gil_delay,
            "ctypes": ctypes_stats(),
            "stacks": stacks,
        }

    def collector(self) -> dict[str, float]:
        """Registry-collector view (``telemetry.register_collector``):
        cumulative session gauges surfaced in every snapshot."""
        out: dict[str, float] = {
            "samples": self.samples,
            "truncated": self.truncated,
            "gil_delay_ns": self.gil_delay_ns,
        }
        for name, (calls, ns) in ctypes_stats().items():
            out[f"ctypes.{name}.calls"] = calls
            out[f"ctypes.{name}.ns"] = ns
        return out

    def stage_totals(self) -> dict[str, int]:
        """Undrained samples per stage tag (CLI breakdown tables)."""
        with self._lock:
            out: dict[str, int] = {}
            for (stage_name, _folded), c in self._counts.items():
                out[stage_name] = out.get(stage_name, 0) + c
        return out

    def self_cum(self) -> tuple[Counter, Counter, int]:
        """(self-sample counts, cumulative-sample counts, total samples)
        aggregated over the UNdrained stacks — the one aggregation the
        CLI report and tests share. A function appearing multiple times
        in one stack is counted once toward its cumulative total."""
        with self._lock:
            counts = dict(self._counts)
            total = self.samples
        return aggregate_self_cum(
            [(s, f, c) for (s, f), c in counts.items()]
        ) + (total,)


def aggregate_self_cum(stacks: list) -> tuple[Counter, Counter]:
    """Fold ``[stage, "a;b;c", count]`` records into per-function self
    (leaf) and cumulative (anywhere-on-stack, deduped) sample counts."""
    self_c: Counter[str] = Counter()
    cum_c: Counter[str] = Counter()
    for _stage, folded, count in stacks:
        frames = folded.split(";")
        self_c[frames[-1]] += count
        for name in set(frames):
            cum_c[name] += count
    return self_c, cum_c


def validate_profile_record(obj) -> list[str]:
    """Schema check mirroring ``validate_snapshot``; returns problems."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"profile record is {type(obj).__name__}, not an object"]
    if obj.get("schema") != PROFILE_SCHEMA:
        problems.append(
            f"schema is {obj.get('schema')!r}, want {PROFILE_SCHEMA!r}"
        )
    for key, types in (
        ("node", str), ("pid", int), ("seq", int), ("ts", (int, float)),
        ("interval_ms", (int, float)), ("samples", int),
        ("gil_delay_ns", int), ("stacks", list),
    ):
        if not isinstance(obj.get(key), types):
            problems.append(f"field {key!r} missing or mistyped")
    for i, entry in enumerate(obj.get("stacks") or []):
        if (
            not isinstance(entry, (list, tuple))
            or len(entry) != 3
            or not isinstance(entry[0], str)
            or not isinstance(entry[1], str)
            or not isinstance(entry[2], int)
        ):
            problems.append(f"stack entry {i} malformed: {entry!r}")
            break
    return problems


def reset_for_tests() -> None:
    """Stop any session and clear module state (test isolation)."""
    global TAGGING
    if _ACTIVE is not None:
        _ACTIVE.stop()
    _unwrap_all_libs()
    _CTYPES_STATS.clear()
    _THREAD_STAGE.clear()
    TAGGING = False
