"""JSON-lines snapshot emitter + snapshot schema validation.

One snapshot is one line: a self-describing JSON object carrying the
whole registry state (cumulative counters, current gauges, merged
histograms). Cumulative-not-delta means a reader needs only the LAST
line of a stream — a crashed node's stream is still fully usable up to
its final interval, and intermediate lines give time series for free.

When a :class:`~.trace.TraceBuffer` is attached, each emit additionally
appends one ``hotstuff-trace-v1`` line carrying the protocol trace
events recorded since the previous emit (delta, not cumulative — events
are large and append-only), interleaved with the snapshots in the same
stream. ``benchmark/logs.py`` separates the two schemas when reading.

Unclean shutdown: :func:`arm_shutdown_flush` registers SIGTERM and
``atexit`` hooks that write the ``final: true`` snapshot (and trace
tail, and optionally a flight record) even when the process never
reaches its graceful ``shutdown()`` — the local bench's teardown and
faultline's crash/restart harness both kill nodes, and without this the
last interval of every stream was lost.

``benchmark/logs.py`` consumes these streams (``TelemetryParser``)
alongside its regex path; the CI smoke lane validates them with
``validate_snapshot``.
"""

from __future__ import annotations

import asyncio
import atexit
import json
import logging
import os
import signal
import time

from . import profiler as pyprof
from .dtrace import build_dtrace_record
from .trace import build_trace_record, dump_flight_record

log = logging.getLogger("telemetry")

SCHEMA = "hotstuff-telemetry-v1"
META_SCHEMA = "hotstuff-meta-v1"
DEFAULT_INTERVAL_S = 5.0


def build_meta_record(
    node: str = "",
    interval_s: float | None = None,
    anchor: dict | None = None,
) -> dict:
    """The stream's self-description: every emitter writes one of these
    as its FIRST record so a consumer (the watchtower, the validate CLI,
    a human with ``head -1``) knows what it is looking at without
    guessing from content — which schemas may appear, which node wrote
    it, the wall-clock anchor that places the stream's monotonic trace
    timestamps on a shared timeline, and the writer pid (restarts of the
    same node produce a new meta record mid-stream: a visible epoch
    boundary, not a silent counter reset)."""
    from .dtrace import DTRACE_SCHEMA
    from .profiler import PROFILE_SCHEMA
    from .trace import TRACE_SCHEMA
    from .watchtower import ALERT_SCHEMA

    return {
        "schema": META_SCHEMA,
        "schemas": [
            SCHEMA, TRACE_SCHEMA, DTRACE_SCHEMA, PROFILE_SCHEMA,
            ALERT_SCHEMA,
        ],
        "node": node,
        "pid": os.getpid(),
        "ts": time.time(),
        "anchor": anchor
        or {"mono": time.perf_counter(), "wall": time.time()},
        "interval_s": interval_s,
    }


def validate_meta_record(obj) -> list[str]:
    """Schema check mirroring ``validate_snapshot``; returns problems."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"meta record is {type(obj).__name__}, not an object"]
    if obj.get("schema") != META_SCHEMA:
        problems.append(
            f"schema is {obj.get('schema')!r}, want {META_SCHEMA!r}"
        )
    if not isinstance(obj.get("schemas"), list) or not all(
        isinstance(s, str) for s in obj.get("schemas") or []
    ):
        problems.append("schemas missing or not a list of strings")
    for key, types in (("node", str), ("pid", int), ("ts", (int, float))):
        if not isinstance(obj.get(key), types):
            problems.append(f"field {key!r} missing or mistyped")
    anchor = obj.get("anchor")
    if not isinstance(anchor, dict) or not all(
        isinstance(anchor.get(k), (int, float)) for k in ("mono", "wall")
    ):
        problems.append("anchor missing mono/wall")
    return problems


def build_snapshot(registry, node: str = "", seq: int = 0, final: bool = False) -> dict:
    snap = registry.snapshot()
    return {
        "schema": SCHEMA,
        "node": node,
        "pid": os.getpid(),
        "seq": seq,
        "ts": time.time(),
        "final": final,
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "histograms": snap["histograms"],
    }


def validate_snapshot(obj) -> list[str]:
    """Schema check for one parsed snapshot line; returns a list of
    problems (empty == valid). Deliberately dependency-free — the CI
    smoke lane and tests share it."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"snapshot is {type(obj).__name__}, not an object"]
    if obj.get("schema") != SCHEMA:
        problems.append(f"schema is {obj.get('schema')!r}, want {SCHEMA!r}")
    for key, types in (
        ("node", str), ("pid", int), ("seq", int),
        ("ts", (int, float)), ("final", bool),
        ("counters", dict), ("gauges", dict), ("histograms", dict),
    ):
        if not isinstance(obj.get(key), types):
            problems.append(f"field {key!r} missing or mistyped")
    for name, v in (obj.get("counters") or {}).items():
        if not isinstance(v, int) or v < 0:
            problems.append(f"counter {name!r} not a non-negative int")
    for name, v in (obj.get("gauges") or {}).items():
        if not isinstance(v, (int, float)):
            problems.append(f"gauge {name!r} not a number")
    for name, h in (obj.get("histograms") or {}).items():
        if not isinstance(h, dict):
            problems.append(f"histogram {name!r} not an object")
            continue
        le, counts = h.get("le"), h.get("counts")
        if not isinstance(le, list) or not isinstance(counts, list):
            problems.append(f"histogram {name!r} missing le/counts")
            continue
        if len(counts) != len(le) + 1:
            problems.append(f"histogram {name!r}: {len(counts)} counts "
                            f"for {len(le)} edges (want edges+1)")
        if list(le) != sorted(le):
            problems.append(f"histogram {name!r}: edges not sorted")
        if not isinstance(h.get("count"), int) or not isinstance(
            h.get("sum"), (int, float)
        ):
            problems.append(f"histogram {name!r} missing count/sum")
        elif sum(counts) != h["count"]:
            problems.append(
                f"histogram {name!r}: bucket counts sum to {sum(counts)}, "
                f"count says {h['count']}"
            )
    return problems


class TelemetryEmitter:
    """Appends one snapshot line to ``path`` every ``interval_s`` and a
    ``final`` one at shutdown. Each write is a single buffered
    write+flush of a complete line, so concurrent emitters appending to
    the same file (in-process testbeds) interleave at line granularity.
    With ``trace`` attached, each emit also appends a trace line carrying
    the protocol events recorded since the previous emit."""

    def __init__(
        self,
        registry,
        path: str,
        node: str = "",
        interval_s: float = DEFAULT_INTERVAL_S,
        trace=None,
        dtrace=None,
        profiler=None,
    ) -> None:
        self.registry = registry
        self.path = path
        self.node = node
        self.interval_s = max(float(interval_s), 0.05)
        self.trace = trace  # TraceBuffer or None
        self.dtrace = dtrace  # batch-lifecycle TraceBuffer or None
        # SamplingProfiler, or None to follow the process-active session
        # lazily (nodes arm the profiler from the environment after the
        # emitter exists; a fixed None would silently drop its records).
        self.profiler = profiler
        self._trace_seq = 0  # last trace event seq already streamed
        self._dtrace_seq = 0  # last dtrace event seq already streamed
        self._seq = 0
        self._final_done = False
        self._meta_done = False
        self._task: asyncio.Task | None = None
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def emit(self, final: bool = False) -> dict:
        if final:
            if self._final_done:
                # Already flushed (signal handler / atexit raced the
                # graceful shutdown): final state is on disk, don't
                # duplicate it.
                return {}
            self._final_done = True
        snapshot = build_snapshot(
            self.registry, node=self.node, seq=self._seq, final=final
        )
        self._seq += 1
        lines = []
        if not self._meta_done:
            # Stream self-description rides as the first record this
            # emitter contributes (per WRITER, not per file: in-process
            # testbeds append several emitters to one file, and a node
            # restart appends a fresh meta record — the epoch boundary).
            self._meta_done = True
            anchor = self.trace.anchor() if self.trace is not None else None
            lines.append(
                json.dumps(
                    build_meta_record(
                        node=self.node,
                        interval_s=self.interval_s,
                        anchor=anchor,
                    ),
                    separators=(",", ":"),
                )
            )
        lines.append(json.dumps(snapshot, separators=(",", ":")))
        if self.trace is not None:
            events = self.trace.events_since(self._trace_seq)
            if events:
                self._trace_seq = events[-1][0]
                record = build_trace_record(self.trace, events, node=self.node)
                lines.append(json.dumps(record, separators=(",", ":")))
        if self.dtrace is not None:
            # Batch-lifecycle events ride the same stream as their own
            # delta line (same contract as the round trace above).
            events = self.dtrace.events_since(self._dtrace_seq)
            if events:
                self._dtrace_seq = events[-1][0]
                record = build_dtrace_record(
                    self.dtrace, events, node=self.node
                )
                lines.append(json.dumps(record, separators=(",", ":")))
        prof = self.profiler if self.profiler is not None else pyprof.active()
        if prof is not None:
            # Folded stacks sampled since the previous emit ride the same
            # stream as one ``hotstuff-profile-v1`` line (delta, like
            # trace events; the sampler keeps nothing after the drain).
            profile = prof.drain_record(node=self.node)
            if profile is not None:
                lines.append(json.dumps(profile, separators=(",", ":")))
        try:
            with open(self.path, "a") as f:
                f.write("\n".join(lines) + "\n")
        except OSError as e:  # telemetry must never kill the node
            log.error("cannot write telemetry snapshot to %s: %s", self.path, e)
        return snapshot

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            self.emit()

    def spawn(self) -> "TelemetryEmitter":
        self._task = asyncio.create_task(self._run(), name="telemetry_emitter")
        return self

    async def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.emit(final=True)


def arm_shutdown_flush(
    emitter: TelemetryEmitter, flight_path: str | None = None
) -> None:
    """Guarantee the ``final: true`` snapshot survives unclean teardown.

    Registers an ``atexit`` hook and chains a SIGTERM handler: both flush
    the final snapshot (idempotent — ``emit(final=True)`` runs at most
    once per emitter) and, when ``flight_path`` is given, dump the flight
    record. The SIGTERM handler then restores the previous disposition
    and re-raises the signal so the process still dies with the expected
    status — this instrumentation observes shutdown, it doesn't veto it.
    SIGKILL remains unsurvivable by design; benches that want the final
    interval send SIGTERM first (``benchmark/local.py`` does).
    """

    def _flush(reason: str) -> None:
        try:
            emitter.emit(final=True)
            if flight_path is not None and emitter.trace is not None:
                dump_flight_record(
                    flight_path, reason, emitter.trace, emitter.registry
                )
        except Exception as e:  # noqa: BLE001 — shutdown paths never raise
            log.error("telemetry shutdown flush failed: %s", e)

    atexit.register(_flush, "atexit")

    try:
        previous = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            _flush("sigterm")
            if callable(previous):
                previous(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        # Not the main thread (in-process testbeds spawn emitters from
        # worker contexts): the atexit hook still covers interpreter exit.
        pass
