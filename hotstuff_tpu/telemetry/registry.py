"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Hot-path design: every counter/histogram write touches only the calling
thread's private shard (a plain Python list reached through
``threading.local``) — no lock, no cross-thread cache traffic — so the
consensus event loop, the C-extension crypto workers, and the superbatch
flusher thread can all record without contending. Shards are merged only
at SNAPSHOT time (read-side pays, write-side never does). Merged reads
are not a linearizable cut across threads — fine for telemetry, where a
snapshot races in-flight increments by design.

Gauges are last-write-wins scalars (plus ``set_min``/``set_max`` for
watermark timestamps); they carry no shards because a gauge is a single
current value, not an accumulation.

Collectors bridge state that lives OUTSIDE this registry — the C++
engines' internal counters (``hs_net_stats_ex``, ``hs_ed25519_stats``),
the superbatch backend's totals — behind one snapshot call: a collector
is polled once per ``snapshot()`` and its values appear as gauges.
"""

from __future__ import annotations

import logging
import threading
from bisect import bisect_left

log = logging.getLogger("telemetry")

# Default bucket boundaries (upper-inclusive edges; the implicit last
# bucket is +Inf). Chosen to cover the observed dynamic range of this
# system: sub-ms handler stages up to multi-second view changes, bytes
# from single transactions to the 64 MiB frame cap, occupancies from a
# lone request to a full fused window.
DURATION_MS_BUCKETS = (
    0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1_000, 2_500, 5_000, 10_000, 30_000,
)
# Fine-grained duration buckets: the crypto plane's per-signature regime
# is 22-26 µs (0.022-0.026 ms) and native-path spans at small committees
# sit under DURATION_MS_BUCKETS' 0.1 ms floor — both collapsed into one
# bucket there. These edges resolve 1 µs .. 1 s; metrics pick their scale
# per name (``Registry.histogram(name, buckets)``).
FINE_DURATION_MS_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 1_000,
)
SIZE_BYTES_BUCKETS = (
    64, 256, 1_024, 4_096, 16_384, 65_536, 262_144, 1_048_576, 16_777_216,
)
COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1_024)

_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)


def _check_name(name: str) -> str:
    if not name or not set(name) <= _NAME_OK:
        raise ValueError(f"bad metric name {name!r}")
    return name


class Counter:
    """Monotonic counter, thread-sharded (see module docstring)."""

    __slots__ = ("name", "_local", "_cells", "_lock")

    def __init__(self, name: str) -> None:
        self.name = _check_name(name)
        self._local = threading.local()
        self._cells: list[list[int]] = []
        self._lock = threading.Lock()

    def _cell(self) -> list[int]:
        cell = getattr(self._local, "cell", None)
        if cell is None:
            cell = [0]
            self._local.cell = cell
            with self._lock:  # registration only: once per thread
                self._cells.append(cell)
        return cell

    def inc(self, n: int = 1) -> None:
        self._cell()[0] += n

    def value(self) -> int:
        with self._lock:
            return sum(cell[0] for cell in self._cells)


class Gauge:
    """Last-write-wins scalar; ``None`` until first set (unset gauges are
    omitted from snapshots)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str) -> None:
        self.name = _check_name(name)
        self._value: float | None = None

    def set(self, v: float) -> None:
        self._value = v

    def set_min(self, v: float) -> None:
        cur = self._value
        if cur is None or v < cur:
            self._value = v

    def set_max(self, v: float) -> None:
        cur = self._value
        if cur is None or v > cur:
            self._value = v

    def value(self) -> float | None:
        return self._value


class _HistCell:
    __slots__ = ("counts", "sum", "n")

    def __init__(self, n_buckets: int) -> None:
        self.counts = [0] * n_buckets
        self.sum = 0.0
        self.n = 0


class Histogram:
    """Fixed-bucket histogram, thread-sharded. ``buckets`` are the
    upper-inclusive edges; one implicit overflow bucket is appended."""

    __slots__ = ("name", "buckets", "_local", "_cells", "_lock")

    def __init__(self, name: str, buckets=DURATION_MS_BUCKETS) -> None:
        self.name = _check_name(name)
        edges = tuple(float(b) for b in buckets)
        if not edges or list(edges) != sorted(set(edges)):
            raise ValueError(f"histogram buckets must be sorted/unique: {buckets}")
        self.buckets = edges
        self._local = threading.local()
        self._cells: list[_HistCell] = []
        self._lock = threading.Lock()

    def _cell(self) -> _HistCell:
        cell = getattr(self._local, "cell", None)
        if cell is None:
            cell = _HistCell(len(self.buckets) + 1)
            self._local.cell = cell
            with self._lock:
                self._cells.append(cell)
        return cell

    def observe(self, v: float) -> None:
        cell = self._cell()
        # bisect_left: a value equal to an edge lands in that edge's
        # bucket — edges are upper-INCLUSIVE ("le", Prometheus-style).
        cell.counts[bisect_left(self.buckets, v)] += 1
        cell.sum += v
        cell.n += 1

    def merged(self) -> tuple[list[int], float, int]:
        """(bucket counts incl. overflow, value sum, observation count)."""
        counts = [0] * (len(self.buckets) + 1)
        total = 0.0
        n = 0
        with self._lock:
            cells = list(self._cells)
        for cell in cells:
            for i, c in enumerate(cell.counts):
                counts[i] += c
            total += cell.sum
            n += cell.n
        return counts, total, n

    def mean(self) -> float:
        _, total, n = self.merged()
        return total / n if n else 0.0


class Registry:
    """Name -> metric, with collector callbacks polled at snapshot time."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._collectors: dict[str, object] = {}  # name -> callable

    def _get(self, name: str, factory):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = factory()
            return metric

    def counter(self, name: str) -> Counter:
        metric = self._get(name, lambda: Counter(name))
        if not isinstance(metric, Counter):
            raise TypeError(f"{name!r} is already a {type(metric).__name__}")
        return metric

    def gauge(self, name: str) -> Gauge:
        metric = self._get(name, lambda: Gauge(name))
        if not isinstance(metric, Gauge):
            raise TypeError(f"{name!r} is already a {type(metric).__name__}")
        return metric

    def histogram(self, name: str, buckets=DURATION_MS_BUCKETS) -> Histogram:
        metric = self._get(name, lambda: Histogram(name, buckets))
        if not isinstance(metric, Histogram):
            raise TypeError(f"{name!r} is already a {type(metric).__name__}")
        return metric

    def register_collector(self, name: str, fn) -> None:
        """``fn() -> dict[str, number]``: polled once per snapshot, values
        merged into the gauge section under their own names. Re-registering
        ``name`` replaces the previous collector (process-wide singletons
        re-created across test event loops must not accumulate)."""
        with self._lock:
            self._collectors[name] = fn

    def snapshot(self) -> dict:
        """Plain-data view of every metric (JSON-serializable)."""
        with self._lock:
            metrics = dict(self._metrics)
            collectors = dict(self._collectors)
        counters: dict[str, int] = {}
        gauges: dict[str, float] = {}
        histograms: dict[str, dict] = {}
        for name, metric in sorted(metrics.items()):
            if isinstance(metric, Counter):
                counters[name] = metric.value()
            elif isinstance(metric, Gauge):
                v = metric.value()
                if v is not None:
                    gauges[name] = v
            else:
                counts, total, n = metric.merged()
                histograms[name] = {
                    "le": list(metric.buckets),
                    "counts": counts,
                    "sum": total,
                    "count": n,
                }
        for cname, fn in sorted(collectors.items()):
            try:
                for k, v in fn().items():
                    gauges[f"{cname}.{k}"] = v
            except Exception as e:  # noqa: BLE001 — telemetry must not kill
                log.warning("telemetry collector %s failed: %s", cname, e)
        return {"counters": counters, "gauges": gauges, "histograms": histograms}

    def reset(self) -> None:
        """Drop every metric and collector (tests)."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()


def diff_counters(before: dict, after: dict) -> dict[str, int]:
    """Per-name deltas of two ``snapshot()['counters']`` maps (new names
    count from zero) — the measured-window primitive benchmarks use."""
    return {
        name: value - before.get(name, 0)
        for name, value in after.items()
        if value != before.get(name, 0)
    }
