"""Watchtower: the ONLINE observability plane — streaming detection and
per-peer accountability scoring over the telemetry streams as they are
written.

Everything else in this package is post-hoc: ``trace_assemble``,
``profile_assemble``, the SLO engine and the soak verdict all judge a
run after it ends, which is how the two committed incidents
(``results/soak-slo-n4-60s-chaos7.json``: post-heal vote withholding;
``soak-slo-n4-60s-chaos3.json``: a laggard that commits nothing in the
tail) were found minutes after the bytes explaining them were on disk.
The :class:`Watchtower` consumes the same records *incrementally* — one
``ingest_record`` call per stream line, fed by a tail-follower
(``benchmark.logs.StreamFollower``) or a replay loop — and maintains:

- **per-peer health scores** (:meth:`Watchtower.scoreboard`):
  vote-participation rate per round window, propose→vote turnaround
  percentile, commit-height lag vs. the quorum frontier,
  timeout-emission rate, equivocation evidence;
- **online detectors** that emit structured ``hotstuff-alert-v1``
  records naming the accused peers, the evidence window, and a
  confidence — see the detector catalog below;
- an **alert hook** (``on_alert``) for capture: an
  :class:`AlertCapture` dumps the flight record plus a bounded
  profiler session at the moment of detection, so the evidence is on
  disk when a human arrives.

Evidence model: trace events carry ``(seq, node, round, stage, t_mono
[, detail])`` where ``node`` is the OBSERVER. ``vote_rx`` details name
``"<author>|<block digest>"`` (who voted, for what — recorded by the
round's collector), ``propose``/``propose_send`` details name
``"<author>|<digest>"``, ``commit`` details carry ``"h<height>"``.
Accusations are therefore grounded in what *other* nodes observed
wherever possible — a withholding voter is one whose votes stop
arriving at collectors, not one who merely stops self-reporting.

Detector catalog (all tunable via :class:`WatchtowerConfig`):

- ``silent_voter``: a peer whose vote-participation rate stays under
  ``silent_participation_max`` for ``silent_windows`` consecutive
  closed windows while at least two other peers vote normally. The
  chaos-seed-7 signature (withholding post-heal); also fires on a
  crashed peer — the evidence says whether the peer was otherwise
  alive (``alive: true`` == verifying/proposing but not voting).
- ``laggard``: a peer whose commit height does not advance for
  ``laggard_windows`` consecutive windows while the quorum frontier
  advances, with lag ≥ ``laggard_min_lag``. The chaos-seed-3
  signature ("commits nothing in the tail").
- ``grinding_leader``: with the window's timeout rate elevated, a
  peer whose proposals repeatedly fail to commit
  (``mode: "uncommitted_proposals"``) or a peer that is demonstrably
  alive but never proposes while others do (``mode:
  "no_proposals"`` — the faultline ``silent_leader`` behavior).
- ``partitioned_clique``: the window's communication graph (vote
  author→collector, proposer→receiver edges) splits into ≥2
  connected components and at least one component shows liveness
  effort (votes/timeouts) without commits while another commits —
  the accused are the cut-off clique.
- ``slope_breach``: per-node RSS / store-size growth rate over a
  sliding window exceeds the bound — the same ``gauge_growth``
  semantics as :mod:`hotstuff_tpu.telemetry.slo`, evaluated online.
- ``sync_stall``: a peer whose state-sync probe loop stays active
  (``statesync.active``) with a frontier gap ≥ ``sync_stall_min_gap``
  that is NOT closing for ``sync_stall_budget_s`` — a rejoining
  replica stuck behind the quorum (peers refusing to serve it, a
  snapshot it keeps rejecting, or a truncation horizon nobody can
  bridge). A closing gap re-anchors the budget: slow-but-progressing
  catch-up never fires.
- ``equivocation``: conflicting-vote or conflicting-proposal evidence
  — the same (author, round) seen with two different digests.
  Immediate, confidence 1.0: this is cryptographic-grade evidence of
  byzantine behavior, not a statistical inference.

Validation is the point: ``benchmark/detector_bench.py`` replays seeded
faultline schedules (the fault plan IS the label set) through this
exact ingest path and scores precision / recall / time-to-detection;
``benchmark/watchtower_smoke.py`` gates the attached-vs-detached
overhead and zero-false-positive behavior on fault-free runs in CI.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict, deque
from dataclasses import dataclass

ALERT_SCHEMA = "hotstuff-alert-v1"
CAPTURE_SCHEMA = "hotstuff-capture-v1"

DETECTORS = (
    "silent_voter",
    "laggard",
    "grinding_leader",
    "partitioned_clique",
    "slope_breach",
    "digest_queue_starvation",
    "sync_stall",
    "equivocation",
)

#: Version of the detector catalog above (bump whenever a detector is
#: added, removed, or its evidence/confidence semantics change). Stamped
#: into every alert and every detector_bench/detector_sweep verdict next
#: to the config fingerprint, so scorecards are comparable across runs:
#: same (catalog, config hash) ⇒ same detection semantics.
DETECTOR_CATALOG_VERSION = 1

#: trace stages that constitute peer-behavior evidence. Anything else in
#: the ring (faultline injection audit events, future stages) must not
#: mint phantom peers or skew scores — observed live: the "faultline"
#: injection label being accused of withholding votes.
_PROTOCOL_STAGES = frozenset(
    (
        "propose_send", "propose", "verified", "vote_send", "vote_rx",
        "first_vote", "qc", "commit", "timeout",
    )
)


def validate_alert_record(obj) -> list[str]:
    """Schema check mirroring ``validate_snapshot``; returns problems."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"alert record is {type(obj).__name__}, not an object"]
    if obj.get("schema") != ALERT_SCHEMA:
        problems.append(
            f"schema is {obj.get('schema')!r}, want {ALERT_SCHEMA!r}"
        )
    if obj.get("detector") not in DETECTORS:
        problems.append(f"unknown detector {obj.get('detector')!r}")
    accused = obj.get("accused")
    if (
        not isinstance(accused, list)
        or not accused
        or not all(isinstance(a, str) for a in accused)
    ):
        problems.append("accused missing or not a non-empty list of strings")
    conf = obj.get("confidence")
    if not isinstance(conf, (int, float)) or not (0.0 <= conf <= 1.0):
        problems.append("confidence missing or not in [0, 1]")
    if not isinstance(obj.get("ts"), (int, float)):
        problems.append("ts missing or not a number")
    if not isinstance(obj.get("evidence"), dict):
        problems.append("evidence missing or not an object")
    if not isinstance(obj.get("config"), str) or not obj.get("config"):
        problems.append("config fingerprint missing or not a string")
    if not isinstance(obj.get("catalog"), int):
        problems.append("catalog version missing or not an int")
    window = obj.get("window")
    if not isinstance(window, dict) or not all(
        isinstance(window.get(k), (int, float)) for k in ("t_lo", "t_hi")
    ):
        problems.append("window missing t_lo/t_hi")
    return problems


@dataclass
class WatchtowerConfig:
    """Detection knobs. Defaults are tuned on the seeded faultline
    schedules in ``benchmark/detector_bench.py`` (chaos seeds 3/7 plus
    fault-free controls) — change them there first."""

    #: close the evidence window after this many newly-seen rounds...
    window_rounds: int = 16
    #: ...or after this much wall time, whichever comes first.
    window_s: float = 5.0
    #: rounds whose newest event is younger than this are held back at
    #: window close (late cross-stream events are normal, not evidence).
    #: Raised automatically to ~1.2x the largest emit interval any
    #: stream's meta record declares: multi-process nodes flush commits
    #: in interval-sized bursts, and judging a round before every
    #: stream's burst covering it can have landed reads emission lag as
    #: misbehavior (observed live: three of four healthy soak nodes
    #: accused as laggards).
    settle_s: float = 1.0
    #: the emit-interval multiple a round must have settled for before a
    #: window will judge it (effective settle = max(settle_s,
    #: settle_multiplier × largest declared emit interval)). Was a
    #: hard-coded 1.2; the detector_sweep searches it.
    settle_multiplier: float = 1.2
    #: alerts below this confidence are suppressed at the source (0.0 =
    #: keep everything). The low-confidence branches (partition
    #: global_stall at 0.5, grinding no_proposals at 0.6) are the main
    #: false-alarm producers on short incidents; the sweep tunes this.
    alert_min_confidence: float = 0.0
    #: windows with fewer vote-active rounds than this are not judged.
    min_rounds: int = 4
    silent_participation_max: float = 0.10
    silent_windows: int = 2
    laggard_windows: int = 2
    laggard_min_lag: int = 8
    laggard_min_frontier_advance: int = 3
    #: a peer is only a laggard once its own stream has demonstrably
    #: lived on (events arriving) for this long WITHOUT a commit — an
    #: emission-lagged healthy stream shows frozen heights too, but its
    #: commits and its liveness signs go stale together. Effective value
    #: is at least 2x the settled emit interval.
    laggard_stale_s: float = 12.0
    grind_timeout_rate: float = 0.25
    grind_min_proposals: int = 2
    #: how long a peer must have gone WITHOUT any observed proposal
    #: before the "alive but never proposing" grinding mode may accuse
    #: it. A single evidence window during a timeout grind spans only a
    #: couple of rounds — far less than one leader rotation — so
    #: "didn't propose in-window" alone is the dominant wrong-peer
    #: attribution in the offline sweep: rotation simply never reached
    #: the accused. Cross-window proposal staleness discriminates: even
    #: mid-grind an honest peer proposes every rotation (~committee
    #: size seconds), while the silent leader stays stale for its whole
    #: fault. 0 keeps the legacy gate (in-window evidence only).
    grind_proposal_stale_s: float = 0.0
    rss_growth_max_bytes_per_s: float = 8 * 1024 * 1024
    store_growth_max_bytes_per_s: float = 32 * 1024 * 1024
    slope_window_s: float = 10.0
    #: sustained growth of the proposer's certified-digest queue
    #: (digests/s over slope_window_s) before ordering is judged to be
    #: starving behind ingest. A queue that merely sits deep but drains
    #: as fast as it fills does not fire — growth is the signal.
    digest_queue_growth_max_per_s: float = 50.0
    #: a state-syncing peer may lag the quorum frontier by at least this
    #: many rounds before the stall budget starts counting...
    sync_stall_min_gap: int = 8
    #: ...and must fail to close that gap for this long before the
    #: ``sync_stall`` detector fires (re-anchored whenever it shrinks).
    sync_stall_budget_s: float = 20.0
    #: per-(detector, accused-set) re-alert backoff, seconds.
    cooldown_s: float = 15.0
    #: alert ring bound (oldest dropped; never grows without bound).
    max_alerts: int = 1024
    #: per-peer turnaround sample reservoir per window history.
    history_windows: int = 8

    @classmethod
    def from_dict(cls, d: dict) -> "WatchtowerConfig":
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown watchtower config keys: {sorted(unknown)}")
        return cls(**d)

    def fingerprint(self) -> str:
        """Short content hash of every knob — the ``config`` field every
        alert and sweep verdict carries. Field defaults count: adding a
        knob changes the fingerprint of the default config, which is the
        point (the detection surface changed)."""
        import hashlib

        payload = json.dumps(
            {k: getattr(self, k) for k in sorted(self.__dataclass_fields__)},
            sort_keys=True,
            separators=(",", ":"),
        )
        return hashlib.sha256(payload.encode()).hexdigest()[:12]

    @classmethod
    def preset(cls, name: str) -> "WatchtowerConfig":
        """Load a committed preset from ``telemetry/presets/<name>.json``
        (e.g. ``tuned-n4``, produced by ``benchmark.detector_sweep``)."""
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "presets",
            f"{name}.json",
        )
        with open(path) as f:
            doc = json.load(f)
        return cls.from_dict(doc["config"] if "config" in doc else doc)


class _Round:
    """Evidence accumulated for one protocol round, across all streams."""

    __slots__ = (
        "votes", "proposes", "propose_t", "vote_send_t", "commit_nodes",
        "timeouts", "propose_senders", "edges", "first_wall", "last_wall",
    )

    def __init__(self) -> None:
        self.votes: dict[str, set[str]] = {}        # author -> digests
        self.proposes: dict[str, set[str]] = {}     # author -> digests
        self.propose_senders: set[str] = set()      # leaders that broadcast
        self.propose_t: dict[str, float] = {}       # receiver -> wall t
        self.vote_send_t: dict[str, float] = {}     # voter -> wall t
        self.commit_nodes: dict[str, float] = {}    # node -> wall t
        self.timeouts: dict[str, int] = {}          # node -> count
        self.edges: set[frozenset] = set()          # observed comms pairs
        self.first_wall = float("inf")
        self.last_wall = 0.0

    def touch(self, t: float) -> None:
        if t < self.first_wall:
            self.first_wall = t
        if t > self.last_wall:
            self.last_wall = t


class _Window:
    """One closed evidence window (a batch of settled rounds)."""

    __slots__ = (
        "rounds", "t_lo", "t_hi", "vote_active_rounds", "voted_rounds",
        "turnaround", "proposals", "proposals_committed", "timeouts",
        "commits", "edges", "active_peers",
    )

    def __init__(self) -> None:
        self.rounds: list[int] = []
        self.t_lo = float("inf")
        self.t_hi = 0.0
        self.vote_active_rounds = 0
        self.voted_rounds: dict[str, int] = defaultdict(int)
        self.turnaround: dict[str, list[float]] = defaultdict(list)
        self.proposals: dict[str, int] = defaultdict(int)
        self.proposals_committed: dict[str, int] = defaultdict(int)
        self.timeouts: dict[str, int] = defaultdict(int)
        self.commits: dict[str, int] = defaultdict(int)
        self.edges: set[frozenset] = set()
        self.active_peers: set[str] = set()


def _pct(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class Watchtower:
    """Streaming analyzer over telemetry records (see module docstring).

    Feed it every parsed stream line via :meth:`ingest_record` (any
    schema — it routes internally) and call :meth:`tick` periodically
    (live mode) or :meth:`flush` at end of stream (replay mode). Both
    return the alerts fired by that call; :attr:`alerts` keeps the
    bounded full list. Single-writer: one thread ingests; ``alerts``
    and ``scoreboard()`` may be read from others (guarded)."""

    def __init__(
        self,
        config: WatchtowerConfig | None = None,
        *,
        alias: dict[str, str] | None = None,
        on_alert=None,
        label: str = "",
    ) -> None:
        self.config = config or WatchtowerConfig()
        self._config_hash = self.config.fingerprint()
        self.alias = dict(alias or {})
        self.on_alert = on_alert
        self.label = label
        self.alerts: list[dict] = []
        self._alerts_lock = threading.Lock()
        self._alert_seq = 0
        self._last_alert_at: dict[tuple, float] = {}

        self._rounds: dict[int, _Round] = {}
        self._max_round_seen = 0
        self._rounds_since_close = 0
        self._last_close_wall: float | None = None
        self._now = 0.0  # newest wall time observed (events or ticks)

        # Per-peer rolling state (survives window closes).
        self._peers: set[str] = set()
        self._heights: dict[str, int] = {}
        self._last_commit_seen: dict[str, float] = {}
        # Anchored at first sight like _last_commit_seen: staleness is
        # "silent since we started watching", never "since epoch".
        self._last_proposal_seen: dict[str, float] = {}
        # Last wall time one of the peer's proposed rounds was seen to
        # commit — healthy-leadership evidence for the grinding
        # detector's uncommitted_proposals mode (a proposal's commit
        # lands ~2 rounds later; judging a single window accuses honest
        # leaders for ordinary 2-chain commit lag under timeouts).
        self._last_proposal_commit_seen: dict[str, float] = {}
        self._max_interval = 0.0  # largest emit interval any meta declares
        self._prev_heights: dict[str, int] = {}
        self._prev_frontier = 0
        self._silent_streak: dict[str, int] = defaultdict(int)
        self._laggard_streak: dict[str, int] = defaultdict(int)
        self._last_seen: dict[str, float] = {}
        self._windows: deque[_Window] = deque(
            maxlen=self.config.history_windows
        )
        self._equivocations: dict[str, int] = defaultdict(int)

        # Per-stream state: wall-clock anchors and resource history.
        self._anchors: dict[str, float] = {}  # source -> wall-mono offset
        self._resources: dict[str, deque] = {}  # node -> (ts, pid, gauges)
        # Proposer digest-queue depth history per node (ROADMAP 3b: the
        # ordering-starved-behind-ingest inversion, judged by slope).
        self._digest_queue: dict[str, deque] = {}  # node -> (ts, pid, depth)
        # State-sync stall anchors: node -> (first_ts, pid, gap at anchor).
        self._sync_state: dict[str, tuple] = {}
        # Conveyor worker health per stream node (latest snapshot wins).
        self._worker_stats: dict[str, dict] = {}
        self._ingress_peak: dict[str, float] = {}
        self._meta: dict[str, dict] = {}

    # -- ingestion -----------------------------------------------------------

    def ingest_record(self, obj: dict, source: str = "") -> list[dict]:
        """Route one parsed stream record; returns alerts fired now."""
        schema = obj.get("schema")
        fired: list[dict] = []
        if schema == "hotstuff-trace-v1":
            anchor = obj.get("anchor") or {}
            if all(
                isinstance(anchor.get(k), (int, float))
                for k in ("mono", "wall")
            ):
                off = anchor["wall"] - anchor["mono"]
                self._anchors[source] = off
            else:
                off = self._anchors.get(source)
            if off is None:
                return fired  # no way onto the shared timeline
            for ev in obj.get("events", ()):
                detail = ev[5] if len(ev) > 5 else None
                fired += self._ingest_event(
                    ev[1], ev[2], ev[3], ev[4] + off, detail
                )
        elif schema == "hotstuff-telemetry-v1":
            fired += self._ingest_snapshot(obj, source)
        elif schema == "hotstuff-meta-v1":
            self._meta[source or obj.get("node", "")] = obj
            interval = obj.get("interval_s")
            if isinstance(interval, (int, float)) and interval > self._max_interval:
                self._max_interval = float(interval)
        # profile / alert / unknown records: not evidence, ignored.
        fired += self._maybe_close()
        return fired

    def _ingest_event(
        self, node: str, round_: int, stage: str, t: float, detail
    ) -> list[dict]:
        fired: list[dict] = []
        if stage not in _PROTOCOL_STAGES:
            return fired
        if t > self._now:
            self._now = t
        if self._last_close_wall is None:
            self._last_close_wall = t
        if node not in self._peers:
            self._peers.add(node)
            self._last_commit_seen.setdefault(node, t)
            self._last_proposal_seen.setdefault(node, t)
            self._last_proposal_commit_seen.setdefault(node, t)
        self._last_seen[node] = t
        if round_ > self._max_round_seen:
            self._max_round_seen = round_
        rd = self._rounds.get(round_)
        if rd is None:
            rd = self._rounds[round_] = _Round()
            self._rounds_since_close += 1
        rd.touch(t)

        if stage == "vote_rx" and detail:
            author, sep, digest = detail.partition("|")
            if not (sep and author and digest):
                return fired  # malformed detail: not evidence, not a peer
            if author not in self._peers:
                self._peers.add(author)
                self._last_commit_seen.setdefault(author, t)
                self._last_proposal_seen.setdefault(author, t)
                self._last_proposal_commit_seen.setdefault(author, t)
            self._last_seen[author] = max(self._last_seen.get(author, 0), t)
            seen = rd.votes.setdefault(author, set())
            if digest not in seen and seen:
                fired += self._alert(
                    "equivocation",
                    [author],
                    1.0,
                    t,
                    {"round": round_, "kind": "conflicting_votes",
                     "digests": sorted(seen | {digest})[:4],
                     "observer": node},
                    window=(t, t),
                )
                self._equivocations[author] += 1
            seen.add(digest)
            # The vote crossed author -> this collector: a live edge of
            # the communication graph (partition detection).
            rd.edges.add(frozenset((author, node)))
        elif stage in ("propose", "propose_send") and detail:
            author, sep, digest = detail.partition("|")
            if not (sep and author and digest):
                author = None  # malformed detail: keep the timing evidence
        else:
            author = None
        if stage in ("propose", "propose_send") and author is not None:
            self._peers.add(author)
            # Exoneration evidence must come from ANOTHER node's stream:
            # a silent leader's own telemetry still self-reports
            # propose_send (it builds and "sends"; the network eats it),
            # and a byzantine node can claim anything about itself. Only
            # a proposal some other node actually RECEIVED proves the
            # peer proposed.
            if (
                stage == "propose"
                and author != node
                and t > self._last_proposal_seen.get(author, 0)
            ):
                self._last_proposal_seen[author] = t
            seen = rd.proposes.setdefault(author, set())
            if digest not in seen and seen:
                fired += self._alert(
                    "equivocation",
                    [author],
                    1.0,
                    t,
                    {"round": round_, "kind": "conflicting_proposals",
                     "digests": sorted(seen | {digest})[:4],
                     "observer": node},
                    window=(t, t),
                )
                self._equivocations[author] += 1
            seen.add(digest)

        if stage == "propose_send":
            rd.propose_senders.add(node)
        elif stage == "propose":
            if node not in rd.propose_t:
                rd.propose_t[node] = t
        elif stage == "vote_send":
            if node not in rd.vote_send_t:
                rd.vote_send_t[node] = t
        elif stage == "commit":
            rd.commit_nodes.setdefault(node, t)
            if t > self._last_commit_seen.get(node, 0):
                self._last_commit_seen[node] = t
            height = round_
            if isinstance(detail, str) and detail.startswith("h"):
                try:
                    height = max(height, int(detail[1:]))
                except ValueError:
                    pass
            if height > self._heights.get(node, 0):
                self._heights[node] = height
        elif stage == "timeout":
            rd.timeouts[node] = rd.timeouts.get(node, 0) + 1
        return fired

    def _ingest_snapshot(self, snap: dict, source: str) -> list[dict]:
        fired: list[dict] = []
        ts = snap.get("ts")
        if not isinstance(ts, (int, float)):
            return fired
        if ts > self._now:
            self._now = ts
        node = snap.get("node") or source
        gauges = snap.get("gauges") or {}
        # Conveyor data-plane health per node: store depth + shed/cert
        # counters feed the scoreboard's dataplane section, so an SLO
        # breach under load names which node's workers were drowning.
        counters = snap.get("counters") or {}
        worker: dict[str, float] = {}
        for key, label in (
            ("mempool.worker.store_depth", "store_depth"),
            ("mempool.worker.ingress_depth", "ingress_depth"),
        ):
            v = gauges.get(key)
            if isinstance(v, (int, float)):
                worker[label] = v
        for key, label in (
            ("mempool.worker.ingress_tx", "ingress_tx"),
            ("mempool.worker.shed_tx", "shed_tx"),
            ("mempool.worker.batches_sealed", "batches_sealed"),
            ("mempool.worker.certs_formed", "certs_formed"),
            ("mempool.worker.throttle_events", "throttle_events"),
            ("mempool.resolver.unresolved", "resolver_unresolved"),
            ("net.native.ingress.reads", "ingress_reads"),
            ("net.native.ingress.frames", "ingress_frames"),
            ("net.native.ingress.batches", "ingress_batches"),
        ):
            v = counters.get(key)
            if isinstance(v, (int, float)):
                worker[label] = v
        if worker:
            depth = worker.get("ingress_depth")
            if isinstance(depth, (int, float)):
                self._ingress_peak[node] = max(
                    self._ingress_peak.get(node, 0.0), depth
                )
            self._worker_stats[node] = worker
        fired += self._check_digest_queue(node, snap, gauges, ts)
        fired += self._check_sync_stall(node, snap, gauges, ts)
        tracked = {
            k: gauges[k]
            for k in ("resource.rss_bytes", "resource.store_bytes")
            if isinstance(gauges.get(k), (int, float))
        }
        if not tracked:
            return fired
        hist = self._resources.setdefault(node, deque(maxlen=64))
        pid = snap.get("pid")
        if hist and hist[-1][1] != pid:
            hist.clear()  # restart: a fresh process, not growth
        hist.append((ts, pid, tracked))
        cfg = self.config
        bounds = {
            "resource.rss_bytes": cfg.rss_growth_max_bytes_per_s,
            "resource.store_bytes": cfg.store_growth_max_bytes_per_s,
        }
        # Oldest sample at least slope_window_s back bounds the slope.
        base = None
        for old_ts, _pid, old in hist:
            if ts - old_ts >= cfg.slope_window_s:
                base = (old_ts, old)
            else:
                break
        if base is None:
            return fired
        for metric, bound in bounds.items():
            a, b = tracked.get(metric), base[1].get(metric)
            if a is None or b is None:
                continue
            secs = ts - base[0]
            growth = (a - b) / secs if secs > 0 else 0.0
            if growth > bound:
                fired += self._alert(
                    "slope_breach",
                    [node],
                    min(1.0, 0.5 + 0.5 * (growth / bound - 1.0)),
                    ts,
                    {"metric": metric,
                     "growth_bytes_per_s": round(growth, 1),
                     "max_bytes_per_s": bound,
                     "window_s": round(secs, 1)},
                    window=(base[0], ts),
                )
        return fired

    def _check_digest_queue(
        self, node: str, snap: dict, gauges: dict, ts: float
    ) -> list[dict]:
        """Sustained growth of ``consensus.proposer.digest_queue_depth``
        — certified digests arriving faster than proposals drain them,
        the ordering-starves-behind-ingest inversion the data plane
        exists to prevent. Same slope machinery as the resource
        detectors: base sample ≥ slope_window_s back, growth judged in
        digests/s, a process restart clears the history."""
        depth = gauges.get("consensus.proposer.digest_queue_depth")
        if not isinstance(depth, (int, float)):
            return []
        hist = self._digest_queue.setdefault(node, deque(maxlen=64))
        pid = snap.get("pid")
        if hist and hist[-1][1] != pid:
            hist.clear()
        hist.append((ts, pid, depth))
        cfg = self.config
        base = None
        for old_ts, _pid, old_depth in hist:
            if ts - old_ts >= cfg.slope_window_s:
                base = (old_ts, old_depth)
            else:
                break
        if base is None:
            return []
        secs = ts - base[0]
        growth = (depth - base[1]) / secs if secs > 0 else 0.0
        bound = cfg.digest_queue_growth_max_per_s
        if growth <= bound:
            return []
        return self._alert(
            "digest_queue_starvation",
            [node],
            min(1.0, 0.5 + 0.5 * (growth / bound - 1.0)),
            ts,
            {"metric": "consensus.proposer.digest_queue_depth",
             "depth": depth,
             "growth_per_s": round(growth, 1),
             "max_per_s": bound,
             "window_s": round(secs, 1)},
            window=(base[0], ts),
        )

    def _check_sync_stall(
        self, node: str, snap: dict, gauges: dict, ts: float
    ) -> list[dict]:
        """A peer stuck in state-sync: probe loop active with a frontier
        gap that is not closing. Anchored on the first qualifying
        snapshot; re-anchored whenever the gap shrinks (progress resets
        the budget) or the process restarts."""
        cfg = self.config
        active = gauges.get("statesync.active")
        if not isinstance(active, (int, float)) or not active:
            self._sync_state.pop(node, None)
            return []
        gap = gauges.get("statesync.frontier_gap")
        gap = gap if isinstance(gap, (int, float)) else 0
        if gap < cfg.sync_stall_min_gap:
            self._sync_state.pop(node, None)
            return []
        pid = snap.get("pid")
        anchor = self._sync_state.get(node)
        if anchor is None or anchor[1] != pid:
            self._sync_state[node] = (ts, pid, gap)
            return []
        first_ts, _pid, anchor_gap = anchor
        if gap < anchor_gap:
            # Catch-up is working, just slow: restart the budget from
            # the improved gap so only a STALL — not a long but
            # progressing sync — ever fires.
            self._sync_state[node] = (ts, pid, gap)
            return []
        elapsed = ts - first_ts
        if elapsed < cfg.sync_stall_budget_s:
            return []
        return self._alert(
            "sync_stall",
            [node],
            min(1.0, 0.5 + 0.5 * (elapsed / cfg.sync_stall_budget_s - 1.0)),
            ts,
            {"frontier_gap": gap,
             "anchor_gap": anchor_gap,
             "stalled_s": round(elapsed, 1),
             "budget_s": cfg.sync_stall_budget_s},
            window=(first_ts, ts),
        )

    # -- windowing -----------------------------------------------------------

    def tick(self, now: float | None = None) -> list[dict]:
        """Periodic evaluation hook for live followers. ``now`` defaults
        to the newest wall time observed (replay) or ``time.time()``
        should the caller pass it (live)."""
        if now is not None and now > self._now:
            self._now = now
        return self._maybe_close()

    def feed(self, records, now: float | None = None) -> list[dict]:
        """Batch ingestion: drive a whole stream (or a merged timeline)
        through the tower in one call — no tail-follower, no sleeps.
        ``records`` yields parsed stream objects, or ``(obj, source)``
        pairs when per-source anchor keying matters (multi-stream
        replay). Windows close inline as the observed wall clock
        advances, exactly as they would under a live follower; a final
        ``tick(now)`` (``now=None`` → the newest observed wall time)
        judges anything due. Replaying a full schedule is milliseconds —
        this is Oracle's inner loop (``benchmark.detector_sweep``)."""
        fired: list[dict] = []
        for rec in records:
            if isinstance(rec, tuple):
                obj, source = rec
            else:
                obj, source = rec, ""
            fired.extend(self.ingest_record(obj, source))
        fired.extend(self.tick(now))
        return fired

    def flush(self) -> list[dict]:
        """End of stream: close every pending round and judge."""
        return self._maybe_close(force=True)

    def _effective_settle(self) -> float:
        # Streams flush in emit-interval bursts: a round is only fully
        # observable once every stream's burst covering it landed.
        return max(
            self.config.settle_s,
            self.config.settle_multiplier * self._max_interval,
        )

    def _maybe_close(self, force: bool = False) -> list[dict]:
        cfg = self.config
        if self._last_close_wall is None:
            return []
        due = (
            force
            or self._rounds_since_close >= cfg.window_rounds
            or (
                self._now - self._last_close_wall >= cfg.window_s
                and self._rounds
            )
        )
        if not due:
            return []
        settle_cut = self._now - (0.0 if force else self._effective_settle())
        folded = [
            r for r, rd in self._rounds.items() if rd.last_wall <= settle_cut
        ]
        if not folded:
            self._last_close_wall = self._now
            return []
        win = _Window()
        for r in sorted(folded):
            rd = self._rounds.pop(r)
            win.rounds.append(r)
            win.t_lo = min(win.t_lo, rd.first_wall)
            win.t_hi = max(win.t_hi, rd.last_wall)
            if rd.votes:
                win.vote_active_rounds += 1
                for author in rd.votes:
                    win.voted_rounds[author] += 1
                    win.active_peers.add(author)
            win.edges |= rd.edges
            for author in rd.proposes:
                win.proposals[author] += 1
                win.active_peers.add(author)
                if rd.commit_nodes:
                    win.proposals_committed[author] += 1
                    if rd.last_wall > self._last_proposal_commit_seen.get(
                        author, 0
                    ):
                        self._last_proposal_commit_seen[author] = rd.last_wall
                for receiver in rd.propose_t:
                    win.edges.add(frozenset((author, receiver)))
            for leader in rd.propose_senders:
                win.proposals[leader] = max(win.proposals[leader], 1)
                win.active_peers.add(leader)
                if rd.commit_nodes:
                    win.proposals_committed[leader] = max(
                        win.proposals_committed[leader], 1
                    )
                    if rd.last_wall > self._last_proposal_commit_seen.get(
                        leader, 0
                    ):
                        self._last_proposal_commit_seen[leader] = rd.last_wall
            for node, n in rd.timeouts.items():
                win.timeouts[node] += n
                win.active_peers.add(node)
            for node in rd.commit_nodes:
                win.commits[node] += 1
                win.active_peers.add(node)
            for node in rd.vote_send_t:
                win.active_peers.add(node)
                if node in rd.propose_t:
                    win.turnaround[node].append(
                        max(0.0, rd.vote_send_t[node] - rd.propose_t[node])
                    )
        self._rounds_since_close = len(self._rounds)
        self._last_close_wall = self._now
        self._windows.append(win)
        fired = self._run_windowed_detectors(win)
        return fired

    # -- detectors -----------------------------------------------------------

    def _run_windowed_detectors(self, win: _Window) -> list[dict]:
        cfg = self.config
        fired: list[dict] = []
        t = win.t_hi or self._now
        window = (win.t_lo if win.t_lo != float("inf") else t, t)
        rounds_span = (
            [min(win.rounds), max(win.rounds)] if win.rounds else None
        )

        # silent_voter -------------------------------------------------------
        if win.vote_active_rounds >= cfg.min_rounds:
            rates = {
                p: win.voted_rounds.get(p, 0) / win.vote_active_rounds
                for p in self._peers
            }
            strong = [p for p, r in rates.items() if r >= 0.5]
            if len(strong) >= 2:
                for p, rate in sorted(rates.items()):
                    if rate <= cfg.silent_participation_max:
                        self._silent_streak[p] += 1
                        if self._silent_streak[p] >= cfg.silent_windows:
                            alive = p in win.active_peers
                            fired += self._alert(
                                "silent_voter",
                                [p],
                                min(1.0, 0.6 + 0.2 * (self._silent_streak[p] - cfg.silent_windows) + (0.2 if alive else 0.0)),
                                t,
                                {"participation": round(rate, 3),
                                 "active_rounds": win.vote_active_rounds,
                                 "windows_silent": self._silent_streak[p],
                                 "alive": alive,
                                 "voting_peers": sorted(strong)},
                                window=window,
                                rounds=rounds_span,
                            )
                    else:
                        self._silent_streak[p] = 0
        # laggard ------------------------------------------------------------
        frontier = max(self._heights.values(), default=0)
        frontier_adv = frontier - self._prev_frontier
        commit_stale_s = max(
            cfg.laggard_stale_s, 2.0 * self._effective_settle()
        )
        if frontier_adv >= cfg.laggard_min_frontier_advance:
            for p in sorted(self._peers):
                h = self._heights.get(p, 0)
                lag = frontier - h
                if (
                    lag >= cfg.laggard_min_lag
                    and h <= self._prev_heights.get(p, 0)
                ):
                    self._laggard_streak[p] += 1
                    # The streak builds on height evidence alone, but the
                    # ACCUSATION additionally requires the peer's commits
                    # to be stale beyond any emission burst cadence — a
                    # healthy stream's frozen height between flushes is
                    # lag of the PIPE, not of the node.
                    if (
                        self._laggard_streak[p] >= cfg.laggard_windows
                        and self._now - self._last_commit_seen.get(p, 0.0)
                        >= commit_stale_s
                    ):
                        fired += self._alert(
                            "laggard",
                            [p],
                            min(1.0, 0.6 + min(0.4, lag / 50.0)),
                            t,
                            {"height": h,
                             "frontier": frontier,
                             "lag_rounds": lag,
                             "windows_stalled": self._laggard_streak[p],
                             "frontier_advance": frontier_adv,
                             "commit_stale_s": round(
                                 self._now
                                 - self._last_commit_seen.get(p, 0.0),
                                 1,
                             )},
                            window=window,
                            rounds=rounds_span,
                        )
                else:
                    self._laggard_streak[p] = 0
        self._prev_frontier = frontier
        self._prev_heights = dict(self._heights)

        # grinding_leader ----------------------------------------------------
        n_rounds = len(win.rounds)
        timeout_total = sum(win.timeouts.values())
        timeout_rate = timeout_total / n_rounds if n_rounds else 0.0
        if n_rounds >= cfg.min_rounds and timeout_rate >= cfg.grind_timeout_rate:
            committed_any = sum(win.proposals_committed.values()) > 0
            for p, n in sorted(win.proposals.items()):
                leadership_stale_s = self._now - (
                    self._last_proposal_commit_seen.get(p, 0.0)
                )
                if (
                    n >= cfg.grind_min_proposals
                    and win.proposals_committed.get(p, 0) == 0
                    and committed_any
                    and leadership_stale_s >= cfg.grind_proposal_stale_s
                ):
                    fired += self._alert(
                        "grinding_leader",
                        [p],
                        0.7,
                        t,
                        {"mode": "uncommitted_proposals",
                         "proposals": n,
                         "committed": 0,
                         "leadership_stale_s": round(leadership_stale_s, 1),
                         "timeout_rate": round(timeout_rate, 3)},
                        window=window,
                        rounds=rounds_span,
                    )
            proposers = {p for p, n in win.proposals.items() if n > 0}
            if len(proposers) >= 2:
                for p in sorted(win.active_peers - proposers):
                    # Alive (voting / timing out) but never proposing
                    # while the committee burns timeouts: the silent
                    # leader shape. Needs the peer visibly alive — a
                    # crashed peer is the laggard/silent detectors' job.
                    proposal_stale_s = self._now - self._last_proposal_seen.get(
                        p, 0.0
                    )
                    if (
                        win.voted_rounds.get(p, 0) or win.timeouts.get(p, 0)
                    ) and proposal_stale_s >= cfg.grind_proposal_stale_s:
                        fired += self._alert(
                            "grinding_leader",
                            [p],
                            0.6,
                            t,
                            {"mode": "no_proposals",
                             "proposing_peers": sorted(proposers),
                             "proposal_stale_s": round(proposal_stale_s, 1),
                             "timeout_rate": round(timeout_rate, 3)},
                            window=window,
                            rounds=rounds_span,
                        )

        # partitioned_clique -------------------------------------------------
        peers_in_window = set(win.active_peers)
        if len(peers_in_window) >= 2 and n_rounds >= 1:
            comp = self._components(peers_in_window, win.edges)
            if len(comp) >= 2:
                committing = [
                    c for c in comp if any(win.commits.get(p) for p in c)
                ]
                quiet = [
                    c
                    for c in comp
                    if not any(win.commits.get(p) for p in c)
                    and any(
                        win.timeouts.get(p) or win.voted_rounds.get(p)
                        for p in c
                    )
                ]
                if committing and quiet:
                    for c in quiet:
                        fired += self._alert(
                            "partitioned_clique",
                            sorted(c),
                            0.7,
                            t,
                            {"components": [sorted(x) for x in comp],
                             "committing": [sorted(x) for x in committing]},
                            window=window,
                            rounds=rounds_span,
                        )
                elif (
                    not committing
                    and timeout_total >= cfg.min_rounds
                    and any(len(c) >= 2 for c in comp)
                ):
                    # Global stall with visible clique structure: accuse
                    # the non-largest components. An all-singleton graph
                    # says nothing about WHO is cut from whom (total
                    # churn looks like that too) — the grind/laggard
                    # detectors own that shape.
                    largest = max(comp, key=len)
                    for c in comp:
                        if c is largest:
                            continue
                        fired += self._alert(
                            "partitioned_clique",
                            sorted(c),
                            0.5,
                            t,
                            {"components": [sorted(x) for x in comp],
                             "committing": [],
                             "global_stall": True},
                            window=window,
                            rounds=rounds_span,
                        )
        return fired

    @staticmethod
    def _components(peers: set[str], edges: set[frozenset]) -> list[set[str]]:
        parent = {p: p for p in peers}

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for e in edges:
            members = [p for p in e if p in parent]
            if len(members) == 2:
                ra, rb = find(members[0]), find(members[1])
                if ra != rb:
                    parent[ra] = rb
        groups: dict[str, set[str]] = defaultdict(set)
        for p in peers:
            groups[find(p)].add(p)
        return list(groups.values())

    # -- alerts --------------------------------------------------------------

    def _alert(
        self,
        detector: str,
        accused: list[str],
        confidence: float,
        t: float,
        evidence: dict,
        *,
        window: tuple[float, float],
        rounds: list[int] | None = None,
    ) -> list[dict]:
        if confidence < self.config.alert_min_confidence:
            # Suppressed at the source (cooldown untouched: a later
            # higher-confidence accusation must not find itself muted).
            return []
        accused = [self.alias.get(a, a) for a in accused]
        key = (detector, tuple(sorted(accused)))
        last = self._last_alert_at.get(key)
        if last is not None and t - last < self.config.cooldown_s:
            return []
        self._last_alert_at[key] = t
        alert = {
            "schema": ALERT_SCHEMA,
            "seq": self._alert_seq,
            "detector": detector,
            "accused": accused,
            "confidence": round(float(confidence), 3),
            "ts": t,
            "node": self.label,
            "config": self._config_hash,
            "catalog": DETECTOR_CATALOG_VERSION,
            "window": {
                "t_lo": window[0],
                "t_hi": window[1],
                **({"rounds": rounds} if rounds else {}),
            },
            "evidence": evidence,
        }
        self._alert_seq += 1
        with self._alerts_lock:
            self.alerts.append(alert)
            if len(self.alerts) > self.config.max_alerts:
                del self.alerts[0]
        if self.on_alert is not None:
            try:
                self.on_alert(alert)
            except Exception:  # noqa: BLE001 — capture must not kill ingest
                pass
        return [alert]

    def snapshot_alerts(self) -> list[dict]:
        with self._alerts_lock:
            return list(self.alerts)

    # -- scoreboard ----------------------------------------------------------

    def scoreboard(self) -> dict:
        """Per-peer accountability scores over the recent window history
        (1.0 = healthy). Pure data — harness verdicts embed it."""
        wins = list(self._windows)
        frontier = max(self._heights.values(), default=0)
        active_rounds = sum(w.vote_active_rounds for w in wins)
        n_rounds = sum(len(w.rounds) for w in wins)
        with self._alerts_lock:
            accusations: dict[str, int] = defaultdict(int)
            for a in self.alerts:
                for p in a["accused"]:
                    accusations[p] += 1
        board: dict[str, dict] = {}
        for p in sorted(self._peers):
            name = self.alias.get(p, p)
            voted = sum(w.voted_rounds.get(p, 0) for w in wins)
            participation = voted / active_rounds if active_rounds else None
            samples = sorted(
                s for w in wins for s in w.turnaround.get(p, ())
            )
            timeouts = sum(w.timeouts.get(p, 0) for w in wins)
            h = self._heights.get(p, 0)
            lag = frontier - h
            score = 1.0
            if participation is not None:
                score -= 0.4 * (1.0 - min(1.0, participation * 2))
            score -= 0.3 * min(1.0, lag / 50.0)
            if n_rounds:
                score -= 0.2 * min(1.0, timeouts / n_rounds)
            if accusations.get(name):
                score -= 0.1
            board[name] = {
                "participation": (
                    None if participation is None else round(participation, 3)
                ),
                "turnaround_p90_ms": (
                    None
                    if not samples
                    else round(_pct(samples, 0.9) * 1e3, 3)
                ),
                "commit_height": h,
                "lag_rounds": lag,
                "timeouts_per_round": (
                    round(timeouts / n_rounds, 3) if n_rounds else None
                ),
                "equivocations": self._equivocations.get(p, 0),
                "alerts": accusations.get(name, 0),
                "score": round(max(0.0, score), 3),
            }
        result = {
            "frontier": frontier,
            "windows": len(wins),
            "rounds": n_rounds,
            "peers": board,
        }
        if self._worker_stats:
            # Data-plane section, keyed by telemetry stream node (worker
            # metrics ride snapshots, not the per-peer trace events).
            result["dataplane"] = {
                node: dict(stats)
                for node, stats in sorted(self._worker_stats.items())
            }
        backlog = self.ingress_backlog()
        if backlog:
            result["ingress_backlog"] = backlog
        return result

    def ingress_backlog(self) -> dict:
        """Per-node ingress batching health from the
        ``net.native.ingress.*`` counters and the worker depth gauge:
        how many frames each socket read and each wakeup carried, plus
        the deepest the worker queue has been across the stream's
        snapshots. ``frames_per_wakeup`` near 1.0 under load means the
        transport regressed to the one-frame-per-wakeup floor the
        batched ingress path exists to remove; a rising ``depth_peak``
        with flat ``shed_tx`` is backlog building before the shed
        threshold bites."""
        view: dict[str, dict] = {}
        for node, stats in sorted(self._worker_stats.items()):
            reads = stats.get("ingress_reads")
            frames = stats.get("ingress_frames")
            batches = stats.get("ingress_batches")
            depth = stats.get("ingress_depth")
            peak = self._ingress_peak.get(node)
            if not any(
                isinstance(v, (int, float))
                for v in (reads, frames, batches, depth)
            ):
                continue
            entry: dict[str, float | None] = {
                "reads": reads,
                "frames": frames,
                "batches": batches,
                "depth": depth,
                "depth_peak": peak,
                "shed_tx": stats.get("shed_tx"),
                "frames_per_read": (
                    round(frames / reads, 3) if reads and frames else None
                ),
                "frames_per_wakeup": (
                    round(frames / batches, 3)
                    if batches and frames
                    else None
                ),
            }
            view[node] = entry
        return view


class AlertCapture:
    """Alert-triggered evidence capture (``on_alert`` hook).

    Always writes one ``hotstuff-capture-v1`` JSON per alert (the alert
    plus the watcher's scoreboard at that instant). When constructed
    with the live process's ``trace`` buffer and ``registry`` — the
    in-process testbeds, where the watchtower shares a process with the
    accused engines — it additionally dumps a flight record and runs a
    bounded sampling-profiler session, so the postmortem evidence is on
    disk at the moment of detection rather than at teardown. A follower
    watching another process's streams captures evidence only; the
    nodes' own flight recorders (``arm_shutdown_flush``) stay the
    capture path for their in-process state.
    """

    def __init__(
        self,
        directory: str,
        *,
        watchtower: Watchtower | None = None,
        trace=None,
        registry=None,
        profile_s: float = 2.0,
        profile_interval_ms: float = 5.0,
        max_captures: int = 4,
    ) -> None:
        self.directory = directory
        self.watchtower = watchtower
        self.trace = trace
        self.registry = registry
        self.profile_s = profile_s
        self.profile_interval_ms = profile_interval_ms
        self.max_captures = max_captures
        self.captured = 0
        self.paths: list[str] = []
        self._profiling = False
        os.makedirs(directory, exist_ok=True)

    def __call__(self, alert: dict) -> None:
        if self.captured >= self.max_captures:
            return
        self.captured += 1
        # Re-created per capture: harness setups may wipe the work tree
        # after this hook is armed.
        os.makedirs(self.directory, exist_ok=True)
        base = os.path.join(
            self.directory,
            f"watchtower-capture-{alert['seq']:03d}-{alert['detector']}",
        )
        capture: dict = {"evidence": base + ".json"}
        record = {
            "schema": CAPTURE_SCHEMA,
            "ts": time.time(),
            "alert": alert,
            "scoreboard": (
                self.watchtower.scoreboard()
                if self.watchtower is not None
                else None
            ),
        }
        if self.trace is not None:
            from .trace import dump_flight_record

            flight = dump_flight_record(
                base + "-flight.json",
                f"alert:{alert['detector']}",
                self.trace,
                self.registry,
            )
            if flight:
                capture["flight_record"] = flight
        if self.trace is not None and self.profile_s > 0 and not self._profiling:
            capture["profile"] = self._profile_session(base)
        try:
            with open(capture["evidence"], "w") as f:
                json.dump(record, f, indent=2, sort_keys=True)
                f.write("\n")
        except OSError:
            capture.pop("evidence", None)
        self.paths.append(base + ".json")
        alert["capture"] = capture

    def _profile_session(self, base: str) -> str | None:
        """Bounded profiler burst: start the all-thread sampler (unless
        one is already live), stop after ``profile_s`` on a timer, and
        write the folded stacks next to the capture."""
        from . import profiler as pyprof

        if pyprof.active() is not None:
            return None  # a session is already streaming records
        try:
            prof = pyprof.SamplingProfiler(
                interval_ms=self.profile_interval_ms
            )
            prof.start(mode="thread")
        except Exception:  # noqa: BLE001 — capture is advisory
            return None
        self._profiling = True
        path = base + "-profile.json"

        def _finish() -> None:
            try:
                prof.stop()
                rec = prof.drain_record(node="watchtower-capture")
                if rec is not None:
                    with open(path, "w") as f:
                        json.dump(rec, f)
                        f.write("\n")
            except Exception:  # noqa: BLE001
                pass
            finally:
                self._profiling = False

        timer = threading.Timer(self.profile_s, _finish)
        timer.daemon = True
        timer.start()
        return path
