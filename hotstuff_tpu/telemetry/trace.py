"""Causal trace plane: per-round protocol events and the flight recorder.

Where the registry answers "how much" (counters, histograms), this module
answers "in what order, on which node": every traced protocol mark —
leader proposal broadcast, replica receive/verify/vote, collector vote
fan-in, QC formation, commit — lands as one event tuple in a process-wide
bounded ring (:class:`TraceBuffer`). Two consumers read the ring:

- the :class:`~.emitter.TelemetryEmitter` drains *new* events into
  ``hotstuff-trace-v1`` JSON lines interleaved with snapshots, which
  ``benchmark/trace_assemble.py`` merges across nodes into per-block
  causal timelines with critical-path attribution;
- the **flight recorder** (:func:`dump_flight_record`) dumps the *whole*
  ring — the last ``capacity`` protocol events — plus a registry snapshot
  when something goes wrong (faultline checker failure, node crash,
  SIGTERM), turning "safety violated, good luck" into a postmortem.

Event timestamps are ``time.perf_counter()`` (monotonic); each buffer
carries a wall-clock **anchor** captured at construction so cross-process
consumers can map monotonic times onto one wall timeline:
``wall = anchor.wall + (t - anchor.mono)``. Recording costs one lock
acquire + deque append per event and only happens when telemetry is
enabled, so the disabled hot path pays nothing.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from bisect import bisect_right
from collections import deque

log = logging.getLogger("telemetry")

TRACE_SCHEMA = "hotstuff-trace-v1"
FLIGHT_SCHEMA = "hotstuff-flightrec-v1"

#: default ring capacity; override with HOTSTUFF_FLIGHT_CAPACITY.
DEFAULT_CAPACITY = 65_536


def _env_capacity() -> int:
    try:
        return max(256, int(os.environ.get("HOTSTUFF_FLIGHT_CAPACITY", "")))
    except ValueError:
        return DEFAULT_CAPACITY


class TraceBuffer:
    """Bounded ring of ``(seq, node, round, stage, t_mono[, detail])``
    events.

    ``seq`` is a process-wide monotonically increasing id: the emitter
    remembers the last seq it streamed and fetches only newer events
    (:meth:`events_since`), while the flight recorder copies the whole
    ring (:meth:`snapshot_events`) — the two consumers never contend over
    a destructive drain. Eviction (ring overflow) is counted, never
    silent.

    ``detail`` is an optional short string payload carrying the
    per-event fields the streaming analyzers need beyond (node, round,
    stage): a ``vote_rx`` event's ``"<author>|<block digest>"``, a
    ``propose`` event's ``"<author>|<digest>"``, a ``commit`` event's
    ``"h<last_committed_round>"``. Events without a detail stay
    5-tuples, so pre-existing streams and consumers are unaffected.
    """

    __slots__ = (
        "_events", "_lock", "_seq", "evicted",
        "anchor_mono", "anchor_wall", "capacity",
    )

    def __init__(self, capacity: int | None = None) -> None:
        self.capacity = capacity or _env_capacity()
        self._events: deque[tuple] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.evicted = 0
        self.anchor_mono = time.perf_counter()
        self.anchor_wall = time.time()

    def record(
        self,
        node: str,
        round_: int,
        stage: str,
        t: float | None = None,
        detail: str | None = None,
    ) -> None:
        if t is None:
            t = time.perf_counter()
        with self._lock:
            if len(self._events) == self.capacity:
                self.evicted += 1
            self._seq += 1
            if detail is None:
                self._events.append((self._seq, node, round_, stage, t))
            else:
                self._events.append(
                    (self._seq, node, round_, stage, t, detail)
                )

    def last_seq(self) -> int:
        return self._seq

    def events_since(self, seq: int) -> list[tuple]:
        """Events with seq strictly greater than ``seq`` (oldest first)."""
        with self._lock:
            events = list(self._events)
        if not events or events[-1][0] <= seq:
            return []
        # Events are seq-sorted; binary-search the cut instead of scanning.
        idx = bisect_right([e[0] for e in events], seq)
        return events[idx:]

    def snapshot_events(self) -> list[tuple]:
        with self._lock:
            return list(self._events)

    def anchor(self) -> dict:
        return {"mono": self.anchor_mono, "wall": self.anchor_wall}

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._seq = 0
            self.evicted = 0
            self.anchor_mono = time.perf_counter()
            self.anchor_wall = time.time()


def build_trace_record(
    buffer: TraceBuffer, events: list[tuple], node: str = ""
) -> dict:
    """One ``hotstuff-trace-v1`` stream line carrying ``events``."""
    return {
        "schema": TRACE_SCHEMA,
        "node": node,
        "pid": os.getpid(),
        "anchor": buffer.anchor(),
        "evicted": buffer.evicted,
        "events": [list(e) for e in events],
    }


def validate_trace_record(obj) -> list[str]:
    """Schema check mirroring ``validate_snapshot``; returns problems."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"trace record is {type(obj).__name__}, not an object"]
    if obj.get("schema") != TRACE_SCHEMA:
        problems.append(f"schema is {obj.get('schema')!r}, want {TRACE_SCHEMA!r}")
    anchor = obj.get("anchor")
    if not isinstance(anchor, dict) or not all(
        isinstance(anchor.get(k), (int, float)) for k in ("mono", "wall")
    ):
        problems.append("anchor missing mono/wall")
    events = obj.get("events")
    if not isinstance(events, list):
        problems.append("events missing or not a list")
        return problems
    for i, ev in enumerate(events):
        if (
            not isinstance(ev, (list, tuple))
            or len(ev) not in (5, 6)
            or not isinstance(ev[0], int)
            or not isinstance(ev[1], str)
            or not isinstance(ev[2], int)
            or not isinstance(ev[3], str)
            or not isinstance(ev[4], (int, float))
            or (len(ev) == 6 and not isinstance(ev[5], str))
        ):
            problems.append(f"event {i} malformed: {ev!r}")
            break
    return problems


def dump_flight_record(
    path: str,
    reason: str,
    buffer: TraceBuffer,
    registry=None,
    extra: dict | None = None,
) -> str | None:
    """Write the flight record — the ring's recent protocol events plus a
    registry snapshot — to ``path``. Returns the path, or None when the
    write failed (the recorder must never take the process down with it:
    it runs from crash paths and signal handlers)."""
    record = {
        "schema": FLIGHT_SCHEMA,
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "anchor": buffer.anchor(),
        "evicted": buffer.evicted,
        "events": [list(e) for e in buffer.snapshot_events()],
    }
    if registry is not None:
        try:
            record["snapshot"] = registry.snapshot()
        except Exception as e:  # noqa: BLE001 — postmortem must not raise
            record["snapshot_error"] = str(e)
    if extra:
        record.update(extra)
    try:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(record, f)
            f.write("\n")
    except OSError as e:
        log.error("cannot write flight record to %s: %s", path, e)
        return None
    log.warning("flight record (%s) dumped to %s", reason, path)
    return path
