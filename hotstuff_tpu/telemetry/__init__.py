"""Unified telemetry plane: metrics registry, round-trace spans, and
JSON-lines snapshot emission.

Every plane of the system records here — consensus core/synchronizer,
mempool, network (asyncio and the C++ engine via its stats collector),
crypto superbatching and the native ed25519 engine — and one
``snapshot()`` (or a running ``TelemetryEmitter``) serializes the whole
process's state. ``benchmark/logs.py`` reads the emitted streams;
``docs/telemetry.md`` is the metric catalog.

Enablement: telemetry is OFF by default; recording sites then go through
shared no-op metric objects (one attribute call, no state) so the
disabled cost is a cheap method dispatch on already-hot paths and zero
memory. Enable explicitly with ``telemetry.enable()`` BEFORE spawning
actors (they capture their metric objects at construction), or via the
environment:

- ``HOTSTUFF_TELEMETRY_DIR=<dir>``: enable + each node process writes
  ``<dir>/telemetry-<node>.jsonl`` (the local-bench layout).
- ``HOTSTUFF_TELEMETRY=<file>``: enable + write snapshots to one file.
- ``HOTSTUFF_TELEMETRY_INTERVAL=<seconds>``: snapshot period (default 5).

The benchmark-interface tables (``record_created`` / ``record_sealed`` /
``record_commit``) mirror the regex measurement contract of
``benchmark/logs.py`` at the exact code sites that emit the regex-scraped
log lines, so the telemetry stream and the log scrape measure the same
events. Sharing one process-wide table across in-process testbed nodes
reproduces the parser's cross-node merge (earliest proposal, first
commit wins) automatically.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

from .emitter import (
    DEFAULT_INTERVAL_S,
    META_SCHEMA,
    SCHEMA,
    TelemetryEmitter,
    arm_shutdown_flush,
    build_meta_record,
    build_snapshot,
    validate_meta_record,
    validate_snapshot,
)
from .profiler import (
    PROFILE_SCHEMA,
    SamplingProfiler,
    validate_profile_record,
)
from .registry import (
    COUNT_BUCKETS,
    DURATION_MS_BUCKETS,
    FINE_DURATION_MS_BUCKETS,
    SIZE_BYTES_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    diff_counters,
)
from .dtrace import (
    DTRACE_SCHEMA,
    build_dtrace_record,
    intern_label,
    validate_dtrace_record,
)
from .spans import RoundTrace
from .trace import (
    FLIGHT_SCHEMA,
    TRACE_SCHEMA,
    TraceBuffer,
    build_trace_record,
    dump_flight_record,
    validate_trace_record,
)
from .watchtower import (
    ALERT_SCHEMA,
    AlertCapture,
    Watchtower,
    WatchtowerConfig,
    validate_alert_record,
)

__all__ = [
    "COUNT_BUCKETS",
    "DURATION_MS_BUCKETS",
    "FINE_DURATION_MS_BUCKETS",
    "SIZE_BYTES_BUCKETS",
    "SCHEMA",
    "META_SCHEMA",
    "TRACE_SCHEMA",
    "DTRACE_SCHEMA",
    "FLIGHT_SCHEMA",
    "PROFILE_SCHEMA",
    "ALERT_SCHEMA",
    "AlertCapture",
    "Watchtower",
    "WatchtowerConfig",
    "validate_alert_record",
    "build_meta_record",
    "validate_meta_record",
    "SamplingProfiler",
    "validate_profile_record",
    "DEFAULT_INTERVAL_S",
    "Counter",
    "Gauge",
    "Histogram",
    "NULL_COUNTER",
    "NULL_GAUGE",
    "NULL_HISTOGRAM",
    "Registry",
    "RoundTrace",
    "TelemetryEmitter",
    "TraceBuffer",
    "arm_shutdown_flush",
    "build_snapshot",
    "build_trace_record",
    "build_dtrace_record",
    "intern_label",
    "validate_snapshot",
    "validate_trace_record",
    "validate_dtrace_record",
    "diff_counters",
    "dump_flight_record",
    "counter",
    "gauge",
    "histogram",
    "get_registry",
    "register_collector",
    "enable",
    "disable",
    "enabled",
    "env_interval_s",
    "env_stream_path",
    "env_flight_path",
    "record_created",
    "record_sealed",
    "record_commit",
    "round_trace",
    "trace_buffer",
    "trace_event",
    "dtrace_buffer",
    "dtrace_enabled",
    "dtrace_event",
    "set_dtrace_detached",
    "reset_for_tests",
]

_REGISTRY = Registry()
_TRACE_BUFFER = TraceBuffer()
_DTRACE_BUFFER = TraceBuffer()
_ENABLED = bool(
    os.environ.get("HOTSTUFF_TELEMETRY") or os.environ.get("HOTSTUFF_TELEMETRY_DIR")
)
# ``HOTSTUFF_DTRACE=0`` detaches ONLY the batch-lifecycle plane while the
# rest of telemetry stays armed — the CI overhead gate's control leg.
_DTRACE_DETACHED = os.environ.get("HOTSTUFF_DTRACE", "") == "0"


class _NullCounter:
    __slots__ = ()

    def inc(self, n: int = 1) -> None:
        pass

    def value(self) -> int:
        return 0


class _NullGauge:
    __slots__ = ()

    def set(self, v: float) -> None:
        pass

    set_min = set_max = set

    def value(self):
        return None


class _NullHistogram:
    __slots__ = ()

    def observe(self, v: float) -> None:
        pass

    def merged(self):
        return [], 0.0, 0

    def mean(self) -> float:
        return 0.0


# Public no-op singletons: what counter()/gauge()/histogram() return when
# disabled, and safe class-level defaults for state-only instances (tests
# construct actors via __new__ without running __init__).
NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()
_NULL_COUNTER = NULL_COUNTER
_NULL_GAUGE = NULL_GAUGE
_NULL_HISTOGRAM = NULL_HISTOGRAM


def enable() -> Registry:
    """Turn recording on (idempotent). Call BEFORE spawning actors: they
    capture their metric objects at construction time."""
    global _ENABLED
    _ENABLED = True
    return _REGISTRY


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def get_registry() -> Registry:
    """The process registry (live even when recording is disabled, so
    benchmarks can enable/diff around a measurement window)."""
    return _REGISTRY


def counter(name: str):
    return _REGISTRY.counter(name) if _ENABLED else _NULL_COUNTER


def gauge(name: str):
    return _REGISTRY.gauge(name) if _ENABLED else _NULL_GAUGE


def histogram(name: str, buckets=DURATION_MS_BUCKETS):
    return _REGISTRY.histogram(name, buckets) if _ENABLED else _NULL_HISTOGRAM


def register_collector(name: str, fn) -> None:
    """Register unconditionally (registration is one-time and cheap);
    collectors only run when something snapshots the registry."""
    _REGISTRY.register_collector(name, fn)


def env_interval_s() -> float:
    try:
        return float(os.environ.get("HOTSTUFF_TELEMETRY_INTERVAL", ""))
    except ValueError:
        return DEFAULT_INTERVAL_S


def env_stream_path(node: str = "") -> str | None:
    """Where this process should stream snapshots per the environment, or
    None when no stream is configured (metrics may still be enabled
    programmatically for in-process snapshots)."""
    path = os.environ.get("HOTSTUFF_TELEMETRY")
    if path:
        return path
    directory = os.environ.get("HOTSTUFF_TELEMETRY_DIR")
    if directory:
        safe = "".join(c if c.isalnum() else "-" for c in node) or str(os.getpid())
        return os.path.join(directory, f"telemetry-{safe}.jsonl")
    return None


def env_flight_path(node: str = "") -> str | None:
    """Where this process should dump flight records: HOTSTUFF_FLIGHT_DIR
    explicitly, else next to the telemetry stream when one is configured,
    else None (flight recording stays in-memory only)."""
    safe = "".join(c if c.isalnum() else "-" for c in node) or str(os.getpid())
    directory = os.environ.get("HOTSTUFF_FLIGHT_DIR")
    if not directory:
        stream = env_stream_path(node)
        if stream is None:
            return None
        directory = os.path.dirname(os.path.abspath(stream))
    return os.path.join(directory, f"flightrec-{safe}.json")


# ---------------------------------------------------------------------------
# Benchmark-interface tables (the regex contract, telemetry-side).
#
# ``benchmark/logs.py`` measures from three log families: "Created B -> d"
# (proposer, per payload digest), "Batch d contains N B" (batch creator),
# "Committed B -> d" (every node). The same code sites call the three
# recorders below. Cross-site joins happen here: a commit pops the
# digest's proposal timestamp (commit latency) and its sealed size
# (committed bytes) exactly once — the pop IS the parser's
# earliest-commit-wins merge when testbed nodes share this process.
# ---------------------------------------------------------------------------

_TABLE_CAP = 16_384
_tables_lock = threading.Lock()
_proposed: OrderedDict[bytes, float] = OrderedDict()
_sealed: OrderedDict[bytes, int] = OrderedDict()


def _bounded_put(table: OrderedDict, key: bytes, value) -> None:
    if len(table) >= _TABLE_CAP:
        table.popitem(last=False)
    table[key] = value


def record_created(digest: bytes, ts: float | None = None) -> None:
    """A proposer put batch ``digest`` into a block (one call per payload
    digest, at the "Created B -> d" log site)."""
    if not _ENABLED:
        return
    ts = time.time() if ts is None else ts
    with _tables_lock:
        _bounded_put(_proposed, digest, ts)
    _REGISTRY.counter("consensus.batches_proposed").inc()
    _REGISTRY.gauge("consensus.first_proposal_ts").set_min(ts)


def record_sealed(digest: bytes, nbytes: int) -> None:
    """The mempool sealed a batch (the "Batch d contains N B" log site)."""
    if not _ENABLED:
        return
    with _tables_lock:
        _bounded_put(_sealed, digest, nbytes)
    _REGISTRY.counter("mempool.batches_sealed").inc()
    _REGISTRY.counter("mempool.sealed_bytes").inc(nbytes)
    _REGISTRY.histogram("mempool.batch_bytes", SIZE_BYTES_BUCKETS).observe(nbytes)


def record_commit(digest: bytes, ts: float | None = None) -> None:
    """A node committed a block containing batch ``digest`` (the
    "Committed B -> d" log site; every node calls this for every
    committed payload digest)."""
    if not _ENABLED:
        return
    ts = time.time() if ts is None else ts
    with _tables_lock:
        created = _proposed.pop(digest, None)
        size = _sealed.pop(digest, None)
    _REGISTRY.counter("consensus.commit_events").inc()
    if created is not None or size is not None:
        # Only the digest's FIRST newsworthy commit moves the window end —
        # the pop semantics give exactly the regex parser's
        # earliest-commit-wins merge when testbed nodes share a process.
        _REGISTRY.gauge("consensus.last_commit_ts").set_max(ts)
    if created is not None:
        _REGISTRY.counter("consensus.batches_committed").inc()
        _REGISTRY.histogram(
            "consensus.commit_latency_ms", DURATION_MS_BUCKETS
        ).observe((ts - created) * 1e3)
    if size is not None:
        _REGISTRY.counter("consensus.committed_bytes").inc(size)


def round_trace(node: str = "") -> RoundTrace | None:
    """A RoundTrace bound to the process registry and the process trace
    buffer, or None when disabled (cores hold the None and skip marking
    entirely). ``node`` labels this core's events in the cross-node
    trace stream — in-process committees share one buffer, so the label
    is what keeps each engine's timeline separable."""
    if not _ENABLED:
        return None
    return RoundTrace(_REGISTRY, node=node, events=_TRACE_BUFFER)


def trace_buffer() -> TraceBuffer:
    """The process trace ring (live even when disabled, so emitters and
    the flight recorder can be wired up before/without enablement)."""
    return _TRACE_BUFFER


def trace_event(
    node: str, round_: int, stage: str, detail: str | None = None
) -> None:
    """Record one protocol trace event into the process ring (no-op when
    telemetry is disabled). For sites without a RoundTrace — the
    proposer's broadcast mark, faultline injections."""
    if _ENABLED:
        _TRACE_BUFFER.record(node, round_, stage, detail=detail)


def dtrace_buffer() -> TraceBuffer:
    """The process batch-lifecycle ring (live even when disabled, so the
    emitter can be wired up before/without enablement)."""
    return _DTRACE_BUFFER


def dtrace_enabled() -> bool:
    """Whether the batch-lifecycle plane records: telemetry must be on
    AND ``HOTSTUFF_DTRACE=0`` must not have detached it. Instrumentation
    sites gate label interning on this, so a detached run pays nothing
    dtrace-specific."""
    return _ENABLED and not _DTRACE_DETACHED


def set_dtrace_detached(detached: bool) -> None:
    """Runtime override of the ``HOTSTUFF_DTRACE=0`` detach switch.
    This is the overhead smoke's paired-measurement hook (it alternates
    the lifeline plane per batch inside one process); production code
    configures the plane via the environment instead.
    ``reset_for_tests`` recomputes the flag from the environment."""
    global _DTRACE_DETACHED
    _DTRACE_DETACHED = detached


def dtrace_event(
    node: str, digest, stage: str,
    t: float | None = None, detail: str | None = None,
) -> None:
    """Record one batch-lifecycle event into the dtrace ring (no-op when
    telemetry is disabled or the dtrace plane is detached). ``digest`` is
    the batch digest's raw bytes (interned to the shared ``base64[:16]``
    label) or an already-interned label string. ``t`` overrides the
    timestamp — the seal site back-dates the ``ingress`` event to the
    bundle's recorded arrival instant."""
    if _ENABLED and not _DTRACE_DETACHED:
        label = digest if isinstance(digest, str) else intern_label(digest)
        _DTRACE_BUFFER.record(node, label, stage, t=t, detail=detail)


def reset_for_tests() -> None:
    """Clear registry, tables, trace ring, and enablement (isolation)."""
    global _ENABLED, _DTRACE_DETACHED
    from . import dtrace as _dtrace, profiler as _profiler, resources as _resources

    _profiler.reset_for_tests()
    _resources.reset_for_tests()
    _dtrace.reset_for_tests()
    _REGISTRY.reset()
    _TRACE_BUFFER.clear()
    _DTRACE_BUFFER.clear()
    with _tables_lock:
        _proposed.clear()
        _sealed.clear()
    _ENABLED = bool(
        os.environ.get("HOTSTUFF_TELEMETRY")
        or os.environ.get("HOTSTUFF_TELEMETRY_DIR")
    )
    _DTRACE_DETACHED = os.environ.get("HOTSTUFF_DTRACE", "") == "0"
