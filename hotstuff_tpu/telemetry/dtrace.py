"""Lifeline: per-batch data-plane lifecycle tracing (``hotstuff-dtrace-v1``).

The round-trace plane (:mod:`.trace`) stops at the consensus boundary: a
committed block's propose→vote→QC→commit path is fully attributed while
everything the Conveyor data plane does before ordering — bundle
ingress, sealing, dissemination, 2f+1 ack fan-in, cert→proposer queue
wait — and after it (commit-path resolution) was a black box of
aggregate counters. This module is the missing axis: one bounded ring of
``(seq, node, batch, stage, t_mono[, detail])`` events keyed by the
BATCH DIGEST instead of the round number, recorded at each lifecycle
stage and drained by the same :class:`~.emitter.TelemetryEmitter` into
``hotstuff-dtrace-v1`` JSON lines interleaved with the snapshots.
``benchmark/dtrace_assemble.py`` merges both stream kinds across nodes
into one causal timeline per committed batch.

The lifecycle stages, in causal order (see ``docs/telemetry.md``):

- ``ingress``   — earliest client bundle contributing to the batch
                  arrived at the worker (recorded at seal time with the
                  arrival timestamp, so the hot ingress path pays zero)
- ``seal``      — the batcher sealed the batch (detail:
                  ``w<id>|<txs>tx|<bytes>B[|s<id>,...]`` — worker shard,
                  size, and leading sample ids for the client-log join)
- ``disseminate`` — dissemination frames handed to the ReliableSender
- ``ack``       — one peer's signed availability ack verified (detail:
                  the signer label)
- ``cert``      — 2f+1 stake reached, the AvailabilityCert exists
- ``enqueue``   — the certified digest entered a proposer queue (own
                  certifier, or a peer cert received on the wire — v1
                  and v2 cert frames both land here)
- ``proposed``  — a leader drained the digest into a block (detail:
                  ``r<round>`` — THE join point onto the round trace)
- ``committed`` — a node 2-chain-committed a block carrying the digest
                  (detail: ``r<round>``)
- ``resolved``  — the commit-path resolver materialized the batch bytes

Batches are labeled by their **interned digest label** — the same
``base64[:16]`` rendering as ``repr(Digest)``, which is what the round
trace's ``propose_send`` detail and the benchmark log lines already
print — through a small bounded cache so the hot path stays "one dict
hit + one ring append". Everything is gated on ``telemetry.enabled()``;
the disabled cost is one boolean check.
"""

from __future__ import annotations

import base64
import os
import threading
from collections import OrderedDict

from .trace import TraceBuffer

DTRACE_SCHEMA = "hotstuff-dtrace-v1"

#: the lifecycle stages a batch may leave behind, in causal order.
STAGES = (
    "ingress", "seal", "disseminate", "ack", "cert", "enqueue",
    "proposed", "committed", "resolved",
)

#: bounded digest→label intern cache (a soak seals far more batches than
#: fit here; eviction only costs a re-encode, never correctness).
_INTERN_CAP = 8192
_intern_lock = threading.Lock()
_interned: OrderedDict[bytes, str] = OrderedDict()


def intern_label(data: bytes) -> str:
    """The batch's stream label: ``base64[:16]`` of the digest bytes —
    identical to ``repr(Digest)`` so dtrace events, round-trace details,
    and the benchmark log lines all name a batch the same way."""
    with _intern_lock:
        label = _interned.get(data)
        if label is None:
            label = base64.standard_b64encode(data).decode()[:16]
            if len(_interned) >= _INTERN_CAP:
                _interned.popitem(last=False)
            _interned[data] = label
    return label


def build_dtrace_record(
    buffer: TraceBuffer, events: list[tuple], node: str = ""
) -> dict:
    """One ``hotstuff-dtrace-v1`` stream line carrying ``events``."""
    return {
        "schema": DTRACE_SCHEMA,
        "node": node,
        "pid": os.getpid(),
        "anchor": buffer.anchor(),
        "evicted": buffer.evicted,
        "events": [list(e) for e in events],
    }


def validate_dtrace_record(obj) -> list[str]:
    """Schema check mirroring ``validate_trace_record``; returns
    problems. The one structural difference from the round trace: slot 2
    is the batch's interned digest LABEL (a string), not a round int."""
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"dtrace record is {type(obj).__name__}, not an object"]
    if obj.get("schema") != DTRACE_SCHEMA:
        problems.append(
            f"schema is {obj.get('schema')!r}, want {DTRACE_SCHEMA!r}"
        )
    anchor = obj.get("anchor")
    if not isinstance(anchor, dict) or not all(
        isinstance(anchor.get(k), (int, float)) for k in ("mono", "wall")
    ):
        problems.append("anchor missing mono/wall")
    events = obj.get("events")
    if not isinstance(events, list):
        problems.append("events missing or not a list")
        return problems
    for i, ev in enumerate(events):
        if (
            not isinstance(ev, (list, tuple))
            or len(ev) not in (5, 6)
            or not isinstance(ev[0], int)
            or not isinstance(ev[1], str)
            or not isinstance(ev[2], str)
            or not isinstance(ev[3], str)
            or not isinstance(ev[4], (int, float))
            or (len(ev) == 6 and not isinstance(ev[5], str))
        ):
            problems.append(f"event {i} malformed: {ev!r}")
            break
    return problems


def reset_for_tests() -> None:
    with _intern_lock:
        _interned.clear()
