"""Resource observability: RSS, on-disk store/MetaLog size, and
tracemalloc growth, surfaced as snapshot gauges.

Long soaks fail in a mode the protocol metrics cannot see: memory or
disk grows without bound until the box dies hours later (ROADMAP item 4
names snapshot+truncate of the MetaLog for exactly this reason). The
collector registered here is polled once per telemetry snapshot — the
gauges land in every ``hotstuff-telemetry-v1`` line, so the SLO
engine's ``gauge_growth`` kind (``telemetry/slo.py``) can gate a soak
on "RSS grows slower than X bytes/s in every window" instead of
somebody eyeballing ``ps`` output.

Gauges (all under the ``resource.`` collector prefix):

- ``rss_bytes``: resident set from ``/proc/self/statm`` (Linux; falls
  back to ``resource.getrusage`` elsewhere).
- ``store_bytes``: recursive on-disk size of the registered store
  directory (data log + ``meta.log`` + native WAL). Absent when the
  node runs an in-memory store.
- ``open_fds``: ``/proc/self/fd`` entry count (socket/file leaks show
  up here long before accept() starts failing).
- ``tracemalloc_total_bytes`` / ``tracemalloc_top_growth_bytes``: only
  when tracing is on (``HOTSTUFF_TRACEMALLOC=1`` or ``install(
  tracemalloc_on=True)``) — total traced size and the single largest
  per-site growth since the previous poll, with the top sites logged at
  DEBUG. Tracing costs real memory/CPU, so it is opt-in; RSS is the
  always-on signal.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("telemetry")

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_bytes() -> int | None:
    """Resident set size of this process, or None when unmeasurable."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource as _resource

        # ru_maxrss is KiB on Linux (peak, not current — still monotone
        # enough for growth gating when /proc is unavailable).
        return _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # noqa: BLE001 — observability must not raise
        return None


def dir_bytes(path: str) -> int:
    """Recursive apparent size of ``path`` (0 for a missing path —
    a store not yet created is empty, not an error)."""
    total = 0
    try:
        for root, _dirs, files in os.walk(path):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(root, name))
                except OSError:
                    pass  # file vanished mid-walk (compaction)
    except OSError:
        return 0
    return total


def open_fds() -> int | None:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


class _TracemallocWatch:
    """Per-site growth between collector polls: keeps the previous poll's
    top sites (keyed file:lineno) and reports the largest positive
    delta. Bounded: only the top ``keep`` sites by size are remembered."""

    def __init__(self, keep: int = 50) -> None:
        self.keep = keep
        self._prev: dict[str, int] = {}

    def poll(self) -> tuple[int, int]:
        """(total traced bytes, largest per-site growth since last poll)."""
        import tracemalloc

        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("lineno")
        total = sum(s.size for s in stats)
        current: dict[str, int] = {}
        for s in stats[: self.keep]:
            frame = s.traceback[0]
            current[f"{os.path.basename(frame.filename)}:{frame.lineno}"] = s.size
        growth = [
            (size - self._prev.get(site, 0), site)
            for site, size in current.items()
        ]
        growth.sort(reverse=True)
        top_growth = max(0, growth[0][0]) if growth else 0
        if growth and growth[0][0] > 0:
            log.debug(
                "tracemalloc top growth: %s",
                ", ".join(f"{site} +{delta}" for delta, site in growth[:3]),
            )
        self._prev = current
        return total, top_growth


_STORE_PATH: str | None = None
_TM_WATCH: _TracemallocWatch | None = None


def _collect() -> dict[str, float]:
    out: dict[str, float] = {}
    rss = rss_bytes()
    if rss is not None:
        out["rss_bytes"] = rss
    fds = open_fds()
    if fds is not None:
        out["open_fds"] = fds
    if _STORE_PATH:
        out["store_bytes"] = dir_bytes(_STORE_PATH)
    if _TM_WATCH is not None:
        import tracemalloc

        if tracemalloc.is_tracing():
            total, top_growth = _TM_WATCH.poll()
            out["tracemalloc_total_bytes"] = total
            out["tracemalloc_top_growth_bytes"] = top_growth
    return out


def install(store_path: str | None = None, tracemalloc_on: bool | None = None) -> None:
    """Register the ``resource`` collector on the process registry
    (idempotent — re-registration replaces; the last store path wins).
    ``tracemalloc_on=None`` defers to ``HOTSTUFF_TRACEMALLOC``."""
    global _STORE_PATH, _TM_WATCH
    from . import register_collector

    if store_path is not None:
        _STORE_PATH = store_path
    if tracemalloc_on is None:
        tracemalloc_on = bool(os.environ.get("HOTSTUFF_TRACEMALLOC"))
    if tracemalloc_on:
        import tracemalloc

        if not tracemalloc.is_tracing():
            tracemalloc.start()
        if _TM_WATCH is None:
            _TM_WATCH = _TracemallocWatch()
    register_collector("resource", _collect)


def reset_for_tests() -> None:
    global _STORE_PATH, _TM_WATCH
    _STORE_PATH = None
    _TM_WATCH = None
