"""Consensus configuration (reference ``consensus/src/config.rs``).

One consensus address per node; stake-weighted quorums of 2f+1.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from hotstuff_tpu.crypto import PublicKey

log = logging.getLogger("consensus")

Stake = int
Round = int


@dataclass
class Parameters:
    """Defaults match the reference (``consensus/src/config.rs:16-23``)."""

    timeout_delay: int = 5_000  # ms
    sync_retry_delay: int = 10_000  # ms
    # fsync the persisted voting state on every update: survives power
    # loss, at ~ms extra latency per vote. Off by default (process-crash
    # safety only), matching typical BFT deployment practice.
    persist_sync: bool = False
    # Committee-scale vote handling: accumulate unverified votes and
    # batch-verify the assembled QC's 2f+1 signatures in one crypto call
    # (byzantine signatures are identified and ejected on failure). Pairs
    # with the TPU crypto backend; worthwhile from ~100 validators.
    batch_vote_verification: bool = False
    # "round-robin" (reference behavior) or "reputation" (DiemBFT-style
    # active-set election: crashed validators stop being elected after
    # the committed window rotates past them — see consensus/leader.py).
    leader_elector: str = "round-robin"
    # Wire-format v2: certificates ship as a seat bitmap + concatenated
    # signatures instead of repeated (pubkey, signature) pairs (~33%
    # smaller proposals at N=200). Decoders ALWAYS accept both formats;
    # this flag only selects what this node emits, so a committee is
    # migrated by flipping the config per epoch — nodes still on v1
    # interoperate throughout. HOTSTUFF_WIRE_V2=0 force-disables.
    wire_v2: bool = True
    # Snapshot/truncate retention depth in committed rounds (Lazarus):
    # the store keeps roughly this many rounds of chain below the commit
    # head, truncating the rest behind a certified snapshot frontier —
    # store growth bounded by retention, not uptime. 0 disables
    # compaction entirely (full history retained, the historic behavior).
    retention_rounds: int = 0

    def log(self) -> None:
        # Picked up by the benchmark log parser (reference ``config.rs:25-31``).
        log.info("Timeout delay set to %d ms", self.timeout_delay)
        log.info("Sync retry delay set to %d ms", self.sync_retry_delay)
        if self.retention_rounds > 0:
            log.info("Store retention set to %d rounds", self.retention_rounds)


@dataclass
class Authority:
    stake: Stake
    address: tuple[str, int]


@dataclass
class Committee:
    authorities: dict[PublicKey, Authority]
    epoch: int = 1

    def size(self) -> int:
        return len(self.authorities)

    def stake(self, name: PublicKey) -> Stake:
        a = self.authorities.get(name)
        return a.stake if a else 0

    def total_stake(self) -> Stake:
        return sum(a.stake for a in self.authorities.values())

    def quorum_threshold(self) -> Stake:
        # 2f+1 out of N=3f+1 by stake (reference ``config.rs:67-72``).
        return 2 * self.total_stake() // 3 + 1

    def validity_threshold(self) -> Stake:
        # f+1 by stake: any set this heavy contains at least one honest
        # authority — the timeout-amplification trigger (Core.handle_timeout).
        return (self.total_stake() - 1) // 3 + 1

    def address(self, name: PublicKey) -> tuple[str, int] | None:
        a = self.authorities.get(name)
        return a.address if a else None

    def broadcast_addresses(self, name: PublicKey) -> list[tuple[PublicKey, tuple[str, int]]]:
        """(name, address) of every node except ``name`` (reference
        ``config.rs:78-84``)."""
        return [(pk, a.address) for pk, a in self.authorities.items() if pk != name]

    def sorted_keys(self) -> list[PublicKey]:
        return sorted(self.authorities.keys())
