"""Async bridge between the consensus event loop and the crypto plane.

The Core's select loop must never block on signature verification: a QC
verification is a batch crypto call that — on the TPU backend — involves a
host->device round trip. The bridge runs verifications on a small worker
pool and the Core awaits them, so network handling, timeouts, and other
protocol work continue while the device (or CPU) verifies.

This is the "tokio <-> device dispatch without head-of-line blocking"
component called out in SURVEY.md §7; the reference has no equivalent
because its crypto is synchronous ed25519-dalek on the calling thread.

Batched vote verification (``BatchedVoteVerifier``) is the committee-scale
design (BASELINE.json configs 2-4): instead of verifying each incoming
vote individually (2f+1 sequential verifies per round), votes pass only
cheap stake/round checks on arrival, accumulate in the aggregator, and the
assembled QC's 2f+1 signatures are verified in ONE batch call. If the
batch fails, the byzantine signatures are identified individually and
ejected, and the aggregator keeps collecting.
"""

from __future__ import annotations

import asyncio
import logging
from concurrent.futures import ThreadPoolExecutor

from hotstuff_tpu.telemetry import profiler as pyprof

log = logging.getLogger("consensus")

_EXECUTOR: ThreadPoolExecutor | None = None


def _executor() -> ThreadPoolExecutor:
    global _EXECUTOR
    if _EXECUTOR is None:
        import os

        # Default 2 workers: one verification in flight while the next
        # batch's host prep runs — the device pipeline depth that saturates
        # it. Raise HOTSTUFF_CRYPTO_WORKERS when super-batching
        # (crypto/batching.py) should fuse more concurrent requests — e.g.
        # many in-process validators sharing one device.
        raw = os.environ.get("HOTSTUFF_CRYPTO_WORKERS", "2")
        try:
            workers = int(raw)
            if workers < 1:
                raise ValueError(raw)
        except ValueError:
            raise ValueError(
                f"HOTSTUFF_CRYPTO_WORKERS must be a positive integer, got {raw!r}"
            ) from None
        _EXECUTOR = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="crypto"
        )
    return _EXECUTOR


def _backend_blocks() -> bool:
    """True when the active crypto backend can block the loop for long
    (device round trips / super-batching windows)."""
    from hotstuff_tpu.crypto import get_backend

    return "tpu" in getattr(get_backend(), "name", "")


# Below this many signatures a CPU verification is cheap enough (sub-ms
# native calls) that the executor hop (queue + thread wake + GIL churn,
# straight on the vote path) costs more than running it inline. Above it —
# committee-scale QCs run 8-38 ms/round at N=400-1000 on this box
# (results/committee-crypto-cpu-*.txt) — an inline call head-of-line-blocks
# timers, ACK pumps, and network reads, and the native ctypes verifier
# releases the GIL, so the executor genuinely overlaps on multi-core hosts.
INLINE_SIG_LIMIT = 64


async def verify_off_loop(verify_fn, *args, n_sigs: int = 1):
    """Run a blocking verification callable without head-of-line-blocking
    the event loop; re-raises its exception (ConsensusError/CryptoError) in
    the awaiting task. Device-backed verifications and large CPU batches
    (``n_sigs >= INLINE_SIG_LIMIT``) go to the worker pool; small CPU ones
    run inline (see ``INLINE_SIG_LIMIT``)."""
    if not _backend_blocks() and n_sigs < INLINE_SIG_LIMIT:
        return verify_fn(*args)
    loop = asyncio.get_running_loop()
    if pyprof.TAGGING:
        # The verification runs on a crypto worker thread; tag that
        # thread for the sampling profiler so its stack samples join the
        # trace's verify edge instead of landing unstaged.
        def _tagged():
            with pyprof.stage("verify"):
                return verify_fn(*args)

        return await loop.run_in_executor(_executor(), _tagged)
    return await loop.run_in_executor(_executor(), lambda: verify_fn(*args))
