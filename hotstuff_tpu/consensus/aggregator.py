"""Vote/timeout aggregation into QCs/TCs (reference
``consensus/src/aggregator.rs``).

``QCMaker`` dedups authors, sums stake, emits the QC exactly once at 2f+1;
``TCMaker`` likewise for timeouts. Keyed by round (and block digest for
votes); ``cleanup`` retains only >= the current round.

This is the device batching point for the TPU backend: a QC carries all its
vote signatures, so ``QC.verify`` on receivers becomes one device call per
QC; at scale the verifier fuses QCs across rounds into super-batches.
"""

from __future__ import annotations

import logging

from .config import Committee, Round
from .errors import AuthorityReuse
from .messages import QC, TC, Timeout, Vote

log = logging.getLogger("consensus")


class QCMaker:
    def __init__(self) -> None:
        self.weight = 0
        self.votes = []
        self.used = set()

    def append(self, vote: Vote, committee: Committee) -> QC | None:
        if vote.author in self.used:
            raise AuthorityReuse(str(vote.author))
        self.used.add(vote.author)
        self.votes.append((vote.author, vote.signature))
        self.weight += committee.stake(vote.author)
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # QC is made exactly once
            return QC(hash=vote.hash, round=vote.round, votes=list(self.votes))
        return None


class TCMaker:
    def __init__(self) -> None:
        self.weight = 0
        self.votes = []
        self.used = set()

    def append(self, timeout: Timeout, committee: Committee) -> TC | None:
        if timeout.author in self.used:
            raise AuthorityReuse(str(timeout.author))
        self.used.add(timeout.author)
        self.votes.append((timeout.author, timeout.signature, timeout.high_qc.round))
        self.weight += committee.stake(timeout.author)
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # TC is made exactly once
            return TC(round=timeout.round, votes=list(self.votes))
        return None


class Aggregator:
    def __init__(self, committee: Committee) -> None:
        self.committee = committee
        self.votes_aggregators: dict[Round, dict] = {}
        self.timeouts_aggregators: dict[Round, TCMaker] = {}

    # An honest round has exactly one proposal digest; 2N distinct digests
    # per round is a generous bound that caps the memory an attacker can
    # allocate per round (tightens the reference's open DoS caveat,
    # ``aggregator.rs:29-30`` issue #7).
    MAX_DIGESTS_PER_ROUND_FACTOR = 2

    def add_vote(self, vote: Vote) -> QC | None:
        per_round = self.votes_aggregators.setdefault(vote.round, {})
        key = vote.digest()
        if (
            key not in per_round
            and len(per_round)
            >= self.MAX_DIGESTS_PER_ROUND_FACTOR * self.committee.size()
        ):
            log.warning(
                "dropping vote for round %d: per-round digest bound reached",
                vote.round,
            )
            return None
        return per_round.setdefault(key, QCMaker()).append(vote, self.committee)

    def stored_signature(self, round_: Round, digest, author):
        """The signature currently held for (round, digest, author), if any."""
        maker = self.votes_aggregators.get(round_, {}).get(digest)
        if maker is None:
            return None
        for pk, sig in maker.votes:
            if pk == author:
                return sig
        return None

    def add_timeout(self, timeout: Timeout) -> TC | None:
        return self.timeouts_aggregators.setdefault(
            timeout.round, TCMaker()
        ).append(timeout, self.committee)

    def rebuild_votes(self, round_: Round, digest, good_votes, hash_) -> QC | None:
        """After a batch-verified QC failed, reinstate only the good votes
        for (round, block digest) so aggregation continues; ejected authors
        may vote again (their next signature may be honest).

        With unequal stakes the surviving votes may already meet the quorum
        threshold (the bad vote was not load-bearing): emit that QC now —
        its signatures were individually verified during ejection — instead
        of stalling on a vote that may never come."""
        maker = QCMaker()
        maker.votes = list(good_votes)
        maker.used = {pk for pk, _ in good_votes}
        maker.weight = sum(self.committee.stake(pk) for pk, _ in good_votes)
        self.votes_aggregators.setdefault(round_, {})[digest] = maker
        if maker.weight >= self.committee.quorum_threshold():
            maker.weight = 0  # QC emitted exactly once
            return QC(hash=hash_, round=round_, votes=list(maker.votes))
        return None

    def replace_vote(self, vote: Vote) -> None:
        """Swap an author's stored (unverified) vote for a newly verified
        one — the anti-displacement path of batched verification."""
        makers = self.votes_aggregators.get(vote.round, {})
        maker = makers.get(vote.digest())
        if maker is None or vote.author not in maker.used:
            return
        maker.votes = [
            (pk, sig) if pk != vote.author else (pk, vote.signature)
            for pk, sig in maker.votes
        ]

    def cleanup(self, round_: Round) -> None:
        self.votes_aggregators = {
            k: v for k, v in self.votes_aggregators.items() if k >= round_
        }
        self.timeouts_aggregators = {
            k: v for k, v in self.timeouts_aggregators.items() if k >= round_
        }
