"""Vote/timeout aggregation into QCs/TCs (reference
``consensus/src/aggregator.rs``).

``QCMaker`` dedups authors, sums stake, emits the QC exactly once at 2f+1;
``TCMaker`` likewise for timeouts. Keyed by round (and block digest for
votes); ``cleanup`` retains only >= the current round.

This is the device batching point for the TPU backend: a QC carries all its
vote signatures, so ``QC.verify`` on receivers becomes one device call per
QC; at scale the verifier fuses QCs across rounds into super-batches.
"""

from __future__ import annotations

import logging

from .config import Committee, Round
from .errors import AuthorityReuse
from .messages import QC, TC, Timeout, Vote

log = logging.getLogger("consensus")


class QCMaker:
    def __init__(self) -> None:
        self.weight = 0
        self.votes = []
        self.used = set()

    def append(self, vote: Vote, committee: Committee) -> QC | None:
        if vote.author in self.used:
            raise AuthorityReuse(str(vote.author))
        self.used.add(vote.author)
        self.votes.append((vote.author, vote.signature))
        self.weight += committee.stake(vote.author)
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # QC is made exactly once
            return QC(hash=vote.hash, round=vote.round, votes=list(self.votes))
        return None


class TCMaker:
    def __init__(self) -> None:
        self.weight = 0
        self.votes = []
        self.used = set()

    def append(self, timeout: Timeout, committee: Committee) -> TC | None:
        if timeout.author in self.used:
            raise AuthorityReuse(str(timeout.author))
        self.used.add(timeout.author)
        self.votes.append((timeout.author, timeout.signature, timeout.high_qc.round))
        self.weight += committee.stake(timeout.author)
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # TC is made exactly once
            return TC(round=timeout.round, votes=list(self.votes))
        return None


class Aggregator:
    def __init__(self, committee: Committee) -> None:
        self.committee = committee
        self.votes_aggregators: dict[Round, dict] = {}
        self.timeouts_aggregators: dict[Round, TCMaker] = {}
        # Per-round author -> digest-bucket binding: each authority occupies
        # at most ONE digest bucket per round, so the number of buckets is
        # bounded by committee size and no set of byzantine members can
        # displace honest votes by fabricating digests (tightens the
        # reference's open DoS caveat, ``aggregator.rs:29-30`` issue #7).
        self.author_bucket: dict[Round, dict] = {}

    def add_vote(self, vote: Vote) -> QC | None:
        per_round = self.votes_aggregators.setdefault(vote.round, {})
        buckets = self.author_bucket.setdefault(vote.round, {})
        key = vote.digest()
        prev = buckets.get(vote.author)
        if prev is not None and prev != key:
            # The author already voted for a different digest this round:
            # equivocation (verified path) or a possible spoof (batched
            # path — the core re-seats after individual verification).
            raise AuthorityReuse(str(vote.author))
        qc = per_round.setdefault(key, QCMaker()).append(vote, self.committee)
        buckets[vote.author] = key
        return qc

    def reseat_vote(self, vote: Vote) -> QC | None:
        """Place an INDIVIDUALLY VERIFIED vote whose author's slot was taken.

        Same-bucket conflict: the stored (possibly spoofed) signature is
        swapped for the genuine one. Cross-bucket conflict: the author's old
        entry — spoofed, or genuine equivocation by a byzantine author;
        either way not worth keeping over a verified vote — is evicted and
        the vote is added normally (it may complete a quorum, so the QC
        return value must be handled like ``add_vote``'s)."""
        buckets = self.author_bucket.get(vote.round, {})
        prev = buckets.get(vote.author)
        key = vote.digest()
        if prev == key:
            self.replace_vote(vote)
            return None
        if prev is not None:
            makers = self.votes_aggregators.get(vote.round, {})
            maker = makers.get(prev)
            if maker is not None and vote.author in maker.used:
                maker.votes = [
                    (pk, sig) for pk, sig in maker.votes if pk != vote.author
                ]
                maker.used.discard(vote.author)
                maker.weight = max(
                    0, maker.weight - self.committee.stake(vote.author)
                )
                if not maker.used:
                    del makers[prev]
            del buckets[vote.author]
        return self.add_vote(vote)

    def stored_signature(self, round_: Round, digest, author):
        """The signature currently held for (round, digest, author), if any."""
        maker = self.votes_aggregators.get(round_, {}).get(digest)
        if maker is None:
            return None
        for pk, sig in maker.votes:
            if pk == author:
                return sig
        return None

    def add_timeout(self, timeout: Timeout) -> TC | None:
        return self.timeouts_aggregators.setdefault(
            timeout.round, TCMaker()
        ).append(timeout, self.committee)

    def eject_votes(self, round_: Round, digest, bad, hash_):
        """After a batch-verified QC failed: remove the given bad
        ``(author, signature)`` pairs from the CURRENT maker for
        (round, block digest) and free those authors' buckets so they may
        vote again (their next signature may be honest).

        This is keyed by the exact (author, signature) pair, not by author:
        an author whose spoofed signature appears in a stale QC snapshot
        but whose seat has since been replaced by an individually-verified
        genuine signature keeps the genuine vote.

        Returns ``(qc, ejected_authors)``: with unequal stakes the
        surviving votes may already meet the quorum threshold (the bad
        vote was not load-bearing) — the caller re-verifies any emitted QC
        since survivors may include later, not-yet-verified seatings."""
        maker = self.votes_aggregators.get(round_, {}).get(digest)
        if maker is None:
            return None, set()
        bad_keys = {(bytes(pk.data), bytes(sig.data)) for pk, sig in bad}
        survivors = [
            (pk, sig)
            for pk, sig in maker.votes
            if (bytes(pk.data), bytes(sig.data)) not in bad_keys
        ]
        ejected = {pk for pk, _ in maker.votes} - {pk for pk, _ in survivors}
        maker.votes = survivors
        maker.used = {pk for pk, _ in survivors}
        maker.weight = sum(self.committee.stake(pk) for pk, _ in survivors)
        buckets = self.author_bucket.get(round_, {})
        for pk in ejected:
            buckets.pop(pk, None)
        if maker.weight >= self.committee.quorum_threshold():
            maker.weight = 0  # QC emitted exactly once
            return QC(hash=hash_, round=round_, votes=list(maker.votes)), ejected
        return None, ejected

    def replace_vote(self, vote: Vote) -> None:
        """Swap an author's stored (unverified) vote for a newly verified
        one — the anti-displacement path of batched verification."""
        makers = self.votes_aggregators.get(vote.round, {})
        maker = makers.get(vote.digest())
        if maker is None or vote.author not in maker.used:
            return
        maker.votes = [
            (pk, sig) if pk != vote.author else (pk, vote.signature)
            for pk, sig in maker.votes
        ]

    def cleanup(self, round_: Round) -> None:
        self.votes_aggregators = {
            k: v for k, v in self.votes_aggregators.items() if k >= round_
        }
        self.timeouts_aggregators = {
            k: v for k, v in self.timeouts_aggregators.items() if k >= round_
        }
        self.author_bucket = {
            k: v for k, v in self.author_bucket.items() if k >= round_
        }
