"""Vote/timeout aggregation into QCs/TCs (reference
``consensus/src/aggregator.rs``).

``QCMaker`` dedups authors, sums stake, emits the QC exactly once at 2f+1;
``TCMaker`` likewise for timeouts. Keyed by round (and block digest for
votes); ``cleanup`` retains only >= the current round.

This is the device batching point for the TPU backend: a QC carries all its
vote signatures, so ``QC.verify`` on receivers becomes one device call per
QC; at scale the verifier fuses QCs across rounds into super-batches.
"""

from __future__ import annotations

from .config import Committee, Round
from .errors import AuthorityReuse
from .messages import QC, TC, Timeout, Vote


class QCMaker:
    def __init__(self) -> None:
        self.weight = 0
        self.votes = []
        self.used = set()

    def append(self, vote: Vote, committee: Committee) -> QC | None:
        if vote.author in self.used:
            raise AuthorityReuse(str(vote.author))
        self.used.add(vote.author)
        self.votes.append((vote.author, vote.signature))
        self.weight += committee.stake(vote.author)
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # QC is made exactly once
            return QC(hash=vote.hash, round=vote.round, votes=list(self.votes))
        return None


class TCMaker:
    def __init__(self) -> None:
        self.weight = 0
        self.votes = []
        self.used = set()

    def append(self, timeout: Timeout, committee: Committee) -> TC | None:
        if timeout.author in self.used:
            raise AuthorityReuse(str(timeout.author))
        self.used.add(timeout.author)
        self.votes.append((timeout.author, timeout.signature, timeout.high_qc.round))
        self.weight += committee.stake(timeout.author)
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # TC is made exactly once
            return TC(round=timeout.round, votes=list(self.votes))
        return None


class Aggregator:
    def __init__(self, committee: Committee) -> None:
        self.committee = committee
        self.votes_aggregators: dict[Round, dict] = {}
        self.timeouts_aggregators: dict[Round, TCMaker] = {}

    def add_vote(self, vote: Vote) -> QC | None:
        # NOTE: inherits the reference's DoS caveat (``aggregator.rs:29-30``):
        # bounded by cleanup() per round advance.
        return (
            self.votes_aggregators.setdefault(vote.round, {})
            .setdefault(vote.digest(), QCMaker())
            .append(vote, self.committee)
        )

    def add_timeout(self, timeout: Timeout) -> TC | None:
        return self.timeouts_aggregators.setdefault(
            timeout.round, TCMaker()
        ).append(timeout, self.committee)

    def cleanup(self, round_: Round) -> None:
        self.votes_aggregators = {
            k: v for k, v in self.votes_aggregators.items() if k >= round_
        }
        self.timeouts_aggregators = {
            k: v for k, v in self.timeouts_aggregators.items() if k >= round_
        }
