"""Resettable timeout timer (reference ``consensus/src/timer.rs:10-34``).

``wait()`` completes when the deadline passes; ``reset()`` pushes the
deadline forward — an in-flight ``wait()`` observes the new deadline and
keeps sleeping, matching the reference's resettable ``Sleep``.

The clock is injectable: the real stack uses ``time.monotonic`` (the
default — behavior unchanged), while the deterministic simulation plane
(:mod:`hotstuff_tpu.sim`) passes a virtual clock and never calls
``wait()`` — it reads ``deadline`` and fires expiries from its event
heap, making the Timer a thin state holder over the injected clock.
"""

from __future__ import annotations

import asyncio
import time


class Timer:
    def __init__(self, duration_ms: int, clock=time.monotonic) -> None:
        self.duration = duration_ms / 1000.0
        self._clock = clock
        self._deadline = clock() + self.duration

    @property
    def deadline(self) -> float:
        """The instant (on the injected clock) the timer next expires."""
        return self._deadline

    def reset(self) -> None:
        self._deadline = self._clock() + self.duration

    async def wait(self) -> None:
        while True:
            remaining = self._deadline - self._clock()
            if remaining <= 0:
                return
            await asyncio.sleep(remaining)
