"""Resettable timeout timer (reference ``consensus/src/timer.rs:10-34``).

``wait()`` completes when the deadline passes; ``reset()`` pushes the
deadline forward — an in-flight ``wait()`` observes the new deadline and
keeps sleeping, matching the reference's resettable ``Sleep``.
"""

from __future__ import annotations

import asyncio
import time


class Timer:
    def __init__(self, duration_ms: int) -> None:
        self.duration = duration_ms / 1000.0
        self._deadline = time.monotonic() + self.duration

    def reset(self) -> None:
        self._deadline = time.monotonic() + self.duration

    async def wait(self) -> None:
        while True:
            remaining = self._deadline - time.monotonic()
            if remaining <= 0:
                return
            await asyncio.sleep(remaining)
