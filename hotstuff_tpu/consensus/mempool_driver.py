"""Payload availability gate (reference ``consensus/src/mempool.rs``).

``verify(block)`` checks every payload digest is AVAILABLE: either the
batch itself is in the store, or a verified **availability certificate**
(the Conveyor data plane's 2f+1 signed acks, stored under
``cert_key(digest)``) proves the committee holds it — the Narwhal rule
that lets a replica vote on a block whose batches it never received,
keeping dissemination bandwidth off the ordering critical path. When
neither is present it sends ``Synchronize`` to the mempool and parks the
block in the PayloadWaiter, which re-injects it to the Core once all
batches arrive (store ``notify_read`` on each missing digest).
``cleanup(round)`` propagates GC to the mempool and cancels stale
waiters.
"""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu.crypto import Digest
from hotstuff_tpu.mempool import Cleanup as MempoolCleanup
from hotstuff_tpu.mempool import Synchronize as MempoolSynchronize
from hotstuff_tpu.mempool.dataplane.messages import cert_key
from hotstuff_tpu.store import Store

from .config import Round
from .messages import Block

log = logging.getLogger("consensus")


class MempoolDriver:
    def __init__(
        self,
        store: Store,
        tx_mempool: asyncio.Queue,
        tx_loopback: asyncio.Queue,
    ) -> None:
        self.store = store
        self.tx_mempool = tx_mempool
        self.tx_loopback = tx_loopback
        # block digest -> (round, waiter task)
        self._pending: dict[Digest, tuple[Round, asyncio.Task]] = {}

    async def verify(self, block: Block) -> bool:
        """True if every payload batch is local OR carries a stored
        availability certificate; otherwise triggers sync and parks the
        block (reference ``mempool.rs:40-64``). Certificates are verified
        against the mempool committee BEFORE they are stored (worker
        ingress / cert formation), so presence here is proof."""
        missing = []
        for d in block.payload:
            if await self.store.read(d.data) is not None:
                continue
            if await self.store.read(cert_key(d.data)) is not None:
                continue  # certified available: vote without the bytes
            missing.append(d)
        if not missing:
            return True
        await self.tx_mempool.put(MempoolSynchronize(missing, block.author))
        digest = block.digest()
        if digest not in self._pending:
            task = asyncio.create_task(self._waiter(missing, block))
            self._pending[digest] = (block.round, task)
        return False

    async def _waiter(self, missing: list[Digest], block: Block) -> None:
        await asyncio.gather(*[self.store.notify_read(d.data) for d in missing])
        self._pending.pop(block.digest(), None)
        await self.tx_loopback.put(("loopback", block))

    async def cleanup(self, round_: Round) -> None:
        await self.tx_mempool.put(MempoolCleanup(round_))
        stale = [d for d, (r, _) in self._pending.items() if r <= round_]
        for d in stale:
            _, task = self._pending.pop(d)
            task.cancel()

    def shutdown(self) -> None:
        for _, task in self._pending.values():
            task.cancel()
