"""Payload availability gate (reference ``consensus/src/mempool.rs``).

``verify(block)`` checks every payload digest is in the store; when batches
are missing it sends ``Synchronize`` to the mempool and parks the block in
the PayloadWaiter, which re-injects it to the Core once all batches arrive
(store ``notify_read`` on each missing digest). ``cleanup(round)`` propagates
GC to the mempool and cancels stale waiters.
"""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu.crypto import Digest
from hotstuff_tpu.mempool import Cleanup as MempoolCleanup
from hotstuff_tpu.mempool import Synchronize as MempoolSynchronize
from hotstuff_tpu.store import Store

from .config import Round
from .messages import Block

log = logging.getLogger("consensus")


class MempoolDriver:
    def __init__(
        self,
        store: Store,
        tx_mempool: asyncio.Queue,
        tx_loopback: asyncio.Queue,
    ) -> None:
        self.store = store
        self.tx_mempool = tx_mempool
        self.tx_loopback = tx_loopback
        # block digest -> (round, waiter task)
        self._pending: dict[Digest, tuple[Round, asyncio.Task]] = {}

    async def verify(self, block: Block) -> bool:
        """True if all payload batches are local; otherwise triggers sync and
        parks the block (reference ``mempool.rs:40-64``)."""
        missing = [
            d for d in block.payload if await self.store.read(d.data) is None
        ]
        if not missing:
            return True
        await self.tx_mempool.put(MempoolSynchronize(missing, block.author))
        digest = block.digest()
        if digest not in self._pending:
            task = asyncio.create_task(self._waiter(missing, block))
            self._pending[digest] = (block.round, task)
        return False

    async def _waiter(self, missing: list[Digest], block: Block) -> None:
        await asyncio.gather(*[self.store.notify_read(d.data) for d in missing])
        self._pending.pop(block.digest(), None)
        await self.tx_loopback.put(("loopback", block))

    async def cleanup(self, round_: Round) -> None:
        await self.tx_mempool.put(MempoolCleanup(round_))
        stale = [d for d, (r, _) in self._pending.items() if r <= round_]
        for d in stale:
            _, task = self._pending.pop(d)
            task.cancel()

    def shutdown(self) -> None:
        for _, task in self._pending.values():
            task.cancel()
