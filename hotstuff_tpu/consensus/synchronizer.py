"""Consensus block-ancestry synchronizer (reference
``consensus/src/synchronizer.rs``).

``get_parent_block`` reads the store or fires a ``SyncRequest`` to the block
author and suspends processing; an inner task waits on store ``notify_read``
and loops delivered blocks back to the Core. A coarse timer re-broadcasts
expired requests to all peers ("perfect point-to-point link",
``synchronizer.rs:84-105``).
"""

from __future__ import annotations

import asyncio
import logging
import time

from hotstuff_tpu import telemetry
from hotstuff_tpu.crypto import Digest, PublicKey
from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store

from .config import Committee
from .messages import Block, QC, encode_sync_request

log = logging.getLogger("consensus")

TIMER_ACCURACY = 5.0  # s (reference ``synchronizer.rs:22``)


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        tx_loopback: asyncio.Queue,
        sync_retry_delay: int,
        clock=time.monotonic,
    ) -> None:
        self.name = name
        self.committee = committee
        self.store = store
        self.tx_loopback = tx_loopback
        self.sync_retry_delay = sync_retry_delay / 1000.0
        # Injectable clock (default untouched): request timestamps must
        # come from the same clock the simulation plane advances, or
        # sim runs would judge expiry against wall time.
        self._clock = clock
        self.network = SimpleSender()
        self._pending: set[Digest] = set()  # block digests being waited on
        self._requests: dict[Digest, float] = {}  # parent digest -> first-request ts
        # parent digest -> last (re)send ts: a retried request re-arms at
        # sync_retry_delay cadence instead of being re-broadcast on every
        # poll tick once expired (the committee-wide duplicate storm).
        self._last_sent: dict[Digest, float] = {}
        self._ancestor_cache: dict[bytes, Block] = {}  # digest -> Block
        # Truncation floor (Lazarus): digest of the snapshot frontier
        # block F. Below it the chain is truncated everywhere — walks
        # stop at F instead of suspending on an unservable parent.
        self._floor: Digest | None = None
        self._floor_round = 0
        # digest -> waiter task for DIRECT pulls (request_block), so a
        # caller can cancel one that will never resolve (see
        # cancel_request) without leaking the store obligation.
        self._direct: dict[Digest, asyncio.Task] = {}
        self._tasks: set[asyncio.Task] = set()
        self._main = asyncio.create_task(self._run(), name="consensus_synchronizer")

    async def _waiter(self, wait_on: Digest, deliver: Block) -> None:
        await self.store.notify_read(wait_on.data)
        self._pending.discard(deliver.digest())
        self._requests.pop(deliver.parent(), None)
        self._last_sent.pop(deliver.parent(), None)
        await self.tx_loopback.put(("loopback", deliver))

    def _suspend(self, block: Block) -> None:
        """Register the waiter + sync request for ``block``'s missing
        parent. Runs SYNCHRONOUSLY inside ``get_parent_block`` (i.e. in
        the Core's processing step): the solicited-block rule
        (``requested``) must observe the registration before the Core
        dequeues the next network frame, and on the inline-verification
        CPU path there is no yield point between frames — a registration
        deferred to a background task would race and misclassify helper
        chain ancestors as unsolicited."""
        digest = block.digest()
        if digest in self._pending:
            return
        self._pending.add(digest)
        parent = block.parent()
        task = asyncio.create_task(self._waiter(parent, block))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        if parent not in self._requests:
            log.debug("requesting sync for block %s", parent)
            telemetry.counter("consensus.sync_requests").inc()
            now = self._clock()
            self._requests[parent] = now
            self._last_sent[parent] = now
            address = self.committee.address(block.author)
            if address is not None:
                self.network.send(
                    address, encode_sync_request(parent, self.name)
                )

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(TIMER_ACCURACY)
            # Idle fast path: with no outstanding requests (the steady
            # state) the tick does no work at all — the old loop built
            # the broadcast address list and sorted an empty view every
            # TIMER_ACCURACY, forever, on every engine in the process.
            if not self._requests:
                continue
            now = self._clock()
            retries = self._expired_frontiers(now)
            if not retries:
                continue
            addresses = [
                a for _, a in self.committee.broadcast_addresses(self.name)
            ]
            for frontier in retries:
                log.debug("requesting sync for block %s (retry)", frontier)
                self.network.broadcast(
                    addresses, encode_sync_request(frontier, self.name)
                )

    #: how many expired frontiers to re-request per tick (see
    #: _expired_frontiers).
    RETRY_FRONTIERS = 3

    def _expired_frontiers(self, now: float) -> list[Digest]:
        """Expired requests worth re-broadcasting now, newest-first.

        Retry only the walk FRONTIERS (the newest few expired requests =
        the deepest missing ancestors): their chain replies (helpers
        serve ancestors in bulk) plus the notify_read unwind heal
        everything shallower. Rebroadcasting every outstanding request —
        one per missed round — floods the committee with O(gap)
        redeliveries per tick, which is exactly the storm that kept a
        straggler from ever catching up. A small K (not 1) covers
        independent missing chains (e.g. a fork from a view change) so
        none starves behind another's walk.

        Expiry judges the LAST send, not the first request: once a
        request aged past sync_retry_delay the old loop re-broadcast it
        on EVERY tick until it resolved — duplicate sync traffic the
        helpers then answered with duplicate chains. Each retry now
        re-arms the request for a full sync_retry_delay.
        """
        expired = sorted(
            (
                (self._requests[digest], digest)
                for digest, sent in self._last_sent.items()
                if sent + self.sync_retry_delay < now
            ),
            key=lambda e: e[0],
            reverse=True,
        )
        retries = [digest for _, digest in expired[: self.RETRY_FRONTIERS]]
        for digest in retries:
            self._last_sent[digest] = now
        return retries

    def note_floor(self, frontier: Block) -> None:
        """Adopt ``frontier`` as the truncation floor (restored from our
        own snapshot record, set by the compactor, or installed from a
        verified peer snapshot). Any outstanding request for its truncated
        parent can never be served — cancel it, and release ``frontier``
        itself from pending (the installer just materialized it)."""
        self._floor = frontier.digest()
        self._floor_round = frontier.round
        parent = frontier.parent()
        self._requests.pop(parent, None)
        self._last_sent.pop(parent, None)
        self._pending.discard(frontier.digest())
        # Cached ancestors strictly below the floor may no longer be in
        # the store — drop them so cache and store agree on what a walk
        # can reach (a cached block whose stored parent was truncated
        # would otherwise suspend on an unservable digest).
        for key in [
            k
            for k, b in self._ancestor_cache.items()
            if b.round < frontier.round
        ]:
            del self._ancestor_cache[key]

    def request_block(self, digest: Digest, address) -> None:
        """Directly solicit ``digest`` from the peer at ``address`` (the
        state-sync frontier pull). Registers it as requested — so the
        lenient-leader solicited-block rule admits the reply chain and the
        retry timer re-broadcasts on loss — and self-cleans once the block
        lands in the store."""
        if digest in self._requests:
            return
        log.debug("requesting state-sync frontier block %s", digest)
        telemetry.counter("consensus.sync_requests").inc()
        now = self._clock()
        self._requests[digest] = now
        self._last_sent[digest] = now
        if address is not None:
            self.network.send(address, encode_sync_request(digest, self.name))
        task = asyncio.create_task(self._request_waiter(digest))
        self._direct[digest] = task
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _request_waiter(self, digest: Digest) -> None:
        try:
            await self.store.notify_read(digest.data)
        finally:
            # Runs on fulfilment AND on cancel_request: either way the
            # request entries must not outlive the waiter (a cancelled
            # notify_read drops its store obligation in its own finally).
            self._direct.pop(digest, None)
            self._requests.pop(digest, None)
            self._last_sent.pop(digest, None)

    def cancel_request(self, digest: Digest) -> None:
        """Withdraw a direct pull that will never be served (e.g. a
        forged frontier digest from an unauthenticated state_response):
        releases the retry entries, the waiter task, and — through the
        waiter's cancellation — the store's notify_read obligation."""
        task = self._direct.pop(digest, None)
        if task is not None:
            task.cancel()
        self._requests.pop(digest, None)
        self._last_sent.pop(digest, None)

    def is_pending(self, digest: Digest) -> bool:
        """True if ``digest`` is a block already suspended awaiting its
        ancestors (chain-reply redeliveries skip re-verification)."""
        return digest in self._pending

    def requested(self, digest: Digest) -> bool:
        """True if ``digest`` is a block this node has actively asked a
        peer for (an outstanding sync request). Used by the lenient
        leader path: only solicited blocks may be stored from an
        unexpected author — they are certified-chain members by
        construction (we requested them as some received block's
        ancestor), so a byzantine member cannot grow the store with
        unsolicited fabrications."""
        return digest in self._requests

    # Recently-deserialized blocks, keyed by digest. Content-addressed
    # and immutable, so the cache can never go stale; it exists because
    # the steady-state commit path re-reads the SAME two ancestors it
    # processed one round ago (b1 of round r is block of round r-1) and
    # re-deserializing a 67-vote QC per read was a top-five CPU line of
    # the N=100 protocol bench.
    _ANCESTOR_CACHE_CAP = 128

    def cache_block(self, block: Block) -> None:
        """Offer a just-stored block to the ancestor cache (it is the
        parent the next round's commit walk will ask for)."""
        if len(self._ancestor_cache) >= self._ANCESTOR_CACHE_CAP:
            self._ancestor_cache.clear()
        self._ancestor_cache[block.digest().data] = block

    async def get_parent_block(self, block: Block) -> Block | None:
        """The parent if stored; None after scheduling a sync (reference
        ``synchronizer.rs:120-134``)."""
        if block.qc == QC.genesis():
            return Block.genesis()
        if self._floor is not None and block.digest() == self._floor:
            # ``block`` IS the truncation frontier: its ancestry is
            # truncated (here and at every peer past the horizon). Serve a
            # genesis placeholder — round 0 can never satisfy the 2-chain
            # commit rule, and the commit walk stops at
            # last_committed_round (>= the floor round) before reaching
            # it, so the placeholder is never committed.
            return Block.genesis()
        if self._floor_round and block.round <= self._floor_round:
            # Stale delivery at or below the horizon (a reordered or
            # byzantine replay of a long-committed round, or a fork
            # abandoned before the floor): its ancestry is truncated at
            # every honest peer, so suspending would park a request no
            # one can serve. Same placeholder argument as above — a
            # round this old can neither commit nor earn a vote.
            return Block.genesis()
        parent_digest = block.parent().data
        cached = self._ancestor_cache.get(parent_digest)
        if cached is not None:
            return cached
        data = await self.store.read(parent_digest)
        if data is not None:
            parent = Block.deserialize(data)
            if len(self._ancestor_cache) >= self._ANCESTOR_CACHE_CAP:
                self._ancestor_cache.clear()  # tiny working set; coarse GC
            self._ancestor_cache[parent_digest] = parent
            return parent
        self._suspend(block)
        return None

    async def get_ancestors(self, block: Block) -> tuple[Block, Block] | None:
        """(b0, b1) where b0 <- |qc0; b1| <- |qc1; block|, or None if the
        chain is incomplete (reference ``synchronizer.rs:136-149``)."""
        b1 = await self.get_parent_block(block)
        if b1 is None:
            return None
        b0 = await self.get_parent_block(b1)
        assert b0 is not None, "we should have all ancestors of delivered blocks"
        return (b0, b1)

    def shutdown(self) -> None:
        self._main.cancel()
        for t in self._tasks:
            t.cancel()
