"""Consensus block-ancestry synchronizer (reference
``consensus/src/synchronizer.rs``).

``get_parent_block`` reads the store or fires a ``SyncRequest`` to the block
author and suspends processing; an inner task waits on store ``notify_read``
and loops delivered blocks back to the Core. A coarse timer re-broadcasts
expired requests to all peers ("perfect point-to-point link",
``synchronizer.rs:84-105``).
"""

from __future__ import annotations

import asyncio
import logging
import time

from hotstuff_tpu.crypto import Digest, PublicKey
from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store

from .config import Committee
from .messages import Block, QC, encode_sync_request

log = logging.getLogger("consensus")

TIMER_ACCURACY = 5.0  # s (reference ``synchronizer.rs:22``)
CHANNEL_CAPACITY = 1_000


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        tx_loopback: asyncio.Queue,
        sync_retry_delay: int,
    ) -> None:
        self.name = name
        self.committee = committee
        self.store = store
        self.tx_loopback = tx_loopback
        self.sync_retry_delay = sync_retry_delay / 1000.0
        self.network = SimpleSender()
        self._inner: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        self._pending: set[Digest] = set()  # block digests being waited on
        self._requests: dict[Digest, float] = {}  # parent digest -> first-request ts
        self._tasks: set[asyncio.Task] = set()
        self._main = asyncio.create_task(self._run(), name="consensus_synchronizer")

    async def _waiter(self, wait_on: Digest, deliver: Block) -> None:
        await self.store.notify_read(wait_on.data)
        self._pending.discard(deliver.digest())
        self._requests.pop(deliver.parent(), None)
        await self.tx_loopback.put(("loopback", deliver))

    async def _run(self) -> None:
        get_block = asyncio.create_task(self._inner.get())
        timer = asyncio.create_task(asyncio.sleep(TIMER_ACCURACY))
        while True:
            done, _ = await asyncio.wait(
                {get_block, timer}, return_when=asyncio.FIRST_COMPLETED
            )
            if get_block in done:
                block: Block = get_block.result()
                get_block = asyncio.create_task(self._inner.get())
                digest = block.digest()
                if digest not in self._pending:
                    self._pending.add(digest)
                    parent = block.parent()
                    task = asyncio.create_task(self._waiter(parent, block))
                    self._tasks.add(task)
                    task.add_done_callback(self._tasks.discard)
                    if parent not in self._requests:
                        log.debug("requesting sync for block %s", parent)
                        self._requests[parent] = time.monotonic()
                        address = self.committee.address(block.author)
                        if address is not None:
                            self.network.send(
                                address, encode_sync_request(parent, self.name)
                            )
            if timer in done:
                timer = asyncio.create_task(asyncio.sleep(TIMER_ACCURACY))
                now = time.monotonic()
                addresses = [
                    a for _, a in self.committee.broadcast_addresses(self.name)
                ]
                for digest, ts in self._requests.items():
                    if ts + self.sync_retry_delay < now:
                        log.debug("requesting sync for block %s (retry)", digest)
                        self.network.broadcast(
                            addresses, encode_sync_request(digest, self.name)
                        )

    async def get_parent_block(self, block: Block) -> Block | None:
        """The parent if stored; None after scheduling a sync (reference
        ``synchronizer.rs:120-134``)."""
        if block.qc == QC.genesis():
            return Block.genesis()
        data = await self.store.read(block.parent().data)
        if data is not None:
            return Block.deserialize(data)
        await self._inner.put(block)
        return None

    async def get_ancestors(self, block: Block) -> tuple[Block, Block] | None:
        """(b0, b1) where b0 <- |qc0; b1| <- |qc1; block|, or None if the
        chain is incomplete (reference ``synchronizer.rs:136-149``)."""
        b1 = await self.get_parent_block(block)
        if b1 is None:
            return None
        b0 = await self.get_parent_block(b1)
        assert b0 is not None, "we should have all ancestors of delivered blocks"
        return (b0, b1)

    def shutdown(self) -> None:
        self._main.cancel()
        for t in self._tasks:
            t.cancel()
