"""Block proposer (reference ``consensus/src/proposer.rs``).

Owns the payload buffer fed by mempool digests. On ``Make(round, qc, tc)``
builds and signs a block draining the buffer, reliable-broadcasts it, loops
it back to the Core, then blocks until 2f+1 stake has ACKed — the leader's
back-pressure control system (``proposer.rs:105-121``).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass

from hotstuff_tpu import telemetry
from hotstuff_tpu.crypto import Digest, PublicKey, SignatureService
from hotstuff_tpu.network import ReliableSender

from .config import Committee, Round
from .messages import Block, QC, TC, encode_propose

log = logging.getLogger("consensus")


@dataclass
class Make:
    round: Round
    qc: QC
    tc: TC | None


@dataclass
class Cleanup:
    digests: list[Digest]


class Proposer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        signature_service: SignatureService,
        rx_mempool: asyncio.Queue,
        rx_message: asyncio.Queue,
        tx_loopback: asyncio.Queue,
        benchmark: bool = False,
        wire_seats=None,
    ) -> None:
        self.name = name
        self.committee = committee
        self.signature_service = signature_service
        self.rx_mempool = rx_mempool
        self.rx_message = rx_message
        self.tx_loopback = tx_loopback
        self.benchmark = benchmark
        # Wire-format v2 seat table for outgoing proposals (None = v1).
        self.wire_seats = wire_seats
        self.buffer: set[Digest] = set()
        self.network = ReliableSender()

    @classmethod
    def spawn(cls, *args, **kwargs) -> asyncio.Task:
        self = cls(*args, **kwargs)
        return asyncio.create_task(self._run(), name="proposer")

    async def _run(self) -> None:
        get_digest = asyncio.create_task(self.rx_mempool.get())
        get_message = asyncio.create_task(self.rx_message.get())
        while True:
            done, _ = await asyncio.wait(
                {get_digest, get_message}, return_when=asyncio.FIRST_COMPLETED
            )
            if get_digest in done:
                self.buffer.add(get_digest.result())
                # Greedy drain: on a CPU-saturated loop this task is
                # scheduled far less often than digests arrive (ingest
                # tasks are always runnable), and one-digest-per-turn
                # let the queue backlog while proposals went out nearly
                # empty — ordering starving behind ingest inside the
                # event loop, the exact inversion the data plane exists
                # to prevent. Take everything ready.
                while True:
                    try:
                        self.buffer.add(self.rx_mempool.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                get_digest = asyncio.create_task(self.rx_mempool.get())
            if get_message in done:
                message = get_message.result()
                get_message = asyncio.create_task(self.rx_message.get())
                if isinstance(message, Make):
                    await self._make_block(message.round, message.qc, message.tc)
                elif isinstance(message, Cleanup):
                    for d in message.digests:
                        self.buffer.discard(d)

    async def _make_block(self, round_: Round, qc: QC, tc: TC | None) -> None:
        payload = list(self.buffer)
        self.buffer.clear()
        if telemetry.enabled():
            # How much certified work each proposal drains, and how much
            # is still queued upstream — the first diagnostic when
            # ingest outruns ordering.
            telemetry.gauge("consensus.proposer.payload_drained").set(
                len(payload)
            )
            telemetry.gauge("consensus.proposer.digest_queue_depth").set(
                self.rx_mempool.qsize()
            )
        block = await Block.new(
            qc, tc, self.name, round_, payload, self.signature_service
        )
        if block.payload:
            log.info("Created %s", block)
            for d in block.payload:
                # Telemetry mirror of the "Created B -> d" measurement
                # contract (no-op unless telemetry is enabled).
                telemetry.record_created(d.data)
            if telemetry.dtrace_enabled():
                # Lifeline join point: each payload digest leaves the
                # queue-wait edge here, and the ``r<round>`` detail keys
                # the batch timeline onto the round trace's ordering
                # breakdown for this round.
                name_label = repr(self.name)
                for d in block.payload:
                    telemetry.dtrace_event(
                        name_label,
                        telemetry.intern_label(d.data),
                        "proposed",
                        detail=f"r{round_}",
                    )
            if self.benchmark:
                for d in block.payload:
                    # NOTE: benchmark measurement interface (reference
                    # ``proposer.rs:76-80``).
                    log.info("Created %s -> %s", block, d)
        log.debug("Broadcasting %r", block)
        # Cross-node trace anchor: the leader's broadcast instant is t=0
        # of the round's causal timeline (the propose_send→propose edge
        # at each replica is wire + receiver decode + core queue wait).
        # The detail names the author + block digest so stream analyzers
        # can attribute the round's proposal and spot conflicting blocks
        # (one extra digest hash per broadcast, leader-side only — and
        # only when telemetry is enabled).
        telemetry.trace_event(
            repr(self.name),
            round_,
            "propose_send",
            detail=(
                f"{self.name!r}|{block.digest()!r}"
                if telemetry.enabled()
                else None
            ),
        )

        serialized = encode_propose(block, self.wire_seats)
        names_addresses = self.committee.broadcast_addresses(self.name)
        handlers = [
            (name, await self.network.send(addr, serialized))
            for name, addr in names_addresses
        ]
        await self.tx_loopback.put(("loopback", block))

        # Control system: wait for 2f+1 stake to ACK before proposing again.
        from hotstuff_tpu.utils.quorum import cancel_remaining, wait_for_ack_quorum

        _, remaining = await wait_for_ack_quorum(
            handlers,
            self.committee.stake,
            self.committee.stake(self.name),
            self.committee.quorum_threshold(),
        )
        # The reference drops the remaining handlers here, cancelling their
        # retransmission — slow nodes catch up via the synchronizer instead.
        cancel_remaining(remaining)
