"""Consensus layer: 2-chain HotStuff (Jolteon/Diem-style) state-machine
replication core (reference ``consensus/src/``)."""

from .config import Authority, Committee, Parameters
from .consensus import Consensus
from .messages import QC, TC, Block, Timeout, Vote

__all__ = [
    "Authority",
    "Committee",
    "Parameters",
    "Consensus",
    "Block",
    "Vote",
    "QC",
    "TC",
    "Timeout",
]
