"""Consensus helper: serve peers' ``SyncRequest``s — read the block from the
store and reply with a full ``Propose`` message so it flows the requester's
normal proposal path (reference ``consensus/src/helper.rs:26-68``)."""

from __future__ import annotations

import asyncio
import logging
import time

from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store
from hotstuff_tpu.utils.tasks import log_task_death

from .config import Committee
from .messages import Block, encode_propose, encode_state_response
from .statesync import SNAPSHOT_KEY, SnapshotError, peek_frontier

log = logging.getLogger("consensus")


#: How many blocks a single SyncRequest reply may carry (the requested
#: block + ancestors, NEWEST first — see the send loop for why). A
#: straggler that missed a RANGE of blocks would otherwise walk backward
#: one block per round trip (request parent -> reply -> discover
#: grandparent missing -> request ...) — slower than a fast committee
#: extends the chain, i.e. it never catches up. With chain replies each
#: delivered ancestor suspends-and-requests the next synchronously, and
#: once the deepest lands on stored ground the notify_read unwind
#: re-delivers the whole suspended range: ~CHAIN_DEPTH rounds heal per
#: RTT. Sized as a compromise: the common request is ONE lost block (the
#: extra ancestors are redundant wire traffic, discarded by the
#: requester's redelivery short-circuit), while a deep catch-up iterates
#: frontier requests at one chain per RTT.
CHAIN_DEPTH = 16


class Helper:
    @classmethod
    def spawn(
        cls,
        committee: Committee,
        store: Store,
        rx_request: asyncio.Queue,
        sync_retry_delay: int = 5_000,
    ) -> asyncio.Task:
        network = SimpleSender()
        # Snapshot replies are heavy (two blocks + a 2f+1-signature QC)
        # and the request's origin field is unsigned and spoofable: an
        # attacker spraying unknown digests with a victim's origin would
        # otherwise have every helper amplify traffic at the victim. One
        # snapshot reply per origin per half retry window caps the
        # amplification at a trickle while never throttling an honest
        # straggler (its synchronizer re-asks at sync_retry cadence). The
        # map is bounded by committee size (unknown origins are rejected).
        snap_interval_s = sync_retry_delay / 2_000.0
        snap_last_sent: dict = {}

        async def run():
            while True:
                digest, origin = await rx_request.get()
                try:
                    address = committee.address(origin)
                    if address is None:
                        log.warning(
                            "received sync request from unknown node %s", origin
                        )
                        continue
                    data = await store.read(digest.data)
                    if data is not None:
                        block = Block.deserialize(data)
                        # Send the requested block plus up to
                        # CHAIN_DEPTH-1 ancestors, NEWEST FIRST: when
                        # the requester processes the requested block it
                        # suspends on the (missing) parent and registers
                        # a sync request for it synchronously — before
                        # the next reply frame is dequeued — so each
                        # successive ancestor arrives already solicited
                        # (the lenient leader path stores solicited
                        # blocks only). The deepest delivered ancestor
                        # lands on stored ground and the notify_read
                        # unwind then re-delivers the whole suspended
                        # range in order.
                        network.send(address, encode_propose(block))
                        cur = block
                        sent = 1
                        while sent < CHAIN_DEPTH:
                            pdata = await store.read(cur.parent().data)
                            if pdata is None:
                                break
                            cur = Block.deserialize(pdata)
                            network.send(address, encode_propose(cur))
                            sent += 1
                    else:
                        # Unservable digest — most likely truncated below
                        # our snapshot horizon. Answer with the snapshot
                        # record (frontier + 2-chain commit proof) so a
                        # cold joiner establishes a verified floor instead
                        # of re-requesting an unservable block forever.
                        # Rate-limited per origin (and checked BEFORE the
                        # meta read) so forged requests cost the server
                        # and the accused origin almost nothing.
                        now = time.monotonic()
                        last = snap_last_sent.get(origin)
                        if last is not None and now - last < snap_interval_s:
                            continue
                        snap = await store.read_meta(SNAPSHOT_KEY)
                        if snap is not None:
                            try:
                                round_, frontier = peek_frontier(snap)
                            except SnapshotError as e:
                                log.error("corrupt snapshot record: %s", e)
                            else:
                                snap_last_sent[origin] = now
                                network.send(
                                    address,
                                    encode_state_response(
                                        round_, frontier, snap
                                    ),
                                )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # One corrupt stored block (or store error) must not
                    # permanently kill the helper for all future requests.
                    log.error(
                        "failed to serve sync request for %s: %s", digest, e
                    )

        task = asyncio.create_task(run(), name="consensus_helper")
        task.add_done_callback(log_task_death)
        return task
