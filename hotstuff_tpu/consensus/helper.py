"""Consensus helper: serve peers' ``SyncRequest``s — read the block from the
store and reply with a full ``Propose`` message so it flows the requester's
normal proposal path (reference ``consensus/src/helper.rs:26-68``)."""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store
from hotstuff_tpu.utils.tasks import log_task_death

from .config import Committee
from .messages import Block, encode_propose

log = logging.getLogger("consensus")


class Helper:
    @classmethod
    def spawn(
        cls, committee: Committee, store: Store, rx_request: asyncio.Queue
    ) -> asyncio.Task:
        network = SimpleSender()

        async def run():
            while True:
                digest, origin = await rx_request.get()
                try:
                    address = committee.address(origin)
                    if address is None:
                        log.warning(
                            "received sync request from unknown node %s", origin
                        )
                        continue
                    data = await store.read(digest.data)
                    if data is not None:
                        block = Block.deserialize(data)
                        network.send(address, encode_propose(block))
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # One corrupt stored block (or store error) must not
                    # permanently kill the helper for all future requests.
                    log.error(
                        "failed to serve sync request for %s: %s", digest, e
                    )

        task = asyncio.create_task(run(), name="consensus_helper")
        task.add_done_callback(log_task_death)
        return task
