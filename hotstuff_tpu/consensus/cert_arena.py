"""Process-wide certificate-verdict arena: each distinct certificate is
fully verified once per process per committee.

Why this is legitimate where the opt-in ``crypto._VERIFY_MEMO`` is not:
the live superbatch plane (``crypto/batching.py``) already prices the N
in-process copies of one rebroadcast QC at ONE inner MSM whenever their
verify requests pool in a fused window — documented there as the big win
under contention. That dedup is *timing-dependent*: whether node 700's
copy fuses with node 3's depends on flush scheduling, so at N=1000 a
round pays one MSM or several for the same cert depending on jitter. For
AGGREGATE certificates (wire-v2 bitmap + packed signature buffer, one
fused RLC statement per cert — see ``crypto.backend_verify_cert``) this
arena makes that existing cross-node dedup deterministic: the first
verifier pays the MSM, every later in-process arrival of the same cert
under the same committee hits the arena. It also models the deployment
the paper's linear-authenticator direction targets: with a threshold/
aggregate authenticator each replica verifies ONE aggregate check per
cert, so the per-replica cost the testbed skips on a hit is the O(1)
aggregate check, not 2f+1 per-signature verifications. The committed
benchmark rows name the configuration; ``HOTSTUFF_CERT_ARENA=0`` is the
kill-switch for A/B runs where every node must pay its own verify (the
equivalence tests run both ways).

Success-only: failed certs are NOT cached — a byzantine cert re-raises on
every arrival, byte-for-byte the per-node behavior (and the per-node
``CertificateCache`` never caches failures either). Keyed by
(committee fingerprint, canonical cert key): the same bytes verified
under different committees (tests) must not alias, and the canonical key
is shared across wire formats so a v1 and v2 copy of one cert hit the
same entry.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict

from hotstuff_tpu import telemetry


def enabled() -> bool:
    """Read per call so tests and operators can flip the switch live."""
    return os.environ.get("HOTSTUFF_CERT_ARENA", "1") != "0"


def committee_fp(committee) -> bytes:
    """Stable fingerprint of a committee's verification-relevant state:
    sorted (key, stake) pairs plus the quorum threshold. Memoized on the
    committee object — membership is fixed per epoch (parity with the
    reference's static committees)."""
    fp = getattr(committee, "_cert_arena_fp", None)
    if fp is None:
        h = hashlib.sha256()
        for pk in sorted(committee.authorities):
            h.update(pk.data)
            h.update(committee.authorities[pk].stake.to_bytes(8, "little"))
        h.update(committee.quorum_threshold().to_bytes(8, "little"))
        fp = h.digest()
        try:
            committee._cert_arena_fp = fp
        except AttributeError:
            pass  # slotted/frozen committee variants just re-hash
    return fp


class CertArena:
    """Bounded LRU of successfully-verified certificate identities."""

    def __init__(self, cap: int = 8192) -> None:
        self.cap = cap
        self.hits = 0
        self.misses = 0
        self._seen: "OrderedDict[tuple, None]" = OrderedDict()
        # hit()/add() run on crypto worker threads from every engine.
        self._lock = threading.Lock()
        self._m_hits = telemetry.counter("consensus.cert_arena.hits")
        self._m_misses = telemetry.counter("consensus.cert_arena.misses")

    def hit(self, key: tuple) -> bool:
        with self._lock:
            if key in self._seen:
                self._seen.move_to_end(key)
                self.hits += 1
                self._m_hits.inc()
                return True
            self.misses += 1
            self._m_misses.inc()
            return False

    def add(self, key: tuple) -> None:
        with self._lock:
            self._seen[key] = None
            self._seen.move_to_end(key)
            while len(self._seen) > self.cap:
                self._seen.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._seen.clear()


_ARENA: CertArena | None = None
_ARENA_LOCK = threading.Lock()


def get_arena() -> CertArena | None:
    """The process singleton, or None when disabled."""
    if not enabled():
        return None
    global _ARENA
    if _ARENA is None:
        with _ARENA_LOCK:
            if _ARENA is None:
                _ARENA = CertArena()
    return _ARENA


def reset() -> None:
    """Drop the singleton (tests: isolate arena state between cases)."""
    global _ARENA
    with _ARENA_LOCK:
        _ARENA = None
