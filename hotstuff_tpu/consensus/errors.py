"""Consensus error types (reference ``consensus/src/error.rs:25-65``)."""

from __future__ import annotations


class ConsensusError(Exception):
    pass


class WrongLeader(ConsensusError):
    pass


class UnknownAuthority(ConsensusError):
    pass


class AuthorityReuse(ConsensusError):
    pass


class QCRequiresQuorum(ConsensusError):
    pass


class TCRequiresQuorum(ConsensusError):
    pass


class InvalidSignature(ConsensusError):
    pass


class MalformedMessage(ConsensusError):
    pass
