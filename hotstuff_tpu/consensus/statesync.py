"""Lazarus: replica state sync + snapshot/truncate log compaction.

Two cooperating pieces, both driven by the Core's event loop (no new tasks,
so the sans-io simulation plane runs them unmodified):

**StateSync** — anti-entropy catch-up for cold-joining or lagging replicas.
A node whose commit frontier stops advancing probes peers (one per tick,
rotating, at ``sync_retry_delay`` cadence) with a ``state_request`` carrying
its own committed round. Peers answer with their commit frontier and — when
the requester is below their truncation horizon — their snapshot record.
The joiner verifies the snapshot's 2-chain commit proof through the normal
batch crypto path BEFORE adopting anything, installs the frontier as a
verified floor, then pulls the remaining suffix through the ordinary
Synchronizer/Helper chain machinery. Once commits flow, the probe loop goes
dormant: a healthy committee pays one queue event per ``sync_retry_delay``.

**Compactor** — snapshot + truncate. Every ``retention_rounds`` of commit
progress it selects a frontier block ``F`` about ``retention_rounds`` behind
the commit head such that the committed chain contains ``c1`` with
``c1.round == F.round + 1`` (the 2-chain commit pattern), writes a snapshot
record ``(F, c1, cert)`` — where ``c1.qc`` certifies ``F`` and ``cert`` is
the QC certifying ``c1`` — durably to the MetaLog, then rewrites
``store.log`` dropping every block (and its payload batch keys) strictly
below ``F``. Store growth is thereby bounded by retention depth, not
uptime.

Why the proof is sound against byzantine servers: ``c1.qc`` certifies
``F``'s digest at ``F``'s round, ``cert`` certifies ``c1`` at the NEXT
round — exactly the consecutive-round 2-chain that commits ``F``. Both QCs
carry 2f+1 signatures over content that binds the full chain topology, so a
byzantine peer cannot present a certified-but-abandoned fork block as a
committed frontier: no such block ever collects the consecutive-round
child certificate.

The record carries NOTHING outside that certified content. Every field a
joiner adopts (commit floor, high QC, last-voted floor) derives from the
two blocks and two QCs the proof covers — an earlier draft carried the
creator's ``last_voted_round`` as a voting-state hint, but the hint was
certified by neither QC, so a byzantine peer could attach ``2^64-1`` to an
otherwise-valid record and permanently mute any honest installer (it would
never satisfy ``block.round > last_voted_round`` again, surviving restarts
via the persisted state). Unauthenticated hints must never be adopted.

Crash discipline: the snapshot record is fsynced BEFORE the log rewrite
(a crash between them restarts with the floor known and the old log
intact); the rewrite itself is tmp + fsync + ``os.replace`` (see
``LogEngine.compact``), so a crash at any point yields one complete log.
"""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu import telemetry
from hotstuff_tpu.crypto import Digest, PublicKey
from hotstuff_tpu.store import StoreError
from hotstuff_tpu.utils.serde import Decoder, Encoder, SerdeError

from .config import Committee
from .crypto_bridge import verify_off_loop
from .errors import ConsensusError
from .messages import (
    QC,
    Block,
    encode_state_request,
    encode_state_response,
)

log = logging.getLogger("consensus")

#: MetaLog key of the snapshot record (overwrite semantics, never in the
#: data log — so truncation can never drop its own floor record).
SNAPSHOT_KEY = b"__store_snapshot__"

# v2 dropped the trailing ``last_voted_round`` hint: it was certified by
# neither QC, so adopting it let a byzantine record mute an honest joiner.
_SNAPSHOT_VERSION = 2


class SnapshotError(ConsensusError):
    """Malformed or unproven snapshot record (byzantine or corrupt)."""


class Snapshot:
    """Decoded snapshot record: frontier ``F``, its consecutive-round child
    ``c1`` (whose ``qc`` certifies ``F``), and ``cert`` — the QC certifying
    ``c1``."""

    __slots__ = ("frontier", "child", "cert")

    def __init__(self, frontier: Block, child: Block, cert: QC) -> None:
        self.frontier = frontier
        self.child = child
        self.cert = cert

    def __repr__(self) -> str:
        return f"Snapshot(F=r{self.frontier.round}, c1=r{self.child.round})"


def encode_snapshot(frontier: Block, child: Block, cert: QC) -> bytes:
    # The frontier (round, digest) leads the record so servers can answer
    # probes from it without deserializing two blocks (see peek_frontier).
    enc = Encoder()
    enc.u8(_SNAPSHOT_VERSION)
    enc.u64(frontier.round).raw(frontier.digest().data)
    enc.bytes(frontier.serialize()).bytes(child.serialize())
    cert.encode(enc)
    return enc.finish()


def peek_frontier(data: bytes) -> tuple[int, Digest]:
    """Frontier (round, digest) from a snapshot record's fixed header —
    the cheap read the Helper/probe-serving paths use."""
    dec = Decoder(data)
    if dec.u8() != _SNAPSHOT_VERSION:
        raise SnapshotError("unknown snapshot version")
    return dec.u64(), Digest(dec.raw(32))


def decode_snapshot(data: bytes) -> Snapshot:
    """Decode + structural validation (topology, no crypto). Raises
    ``SnapshotError`` on any inconsistency — the record is untrusted
    until ``verify_snapshot`` additionally checks both certificates."""
    try:
        dec = Decoder(data)
        if dec.u8() != _SNAPSHOT_VERSION:
            raise SnapshotError("unknown snapshot version")
        frontier_round = dec.u64()
        frontier_digest = Digest(dec.raw(32))
        frontier = Block.deserialize(dec.bytes())
        child = Block.deserialize(dec.bytes())
        cert = QC.decode(dec)
        dec.finish()
    except (SerdeError, ValueError) as e:
        raise SnapshotError(f"malformed snapshot record: {e}") from e
    if frontier.round < 1:
        raise SnapshotError("snapshot frontier at genesis")
    if frontier.round != frontier_round or frontier.digest() != frontier_digest:
        raise SnapshotError("snapshot header does not match frontier block")
    if child.qc.hash != frontier.digest() or child.qc.round != frontier.round:
        raise SnapshotError("child certificate does not certify frontier")
    if child.round != frontier.round + 1:
        raise SnapshotError("child is not the frontier's consecutive round")
    if cert.hash != child.digest() or cert.round != child.round:
        raise SnapshotError("cert does not certify child")
    return Snapshot(frontier, child, cert)


async def verify_snapshot(snap: Snapshot, committee: Committee, cache=None) -> None:
    """Verify the 2-chain commit proof's certificates (2×(2f+1) signatures,
    batched off-loop through the same path QCs on the hot path use).
    Raises ``ConsensusError`` if either certificate is invalid."""
    for qc in (snap.child.qc, snap.cert):
        if cache is not None:
            await verify_off_loop(qc.verify, committee, cache, n_sigs=qc.n_votes())
        else:
            await verify_off_loop(qc.verify, committee, n_sigs=qc.n_votes())


class StateSync:
    """Anti-entropy protocol driver. Bound to a Core at ``start`` and fed
    by its event loop (``state_request`` / ``state_response`` /
    ``statesync_tick`` events); all scheduling goes through the Core's
    ``_call_later`` seam, so the simulation plane drives this class on the
    virtual clock without modification."""

    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        sync_retry_delay: int,
        active: bool = True,
    ) -> None:
        self.name = name
        self.committee = committee
        self.tick_delay_s = sync_retry_delay / 1000.0
        #: probe loop armed (real nodes: yes; opt-in in simulation so
        #: committed sweep seeds keep byte-identical event streams).
        self.active = active
        self._core = None
        self._peers = [pk for pk, _ in committee.broadcast_addresses(name)]
        self._next_peer = 0
        self._last_seen_commit = -1
        # At most ONE direct frontier pull in flight (see _request_frontier):
        # frontier claims in state_responses are unauthenticated, so each
        # must be bounded in what it can allocate.
        self._pull: Digest | None = None
        self._pull_ticks = 0
        # Per-origin tick index of the last snapshot reply (server side):
        # snapshot records are heavy (two blocks + a 2f+1-signature QC), so
        # replies are rate-limited to the probe cadence per origin.
        self._snap_served: dict[PublicKey, int] = {}
        self._tick_no = 0
        self._g_active = telemetry.gauge("statesync.active")
        self._g_gap = telemetry.gauge("statesync.frontier_gap")
        self._m_probes = telemetry.counter("statesync.probes_sent")
        self._m_installed = telemetry.counter("statesync.snapshots_installed")

    # -- lifecycle ----------------------------------------------------------

    async def start(self, core) -> None:
        """Called from the Core's run preamble (after ``_restore_state``):
        restore the truncation floor from our own snapshot record, then arm
        the probe loop."""
        self._core = core
        data = await core.store.read_meta(SNAPSHOT_KEY)
        if data is not None:
            try:
                snap = decode_snapshot(data)
            except SnapshotError as e:
                # Our own record should never be malformed; a torn MetaLog
                # tail was truncated on replay, so this is disk corruption.
                # Run without a floor (the store may still be complete).
                log.error("ignoring corrupt local snapshot record: %s", e)
            else:
                core.synchronizer.note_floor(snap.frontier)
                # A wipe survivor restarting on a truncated store may have
                # a consensus-state record older than the snapshot (or the
                # commit walk would dip below the floor): adopt the floor.
                if snap.frontier.round > core.last_committed_round:
                    core.last_committed_round = snap.frontier.round
                    core._last_committed_digest = snap.frontier.digest()
                core.increase_last_voted_round(snap.child.round)
                core.update_high_qc(snap.cert)
                if core.round <= snap.cert.round:
                    core.round = snap.cert.round + 1
        if self.active:
            self._schedule_tick()

    def _schedule_tick(self) -> None:
        self._core._call_later(self.tick_delay_s, ("statesync_tick", None))

    # -- probe loop (requester side) -----------------------------------------

    #: Ticks before an unresolved direct frontier pull is presumed bogus
    #: and cancelled (the retry timer got >= 2 full-committee rebroadcast
    #: windows by then; a servable block resolves far sooner).
    PULL_TTL_TICKS = 3

    async def handle_tick(self, _payload=None) -> None:
        core = self._core
        self._tick_no += 1
        if self._pull is not None:
            if not core.synchronizer.requested(self._pull):
                self._pull = None  # resolved: the slot frees
            else:
                self._pull_ticks += 1
                if self._pull_ticks >= self.PULL_TTL_TICKS:
                    # An unauthenticated frontier claim pointed us at a
                    # digest no peer serves: evict it (cancelling releases
                    # the request entries, the store obligation, and the
                    # waiter task) so state sync cannot wedge on it and a
                    # byzantine stream cannot accumulate state.
                    core.synchronizer.cancel_request(self._pull)
                    self._pull = None
        if core.last_committed_round > self._last_seen_commit:
            # Commits progressed since the last tick: dormant. (An idle
            # committee still advances rounds and commits empty blocks, so
            # a healthy node never probes.)
            self._last_seen_commit = core.last_committed_round
            self._g_active.set(0)
        else:
            self._g_active.set(1)
            self._probe()
        self._schedule_tick()

    def _probe(self) -> None:
        """One frontier probe per tick, rotating through peers so a single
        slow/dead peer cannot stall catch-up."""
        if not self._peers:
            return
        pk = self._peers[self._next_peer % len(self._peers)]
        self._next_peer += 1
        address = self.committee.address(pk)
        if address is None:
            return
        self._m_probes.inc()
        log.debug("statesync probe -> %s (committed r%d)",
                  pk, self._core.last_committed_round)
        self._core.network.send(
            address,
            encode_state_request(self._core.last_committed_round, self.name),
        )

    # -- server side ---------------------------------------------------------

    async def handle_state_request(self, payload) -> None:
        since_round, origin = payload
        core = self._core
        address = self.committee.address(origin)
        if address is None:
            log.warning("state request from unknown node %s", origin)
            return
        digest = core._last_committed_digest
        if digest is None:
            return  # nothing committed yet: nothing to serve
        snapshot = None
        # The origin field is unsigned and spoofable, and the snapshot
        # record is heavy (two blocks + a 2f+1-signature QC): rate-limit
        # snapshot attachment per claimed origin to the probe cadence so a
        # spray of forged requests cannot amplify traffic at a victim.
        # Honest joiners probe each peer at most once per rotation of the
        # tick loop, so this never throttles a real catch-up. The map is
        # bounded by committee size (unknown origins returned above).
        if self._snap_served.get(origin) != self._tick_no:
            data = await core.store.read_meta(SNAPSHOT_KEY)
            if data is not None:
                try:
                    snap_round, _ = peek_frontier(data)
                except SnapshotError:
                    snap_round = None
                # Below our truncation horizon the requester can never heal
                # by chain replay from us — attach the snapshot so it can
                # establish a floor. (At or above the horizon the ordinary
                # chain machinery serves everything; skip the heavy record.)
                if snap_round is not None and since_round < snap_round:
                    snapshot = data
                    self._snap_served[origin] = self._tick_no
        core.network.send(
            address,
            encode_state_response(core.last_committed_round, digest, snapshot),
        )

    # -- requester side -------------------------------------------------------

    async def handle_state_response(self, payload) -> None:
        frontier_round, frontier_digest, snapshot = payload
        core = self._core
        gap = frontier_round - core.last_committed_round
        self._g_gap.set(max(0, gap))
        if gap <= 0:
            return  # we are at or past this peer's frontier
        if snapshot is not None:
            try:
                snap = decode_snapshot(snapshot)
            except SnapshotError as e:
                log.warning("rejecting snapshot from peer: %s", e)
                return
            if snap.frontier.round > core.last_committed_round:
                # Raises into _guarded on a byzantine proof — NOTHING is
                # adopted before both certificates verify.
                await verify_snapshot(snap, self.committee, core._cert_cache)
                await self._install(snap, snapshot)
        # Pull the suffix between our (possibly just-raised) frontier and
        # the peer's through the normal sync machinery: the helper answers
        # with ancestor chains, and the suspend/unwind walk heals up to
        # the live window, where ordinary proposals take over.
        if frontier_round > core.last_committed_round:
            self._request_frontier(frontier_digest)

    def _request_frontier(self, digest: Digest) -> None:
        """Solicit the claimed frontier block — at most ONE such direct
        pull in flight. The (round, digest) claim in a state_response is
        unauthenticated, so an unbounded pull per response would let a
        byzantine peer grow a request entry, a store obligation, and a
        waiter task per forged digest, forever. One slot, freed on
        resolution or evicted after ``PULL_TTL_TICKS`` (see handle_tick),
        bounds the damage to O(1); honest catch-up needs only one frontier
        walk at a time anyway."""
        sync = self._core.synchronizer
        if self._pull is not None and sync.requested(self._pull):
            return  # slot busy: the retry timer is still driving it
        pk = self._peers[self._next_peer % len(self._peers)] if self._peers else None
        address = self.committee.address(pk) if pk is not None else None
        self._pull = digest
        self._pull_ticks = 0
        sync.request_block(digest, address)

    async def _install(self, snap: Snapshot, raw: bytes) -> None:
        """Adopt a VERIFIED snapshot: persist the floor record first
        (fsync — a crash right after must restart knowing the floor), then
        materialize F and c1 so suspended chain walks unwind onto them."""
        core = self._core
        log.info(
            "installing snapshot: frontier r%d (was r%d)",
            snap.frontier.round,
            core.last_committed_round,
        )
        self._m_installed.inc()
        await core.store.write_meta(SNAPSHOT_KEY, raw, sync=True)
        core.synchronizer.note_floor(snap.frontier)
        core.last_committed_round = max(
            core.last_committed_round, snap.frontier.round
        )
        core._last_committed_digest = snap.frontier.digest()
        # Never vote at or below the adopted window — but raise the floor
        # ONLY to what the certificates prove (c1's round). Rounds above
        # that are unproven by this record, and adopting any unauthenticated
        # hint here would let a byzantine snapshot mute this node forever.
        core.increase_last_voted_round(snap.child.round)
        await core.process_qc(snap.cert)  # adopt high_qc, enter cert.round+1
        await core._persist_state()
        # Writing F releases notify_read waiters of blocks suspended on it
        # — do it AFTER the consensus state above is consistent.
        await core.store.write(snap.frontier.digest().data, snap.frontier.serialize())
        await core.store.write(snap.child.digest().data, snap.child.serialize())
        core.synchronizer.cache_block(snap.frontier)
        core.synchronizer.cache_block(snap.child)


class Compactor:
    """Snapshot + truncate driver. ``note_commit`` tracks the commit head;
    ``maybe_compact`` fires once the head is ``2 × retention_rounds`` past
    the previous snapshot (hysteresis: each rewrite costs a full log copy,
    so truncate in retention-sized steps, not per round)."""

    def __init__(self, store, retention_rounds: int) -> None:
        self.store = store
        self.retention = retention_rounds
        self._snapshot_round = 0
        self._head: Block | None = None
        self._rewrite_task = None  # in-flight background log rewrite
        self._m_compactions = telemetry.counter("store.compactions")
        self._m_freed = telemetry.counter("store.compaction_bytes_freed")
        self._g_snapshot_round = telemetry.gauge("store.snapshot_round")

    def note_commit(self, block: Block) -> None:
        if self._head is None or block.round > self._head.round:
            self._head = block

    async def _read_parent(self, block: Block) -> Block | None:
        if block.qc == QC.genesis():
            return None
        data = await self.store.read(block.parent().data)
        if data is None:
            return None  # previous truncation floor (or genesis)
        return Block.deserialize(data)

    async def maybe_compact(self, core) -> None:
        if self.retention <= 0 or self._head is None:
            return
        if self._rewrite_task is not None and not self._rewrite_task.done():
            return  # previous rewrite still running off-loop
        if core.last_committed_round - self._snapshot_round < 2 * self.retention:
            return
        target = core.last_committed_round - self.retention
        # Walk the committed chain head -> tail for the newest proof pair
        # (F, c1) with consecutive rounds at or below the target. `above`
        # is c1's chain child: its qc is the certificate committing c1.
        above: Block | None = None
        child = self._head
        parent = await self._read_parent(child)
        while parent is not None:
            if (
                parent.round <= target
                and above is not None
                and child.round == parent.round + 1
            ):
                break
            above, child, parent = child, parent, await self._read_parent(parent)
        else:
            return  # no consecutive-round pair in reach — retry next commit
        frontier, c1, cert = parent, child, above.qc
        snapshot = encode_snapshot(frontier, c1, cert)
        # Floor record FIRST, durably: a crash between this write and the
        # log rewrite restarts with the floor known and the old log whole.
        await self.store.write_meta(SNAPSHOT_KEY, snapshot, sync=True)
        # Drop set: every block strictly below F back to the previous
        # floor, plus their payload batch keys (committed long ago; peers
        # below the horizon catch up by snapshot, not batch replay).
        drop: list[bytes] = []
        cur = await self._read_parent(frontier)
        while cur is not None:
            drop.append(cur.digest().data)
            for d in cur.payload:
                drop.append(d.data)
            cur = await self._read_parent(cur)
        # The floor is durable and the drop set is walked: adopt the
        # snapshot NOW — the log rewrite only reclaims space and must not
        # hold up the commit path (store engines run the bulk copy on an
        # executor; see Store.compact). On the real plane it runs as a
        # background task so this node keeps voting while the file is
        # rewritten; the sim plane (MemEngine, no executor, no tasks)
        # compacts inline, which is a dict pop there.
        self._snapshot_round = frontier.round
        core.synchronizer.note_floor(frontier)
        self._g_snapshot_round.set(frontier.round)

        async def _rewrite() -> None:
            try:
                freed = await self.store.compact(drop)
            except (StoreError, OSError) as e:
                # The old log stays live (engines restore their append
                # handle on every failure path); space is reclaimed at
                # the next trigger.
                log.error("log compaction failed (will retry): %s", e)
                return
            self._m_compactions.inc()
            self._m_freed.inc(freed)
            log.info(
                "snapshot at r%d: dropped %d keys below the floor, "
                "freed %d bytes",
                frontier.round,
                len(drop),
                freed,
            )

        if self.store.compaction_offloaded():
            self._rewrite_task = asyncio.create_task(
                _rewrite(), name="store_compaction"
            )
        else:
            await _rewrite()

    async def drain(self) -> None:
        """Wait for an in-flight background rewrite (tests, shutdown —
        the store must not be closed under a live rewrite thread)."""
        if self._rewrite_task is not None:
            await self._rewrite_task
            self._rewrite_task = None
