"""Consensus root: wires the consensus actors and the network receiver
(reference ``consensus/src/consensus.rs:45-162``).

Routing: ``SyncRequest`` -> Helper; ``Propose`` is ACKed then sent to the
Core; ``Vote``/``Timeout``/``TC`` go straight to the Core.
"""

from __future__ import annotations

import asyncio
import logging
import os

from hotstuff_tpu.crypto import PublicKey, SignatureService
from hotstuff_tpu.network import MessageHandler, Receiver
from hotstuff_tpu.store import Store
from hotstuff_tpu.telemetry import profiler as pyprof
from hotstuff_tpu.utils.serde import SerdeError

from .config import Committee, Parameters
from .core import Core
from .decode_arena import decode_shared
from .errors import MalformedMessage
from .helper import Helper
from .leader import make_elector
from .mempool_driver import MempoolDriver
from .messages import SeatTable, decode_vote_frame
from .proposer import Proposer
from .statesync import Compactor, StateSync
from .synchronizer import Synchronizer

log = logging.getLogger("consensus")

CHANNEL_CAPACITY = 1_000


class ConsensusReceiverHandler(MessageHandler):
    def __init__(
        self,
        tx_consensus: asyncio.Queue,
        tx_helper: asyncio.Queue,
        seats: SeatTable | None = None,
    ) -> None:
        self.tx_consensus = tx_consensus
        self.tx_helper = tx_helper
        # Seat table for wire-format v2 certificate sections. Decoding
        # accepts BOTH formats whenever the table is known — acceptance
        # is not what the wire_v2 parameter gates (that only selects what
        # we emit), so a mixed v1/v2 committee interoperates.
        self.seats = seats

    async def dispatch(self, writer, serialized: bytes) -> None:
        if pyprof.TAGGING:
            # Message decode is the function-level heart of the trace's
            # ingress edge (a proposal decode parses a 2f+1-sig QC); tag
            # it so the sampler blames decode frames on ingress.
            pyprof.set_thread_stage("ingress")
        try:
            # Shared decode arena: a broadcast frame (proposal/timeout/
            # TC) fanned to N in-process engines — or retransmitted
            # byte-identically during a view change — parses once
            # process-wide; every other arrival is a content-addressed
            # hit handing back the same immutable decoded view.
            kind, payload = decode_shared(serialized, self.seats)
        except (SerdeError, MalformedMessage, ValueError) as e:
            log.warning("failed to decode consensus message: %s", e)
            return
        if kind == "sync_request":
            await self.tx_helper.put(payload)
        elif kind == "propose":
            # ACK proposals — the leader's back-pressure signal (reference
            # ``consensus.rs:144-153``).
            await writer.send(b"Ack")
            await self.tx_consensus.put((kind, payload))
        else:
            await self.tx_consensus.put((kind, payload))

    async def dispatch_votes(self, frames: list[bytes]) -> None:
        """Aggregated ingress from the native vote pre-stage: one queue
        put for the whole batch (the core re-checks round/authority and
        performs the full signature verification — the pre-stage is a
        filter, never a trust root)."""
        if pyprof.TAGGING:
            pyprof.set_thread_stage("fanin")
        votes = []
        for frame in frames:
            try:
                votes.append(decode_vote_frame(frame))
            except (SerdeError, MalformedMessage, ValueError) as e:
                log.warning("failed to decode pre-staged vote: %s", e)
        if votes:
            await self.tx_consensus.put(("votes", votes))


class Consensus:
    def __init__(self) -> None:
        self.tasks: list[asyncio.Task] = []
        self.receivers: list[Receiver] = []
        self.synchronizer: Synchronizer | None = None
        self.mempool_driver: MempoolDriver | None = None
        self.compactor = None

    @classmethod
    async def spawn(
        cls,
        name: PublicKey,
        committee: Committee,
        parameters: Parameters,
        signature_service: SignatureService,
        store: Store,
        rx_mempool: asyncio.Queue,  # batch digests from mempool
        tx_mempool: asyncio.Queue,  # Synchronize/Cleanup to mempool
        tx_commit: asyncio.Queue,  # committed blocks out
        benchmark: bool = False,
        profile: bool = False,  # per-stage ns counters -> telemetry registry
    ) -> "Consensus":
        self = cls()
        parameters.log()

        tx_consensus: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        # Loopback blocks ride the SAME merged queue as network messages
        # (tagged ("loopback", block)) — the core consumes one queue.
        tx_loopback: asyncio.Queue = tx_consensus
        tx_proposer: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        tx_helper: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)

        seats = SeatTable.for_committee(committee)
        # wire_v2 selects what WE emit; decode always accepts both (the
        # seat table above), so flipping this per node cannot split a
        # committee — that is the whole negotiation story.
        wire_seats = (
            seats
            if parameters.wire_v2
            and os.environ.get("HOTSTUFF_WIRE_V2", "1") != "0"
            else None
        )

        address = committee.address(name)
        assert address is not None, "our public key is not in the committee"
        # auto_ack: the transport ACKs on frame arrival — the leader's
        # back-pressure signal means "received" (exactly what the
        # handler's first-line ACK meant) without waiting for this
        # process to be scheduled. Non-proposal messages arrive via
        # SimpleSender, which discards replies, so the extra ACK frames
        # are harmless.
        receiver = await Receiver.spawn(
            ("0.0.0.0", address[1]),
            ConsensusReceiverHandler(tx_consensus, tx_helper, seats),
            auto_ack=True,
        )
        self.receivers.append(receiver)
        log.info("Node %s listening to consensus messages on %s", name, address)

        # Native transport: push the committee table down so the vote
        # fan-in stays in C++ (length-validate, seat-check, round-gate,
        # dedupe, batch) and keep the engine's stale-round cutoff synced
        # with the core's round. The asyncio receiver has neither hook —
        # votes then flow per-frame through dispatch() exactly as before.
        on_round_advance = None
        configure_prestage = getattr(receiver, "configure_vote_prestage", None)
        if configure_prestage is not None:
            configure_prestage([pk.data for pk in committee.authorities])
            on_round_advance = receiver.set_round

        leader_elector = make_elector(committee, parameters.leader_elector)
        self.mempool_driver = MempoolDriver(store, tx_mempool, tx_loopback)
        self.synchronizer = Synchronizer(
            name, committee, store, tx_loopback, parameters.sync_retry_delay
        )
        # Lazarus replica lifecycle: every real node answers state probes
        # and runs the (dormant-while-healthy) anti-entropy tick; the
        # compactor arms only when a retention depth is configured.
        statesync = StateSync(name, committee, parameters.sync_retry_delay)
        self.compactor = compactor = (
            Compactor(store, parameters.retention_rounds)
            if parameters.retention_rounds > 0
            else None
        )

        self.tasks.append(
            Core.spawn(
                name,
                committee,
                signature_service,
                store,
                leader_elector,
                self.mempool_driver,
                self.synchronizer,
                parameters.timeout_delay,
                tx_consensus,
                tx_loopback,
                tx_proposer,
                tx_commit,
                benchmark=benchmark,
                persist_sync=parameters.persist_sync,
                batch_vote_verification=parameters.batch_vote_verification,
                on_round_advance=on_round_advance,
                profile=profile,
                wire_seats=wire_seats,
                statesync=statesync,
                compactor=compactor,
            )
        )
        self.tasks.append(
            Proposer.spawn(
                name,
                committee,
                signature_service,
                rx_mempool,
                tx_proposer,
                tx_loopback,
                benchmark=benchmark,
                wire_seats=wire_seats,
            )
        )
        self.tasks.append(
            Helper.spawn(
                committee, store, tx_helper, parameters.sync_retry_delay
            )
        )
        return self

    async def shutdown(self) -> None:
        # Let an in-flight background log rewrite finish before the store
        # is closed underneath its executor thread.
        if self.compactor is not None:
            await self.compactor.drain()
        for t in self.tasks:
            t.cancel()
        if self.synchronizer is not None:
            self.synchronizer.shutdown()
        if self.mempool_driver is not None:
            self.mempool_driver.shutdown()
        for r in self.receivers:
            await r.shutdown()
