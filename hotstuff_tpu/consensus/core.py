"""The 2-chain HotStuff protocol state machine (reference
``consensus/src/core.rs``).

State: round, last_voted_round, last_committed_round, high_qc, timer,
aggregator. Voting safety rules (``core.rs:99-116``):

- rule 1: ``block.round > last_voted_round``
- rule 2: ``block.qc.round + 1 == block.round`` OR the block extends a TC
  (``tc.round + 1 == block.round`` and ``block.qc.round >= max(tc.high_qc_rounds)``)

2-chain commit rule (``core.rs:331-336``): when ``b0.round + 1 == b1.round``
for the chain ``b0 <- |qc0; b1| <- |qc1; block|``, commit ``b0`` and all its
uncommitted ancestors.

Crash-safety improvement over the reference: the voting state
(``last_voted_round``, ``round``, ``high_qc``) is persisted (bounded
atomic-replace record, no log growth) before each vote/timeout signature,
fixing the reference's acknowledged unsafe-recovery TODO (``core.rs:114``,
issue #15) for process crashes. Power/kernel-crash durability additionally
requires ``Parameters.persist_sync`` (fsync per state update — slower).
"""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry import profiler as pyprof
from hotstuff_tpu.crypto import PublicKey, SignatureService
from hotstuff_tpu.faultline import hooks as _faultline
from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store, StoreError
from hotstuff_tpu.utils.serde import Decoder, Encoder, SerdeError
from hotstuff_tpu.utils.tasks import log_task_death

from hotstuff_tpu.crypto import BackendUnavailable, CryptoError

from .aggregator import Aggregator
from .config import Committee, Round
from .crypto_bridge import verify_off_loop
from .errors import ConsensusError, UnknownAuthority, WrongLeader
from .leader import LeaderElector
from .mempool_driver import MempoolDriver
from .messages import (
    QC,
    TC,
    Block,
    CertificateCache,
    Timeout,
    Vote,
    encode_tc,
    encode_timeout,
    encode_vote,
)
from .proposer import Cleanup as ProposerCleanup
from .proposer import Make as ProposerMake
from .synchronizer import Synchronizer
from .timer import Timer

log = logging.getLogger("consensus")

_STATE_KEY = b"__consensus_state__"


class Core:
    # Class-level no-op defaults: state-only instances (tests build Core
    # via ``__new__`` to exercise single handlers) and fully-wired cores
    # with telemetry disabled share the same do-nothing metric objects;
    # ``__init__`` overrides them with live ones when telemetry is on.
    _m_proposals = _m_votes = _m_timeouts_rx = _m_timeouts = telemetry.NULL_COUNTER
    _m_qcs = _m_tcs = _m_rounds = _m_blocks = telemetry.NULL_COUNTER
    _g_round = _g_committed_round = telemetry.NULL_GAUGE
    _trace = None
    _wire_seats = None  # state-only instances broadcast legacy v1
    # Lazarus replica-lifecycle collaborators: None on state-only
    # instances and on nodes that opted out (statesync/compaction are
    # wired by Consensus.spawn when configured).
    _statesync = None
    _compactor = None
    _last_committed_digest = None  # newest committed block's digest

    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        signature_service: SignatureService,
        store: Store,
        leader_elector: LeaderElector,
        mempool_driver: MempoolDriver,
        synchronizer: Synchronizer,
        timeout_delay: int,
        rx_message: asyncio.Queue,
        rx_loopback: asyncio.Queue,
        tx_proposer: asyncio.Queue,
        tx_commit: asyncio.Queue,
        benchmark: bool = False,
        persist_sync: bool = False,
        batch_vote_verification: bool = False,
        on_round_advance=None,
        profile: bool = False,
        wire_seats=None,
        network=None,
        timer=None,
        statesync=None,
        compactor=None,
    ) -> None:
        self.name = name
        self.committee = committee
        self.signature_service = signature_service
        self.store = store
        self.leader_elector = leader_elector
        self.mempool_driver = mempool_driver
        self.synchronizer = synchronizer
        self.rx_message = rx_message
        self.rx_loopback = rx_loopback
        self.tx_proposer = tx_proposer
        self.tx_commit = tx_commit
        self.benchmark = benchmark
        self.persist_sync = persist_sync
        self.batch_vote_verification = batch_vote_verification
        self.round: Round = 1
        self.last_voted_round: Round = 0
        self.last_committed_round: Round = 0
        self.high_qc = QC.genesis()
        # IO seams: the asyncio stack uses the defaults (real timer, real
        # best-effort sender); the deterministic simulation plane injects
        # a virtual-clock timer and an effect-collecting outbox so the
        # SAME handlers run sans-io (hotstuff_tpu/sim/machine.py).
        self.timer = timer if timer is not None else Timer(timeout_delay)
        self.aggregator = Aggregator(committee)
        self.network = network if network is not None else SimpleSender()
        # round -> set of known-byzantine vote keys (author||sig||hash);
        # GC'd with the aggregator on round advance.
        self._bad_sigs: dict[Round, set[bytes]] = {}
        # round -> authors whose seat already holds an INDIVIDUALLY
        # VERIFIED vote: further conflicting votes from them (replays, or
        # genuine equivocation by a proven-byzantine author) drop without
        # paying another signature verification — closes the replay-DoS on
        # the reseat path. GC'd with _bad_sigs.
        self._verified_seats: dict[Round, set] = {}
        # Strong references to in-flight qc_retry timer tasks.
        self._retry_tasks: set[asyncio.Task] = set()
        # Rounds this node already amplified a timeout for (one own
        # timeout per future round, however many peers retransmit).
        self._amplified: set[Round] = set()
        # Native-transport hook: pushes each round advance down to the
        # C++ vote pre-stage so its stale-round cutoff tracks the core's.
        # None on the asyncio transport.
        self._on_round_advance = on_round_advance
        # Wire-format v2 seat table for outgoing timeout/TC broadcasts
        # (None = emit legacy v1). Decode-side acceptance is unconditional.
        self._wire_seats = wire_seats
        # Optional per-stage profiling (benchmark --profile): one
        # perf_counter_ns pair per handled event, accumulated into the
        # telemetry registry as ``consensus.stage.<kind>.{ns,calls}``
        # counters (benchmarks diff registry snapshots around their
        # measured window). One truthiness check per event when off.
        self._profile = bool(profile)
        # Telemetry plane. The metric objects are no-op singletons when
        # telemetry is disabled, so each record below costs one cheap
        # method call; the round tracer is None when disabled (its marks
        # take timestamps, which we skip entirely).
        self._m_proposals = telemetry.counter("consensus.proposals_received")
        self._m_votes = telemetry.counter("consensus.votes_received")
        self._m_timeouts_rx = telemetry.counter("consensus.timeouts_received")
        self._m_timeouts = telemetry.counter("consensus.timeouts_fired")
        self._m_qcs = telemetry.counter("consensus.qcs_formed")
        self._m_tcs = telemetry.counter("consensus.tcs_formed")
        self._m_rounds = telemetry.counter("consensus.rounds_advanced")
        self._m_blocks = telemetry.counter("consensus.blocks_committed")
        self._g_round = telemetry.gauge("consensus.round")
        self._g_committed_round = telemetry.gauge("consensus.last_committed_round")
        # The node label keys this engine's events in the cross-node
        # trace stream (in-process committees share one ring buffer);
        # the 16-char base64 prefix is unique within any real committee.
        self._trace = telemetry.round_trace(node=repr(name))
        # Peer-label cache for trace-event details (vote_rx/propose carry
        # "<author>|<digest>" so stream analyzers can score per-peer
        # behavior); repr(PublicKey) base64-encodes on every call, so the
        # hot vote path interns the label once per peer instead. The
        # one-entry digest memo exists because all 2f+1 votes of a round
        # carry the SAME block hash — one encode per round, not per vote.
        self._peer_labels: dict = {}
        self._vote_digest_memo: tuple[bytes, str] | None = None
        # Replica lifecycle (Lazarus): anti-entropy state sync and
        # snapshot/truncate compaction, both driven by this event loop.
        self._statesync = statesync
        self._compactor = compactor
        # This node's verified-certificate memory: rebroadcast QCs/TCs
        # (every view-change timeout carries the same high_qc; every
        # TC-former broadcasts the TC; timers retransmit) verify once
        # instead of once per arrival — without it, timeout waves at
        # committee scale saturate the core in redundant batch verifies
        # and view changes stretch from one timer period to many.
        self._cert_cache = CertificateCache()

    @classmethod
    def spawn(cls, *args, **kwargs) -> asyncio.Task:
        self = cls(*args, **kwargs)
        task = asyncio.create_task(self.run(), name="consensus_core")
        task.add_done_callback(log_task_death)
        return task

    # -- persistence of voting state (fixes reference issue #15) ------------

    async def _persist_state(self) -> None:
        enc = Encoder()
        enc.u64(self.round).u64(self.last_voted_round).u64(self.last_committed_round)
        self.high_qc.encode(enc)
        await self.store.write_meta(_STATE_KEY, enc.finish(), sync=self.persist_sync)

    async def _restore_state(self) -> None:
        data = await self.store.read_meta(_STATE_KEY)
        if data is None:
            return
        try:
            dec = Decoder(data)
            self.round = dec.u64()
            self.last_voted_round = dec.u64()
            self.last_committed_round = dec.u64()
            self.high_qc = QC.decode(dec)
            dec.finish()
            log.info(
                "Restored consensus state: round %d, last_voted %d",
                self.round,
                self.last_voted_round,
            )
        except Exception as e:  # corrupt state: safer to halt than equivocate
            raise ConsensusError(f"corrupt persisted consensus state: {e}") from e

    # -- helpers ------------------------------------------------------------

    async def store_block(self, block: Block) -> None:
        await self.store.write(block.digest().data, block.serialize())
        # This block is next round's parent: seed the synchronizer's
        # ancestor cache so the commit path doesn't re-deserialize it.
        self.synchronizer.cache_block(block)

    def increase_last_voted_round(self, target: Round) -> None:
        self.last_voted_round = max(self.last_voted_round, target)

    async def make_vote(self, block: Block) -> Vote | None:
        safety_rule_1 = block.round > self.last_voted_round
        safety_rule_2 = block.qc.round + 1 == block.round
        if block.tc is not None:
            can_extend = block.tc.round + 1 == block.round
            can_extend &= block.qc.round >= max(block.tc.high_qc_rounds())
            safety_rule_2 |= can_extend
        if not (safety_rule_1 and safety_rule_2):
            return None
        # Ensure we won't vote for contradicting blocks: persist BEFORE the
        # vote leaves this process.
        self.increase_last_voted_round(block.round)
        await self._persist_state()
        return await Vote.new(block, self.name, self.signature_service)

    async def commit(self, block: Block) -> None:
        if self.last_committed_round >= block.round:
            return
        # Commit the entire chain (needed after view-changes).
        to_commit = [block]
        parent = block
        while self.last_committed_round + 1 < parent.round:
            ancestor = await self.synchronizer.get_parent_block(parent)
            assert ancestor is not None, "committed block should have all ancestors"
            if ancestor.round <= self.last_committed_round:
                # Round GAP (view change abandoned the rounds between):
                # the fetched ancestor is already committed. Appending it
                # again would emit a duplicate commit downstream (double-
                # counted by the benchmark log parser) and feed a
                # duplicate entry into the reputation elector's window —
                # whose content then depends on each node's individual
                # commit batching, silently breaking the
                # identical-prefix => identical-window agreement
                # invariant (observed live as a permanent election
                # disagreement: the "timeout grind").
                break
            to_commit.append(ancestor)
            parent = ancestor
        self.last_committed_round = block.round
        # Commit frontier: what state_request probes are answered with.
        self._last_committed_digest = block.digest()

        for blk in reversed(to_commit):
            self._m_blocks.inc()
            self._g_committed_round.set(blk.round)
            if self._trace is not None:
                self._trace.mark_commit(
                    blk.round, f"h{self.last_committed_round}"
                )
            if blk.payload:
                log.info("Committed %s", blk)
                for d in blk.payload:
                    # Telemetry mirror of the "Committed B -> d" contract
                    # (no-op unless telemetry is enabled).
                    telemetry.record_commit(d.data)
                if telemetry.dtrace_enabled():
                    # Lifeline ordering-edge close: every node marks the
                    # commit per payload digest (the assembler keeps the
                    # earliest — the round-trace first-commit semantics).
                    name_label = repr(self.name)
                    for d in blk.payload:
                        telemetry.dtrace_event(
                            name_label,
                            telemetry.intern_label(d.data),
                            "committed",
                            detail=f"r{blk.round}",
                        )
                if self.benchmark:
                    for d in blk.payload:
                        # NOTE: benchmark measurement interface (reference
                        # ``core.rs:145-149``).
                        log.info("Committed %s -> %s", blk, d)
            log.debug("Committed %r", blk)
            if _faultline.plane is not None:
                # Chaos-run audit line (INFO so it survives the default
                # verbosity): the multi-process checker reconstructs each
                # node's (round, digest) commit stream from these. One
                # module-global load when faultline is off.
                log.info(
                    "FaultlineCommit r=%d d=%s",
                    blk.round,
                    blk.digest().data.hex(),
                )
            # Committed blocks (in commit order) feed the elector's
            # participation window (no-op for round-robin).
            self.leader_elector.update(blk)
            if self._compactor is not None:
                self._compactor.note_commit(blk)
            await self.tx_commit.put(blk)
        if self._compactor is not None:
            await self._compactor.maybe_compact(self)

    def update_high_qc(self, qc: QC) -> None:
        if qc.round > self.high_qc.round:
            self.high_qc = qc

    async def local_timeout_round(self) -> None:
        log.warning("Timeout reached for round %d", self.round)
        self._m_timeouts.inc()
        if self._trace is not None:
            self._trace.mark_timeout(self.round)
        self.increase_last_voted_round(self.round)
        await self._persist_state()
        timeout = await Timeout.new(
            self.high_qc, self.round, self.name, self.signature_service
        )
        log.debug("Created %r", timeout)
        self.timer.reset()
        addresses = [a for _, a in self.committee.broadcast_addresses(self.name)]
        self.network.broadcast(
            addresses, encode_timeout(timeout, self._wire_seats)
        )
        await self.handle_timeout(timeout)

    # -- handlers -----------------------------------------------------------

    # Votes beyond this many rounds ahead are dropped: bounds the state an
    # attacker can allocate for fabricated future rounds.
    MAX_ROUND_LOOKAHEAD = 1_000

    def _peer_label(self, pk) -> str:
        label = self._peer_labels.get(pk)
        if label is None:
            label = self._peer_labels[pk] = repr(pk)
        return label

    def _effective_sigs(self, cert, n: int) -> int:
        """``n`` if the certificate must actually be verified, 0 when a
        byte-identical copy is already in this node's cache — so the
        verify-offload policy (``INLINE_SIG_LIMIT``) prices the REAL work:
        a rebroadcast certificate must not pay an executor hop just to
        hit the cache inside the worker."""
        if cert is None:
            return 0
        if self._cert_cache.hit(CertificateCache.key_of(cert)):
            return 0
        return n

    async def handle_vote_batch(self, votes: list[Vote]) -> None:
        """Aggregated fan-in from the native pre-stage: one dequeue for a
        whole poll cycle's admitted votes. Each vote runs the exact
        per-vote pipeline (cheap checks, aggregation, verification,
        byzantine ejection) under its own error guard, so one byzantine
        vote never poisons the rest of its batch."""
        for vote in votes:
            await self._guarded(self.handle_vote(vote))

    async def handle_vote(self, vote: Vote) -> None:
        log.debug("Processing %r", vote)
        self._m_votes.inc()
        if vote.round < self.round:
            return
        if self._trace is not None:
            self._trace.mark_vote(vote.round)
            # Per-peer accountability evidence: WHO voted, for WHAT — the
            # watchtower's vote-participation and conflicting-vote
            # (equivocation) scorers read these off the trace stream.
            memo = self._vote_digest_memo
            if memo is None or memo[0] != vote.hash.data:
                memo = self._vote_digest_memo = (
                    vote.hash.data, repr(vote.hash)
                )
            self._trace.mark_vote_rx(
                vote.round, self._peer_label(vote.author) + "|" + memo[1]
            )
        if vote.round > self.round + self.MAX_ROUND_LOOKAHEAD:
            log.warning("dropping vote %d rounds ahead", vote.round - self.round)
            return
        if self.batch_vote_verification:
            qc = await self._handle_vote_batched(vote)
        else:
            await verify_off_loop(vote.verify, self.committee)
            qc = self.aggregator.add_vote(vote)
        if qc is not None:
            await self._complete_qc(qc)

    async def _complete_qc(self, qc: QC) -> None:
        log.debug("Assembled %r", qc)
        self._m_qcs.inc()
        if self._trace is not None:
            self._trace.mark_qc(qc.round)
        await self.process_qc(qc)
        if self.name == self.leader_elector.get_leader(self.round):
            await self.generate_proposal(None)

    async def _handle_vote_batched(self, vote: Vote) -> QC | None:
        """Committee-scale path: only cheap checks per vote; the 2f+1
        signatures of the assembled QC are verified in ONE batch call (one
        device dispatch per QC instead of per vote)."""
        if self.committee.stake(vote.author) == 0:
            raise UnknownAuthority(str(vote.author))
        if self._vote_key(vote) in self._bad_sigs.get(vote.round, set()):
            return None  # known-byzantine signature resent: drop cheaply
        try:
            qc = self.aggregator.add_vote(vote)
        except ConsensusError:
            # The author's slot is taken — same bucket or (since the
            # one-bucket-per-author bound) a different digest's bucket —
            # possibly by a spoofed vote that would otherwise displace the
            # honest one. Identical resends drop free; a DIFFERENT
            # signature is verified individually and re-seated if genuine,
            # preserving liveness under spoofing.
            stored = self.aggregator.stored_signature(
                vote.round, vote.digest(), vote.author
            )
            if stored == vote.signature:
                return None
            if vote.author in self._verified_seats.get(vote.round, set()):
                return None  # seat already verified: replay/equivocation
            try:
                await verify_off_loop(vote.verify, self.committee)
            except ConsensusError:
                self._record_bad(vote.round, self._vote_key(vote))
                return None
            self._verified_seats.setdefault(vote.round, set()).add(vote.author)
            qc = self.aggregator.reseat_vote(vote)
        if qc is None:
            return None
        try:
            await verify_off_loop(
                qc.verify, self.committee, self._cert_cache, n_sigs=qc.n_votes()
            )
            return qc
        except BackendUnavailable as e:
            # The assembled QC was NOT judged (device/tunnel failure). Its
            # weight is already consumed in the aggregator, so retry the
            # verification later instead of losing the QC until view change.
            log.error("backend unavailable verifying %r (will retry): %s", qc, e)
            self._schedule_qc_retry(qc, attempt=1)
            return None
        except ConsensusError:
            try:
                return await self._eject_invalid_votes(qc)
            except BackendUnavailable as e:
                # Backend died mid-ejection: the QC is still unjudged.
                log.error("backend died during ejection (will retry): %s", e)
                self._schedule_qc_retry(qc, attempt=1)
                return None

    QC_RETRY_MAX = 6
    QC_RETRY_BASE_S = 0.25

    def _schedule_qc_retry(self, qc: QC, attempt: int) -> None:
        """Bounded backoff retry of an unjudged QC; if the backend stays
        down past the last attempt, the round's timeout/view-change is the
        fallback recovery (as for any liveness failure)."""
        if attempt > self.QC_RETRY_MAX:
            log.error("giving up QC verification retries for %r", qc)
            return
        self._call_later(self.QC_RETRY_BASE_S * attempt, ("qc_retry", (qc, attempt)))

    def _call_later(self, delay_s: float, item) -> None:
        """Re-inject ``item`` onto the merged event queue after
        ``delay_s``. This is the Core's only self-scheduling primitive
        (QC-retry backoff) — the simulation driver overrides it to push a
        virtual-time event instead of sleeping."""

        async def later() -> None:
            await asyncio.sleep(delay_s)
            await self.rx_message.put(item)

        task = asyncio.create_task(later(), name="qc_retry")
        # Strong reference: a sleeping fire-and-forget task may otherwise
        # be garbage-collected before it runs.
        self._retry_tasks.add(task)
        task.add_done_callback(self._retry_tasks.discard)
        task.add_done_callback(log_task_death)

    async def _handle_qc_retry(self, payload) -> None:
        qc, attempt = payload
        if qc.round < self.round:
            return  # the protocol moved on
        try:
            await verify_off_loop(
                qc.verify, self.committee, self._cert_cache, n_sigs=qc.n_votes()
            )
        except BackendUnavailable:
            self._schedule_qc_retry(qc, attempt + 1)
            return
        except ConsensusError:
            try:
                qc = await self._eject_invalid_votes(qc)
            except BackendUnavailable:
                self._schedule_qc_retry(qc, attempt + 1)
                return
            if qc is None:
                return
        await self._complete_qc(qc)

    async def _eject_invalid_votes(self, qc: QC) -> QC | None:
        """A batch-verified QC failed: identify the byzantine signatures
        (off the event loop — this is 2f+1 serial verifies), record them so
        resends drop cheaply, and keep the good votes aggregating. Returns
        a QC if the surviving votes already meet the quorum threshold.

        Loops because ejection operates on the aggregator's CURRENT maker:
        votes seated after the failing QC was assembled may be unverified
        (batched mode), so a re-emitted QC is split again until every
        signature in it verified individually. Each iteration with bad
        signatures removes at least one vote, so the loop is bounded by
        committee size."""
        current = qc
        for _ in range(len(self.committee.authorities) + 1):
            digest = current.digest()

            def split(votes=current.votes, digest=digest):
                good, bad = [], []
                for pk, sig in votes:
                    try:
                        sig.verify(digest, pk)
                        good.append((pk, sig))
                    except BackendUnavailable:
                        raise  # NOT judged: never classify as byzantine
                    except CryptoError:
                        bad.append((pk, sig))
                return good, bad

            _, bad = await verify_off_loop(split, n_sigs=len(current.votes))
            if not bad:
                # Every signature verified individually (a stricter check
                # than the failed cofactored batch): the QC stands.
                return current
            for pk, sig in bad:
                log.warning("ejecting invalid vote signature from %s", pk)
                self._record_bad(
                    current.round, bytes(pk.data) + sig.data + current.hash.data
                )
            next_qc, ejected = self.aggregator.eject_votes(
                current.round, digest, bad, current.hash
            )
            # An ejected author's seat no longer holds a verified vote;
            # forgetting the seat lets their genuine resend be verified
            # and re-seated instead of being dropped as a replay.
            seats = self._verified_seats.get(current.round)
            if seats is not None:
                seats.difference_update(ejected)
            if next_qc is None:
                return None
            current = next_qc
        return None

    @staticmethod
    def _vote_key(vote: Vote) -> bytes:
        return vote.author.data + vote.signature.data + vote.hash.data

    def _record_bad(self, round_: Round, key: bytes) -> None:
        self._bad_sigs.setdefault(round_, set()).add(key)

    async def handle_timeout(self, timeout: Timeout) -> None:
        log.debug("Processing %r", timeout)
        self._m_timeouts_rx.inc()
        if timeout.round < self.round:
            return
        if timeout.round > self.round + self.MAX_ROUND_LOOKAHEAD:
            # Same state-allocation bound as votes: otherwise one
            # byzantine member seats a TCMaker (and pays us a full
            # verification) per arbitrary future round.
            log.warning(
                "dropping timeout %d rounds ahead", timeout.round - self.round
            )
            return
        maker = self.aggregator.timeouts_aggregators.get(timeout.round)
        if maker is not None and timeout.author in maker.used:
            # Duplicate seat: timers retransmit timeouts every
            # timeout_delay, so during a long view change each node
            # receives each peer's timeout many times. Drop BEFORE the
            # signature verification — the post-verify AuthorityReuse
            # rejection priced every retransmission at a full high_qc
            # batch verify, which is exactly the load that stalls
            # committee-scale view changes. An equivocating second
            # timeout from the same author was rejected for reuse
            # anyway — EXCEPT that the old path first adopted its
            # high_qc; keep that convergence channel by letting a
            # retransmission carrying a NEWER high_qc through to the
            # verified path.
            if timeout.high_qc.round <= self.high_qc.round:
                return
        hq = timeout.high_qc
        n_sigs = 1 + (
            0 if hq == QC.genesis() else self._effective_sigs(hq, hq.n_votes())
        )
        await verify_off_loop(
            timeout.verify, self.committee, self._cert_cache, n_sigs=n_sigs
        )
        await self.process_qc(timeout.high_qc)
        tc = self.aggregator.add_timeout(timeout)
        if tc is not None:
            log.debug("Assembled %r", tc)
            self._m_tcs.inc()
            await self.advance_round(tc.round, via_tc=True)
            addresses = [a for _, a in self.committee.broadcast_addresses(self.name)]
            self.network.broadcast(addresses, encode_tc(tc, self._wire_seats))
            if self.name == self.leader_elector.get_leader(self.round):
                await self.generate_proposal(tc)
        elif timeout.round > self.round:
            await self._maybe_amplify_timeout(timeout.round)

    async def _maybe_amplify_timeout(self, round_: Round) -> None:
        """Timeout amplification (the DiemBFT/Jolteon timeout-sync rule):
        once f+1 DISTINCT authorities are seen timing out at a round
        ahead of ours, join that view change by issuing our own timeout
        for it — f+1 guarantees at least one honest node timed out there.

        Why this is load-bearing (found by faultline chaos seed 11): the
        TC is broadcast exactly once, best-effort. If that broadcast is
        lost to a partition/lossy window, the committee splits across two
        adjacent rounds — e.g. two nodes at r (their timeouts sign round
        r) and two at r+1 (their timeouts sign r+1) — and NO round can
        ever accumulate 2f+1 same-round timeouts again: a permanent
        liveness wedge the timers cannot heal, observed as a total
        post-heal commit stall. Amplification re-synchronizes the laggards
        onto the newer round's view change, so the TC forms and the
        committee converges within one timeout period."""
        if round_ in self._amplified:
            return
        maker = self.aggregator.timeouts_aggregators.get(round_)
        if maker is None:
            return
        weight = sum(self.committee.stake(a) for a in maker.used)
        if weight < self.committee.validity_threshold():
            return
        self._amplified.add(round_)
        log.warning(
            "amplifying timeout to round %d (f+1 peers are there)", round_
        )
        telemetry.counter("consensus.timeouts_amplified").inc()
        self.increase_last_voted_round(round_)
        await self._persist_state()
        timeout = await Timeout.new(
            self.high_qc, round_, self.name, self.signature_service
        )
        self.timer.reset()
        addresses = [a for _, a in self.committee.broadcast_addresses(self.name)]
        self.network.broadcast(
            addresses, encode_timeout(timeout, self._wire_seats)
        )
        await self.handle_timeout(timeout)

    async def advance_round(self, round_: Round, via_tc: bool = False) -> None:
        if round_ < self.round:
            return
        self.timer.reset()
        self.round = round_ + 1
        # Entry-cause feed: a TC-entered round elects by round-robin in
        # the reputation elector (the timeout-grind escape hatch — see
        # leader.ReputationLeaderElector.note_round_entry). No-op for
        # round-robin.
        self.leader_elector.note_round_entry(self.round, via_tc)
        self._m_rounds.inc()
        self._g_round.set(self.round)
        if self._on_round_advance is not None:
            self._on_round_advance(self.round)
        log.debug("Moved to round %d", self.round)
        self.aggregator.cleanup(self.round)
        self._bad_sigs = {r: s for r, s in self._bad_sigs.items() if r >= self.round}
        self._verified_seats = {
            r: s for r, s in self._verified_seats.items() if r >= self.round
        }
        self._amplified = {r for r in self._amplified if r >= self.round}

    async def generate_proposal(self, tc: TC | None) -> None:
        await self.tx_proposer.put(ProposerMake(self.round, self.high_qc, tc))

    async def cleanup_proposer(self, b0: Block, b1: Block, block: Block) -> None:
        digests = [*b0.payload, *b1.payload, *block.payload]
        await self.tx_proposer.put(ProposerCleanup(digests))

    async def process_qc(self, qc: QC) -> None:
        await self.advance_round(qc.round)
        self.update_high_qc(qc)

    async def process_block(self, block: Block) -> None:
        log.debug("Processing %r", block)
        if self._trace is not None:
            # Loopback (our own proposal) reaches here without
            # handle_proposal; first mark wins, so the double call on the
            # network path is harmless.
            self._trace.mark_propose(block.round)
        # We need the two ancestors b0 <- |qc0; b1| <- |qc1; block|; if any is
        # missing the synchronizer fetches them and re-injects this block.
        ancestors = await self.synchronizer.get_ancestors(block)
        if ancestors is None:
            log.debug("Processing of %r suspended: missing parent", block.digest())
            return
        b0, b1 = ancestors

        # Store only blocks whose full ancestry we have processed.
        await self.store_block(block)
        await self.cleanup_proposer(b0, b1, block)

        # 2-chain commit rule.
        if b0.round + 1 == b1.round:
            await self.mempool_driver.cleanup(b0.round)
            await self.commit(b0)

        # Round guard: prevents bad leaders from dragging us far into the
        # future (reference ``core.rs:345-349``).
        if block.round != self.round:
            return

        # Leadership gate on the VOTE (the round-robin elector already
        # rejected mismatches in handle_proposal; a lenient elector
        # processes certificates above but never endorses an author its
        # window says is not the leader).
        if block.author != self.leader_elector.get_leader(block.round):
            log.debug(
                "Withholding vote for %r: author is not our expected leader",
                block,
            )
            return

        vote = await self.make_vote(block)
        if vote is not None:
            log.debug("Created %r", vote)
            if self._trace is not None:
                self._trace.mark_vote_send(block.round)
            next_leader = self.leader_elector.get_leader(self.round + 1)
            if next_leader == self.name:
                await self.handle_vote(vote)
            else:
                address = self.committee.address(next_leader)
                assert address is not None, "next leader not in committee"
                self.network.send(address, encode_vote(vote))

    async def handle_proposal(self, block: Block) -> None:
        digest = block.digest()
        self._m_proposals.inc()
        if self._trace is not None:
            self._trace.mark_propose(
                block.round,
                self._peer_label(block.author) + "|" + repr(digest),
            )
        # Redelivery short-circuit: helpers answer sync requests with
        # ancestor CHAINS, so bursts can re-include blocks already fully
        # processed (stored => verified, certificates applied, ancestry
        # complete) or already SUSPENDED awaiting their parents.
        # Re-verifying either is pure waste — at catch-up rates it was
        # most of a straggler's CPU.
        if await self.store.read(digest.data) is not None:
            return
        if self.synchronizer.is_pending(digest):
            return
        author_mismatch = block.author != self.leader_elector.get_leader(
            block.round
        )
        if author_mismatch:
            # Strict electors (round-robin) reject outright — all honest
            # nodes share the same (stateless) leader function, so a
            # mismatch is always a bad proposal. A LENIENT elector's
            # leader opinion derives from the local committed window and
            # can transiently diverge between honest nodes: still verify
            # and process the certificates (QCs advance rounds and
            # high_qc, healing the divergence) but store/vote only under
            # the solicited-block rule below.
            if not self.leader_elector.lenient:
                raise WrongLeader(
                    f"block {digest} from {block.author} at round {block.round}"
                )
        n_sigs = 1
        if block.qc != QC.genesis():
            n_sigs += self._effective_sigs(block.qc, block.qc.n_votes())
        if block.tc is not None:
            n_sigs += self._effective_sigs(block.tc, block.tc.n_votes())
        await verify_off_loop(
            block.verify, self.committee, self._cert_cache, n_sigs=n_sigs
        )
        if self._trace is not None:
            # receive→verified is the crypto-plane edge of the cross-node
            # timeline; the assembler attributes it separately from the
            # decode/queue edge (propose_send→propose) and the vote edge
            # (verified→vote_send).
            self._trace.mark_verified(block.round)
        await self.process_qc(block.qc)
        if block.tc is not None:
            await self.advance_round(block.tc.round, via_tc=True)
        if (
            # Recomputed (not the early ``author_mismatch``): processing
            # the block's TC above may have marked its round TC-entered,
            # flipping a lenient elector to the round-robin fallback —
            # the gate must judge the proposal against that same
            # (post-certificate) leader opinion.
            block.author != self.leader_elector.get_leader(block.round)
            and self.leader_elector.gate_active(block.round)
            and not self.synchronizer.requested(digest)
        ):
            # Lenient mode, unsolicited mismatched author: certificates
            # were applied above, but the block itself is NOT processed
            # or stored. Solicited blocks (our own sync requests) are
            # certified-chain ancestors and flow through — that is the
            # divergence-healing path — while a byzantine member's
            # fabricated blocks (valid signature, reused QC) can never
            # grow the store. The gate lifts while the elector's window
            # is EMPTY (boot/restart): such a node elects round-robin,
            # disagrees with running peers by construction, and must
            # commit their proposals to rebuild its window.
            log.debug(
                "Skipping unsolicited block %s from unexpected author %s",
                digest,
                block.author,
            )
            return
        if not await self.mempool_driver.verify(block):
            log.debug("Processing of %r suspended: missing payload", digest)
            return
        await self.process_block(block)

    # -- Lazarus state sync (thin delegates: the protocol driver lives in
    # consensus/statesync.py; events reach it through the merged queue so
    # the simulation plane drives the identical code path) ------------------

    async def handle_state_request(self, payload) -> None:
        if self._statesync is not None:
            await self._statesync.handle_state_request(payload)

    async def handle_state_response(self, payload) -> None:
        if self._statesync is not None:
            await self._statesync.handle_state_response(payload)

    async def handle_statesync_tick(self, payload) -> None:
        if self._statesync is not None:
            await self._statesync.handle_tick(payload)

    async def handle_tc(self, tc: TC) -> None:
        # Round check BEFORE the 2f+1-signature verification: every node
        # that forms the TC broadcasts it, so all but the first arrival
        # are stale by the time they dequeue — discarding them unverified
        # removes most of a view change's redundant crypto. (A stale TC
        # is never used, so skipping its verification changes nothing.)
        if tc.round < self.round:
            return
        await verify_off_loop(
            tc.verify,
            self.committee,
            self._cert_cache,
            n_sigs=self._effective_sigs(tc, tc.n_votes()),
        )
        if tc.round < self.round:
            return
        await self.advance_round(tc.round, via_tc=True)
        if self.name == self.leader_elector.get_leader(self.round):
            await self.generate_proposal(tc)

    # -- main loop ----------------------------------------------------------

    # Tagged-event dispatch table (kind -> handler method name): the
    # sans-io seam. run() binds it for the asyncio loop below, and the
    # simulation driver (hotstuff_tpu/sim/machine.py) binds the SAME
    # table so both planes dispatch identical events to identical
    # handlers — the real stack and the simulated one cannot drift.
    HANDLERS = {
        "propose": "handle_proposal",
        "vote": "handle_vote",
        "votes": "handle_vote_batch",  # native pre-stage batches
        "timeout": "handle_timeout",
        "tc": "handle_tc",
        "qc_retry": "_handle_qc_retry",  # internal loopback
        "loopback": "process_block",
        "state_request": "handle_state_request",
        "state_response": "handle_state_response",
        "statesync_tick": "handle_statesync_tick",  # internal loopback
    }

    # Sampling-profiler stage seeds: each dequeued event opens under the
    # trace edge its handler starts in; the RoundTrace marks then refine
    # the tag as the handler crosses edge boundaries (e.g. a "propose"
    # event opens as ingress work — dedup lookups, leader checks — until
    # mark_propose flips it to verify).
    STAGE_SEEDS = {
        "propose": "ingress",
        "vote": "fanin",
        "votes": "fanin",
        "timeout": "view_change",
        "tc": "view_change",
        "qc_retry": "verify",
        "loopback": "vote",
        "state_request": "ingress",
        "state_response": "ingress",
        "statesync_tick": "ingress",
    }

    def bound_handlers(self) -> dict:
        return {kind: getattr(self, name) for kind, name in self.HANDLERS.items()}

    async def _timer_pump(self) -> None:
        """Forward timer expiries into the merged event queue. Handshakes
        with the run loop (``_timer_handled``) so an expired-but-unhandled
        timer is queued exactly once."""
        while True:
            await self.timer.wait()
            self._timer_handled.clear()
            # Tag the expiry with the round it fired in: under backlog the
            # event can be dequeued long after the round advanced (and
            # advancing reset the timer), and acting on it then would call
            # increase_last_voted_round for the NEW round — suppressing
            # this node's vote there for no reason.
            await self.rx_message.put(("timer", self.round))
            await self._timer_handled.wait()

    async def run(self) -> None:
        await self._restore_state()
        if self._statesync is not None:
            # Restore the truncation floor from our own snapshot record
            # and arm the anti-entropy probe loop (dormant while commits
            # flow).
            await self._statesync.start(self)
        self.timer.reset()
        if self.name == self.leader_elector.get_leader(self.round):
            await self.generate_proposal(None)

        # ONE merged event queue: network messages, loopback blocks, and
        # timer expiries all arrive as tagged items on ``rx_message`` (the
        # spawn wiring passes the same queue object for both channels), so
        # each event costs a single ``Queue.get`` instead of the
        # select-style three-task ``asyncio.wait`` — the old loop's task
        # churn (3 done-callback registrations + a create_task per event)
        # was a measurable slice of single-core round latency.
        handlers = self.bound_handlers()
        # One module attribute read per event when no profiler session is
        # live (see STAGE_SEEDS).
        stage_seeds = self.STAGE_SEEDS
        self._timer_handled = asyncio.Event()
        timer_task = asyncio.create_task(self._timer_pump(), name="consensus_timer")
        if self._on_round_advance is not None:
            # Seed the pre-stage cutoff with the (possibly restored) round.
            self._on_round_advance(self.round)
        profile = self._profile
        if profile:
            import time as _time

            # Stage counters live in the process telemetry registry:
            # ``consensus.stage.<kind>.{ns,calls}`` — an in-process
            # committee's engines all add into the same counters, giving
            # the whole committee's per-round handler bill in one place
            # (benchmarks diff registry snapshots around their window).
            registry = telemetry.get_registry()
            stage_counters: dict[str, tuple] = {}
        try:
            while True:
                kind, payload = await self.rx_message.get()
                if kind == "timer":
                    # Stale expiry (the round advanced while the event sat
                    # in the queue): drop it — the reset timer covers the
                    # current round.
                    if payload == self.round:
                        await self._guarded(self.local_timeout_round())
                    self._timer_handled.set()
                    continue
                handler = handlers.get(kind)
                if pyprof.TAGGING:
                    pyprof.set_thread_stage(stage_seeds.get(kind, "other"))
                if handler is None:
                    log.error("unexpected protocol message kind %s", kind)
                elif not profile:
                    await self._guarded(handler(payload))
                else:
                    pair = stage_counters.get(kind)
                    if pair is None:
                        pair = stage_counters[kind] = (
                            registry.counter(f"consensus.stage.{kind}.ns"),
                            registry.counter(f"consensus.stage.{kind}.calls"),
                        )
                    t0 = _time.perf_counter_ns()
                    await self._guarded(handler(payload))
                    pair[0].inc(_time.perf_counter_ns() - t0)
                    pair[1].inc()
                if pyprof.TAGGING:
                    # Back to the queue wait: samples here are event-loop
                    # idle/dispatch cost, not the last handler's edge.
                    pyprof.set_thread_stage("idle")
        finally:
            timer_task.cancel()

    async def _guarded(self, coro) -> None:
        """Protocol errors (byzantine input) are logged, never fatal —
        as are store/serialization errors from locally-stored data, which
        the reference run loop likewise logs and survives (reference
        ``core.rs:434-440``: SerializationError/StoreError arms).
        Invariant violations (AssertionError) stay FATAL — safer to halt
        than run on corrupt state — but die loudly via the task
        done-callback, never silently."""
        try:
            await coro
        except ConsensusError as e:
            log.warning("%s: %s", type(e).__name__, e)
        except BackendUnavailable as e:
            # Transient infrastructure failure: the message was not judged;
            # peers will resend. Nothing is cached as byzantine.
            log.error("crypto backend unavailable: %s", e)
        except (SerdeError, StoreError) as e:
            log.error("consensus handler error: %s: %s", type(e).__name__, e)
