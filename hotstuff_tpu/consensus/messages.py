"""Consensus messages: Block, Vote, QC, Timeout, TC (reference
``consensus/src/messages.rs``).

Digest definitions mirror the reference exactly (SHA-512 truncated to 32 B):

- ``Block``: H(author ‖ round_le ‖ payload... ‖ qc.hash)  (``messages.rs:79-90``)
- ``Vote``/``QC``: H(block_hash ‖ round_le)               (``messages.rs:150-162,200-212``)
- ``Timeout``: H(round_le ‖ high_qc.round_le)             (``messages.rs:267-279``)
- ``TC`` per-voter digest: H(tc.round_le ‖ high_qc_round_le) (``messages.rs:303-314``)

``QC.verify`` batches all 2f+1 vote signatures into one
``Signature.verify_batch`` call — the TPU offload site (``messages.rs:180-198``).
"""

from __future__ import annotations

import struct
import threading
from dataclasses import dataclass

from hotstuff_tpu.crypto import (
    BackendUnavailable,
    CryptoError,
    Digest,
    PublicKey,
    SecretKey,
    Signature,
    sha512_digest,
)
from hotstuff_tpu.utils.serde import Decoder, Encoder

from . import errors
from .config import Committee, Round

_U64 = struct.Struct("<Q")

# Decoded public keys interned by raw bytes: the same ~N committee keys
# appear in EVERY QC/TC/vote this process ever decodes (67 per QC at
# N=100), and constructing a fresh PublicKey per appearance — validation,
# copy, re-hash on every dict lookup — was a top CPU line of the N=100
# protocol bench. Interning also makes dict/set lookups hit CPython's
# identity fast path and reuses the cached bytes hash.
_PK_INTERN: dict[bytes, "PublicKey"] = {}


def _intern_pk(raw: bytes) -> PublicKey:
    pk = _PK_INTERN.get(raw)
    if pk is None:
        if len(_PK_INTERN) >= 4096:  # byzantine spray bound; committees are small
            _PK_INTERN.clear()
        pk = _PK_INTERN[raw] = PublicKey(raw)
    return pk


class CertificateCache:
    """Byte-identical certificates that already verified skip re-verification.

    Why: certificates are *rebroadcast*. During a view change every node's
    Timeout carries the same high_qc (2f+1 signatures), the assembled TC is
    broadcast by every node that forms it, and local timers retransmit
    timeouts every ``timeout_delay``. Without a cache each arrival pays the
    full batch verification — at N=40 one timeout wave is ~N² ≈ 1,000
    27-signature batch verifies, which saturates a core and stretches each
    view change from one timer period to many (observed live as a
    "timeout grind": rounds advance ~1 per timeout while commit latency
    collapses). The reference never re-verifies a QC it assembled itself
    but pays this cost on every received copy too (``messages.rs:180-198``).

    One instance per NODE (held by its Core), never module-level: in the
    one-process committee testbed a shared cache would let node B skip work
    node A paid for — unrealistic for the distributed deployment being
    modeled. Keyed by the certificate's exact serialized bytes, so any
    tampered variant misses and verifies from scratch. The committee is
    fixed per Core (epoch changes would need a keyed reset — parity with
    the reference's static membership).
    """

    __slots__ = ("cap", "_seen", "_lock")

    def __init__(self, cap: int = 512) -> None:
        from collections import OrderedDict

        self.cap = cap
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()
        # hit() runs on the event loop (Core._effective_sigs) while
        # hit()/add() run in the crypto ThreadPoolExecutor (QC/TC.verify);
        # OrderedDict check-then-move_to_end is not atomic under that.
        self._lock = threading.Lock()

    @staticmethod
    def key_of(cert) -> bytes:
        # Memoized on the certificate object: the core keys the cache
        # check in _effective_sigs and the verify path re-keys inside
        # QC/TC.verify — one encode instead of two per certificate, and
        # zero for repeats. Certificates are never mutated after
        # construction (ejection builds new QC objects), so the memo
        # cannot go stale.
        key = cert.__dict__.get("_cache_key")
        if key is None:
            enc = Encoder()
            cert.encode(enc)
            key = bytes(enc.finish())
            cert._cache_key = key
        return key

    def hit(self, key: bytes) -> bool:
        with self._lock:
            if key in self._seen:
                self._seen.move_to_end(key)
                return True
            return False

    def add(self, key: bytes) -> None:
        with self._lock:
            self._seen[key] = None
            if len(self._seen) > self.cap:
                self._seen.popitem(last=False)


# ---------------------------------------------------------------------------
# QC
# ---------------------------------------------------------------------------


@dataclass
class QC:
    hash: Digest
    round: Round
    votes: list[tuple[PublicKey, Signature]]

    @classmethod
    def genesis(cls) -> "QC":
        return cls(hash=Digest.default(), round=0, votes=[])

    def digest(self) -> Digest:
        return sha512_digest(self.hash.data, _U64.pack(self.round))

    def __eq__(self, other) -> bool:
        # Vote-set-independent equality (reference ``messages.rs:214-218``).
        return (
            isinstance(other, QC)
            and self.hash == other.hash
            and self.round == other.round
        )

    def verify(
        self, committee: Committee, cache: "CertificateCache | None" = None
    ) -> None:
        """Stake/duplicate accounting, then batch-verify all vote signatures
        (reference ``messages.rs:180-198``). With ``cache``, a byte-identical
        QC that already verified is accepted without re-verification."""
        key = None
        if cache is not None:
            key = CertificateCache.key_of(self)
            if cache.hit(key):
                return
        weight = 0
        used = set()
        for name, _ in self.votes:
            if name in used:
                raise errors.AuthorityReuse(str(name))
            stake = committee.stake(name)
            if stake == 0:
                raise errors.UnknownAuthority(str(name))
            used.add(name)
            weight += stake
        if weight < committee.quorum_threshold():
            raise errors.QCRequiresQuorum("QC requires a quorum")
        try:
            Signature.verify_batch(self.digest(), self.votes)
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e
        if cache is not None:
            cache.add(key)

    def encode(self, enc: Encoder) -> None:
        enc.raw(self.hash.data).u64(self.round).seq(
            self.votes, lambda e, v: e.raw(v[0].data).raw(v[1].data)
        )

    @classmethod
    def decode(cls, dec: Decoder) -> "QC":
        h = Digest(dec.raw(32))
        rnd = dec.u64()
        votes = dec.seq(lambda d: (_intern_pk(d.raw(32)), Signature(d.raw(64))))
        return cls(h, rnd, votes)

    def __repr__(self) -> str:
        return f"QC({self.hash!r}, {self.round})"


# ---------------------------------------------------------------------------
# TC
# ---------------------------------------------------------------------------


@dataclass
class TC:
    round: Round
    votes: list[tuple[PublicKey, Signature, Round]]  # (author, sig, high_qc_round)

    def high_qc_rounds(self) -> list[Round]:
        return [r for _, _, r in self.votes]

    def verify(
        self, committee: Committee, cache: "CertificateCache | None" = None
    ) -> None:
        """Stake accounting, then verify per-voter digests — batched through
        the backend's multi-message path (reference ``messages.rs:283-320``
        verifies sig-by-sig; we keep identical acceptance but one device
        call). With ``cache``, a byte-identical TC that already verified is
        accepted without re-verification (every TC-former broadcasts it)."""
        key = None
        if cache is not None:
            key = CertificateCache.key_of(self)
            if cache.hit(key):
                return
        weight = 0
        used = set()
        for name, _, _ in self.votes:
            if name in used:
                raise errors.AuthorityReuse(str(name))
            stake = committee.stake(name)
            if stake == 0:
                raise errors.UnknownAuthority(str(name))
            used.add(name)
            weight += stake
        if weight < committee.quorum_threshold():
            raise errors.TCRequiresQuorum("TC requires a quorum")
        try:
            Signature.verify_batch_multi(
                [
                    (
                        sha512_digest(_U64.pack(self.round), _U64.pack(hqc_round)),
                        author,
                        sig,
                    )
                    for author, sig, hqc_round in self.votes
                ]
            )
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e
        if cache is not None:
            cache.add(key)

    def encode(self, enc: Encoder) -> None:
        enc.u64(self.round).seq(
            self.votes, lambda e, v: e.raw(v[0].data).raw(v[1].data).u64(v[2])
        )

    @classmethod
    def decode(cls, dec: Decoder) -> "TC":
        rnd = dec.u64()
        votes = dec.seq(
            lambda d: (_intern_pk(d.raw(32)), Signature(d.raw(64)), d.u64())
        )
        return cls(rnd, votes)

    def __repr__(self) -> str:
        return f"TC({self.round}, {self.high_qc_rounds()})"


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------


@dataclass
class Block:
    qc: QC
    tc: TC | None
    author: PublicKey
    round: Round
    payload: list[Digest]
    signature: Signature

    @classmethod
    def genesis(cls) -> "Block":
        return cls(
            qc=QC.genesis(),
            tc=None,
            author=PublicKey(bytes(32)),
            round=0,
            payload=[],
            signature=Signature.default(),
        )

    @classmethod
    async def new(cls, qc, tc, author, round_, payload, signature_service) -> "Block":
        block = cls(qc, tc, author, round_, payload, Signature.default())
        block.signature = await signature_service.request_signature(block.digest())
        return block

    @classmethod
    def new_from_key(cls, qc, tc, author, round_, payload, secret: SecretKey) -> "Block":
        """Synchronous test constructor (reference
        ``consensus/src/tests/common.rs:48-114``)."""
        block = cls(qc, tc, author, round_, payload, Signature.default())
        block.signature = Signature.new(block.digest(), secret)
        return block

    def parent(self) -> Digest:
        return self.qc.hash

    def digest(self) -> Digest:
        return sha512_digest(
            self.author.data,
            _U64.pack(self.round),
            *[d.data for d in self.payload],
            self.qc.hash.data,
        )

    def verify(
        self, committee: Committee, cache: "CertificateCache | None" = None
    ) -> None:
        """Author stake + signature + embedded QC/TC (reference
        ``messages.rs:55-76``). ``cache`` skips re-verifying embedded
        certificates this node already verified (e.g. the QC also carried
        by the timeouts that preceded a view-change proposal)."""
        if committee.stake(self.author) == 0:
            raise errors.UnknownAuthority(str(self.author))
        try:
            self.signature.verify(self.digest(), self.author)
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e
        if self.qc != QC.genesis():
            self.qc.verify(committee, cache)
        if self.tc is not None:
            self.tc.verify(committee, cache)

    def encode(self, enc: Encoder) -> None:
        self.qc.encode(enc)
        enc.option(self.tc, lambda e, tc: tc.encode(e))
        enc.raw(self.author.data).u64(self.round)
        enc.seq(self.payload, lambda e, d: e.raw(d.data))
        enc.raw(self.signature.data)

    @classmethod
    def decode(cls, dec: Decoder) -> "Block":
        qc = QC.decode(dec)
        tc = dec.option(TC.decode)
        author = _intern_pk(dec.raw(32))
        rnd = dec.u64()
        payload = dec.seq(lambda d: Digest(d.raw(32)))
        sig = Signature(dec.raw(64))
        return cls(qc, tc, author, rnd, payload, sig)

    def serialize(self) -> bytes:
        """Standalone encoding — the form blocks are stored under in the
        store (reference ``core.rs:89-93``).

        Memoized: a received block already carries its exact wire bytes
        (attached by the decoder — the encoding is canonical, so bytes
        in == bytes out), and a locally-built block is encoded once for
        its broadcast and reused for the store write. Blocks are treated
        as immutable after construction."""
        wire = self.__dict__.get("_wire")
        if wire is None:
            enc = Encoder()
            self.encode(enc)
            wire = enc.finish()
            self._wire = wire
        return wire

    @classmethod
    def deserialize(cls, data: bytes) -> "Block":
        dec = Decoder(data)
        block = cls.decode(dec)
        dec.finish()
        block._wire = bytes(data)
        return block

    def __str__(self) -> str:
        return f"B{self.round}"

    def __repr__(self) -> str:
        return (
            f"{self.digest()!r}: B({self.author!r}, {self.round}, "
            f"{self.qc!r}, {len(self.payload) * 32})"
        )


# ---------------------------------------------------------------------------
# Vote
# ---------------------------------------------------------------------------


@dataclass
class Vote:
    hash: Digest
    round: Round
    author: PublicKey
    signature: Signature

    @classmethod
    async def new(cls, block: Block, author, signature_service) -> "Vote":
        vote = cls(block.digest(), block.round, author, Signature.default())
        vote.signature = await signature_service.request_signature(vote.digest())
        return vote

    @classmethod
    def new_from_key(cls, hash_: Digest, round_: Round, author, secret) -> "Vote":
        vote = cls(hash_, round_, author, Signature.default())
        vote.signature = Signature.new(vote.digest(), secret)
        return vote

    def digest(self) -> Digest:
        return sha512_digest(self.hash.data, _U64.pack(self.round))

    def verify(self, committee: Committee) -> None:
        if committee.stake(self.author) == 0:
            raise errors.UnknownAuthority(str(self.author))
        try:
            self.signature.verify(self.digest(), self.author)
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e

    def encode(self, enc: Encoder) -> None:
        enc.raw(self.hash.data).u64(self.round).raw(self.author.data).raw(
            self.signature.data
        )

    @classmethod
    def decode(cls, dec: Decoder) -> "Vote":
        return cls(
            Digest(dec.raw(32)),
            dec.u64(),
            PublicKey(dec.raw(32)),
            Signature(dec.raw(64)),
        )

    def __repr__(self) -> str:
        return f"V({self.author!r}, {self.round}, {self.hash!r})"


# ---------------------------------------------------------------------------
# Timeout
# ---------------------------------------------------------------------------


@dataclass
class Timeout:
    high_qc: QC
    round: Round
    author: PublicKey
    signature: Signature

    @classmethod
    async def new(cls, high_qc, round_, author, signature_service) -> "Timeout":
        t = cls(high_qc, round_, author, Signature.default())
        t.signature = await signature_service.request_signature(t.digest())
        return t

    @classmethod
    def new_from_key(cls, high_qc, round_, author, secret) -> "Timeout":
        t = cls(high_qc, round_, author, Signature.default())
        t.signature = Signature.new(t.digest(), secret)
        return t

    def digest(self) -> Digest:
        return sha512_digest(_U64.pack(self.round), _U64.pack(self.high_qc.round))

    def verify(
        self, committee: Committee, cache: "CertificateCache | None" = None
    ) -> None:
        if committee.stake(self.author) == 0:
            raise errors.UnknownAuthority(str(self.author))
        try:
            self.signature.verify(self.digest(), self.author)
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e
        if self.high_qc != QC.genesis():
            # The dominant cost: every node's timeout in a view change
            # carries the same high_qc — the cache collapses N copies to
            # one batch verification.
            self.high_qc.verify(committee, cache)

    def encode(self, enc: Encoder) -> None:
        self.high_qc.encode(enc)
        enc.u64(self.round).raw(self.author.data).raw(self.signature.data)

    @classmethod
    def decode(cls, dec: Decoder) -> "Timeout":
        return cls(
            QC.decode(dec), dec.u64(), PublicKey(dec.raw(32)), Signature(dec.raw(64))
        )

    def __repr__(self) -> str:
        return f"TV({self.author!r}, {self.round}, {self.high_qc!r})"


# ---------------------------------------------------------------------------
# Wire envelope: ConsensusMessage (reference ``consensus.rs:32-39``).
# ---------------------------------------------------------------------------

TAG_PROPOSE = 0
TAG_VOTE = 1
TAG_TIMEOUT = 2
TAG_TC = 3
TAG_SYNC_REQUEST = 4


def encode_propose(block: Block) -> bytes:
    # Rides the block's memoized wire bytes (one encode per block per
    # process, shared between broadcast and store).
    return bytes([TAG_PROPOSE]) + block.serialize()


def encode_vote(vote: Vote) -> bytes:
    enc = Encoder().u8(TAG_VOTE)
    vote.encode(enc)
    return enc.finish()


def encode_timeout(timeout: Timeout) -> bytes:
    enc = Encoder().u8(TAG_TIMEOUT)
    timeout.encode(enc)
    return enc.finish()


def encode_tc(tc: TC) -> bytes:
    enc = Encoder().u8(TAG_TC)
    tc.encode(enc)
    return enc.finish()


def encode_sync_request(missing: Digest, origin: PublicKey) -> bytes:
    return Encoder().u8(TAG_SYNC_REQUEST).raw(missing.data).raw(origin.data).finish()


# Fixed Vote wire layout (TAG_VOTE + Vote.encode):
#   u8 tag | 32B hash | u64 LE round | 32B author | 64B signature
# The native transport's vote pre-stage length-validates and decodes
# round/author from these offsets in C++ (network/native/netcore.cpp);
# this is the matching batch decoder for the frames it admits.
VOTE_WIRE_LEN = 137
_VOTE_ROUND = struct.Struct("<Q")


def decode_vote_frame(data: bytes) -> Vote:
    """Decode one fixed-layout vote frame (fast path: direct slicing, no
    Decoder object). Accepts exactly what ``decode_message`` would return
    ``("vote", ...)`` for."""
    if len(data) != VOTE_WIRE_LEN or data[0] != TAG_VOTE:
        raise errors.MalformedMessage("not a fixed-layout vote frame")
    return Vote(
        Digest(data[1:33]),
        _VOTE_ROUND.unpack_from(data, 33)[0],
        _intern_pk(data[41:73]),
        Signature(data[73:137]),
    )


def decode_message(data: bytes):
    """Returns (kind, payload). Raises on malformed/byzantine input."""
    dec = Decoder(data)
    tag = dec.u8()
    if tag == TAG_PROPOSE:
        block = Block.decode(dec)
        dec.finish()
        # The canonical encoding means the frame's tail IS the block's
        # serialization: attach it so store_block never re-encodes the
        # 2f+1-vote QC it just decoded.
        block._wire = bytes(data[1:])
        return ("propose", block)
    elif tag == TAG_VOTE:
        out = ("vote", Vote(
            Digest(dec.raw(32)), dec.u64(), _intern_pk(dec.raw(32)),
            Signature(dec.raw(64)),
        ))
    elif tag == TAG_TIMEOUT:
        out = ("timeout", Timeout.decode(dec))
    elif tag == TAG_TC:
        out = ("tc", TC.decode(dec))
    elif tag == TAG_SYNC_REQUEST:
        out = ("sync_request", (Digest(dec.raw(32)), PublicKey(dec.raw(32))))
    else:
        raise errors.MalformedMessage(f"unknown consensus tag {tag}")
    dec.finish()
    return out
