"""Consensus messages: Block, Vote, QC, Timeout, TC (reference
``consensus/src/messages.rs``).

Digest definitions mirror the reference exactly (SHA-512 truncated to 32 B):

- ``Block``: H(author ‖ round_le ‖ payload... ‖ qc.hash)  (``messages.rs:79-90``)
- ``Vote``/``QC``: H(block_hash ‖ round_le)               (``messages.rs:150-162,200-212``)
- ``Timeout``: H(round_le ‖ high_qc.round_le)             (``messages.rs:267-279``)
- ``TC`` per-voter digest: H(tc.round_le ‖ high_qc_round_le) (``messages.rs:303-314``)

``QC.verify`` batches all 2f+1 vote signatures into one
``Signature.verify_batch`` call — the TPU offload site (``messages.rs:180-198``).
"""

from __future__ import annotations

import struct
import threading
from collections import OrderedDict
from dataclasses import dataclass

from hotstuff_tpu.crypto import (
    BackendUnavailable,
    CryptoError,
    Digest,
    PublicKey,
    SecretKey,
    Signature,
    backend_verify_cert,
    sha512_digest,
)
from hotstuff_tpu.utils.serde import MAX_LEN, Decoder, Encoder, SerdeError

from . import cert_arena, errors
from .config import Committee, Round

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# Decoded public keys interned by raw bytes: the same ~N committee keys
# appear in EVERY QC/TC/vote this process ever decodes (67 per QC at
# N=100), and constructing a fresh PublicKey per appearance — validation,
# copy, re-hash on every dict lookup — was a top CPU line of the N=100
# protocol bench. Interning also makes dict/set lookups hit CPython's
# identity fast path and reuses the cached bytes hash.
#
# Bounded as a true LRU: the previous clear-at-cap policy dumped the
# whole table — including every live committee key — whenever a
# byzantine spray (or a long soak across key rotations) filled it,
# re-paying N constructions per subsequent certificate. Eviction now
# drops only the coldest entry; committee keys are touched on every
# decode and never age out. Evictions are counted (``intern_evictions``)
# so soaks can see rotation/spray pressure.
_PK_INTERN_CAP = 4096
_PK_INTERN: "OrderedDict[bytes, PublicKey]" = OrderedDict()
intern_evictions = 0


def _intern_pk(raw: bytes) -> PublicKey:
    pk = _PK_INTERN.get(raw)
    if pk is None:
        if len(_PK_INTERN) >= _PK_INTERN_CAP:
            global intern_evictions
            _PK_INTERN.popitem(last=False)
            intern_evictions += 1
            from hotstuff_tpu import telemetry

            telemetry.counter("consensus.intern_pk.evictions").inc()
        pk = _PK_INTERN[raw] = PublicKey(raw)
    else:
        _PK_INTERN.move_to_end(raw)
    return pk


# ---------------------------------------------------------------------------
# Seat table: canonical committee numbering for wire-format v2.
# ---------------------------------------------------------------------------


class SeatTable:
    """Canonical seat numbering of a committee: seat ``i`` is the ``i``-th
    public key in sorted order — the same deterministic order on every
    node, so a certificate can name its signers as a BITMAP of seats
    instead of repeating each 32-byte key on the wire (wire-format v2,
    ~33% smaller proposals at N=200). Keys are interned, so mapping a
    seat back to its PublicKey is a list index — no per-vote decode."""

    __slots__ = ("keys", "index", "nbytes", "fingerprint")

    def __init__(self, keys) -> None:
        self.keys: list[PublicKey] = [_intern_pk(bytes(pk)) for pk in keys]
        self.index: dict[PublicKey, int] = {
            pk: i for i, pk in enumerate(self.keys)
        }
        self.nbytes = (len(self.keys) + 7) // 8  # bitmap width
        self.fingerprint = sha512_digest(*[pk.data for pk in self.keys]).data

    def __len__(self) -> int:
        return len(self.keys)

    @classmethod
    def for_committee(cls, committee: Committee) -> "SeatTable":
        """Memoized on the committee object (committees are static per
        epoch; an epoch change builds a new Committee and thus a new
        table)."""
        table = committee.__dict__.get("_seat_table")
        if table is None:
            table = cls(committee.sorted_keys())
            committee.__dict__["_seat_table"] = table
        return table


# Wire-format v2 marker: set on the vote-count u32 of a QC/TC vote
# section. v1 counts are bounded by MAX_LEN (< 2^26), so the bit is
# unambiguous. Layout after a flagged count (in ascending seat order):
#   QC: bitmap[seats.nbytes] | count * 64B signature
#   TC: bitmap[seats.nbytes] | count * (64B signature + u64 high_qc_round)
_V2_FLAG = 0x8000_0000


def _bitmap_seats(bitmap: bytes, n_seats: int) -> list[int]:
    """Ascending seat indices set in ``bitmap``; rejects bits >= n_seats."""
    seats: list[int] = []
    for byte_i, byte in enumerate(bitmap):
        if not byte:
            continue
        base = byte_i * 8
        for bit in range(8):
            if byte & (1 << bit):
                seat = base + bit
                if seat >= n_seats:
                    raise SerdeError(f"v2 bitmap names unknown seat {seat}")
                seats.append(seat)
    return seats


def _seats_bitmap(seat_indices, nbytes: int) -> bytes:
    out = bytearray(nbytes)
    for s in seat_indices:
        out[s >> 3] |= 1 << (s & 7)
    return bytes(out)


class CertificateCache:
    """Byte-identical certificates that already verified skip re-verification.

    Why: certificates are *rebroadcast*. During a view change every node's
    Timeout carries the same high_qc (2f+1 signatures), the assembled TC is
    broadcast by every node that forms it, and local timers retransmit
    timeouts every ``timeout_delay``. Without a cache each arrival pays the
    full batch verification — at N=40 one timeout wave is ~N² ≈ 1,000
    27-signature batch verifies, which saturates a core and stretches each
    view change from one timer period to many (observed live as a
    "timeout grind": rounds advance ~1 per timeout while commit latency
    collapses). The reference never re-verifies a QC it assembled itself
    but pays this cost on every received copy too (``messages.rs:180-198``).

    One instance per NODE (held by its Core), never module-level: in the
    one-process committee testbed a shared cache would let node B skip work
    node A paid for — unrealistic for the distributed deployment being
    modeled. Keyed by the certificate's exact serialized bytes, so any
    tampered variant misses and verifies from scratch. The committee is
    fixed per Core (epoch changes would need a keyed reset — parity with
    the reference's static membership).
    """

    __slots__ = ("cap", "_seen", "_lock")

    def __init__(self, cap: int = 512) -> None:
        from collections import OrderedDict

        self.cap = cap
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()
        # hit() runs on the event loop (Core._effective_sigs) while
        # hit()/add() run in the crypto ThreadPoolExecutor (QC/TC.verify);
        # OrderedDict check-then-move_to_end is not atomic under that.
        self._lock = threading.Lock()

    @staticmethod
    def key_of(cert) -> bytes:
        # Memoized on the certificate object: the core keys the cache
        # check in _effective_sigs and the verify path re-keys inside
        # QC/TC.verify — one encode instead of two per certificate, and
        # zero for repeats. Certificates are never mutated after
        # construction (ejection builds new QC objects), so the memo
        # cannot go stale. The key is always the CANONICAL (v1) encoding
        # regardless of the wire format the certificate arrived in, so a
        # high_qc received v1 from one peer and v2 from another hits the
        # same entry; lazily-decoded v2 certificates assemble it from
        # raw slices without materializing Signature objects.
        key = cert.__dict__.get("_cache_key")
        if key is None:
            key = cert._canonical_key()
            cert._cache_key = key
        return key

    def hit(self, key: bytes) -> bool:
        with self._lock:
            if key in self._seen:
                self._seen.move_to_end(key)
                return True
            return False

    def add(self, key: bytes) -> None:
        with self._lock:
            self._seen[key] = None
            if len(self._seen) > self.cap:
                self._seen.popitem(last=False)


# ---------------------------------------------------------------------------
# QC
# ---------------------------------------------------------------------------


@dataclass
class QC:
    hash: Digest
    round: Round
    votes: list[tuple[PublicKey, Signature]]

    @classmethod
    def genesis(cls) -> "QC":
        return cls(hash=Digest.default(), round=0, votes=[])

    def digest(self) -> Digest:
        return sha512_digest(self.hash.data, _U64.pack(self.round))

    def __eq__(self, other) -> bool:
        # Vote-set-independent equality (reference ``messages.rs:214-218``).
        return (
            isinstance(other, QC)
            and self.hash == other.hash
            and self.round == other.round
        )

    # -- lazy votes (wire-format v2 decode) --
    #
    # A v2-decoded QC holds ``_raw_votes = (seat_indices, sig_buf, seats)``
    # instead of materialized ``votes``: the verify path consumes raw
    # 64-byte slices of ``sig_buf`` directly and a cache-hit QC never
    # constructs a Signature at all. ``votes`` materializes on first
    # attribute access (idempotent — a benign race between crypto worker
    # threads builds the same list twice and one wins).

    def __getattr__(self, name):
        if name == "votes":
            raw = self.__dict__.get("_raw_votes")
            if raw is not None:
                seat_list, sig_buf, seats = raw
                keys = seats.keys
                votes = [
                    (keys[s], Signature(sig_buf[i * 64 : i * 64 + 64]))
                    for i, s in enumerate(seat_list)
                ]
                self.__dict__["votes"] = votes
                return votes
        raise AttributeError(name)

    def n_votes(self) -> int:
        """Vote count without materializing lazy votes (sig-count input
        to the verify-offload policy)."""
        votes = self.__dict__.get("votes")
        if votes is not None:
            return len(votes)
        raw = self.__dict__.get("_raw_votes")
        return len(raw[0]) if raw is not None else len(self.votes)

    def _canonical_key(self) -> bytes:
        raw = None
        if "votes" not in self.__dict__:
            raw = self.__dict__.get("_raw_votes")
        if raw is not None:
            # v1-canonical bytes assembled straight from the arena
            # slices — no Signature/PublicKey construction.
            seat_list, sig_buf, seats = raw
            keys = seats.keys
            return b"".join(
                (
                    self.hash.data,
                    _U64.pack(self.round),
                    _U32.pack(len(seat_list)),
                    *(
                        keys[s].data + sig_buf[i * 64 : i * 64 + 64]
                        for i, s in enumerate(seat_list)
                    ),
                )
            )
        enc = Encoder()
        self.encode(enc)
        return bytes(enc.finish())

    def verify(
        self, committee: Committee, cache: "CertificateCache | None" = None
    ) -> None:
        """Stake/duplicate accounting, then batch-verify all vote signatures
        (reference ``messages.rs:180-198``). With ``cache``, a byte-identical
        QC that already verified is accepted without re-verification."""
        key = None
        if cache is not None:
            key = CertificateCache.key_of(self)
            if cache.hit(key):
                return
        arena = cert_arena.get_arena()
        akey = None
        if arena is not None:
            akey = (
                cert_arena.committee_fp(committee),
                key if key is not None else CertificateCache.key_of(self),
            )
            if arena.hit(akey):
                if cache is not None:
                    cache.add(key)
                return
        raw = None
        if "votes" not in self.__dict__:
            raw = self.__dict__.get("_raw_votes")
        if raw is not None:
            self._verify_raw(committee, raw)
        else:
            weight = 0
            used = set()
            for name, _ in self.votes:
                if name in used:
                    raise errors.AuthorityReuse(str(name))
                stake = committee.stake(name)
                if stake == 0:
                    raise errors.UnknownAuthority(str(name))
                used.add(name)
                weight += stake
            if weight < committee.quorum_threshold():
                raise errors.QCRequiresQuorum("QC requires a quorum")
            try:
                Signature.verify_batch(self.digest(), self.votes)
            except BackendUnavailable:
                raise  # infrastructure failure, NOT a byzantine signature
            except CryptoError as e:
                raise errors.InvalidSignature(str(e)) from e
        if arena is not None:
            arena.add(akey)
        if cache is not None:
            cache.add(key)

    def _verify_raw(self, committee: Committee, raw) -> None:
        """Raw-slice verification of a lazily-decoded v2 QC: identical
        acceptance to the materialized path (the bitmap decode already
        guarantees distinct seats, so AuthorityReuse cannot arise), but
        the crypto plane consumes 64-byte slices of the arena buffer —
        no Signature objects on the hot path."""
        seat_list, sig_buf, seats = raw
        keys = seats.keys
        weight = 0
        for s in seat_list:
            stake = committee.stake(keys[s])
            if stake == 0:
                raise errors.UnknownAuthority(str(keys[s]))
            weight += stake
        if weight < committee.quorum_threshold():
            raise errors.QCRequiresQuorum("QC requires a quorum")
        digest = self.digest()
        try:
            # ONE fused job per cert: the crypto plane receives the packed
            # signature buffer + stride, never 2f+1 sliced objects; the
            # canonical cert key lets the superbatch dedup concurrent
            # verifies of this cert across in-process nodes.
            backend_verify_cert(
                digest.data,
                [keys[s].data for s in seat_list],
                sig_buf,
                64,
                key=CertificateCache.key_of(self),
            )
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e

    def encode(self, enc: Encoder, seats: "SeatTable | None" = None) -> None:
        enc.raw(self.hash.data).u64(self.round)
        if seats is not None and self._encode_votes_v2(enc, seats):
            return
        enc.seq(self.votes, lambda e, v: e.raw(v[0].data).raw(v[1].data))

    def _encode_votes_v2(self, enc: Encoder, seats: "SeatTable") -> bool:
        raw = None
        if "votes" not in self.__dict__:
            raw = self.__dict__.get("_raw_votes")
        if raw is not None and raw[2] is seats:
            # Re-encode of an unmaterialized arena view for the same
            # committee: the wire section is reproduced from the slices.
            seat_list, sig_buf, _ = raw
            enc.u32(_V2_FLAG | len(seat_list))
            enc.raw(_seats_bitmap(seat_list, seats.nbytes))
            enc.raw(sig_buf)
            return True
        votes = self.votes
        if not votes:
            return False  # genesis stays v1 (no bitmap bytes for nothing)
        index = seats.index
        try:
            pairs = sorted(
                ((index[pk], sig) for pk, sig in votes), key=lambda p: p[0]
            )
        except KeyError:
            return False  # a signer outside the table: fall back to v1
        enc.u32(_V2_FLAG | len(pairs))
        enc.raw(_seats_bitmap([s for s, _ in pairs], seats.nbytes))
        for _, sig in pairs:
            enc.raw(sig.data)
        return True

    @classmethod
    def decode(cls, dec: Decoder, seats: "SeatTable | None" = None) -> "QC":
        h = Digest(dec.raw(32))
        rnd = dec.u64()
        n = dec.u32()
        if n & _V2_FLAG:
            if seats is None:
                raise SerdeError("v2 certificate without a seat table")
            count = n & ~_V2_FLAG
            if count > len(seats):
                raise SerdeError(f"v2 vote count {count} exceeds committee")
            seat_list = _bitmap_seats(dec.raw(seats.nbytes), len(seats))
            if len(seat_list) != count:
                raise SerdeError(
                    f"v2 bitmap popcount {len(seat_list)} != count {count}"
                )
            sig_buf = dec.raw(64 * count)
            qc = cls.__new__(cls)
            qc.hash = h
            qc.round = rnd
            qc.__dict__["_raw_votes"] = (seat_list, sig_buf, seats)
            return qc
        if n > MAX_LEN:
            raise SerdeError(f"sequence count {n} exceeds MAX_LEN")
        votes = [
            (_intern_pk(dec.raw(32)), Signature(dec.raw(64))) for _ in range(n)
        ]
        return cls(h, rnd, votes)

    def __repr__(self) -> str:
        return f"QC({self.hash!r}, {self.round})"


# ---------------------------------------------------------------------------
# TC
# ---------------------------------------------------------------------------


@dataclass
class TC:
    round: Round
    votes: list[tuple[PublicKey, Signature, Round]]  # (author, sig, high_qc_round)

    # Lazy votes, mirroring QC: a v2-decoded TC holds
    # ``_raw_votes = (seat_indices, buf, seats)`` where ``buf`` packs
    # ``count * (64B signature + u64 LE high_qc_round)`` in seat order.
    _REC = 72  # bytes per packed v2 vote record

    def __getattr__(self, name):
        if name == "votes":
            raw = self.__dict__.get("_raw_votes")
            if raw is not None:
                seat_list, buf, seats = raw
                keys = seats.keys
                rec = self._REC
                votes = [
                    (
                        keys[s],
                        Signature(buf[i * rec : i * rec + 64]),
                        _U64.unpack_from(buf, i * rec + 64)[0],
                    )
                    for i, s in enumerate(seat_list)
                ]
                self.__dict__["votes"] = votes
                return votes
        raise AttributeError(name)

    def n_votes(self) -> int:
        votes = self.__dict__.get("votes")
        if votes is not None:
            return len(votes)
        raw = self.__dict__.get("_raw_votes")
        return len(raw[0]) if raw is not None else len(self.votes)

    def high_qc_rounds(self) -> list[Round]:
        if "votes" not in self.__dict__:
            raw = self.__dict__.get("_raw_votes")
            if raw is not None:
                _, buf, _ = raw
                rec = self._REC
                return [
                    _U64.unpack_from(buf, i * rec + 64)[0]
                    for i in range(len(raw[0]))
                ]
        return [r for _, _, r in self.votes]

    def _canonical_key(self) -> bytes:
        raw = None
        if "votes" not in self.__dict__:
            raw = self.__dict__.get("_raw_votes")
        if raw is not None:
            seat_list, buf, seats = raw
            keys = seats.keys
            rec = self._REC
            return b"".join(
                (
                    _U64.pack(self.round),
                    _U32.pack(len(seat_list)),
                    *(
                        keys[s].data + buf[i * rec : i * rec + rec]
                        for i, s in enumerate(seat_list)
                    ),
                )
            )
        enc = Encoder()
        self.encode(enc)
        return bytes(enc.finish())

    def verify(
        self, committee: Committee, cache: "CertificateCache | None" = None
    ) -> None:
        """Stake accounting, then verify per-voter digests — batched through
        the backend's multi-message path (reference ``messages.rs:283-320``
        verifies sig-by-sig; we keep identical acceptance but one device
        call). With ``cache``, a byte-identical TC that already verified is
        accepted without re-verification (every TC-former broadcasts it)."""
        key = None
        if cache is not None:
            key = CertificateCache.key_of(self)
            if cache.hit(key):
                return
        arena = cert_arena.get_arena()
        akey = None
        if arena is not None:
            akey = (
                cert_arena.committee_fp(committee),
                key if key is not None else CertificateCache.key_of(self),
            )
            if arena.hit(akey):
                if cache is not None:
                    cache.add(key)
                return
        raw = None
        if "votes" not in self.__dict__:
            raw = self.__dict__.get("_raw_votes")
        if raw is not None:
            self._verify_raw(committee, raw)
        else:
            weight = 0
            used = set()
            for name, _, _ in self.votes:
                if name in used:
                    raise errors.AuthorityReuse(str(name))
                stake = committee.stake(name)
                if stake == 0:
                    raise errors.UnknownAuthority(str(name))
                used.add(name)
                weight += stake
            if weight < committee.quorum_threshold():
                raise errors.TCRequiresQuorum("TC requires a quorum")
            try:
                Signature.verify_batch_multi(
                    [
                        (
                            sha512_digest(
                                _U64.pack(self.round), _U64.pack(hqc_round)
                            ),
                            author,
                            sig,
                        )
                        for author, sig, hqc_round in self.votes
                    ]
                )
            except BackendUnavailable:
                raise  # infrastructure failure, NOT a byzantine signature
            except CryptoError as e:
                raise errors.InvalidSignature(str(e)) from e
        if arena is not None:
            arena.add(akey)
        if cache is not None:
            cache.add(key)

    def _verify_raw(self, committee: Committee, raw) -> None:
        """Raw-slice verification of a lazily-decoded v2 TC (bitmap seats
        are distinct by construction; acceptance identical to the
        materialized path)."""
        seat_list, buf, seats = raw
        keys = seats.keys
        rec = self._REC
        weight = 0
        for s in seat_list:
            stake = committee.stake(keys[s])
            if stake == 0:
                raise errors.UnknownAuthority(str(keys[s]))
            weight += stake
        if weight < committee.quorum_threshold():
            raise errors.TCRequiresQuorum("TC requires a quorum")
        round_le = _U64.pack(self.round)
        try:
            # Per-seat statements (each voter signs its own high_qc_round),
            # but still ONE fused job over the packed 72-byte records.
            backend_verify_cert(
                [
                    sha512_digest(round_le, buf[i * rec + 64 : i * rec + 72]).data
                    for i in range(len(seat_list))
                ],
                [keys[s].data for s in seat_list],
                buf,
                rec,
                key=CertificateCache.key_of(self),
            )
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e

    def encode(self, enc: Encoder, seats: "SeatTable | None" = None) -> None:
        enc.u64(self.round)
        if seats is not None and self._encode_votes_v2(enc, seats):
            return
        enc.seq(
            self.votes, lambda e, v: e.raw(v[0].data).raw(v[1].data).u64(v[2])
        )

    def _encode_votes_v2(self, enc: Encoder, seats: "SeatTable") -> bool:
        raw = None
        if "votes" not in self.__dict__:
            raw = self.__dict__.get("_raw_votes")
        if raw is not None and raw[2] is seats:
            seat_list, buf, _ = raw
            enc.u32(_V2_FLAG | len(seat_list))
            enc.raw(_seats_bitmap(seat_list, seats.nbytes))
            enc.raw(buf)
            return True
        votes = self.votes
        if not votes:
            return False
        index = seats.index
        try:
            triples = sorted(
                ((index[pk], sig, r) for pk, sig, r in votes),
                key=lambda t: t[0],
            )
        except KeyError:
            return False  # a signer outside the table: fall back to v1
        enc.u32(_V2_FLAG | len(triples))
        enc.raw(_seats_bitmap([s for s, _, _ in triples], seats.nbytes))
        for _, sig, hqc_round in triples:
            enc.raw(sig.data).u64(hqc_round)
        return True

    @classmethod
    def decode(cls, dec: Decoder, seats: "SeatTable | None" = None) -> "TC":
        rnd = dec.u64()
        n = dec.u32()
        if n & _V2_FLAG:
            if seats is None:
                raise SerdeError("v2 certificate without a seat table")
            count = n & ~_V2_FLAG
            if count > len(seats):
                raise SerdeError(f"v2 vote count {count} exceeds committee")
            seat_list = _bitmap_seats(dec.raw(seats.nbytes), len(seats))
            if len(seat_list) != count:
                raise SerdeError(
                    f"v2 bitmap popcount {len(seat_list)} != count {count}"
                )
            buf = dec.raw(cls._REC * count)
            tc = cls.__new__(cls)
            tc.round = rnd
            tc.__dict__["_raw_votes"] = (seat_list, buf, seats)
            return tc
        if n > MAX_LEN:
            raise SerdeError(f"sequence count {n} exceeds MAX_LEN")
        votes = [
            (_intern_pk(dec.raw(32)), Signature(dec.raw(64)), dec.u64())
            for _ in range(n)
        ]
        return cls(rnd, votes)

    def __repr__(self) -> str:
        return f"TC({self.round}, {self.high_qc_rounds()})"


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------


@dataclass
class Block:
    qc: QC
    tc: TC | None
    author: PublicKey
    round: Round
    payload: list[Digest]
    signature: Signature

    @classmethod
    def genesis(cls) -> "Block":
        return cls(
            qc=QC.genesis(),
            tc=None,
            author=PublicKey(bytes(32)),
            round=0,
            payload=[],
            signature=Signature.default(),
        )

    @classmethod
    async def new(cls, qc, tc, author, round_, payload, signature_service) -> "Block":
        block = cls(qc, tc, author, round_, payload, Signature.default())
        block.signature = await signature_service.request_signature(block.digest())
        return block

    @classmethod
    def new_from_key(cls, qc, tc, author, round_, payload, secret: SecretKey) -> "Block":
        """Synchronous test constructor (reference
        ``consensus/src/tests/common.rs:48-114``)."""
        block = cls(qc, tc, author, round_, payload, Signature.default())
        block.signature = Signature.new(block.digest(), secret)
        return block

    def parent(self) -> Digest:
        return self.qc.hash

    def digest(self) -> Digest:
        # Memoized: a block's identity fields are immutable once decoded
        # or constructed (the signature, which is set after, is NOT part
        # of the digest), and the digest is recomputed on the commit
        # walk, store keying, redelivery dedup, and trace details — a
        # top-five hash bill at committee scale. Stored in the instance
        # dict so dataclass __eq__/__repr__ (declared fields only) are
        # untouched.
        d = self.__dict__.get("_digest")
        if d is None:
            d = self.__dict__["_digest"] = sha512_digest(
                self.author.data,
                _U64.pack(self.round),
                *[d.data for d in self.payload],
                self.qc.hash.data,
            )
        return d

    def verify(
        self, committee: Committee, cache: "CertificateCache | None" = None
    ) -> None:
        """Author stake + signature + embedded QC/TC (reference
        ``messages.rs:55-76``). ``cache`` skips re-verifying embedded
        certificates this node already verified (e.g. the QC also carried
        by the timeouts that preceded a view-change proposal)."""
        if committee.stake(self.author) == 0:
            raise errors.UnknownAuthority(str(self.author))
        try:
            self.signature.verify(self.digest(), self.author)
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e
        if self.qc != QC.genesis():
            self.qc.verify(committee, cache)
        if self.tc is not None:
            self.tc.verify(committee, cache)

    def encode(self, enc: Encoder, seats: "SeatTable | None" = None) -> None:
        self.qc.encode(enc, seats)
        enc.option(self.tc, lambda e, tc: tc.encode(e, seats))
        enc.raw(self.author.data).u64(self.round)
        enc.seq(self.payload, lambda e, d: e.raw(d.data))
        enc.raw(self.signature.data)

    @classmethod
    def decode(cls, dec: Decoder, seats: "SeatTable | None" = None) -> "Block":
        qc = QC.decode(dec, seats)
        tc = dec.option(lambda d: TC.decode(d, seats))
        author = _intern_pk(dec.raw(32))
        rnd = dec.u64()
        payload = dec.seq(lambda d: Digest(d.raw(32)))
        sig = Signature(dec.raw(64))
        return cls(qc, tc, author, rnd, payload, sig)

    def serialize(self) -> bytes:
        """Standalone encoding — the form blocks are stored under in the
        store (reference ``core.rs:89-93``).

        Memoized: a received block already carries its exact wire bytes
        (attached by the decoder — the encoding is canonical, so bytes
        in == bytes out), and a locally-built block is encoded once for
        its broadcast and reused for the store write. Blocks are treated
        as immutable after construction."""
        wire = self.__dict__.get("_wire")
        if wire is None:
            enc = Encoder()
            self.encode(enc)
            wire = enc.finish()
            self._wire = wire
        return wire

    @classmethod
    def deserialize(cls, data: bytes) -> "Block":
        dec = Decoder(data)
        block = cls.decode(dec)
        dec.finish()
        block._wire = bytes(data)
        return block

    def __str__(self) -> str:
        return f"B{self.round}"

    def __repr__(self) -> str:
        return (
            f"{self.digest()!r}: B({self.author!r}, {self.round}, "
            f"{self.qc!r}, {len(self.payload) * 32})"
        )


# ---------------------------------------------------------------------------
# Vote
# ---------------------------------------------------------------------------


@dataclass
class Vote:
    hash: Digest
    round: Round
    author: PublicKey
    signature: Signature

    @classmethod
    async def new(cls, block: Block, author, signature_service) -> "Vote":
        vote = cls(block.digest(), block.round, author, Signature.default())
        vote.signature = await signature_service.request_signature(vote.digest())
        return vote

    @classmethod
    def new_from_key(cls, hash_: Digest, round_: Round, author, secret) -> "Vote":
        vote = cls(hash_, round_, author, Signature.default())
        vote.signature = Signature.new(vote.digest(), secret)
        return vote

    def digest(self) -> Digest:
        return sha512_digest(self.hash.data, _U64.pack(self.round))

    def verify(self, committee: Committee) -> None:
        if committee.stake(self.author) == 0:
            raise errors.UnknownAuthority(str(self.author))
        try:
            self.signature.verify(self.digest(), self.author)
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e

    def encode(self, enc: Encoder) -> None:
        enc.raw(self.hash.data).u64(self.round).raw(self.author.data).raw(
            self.signature.data
        )

    @classmethod
    def decode(cls, dec: Decoder) -> "Vote":
        return cls(
            Digest(dec.raw(32)),
            dec.u64(),
            PublicKey(dec.raw(32)),
            Signature(dec.raw(64)),
        )

    def __repr__(self) -> str:
        return f"V({self.author!r}, {self.round}, {self.hash!r})"


# ---------------------------------------------------------------------------
# Timeout
# ---------------------------------------------------------------------------


@dataclass
class Timeout:
    high_qc: QC
    round: Round
    author: PublicKey
    signature: Signature

    @classmethod
    async def new(cls, high_qc, round_, author, signature_service) -> "Timeout":
        t = cls(high_qc, round_, author, Signature.default())
        t.signature = await signature_service.request_signature(t.digest())
        return t

    @classmethod
    def new_from_key(cls, high_qc, round_, author, secret) -> "Timeout":
        t = cls(high_qc, round_, author, Signature.default())
        t.signature = Signature.new(t.digest(), secret)
        return t

    def digest(self) -> Digest:
        return sha512_digest(_U64.pack(self.round), _U64.pack(self.high_qc.round))

    def verify(
        self, committee: Committee, cache: "CertificateCache | None" = None
    ) -> None:
        if committee.stake(self.author) == 0:
            raise errors.UnknownAuthority(str(self.author))
        try:
            self.signature.verify(self.digest(), self.author)
        except BackendUnavailable:
            raise  # infrastructure failure, NOT a byzantine signature
        except CryptoError as e:
            raise errors.InvalidSignature(str(e)) from e
        if self.high_qc != QC.genesis():
            # The dominant cost: every node's timeout in a view change
            # carries the same high_qc — the cache collapses N copies to
            # one batch verification.
            self.high_qc.verify(committee, cache)

    def encode(self, enc: Encoder, seats: "SeatTable | None" = None) -> None:
        self.high_qc.encode(enc, seats)
        enc.u64(self.round).raw(self.author.data).raw(self.signature.data)

    @classmethod
    def decode(cls, dec: Decoder, seats: "SeatTable | None" = None) -> "Timeout":
        return cls(
            QC.decode(dec, seats),
            dec.u64(),
            PublicKey(dec.raw(32)),
            Signature(dec.raw(64)),
        )

    def __repr__(self) -> str:
        return f"TV({self.author!r}, {self.round}, {self.high_qc!r})"


# ---------------------------------------------------------------------------
# Wire envelope: ConsensusMessage (reference ``consensus.rs:32-39``).
# ---------------------------------------------------------------------------

TAG_PROPOSE = 0
TAG_VOTE = 1
TAG_TIMEOUT = 2
TAG_TC = 3
TAG_SYNC_REQUEST = 4
TAG_STATE_REQUEST = 5
TAG_STATE_RESPONSE = 6


def encode_propose(block: Block, seats: "SeatTable | None" = None) -> bytes:
    # v1: rides the block's memoized wire bytes (one encode per block per
    # process, shared between broadcast and store). With ``seats``, the
    # wire carries the v2 (seat-bitmap) certificate encoding instead —
    # memoized separately; the STORE format stays canonical v1.
    if seats is None:
        return bytes([TAG_PROPOSE]) + block.serialize()
    memo = block.__dict__.get("_wire_v2")
    if memo is None or memo[0] is not seats:
        enc = Encoder()
        block.encode(enc, seats)
        memo = (seats, enc.finish())
        block._wire_v2 = memo
    return bytes([TAG_PROPOSE]) + memo[1]


def encode_vote(vote: Vote) -> bytes:
    enc = Encoder().u8(TAG_VOTE)
    vote.encode(enc)
    return enc.finish()


def encode_timeout(timeout: Timeout, seats: "SeatTable | None" = None) -> bytes:
    enc = Encoder().u8(TAG_TIMEOUT)
    timeout.encode(enc, seats)
    return enc.finish()


def encode_tc(tc: TC, seats: "SeatTable | None" = None) -> bytes:
    enc = Encoder().u8(TAG_TC)
    tc.encode(enc, seats)
    return enc.finish()


def encode_sync_request(missing: Digest, origin: PublicKey) -> bytes:
    return Encoder().u8(TAG_SYNC_REQUEST).raw(missing.data).raw(origin.data).finish()


def encode_state_request(since_round: int, origin: PublicKey) -> bytes:
    """Anti-entropy frontier probe: ``origin`` asks a peer where the quorum
    commit frontier is, declaring its own committed round so the peer can
    decide whether a snapshot is worth attaching."""
    return Encoder().u8(TAG_STATE_REQUEST).u64(since_round).raw(origin.data).finish()


def encode_state_response(
    frontier_round: int, frontier: Digest, snapshot: bytes | None
) -> bytes:
    """Reply to a state request (or to a sync request for a truncated
    digest): the peer's committed frontier, optionally carrying its snapshot
    record so a cold joiner can establish a verified floor."""
    enc = Encoder().u8(TAG_STATE_RESPONSE)
    enc.u8(1 if snapshot is not None else 0)
    enc.u64(frontier_round).raw(frontier.data)
    if snapshot is not None:
        enc.raw(snapshot)
    return enc.finish()


# Fixed Vote wire layout (TAG_VOTE + Vote.encode):
#   u8 tag | 32B hash | u64 LE round | 32B author | 64B signature
# The native transport's vote pre-stage length-validates and decodes
# round/author from these offsets in C++ (network/native/netcore.cpp);
# this is the matching batch decoder for the frames it admits.
VOTE_WIRE_LEN = 137
_VOTE_ROUND = struct.Struct("<Q")


def decode_vote_frame(data: bytes) -> Vote:
    """Decode one fixed-layout vote frame (fast path: direct slicing, no
    Decoder object). Accepts exactly what ``decode_message`` would return
    ``("vote", ...)`` for."""
    if len(data) != VOTE_WIRE_LEN or data[0] != TAG_VOTE:
        raise errors.MalformedMessage("not a fixed-layout vote frame")
    return Vote(
        Digest(data[1:33]),
        _VOTE_ROUND.unpack_from(data, 33)[0],
        _intern_pk(data[41:73]),
        Signature(data[73:137]),
    )


def decode_message(data: bytes, seats: "SeatTable | None" = None):
    """Returns (kind, payload). Raises on malformed/byzantine input.

    With ``seats``, wire-format v2 certificate sections (seat bitmap +
    concatenated signatures) are accepted alongside v1; without it a v2
    frame is rejected as malformed (a v1-only peer's behavior)."""
    dec = Decoder(data)
    tag = dec.u8()
    if tag == TAG_PROPOSE:
        block = Block.decode(dec, seats)
        dec.finish()
        # For a v1 frame the canonical encoding means the frame's tail IS
        # the block's serialization: attach it so store_block never
        # re-encodes the 2f+1-vote QC it just decoded. A v2 frame is NOT
        # the store format (stores stay v1-canonical so restores never
        # need a seat table) — serialize() re-encodes once per block,
        # amortized process-wide by the decode arena.
        if "_raw_votes" not in block.qc.__dict__ and (
            block.tc is None or "_raw_votes" not in block.tc.__dict__
        ):
            block._wire = bytes(data[1:])
        return ("propose", block)
    elif tag == TAG_VOTE:
        out = ("vote", Vote(
            Digest(dec.raw(32)), dec.u64(), _intern_pk(dec.raw(32)),
            Signature(dec.raw(64)),
        ))
    elif tag == TAG_TIMEOUT:
        out = ("timeout", Timeout.decode(dec, seats))
    elif tag == TAG_TC:
        out = ("tc", TC.decode(dec, seats))
    elif tag == TAG_SYNC_REQUEST:
        out = ("sync_request", (Digest(dec.raw(32)), PublicKey(dec.raw(32))))
    elif tag == TAG_STATE_REQUEST:
        out = ("state_request", (dec.u64(), PublicKey(dec.raw(32))))
    elif tag == TAG_STATE_RESPONSE:
        has_snapshot = dec.u8()
        if has_snapshot not in (0, 1):
            raise errors.MalformedMessage("state_response snapshot flag")
        round = dec.u64()
        digest = Digest(dec.raw(32))
        # tag(1) + flag(1) + round(8) + digest(32) = 42 bytes consumed; the
        # snapshot record is the whole remaining tail (self-describing codec).
        snapshot = bytes(dec.raw(len(data) - 42)) if has_snapshot else None
        dec.finish()
        return ("state_response", (round, digest, snapshot))
    else:
        raise errors.MalformedMessage(f"unknown consensus tag {tag}")
    dec.finish()
    return out
