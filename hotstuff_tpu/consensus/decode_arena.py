"""Shared decode arena: parse each distinct consensus frame exactly once
per process.

Why: in the one-process committee testbed every broadcast frame — a
proposal carrying a 2f+1-signature QC, a view-change timeout carrying the
same high_qc, a TC — is delivered to N engines and was parsed N times,
once per engine. The PR 7 profile named that loop as the N=200 ingress
wall at function level: ``serde.raw`` 30%, ``Signature/PublicKey.__init__``
18%, ``serde._take`` 15% of the edge. The codec is deterministic and the
decoded objects are immutable by construction (blocks/QCs/TCs are never
mutated after decode; memo attributes are idempotent), so byte-identical
frames decode to interchangeable views — the arena hands every engine a
zero-copy reference to ONE shared decode.

This is pure memoization of a deterministic function, so — unlike the
per-node ``CertificateCache``, which models *verification work* a real
distributed node must pay itself — a process-wide arena does not let one
node skip work another paid for in any way that matters to the modeled
deployment: a multi-process deployment simply sees fewer hits (rebroadcast
timeouts/TCs still repeat byte-identically within one process and still
win).

Only broadcast-shaped kinds are cached (``propose``, ``timeout``, ``tc``).
Votes travel point-to-point (unique per author) and sync requests are
trivial — caching them would only grow the table. Failed parses are NOT
cached: malformed frames re-raise on every arrival, byte-for-byte the
behavior of the per-engine decoder.

Keyed by (seat-table fingerprint, frame bytes): the same bytes decoded
under different committees (tests) must not alias. Bounded by entries AND
bytes with LRU eviction. ``HOTSTUFF_DECODE_ARENA=0`` disables the arena
(every call falls through to a fresh decode) for A/B runs and the
equivalence tests.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from hotstuff_tpu import telemetry

from .messages import SeatTable, decode_message

_CACHEABLE = frozenset(("propose", "timeout", "tc"))


class DecodeArena:
    """Content-addressed cache of decoded consensus frames."""

    def __init__(self, max_entries: int = 2048, max_bytes: int = 64 << 20) -> None:
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.hits = 0
        self.misses = 0
        self.bytes_saved = 0
        self._bytes = 0
        # (fingerprint, frame) -> (kind, payload, nbytes)
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        # Decodes run on the event loop today, but the arena is
        # process-wide state and one uncontended lock acquisition is
        # noise next to a frame parse.
        self._lock = threading.Lock()
        self._metrics_live = None  # refreshed when telemetry flips on/off

    def _metrics(self):
        # The arena outlives telemetry.enable() (module singleton), so
        # metric objects are re-fetched whenever the enabled state flips
        # instead of being captured once at import.
        live = telemetry.enabled()
        if live != self._metrics_live:
            self._metrics_live = live
            self._m_hits = telemetry.counter("consensus.arena.hits")
            self._m_misses = telemetry.counter("consensus.arena.misses")
            self._m_saved = telemetry.counter("consensus.arena.bytes_saved")
            self._m_evict = telemetry.counter("consensus.arena.evictions")
        return self._m_hits, self._m_misses, self._m_saved, self._m_evict

    def decode(self, data: bytes, seats: SeatTable | None = None):
        m_hits, m_misses, m_saved, m_evict = self._metrics()
        key = (seats.fingerprint if seats is not None else None, bytes(data))
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                self.bytes_saved += entry[2]
                m_hits.inc()
                m_saved.inc(entry[2])
                return entry[0], entry[1]
        kind, payload = decode_message(data, seats)
        with self._lock:
            self.misses += 1
            m_misses.inc()
            if kind in _CACHEABLE and key not in self._entries:
                nbytes = len(key[1])
                self._entries[key] = (kind, payload, nbytes)
                self._bytes += nbytes
                while self._entries and (
                    len(self._entries) > self.max_entries
                    or self._bytes > self.max_bytes
                ):
                    _, (_, _, evicted) = self._entries.popitem(last=False)
                    self._bytes -= evicted
                    m_evict.inc()
        return kind, payload

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "bytes_saved": self.bytes_saved,
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0


_ENABLED = os.environ.get("HOTSTUFF_DECODE_ARENA", "1") != "0"
_ARENA = DecodeArena()

# Gauge collector: entry count / resident bytes surface in snapshots
# without a per-decode gauge write.
telemetry.register_collector(
    "consensus.arena",
    lambda: {"entries": len(_ARENA._entries), "bytes": _ARENA._bytes},
)


def arena() -> DecodeArena:
    return _ARENA


def enabled() -> bool:
    return _ENABLED


def decode_shared(data: bytes, seats: SeatTable | None = None):
    """Arena-backed :func:`decode_message`; identical results and
    identical exceptions, minus the redundant re-parses."""
    if not _ENABLED:
        return decode_message(data, seats)
    return _ARENA.decode(data, seats)
