"""Leader election.

``RRLeaderElector`` is the reference behavior: round-robin over sorted
public keys (reference ``consensus/src/leader.rs:16-20``).

``ReputationLeaderElector`` is an opt-in pacemaker variant beyond the
reference (``Parameters.leader_elector = "reputation"``), in the style
of DiemBFT v4's leader reputation: leaders are drawn from validators
that recently PARTICIPATED — authors and QC signers of the last
``window`` committed blocks — with the most recent authors excluded
(spread the load), chosen by a deterministic hash of the round. A
crashed or partitioned validator stops appearing in committed QCs and
drops out of the candidate set after ``window`` commits, so the
committee stops burning timeout rounds electing it — round-robin pays
one ``timeout_delay`` every N rounds per crashed node, forever.

Determinism caveat (why this is opt-in, and why ``lenient``): the
candidate set derives from each node's local committed prefix. Honest
nodes commit identical blocks, but transiently one may lag a commit
behind; during that lag two nodes disagree on a round's leader. If the
lagging node simply REJECTED the proposal (the round-robin code path),
the divergence would be sticky: commits only advance by processing
proposals, so its window could never catch up. Reputation mode
therefore marks itself ``lenient``: the Core verifies and processes a
valid proposal's CERTIFICATES regardless of the local leader opinion —
QCs advance rounds and commits, which updates the window and heals the
divergence — and only the VOTE is withheld for an unexpected author.
Safety is untouched either way (it rests on quorum intersection and the
voting rules, not on leader agreement); the lag costs at most some
withheld votes, covered by the 2f+1 quorum of converged nodes. The boot
window is empty (and empty again after restart — the window is not
persisted), so a fresh node elects round-robin; while its window is
empty the storage gate is lifted entirely (``has_window``) so it can
commit running peers' proposals, rebuild the window, and converge —
withholding votes, not blocking progress, along the way.
"""

from __future__ import annotations

import hashlib
import struct
from collections import deque

from hotstuff_tpu.crypto import PublicKey

from .config import Committee, Round

_U64 = struct.Struct("<Q")


class RRLeaderElector:
    #: strict leader check: unexpected authors are rejected outright
    #: (reference behavior; round-robin needs no committed state, so all
    #: honest nodes always agree and rejection cannot wedge anyone).
    lenient = False

    def __init__(self, committee: Committee) -> None:
        self.committee = committee
        self._sorted = committee.sorted_keys()

    def get_leader(self, round_: Round) -> PublicKey:
        return self._sorted[round_ % len(self._sorted)]

    def update(self, block) -> None:
        """Committed-block feed; round-robin keeps no state."""

    def note_round_entry(self, round_: Round, via_tc: bool) -> None:
        """Round-entry feed (see ReputationLeaderElector); round-robin is
        already window-free and keeps no state."""

    def gate_active(self, round_: Round) -> bool:
        """Elector protocol (see ReputationLeaderElector.gate_active);
        unreachable for round-robin — strict mode rejects mismatched
        authors before the gate."""
        return True


class ReputationLeaderElector:
    """Active-set leader election over a ROUND-LAGGED committed window.

    The lag is the agreement mechanism: a commit lands on different
    nodes at different wall-times, so an election that read the latest
    window would diverge for rounds already in flight — observed live as
    a timeout every commit-lag rounds. Electing round ``r`` only from
    committed blocks with round <= r - LAG means the deciding entries
    are commits every honest participant made many rounds ago; a fresh
    commit influences only elections >= LAG rounds ahead, long after the
    whole committee has it. Nodes that advanced via a TC without the
    underlying blocks withhold votes until they sync (certificates heal
    them — see ``lenient``), costing at most one timeout, not a wedge.
    """

    #: see module docstring: certificate processing must not depend on
    #: the (window-derived, transiently divergent) leader opinion.
    lenient = True

    #: elections for round r use only commits with round <= r - LAG.
    #: Must exceed the 2-chain commit lag (2) plus processing skew.
    LAG = 6

    #: TC-entered rounds remembered for the round-robin fallback (old
    #: entries expire FIFO; the set only needs to cover rounds the core
    #: still elects for — current, next, and recent block rounds).
    TC_MEMORY = 64

    def __init__(
        self, committee: Committee, window: int = 10, exclude: int = 1
    ) -> None:
        self.committee = committee
        self._sorted = committee.sorted_keys()
        self.exclude = exclude
        self.window = window
        # Retain LAG extra entries: the electing set is "the last
        # `window` commits with round <= horizon", and a node that has
        # committed up to LAG blocks PAST the horizon must not have
        # evicted entries a less-advanced node still selects — identical
        # committed prefixes must yield identical electing sets.
        self._window: deque = deque(maxlen=window + self.LAG)
        # Rounds entered through a TimeoutCertificate (timeout-grind
        # killer — see note_round_entry).
        self._tc_rounds: deque = deque(maxlen=self.TC_MEMORY)
        self._tc_set: set = set()

    def _anchored(self, round_: Round) -> list:
        horizon = round_ - self.LAG
        entries = [e for e in self._window if e[0] <= horizon]
        return entries[-self.window :]

    def gate_active(self, round_: Round) -> bool:
        """True only when this node's election for ``round_`` rests on a
        FULL anchored window — the regime where honest nodes provably
        agree (identical committed prefixes => identical last-`window`
        anchored entries). A sparse or empty anchored set (boot; the
        first rounds after a restart — the window is not persisted)
        means the node's opinion is round-robin-ish and likely diverges
        from running peers: the Core then lifts the solicited-block
        storage gate so the node can still process and COMMIT peers'
        proposals, rebuild its window, and converge. Gating storage in
        that regime wedged a committee into a timeout grind: every
        proposal skipped, no commits, windows frozen, disagreement
        permanent."""
        return len(self._anchored(round_)) >= self.window

    def update(self, block) -> None:
        """Feed committed blocks in commit order (Core.commit calls this).

        Non-members are filtered out: the genesis block's author (and its
        empty QC) are placeholders, not electable validators."""
        members = self.committee.authorities
        author = block.author if block.author in members else None
        signers = tuple(
            pk for pk, _ in block.qc.votes if pk in members
        )
        if author is None and not signers:
            return  # genesis: nothing electable
        self._window.append((block.round, author, signers))

    def note_round_entry(self, round_: Round, via_tc: bool) -> None:
        """Round-entry feed from the Core (``advance_round``): whether
        ``round_`` was reached through a QC or a TimeoutCertificate.

        Why this exists — the residual "timeout grind" root cause: when
        honest nodes' windows transiently DIVERGE (a straggler that
        TC-advanced past its commit progress; the boot transition from
        round-robin to window election under a vote split), rounds can
        reach a regime where no candidate is self-elected AND endorsed
        by a quorum. Nothing commits in a timeout round, so the windows
        that caused the disagreement stay FROZEN — convergence waited on
        a hash(round) coincidence, burning a full ``timeout_delay`` per
        miss (observed as multi-second stalls with rounds advancing,
        ~2/30 e2e runs). A round entered via TC therefore falls back to
        ROUND-ROBIN election: window-free, so every honest node that saw
        the round time out agrees on the next leader deterministically
        — one wasted timeout is the worst case, the first post-TC
        commit refills the windows, and window election resumes. (The
        DiemBFT/Jolteon pacemakers use the same escape hatch.) Safety is
        untouched: leader choice only gates votes and storage, never
        quorum intersection.
        """
        if not via_tc:
            return
        if round_ not in self._tc_set:
            if len(self._tc_rounds) == self._tc_rounds.maxlen:
                self._tc_set.discard(self._tc_rounds[0])
            self._tc_rounds.append(round_)
            self._tc_set.add(round_)

    def get_leader(self, round_: Round) -> PublicKey:
        if round_ in self._tc_set:
            # TC-entered round: deterministic window-free fallback (see
            # note_round_entry).
            return self._sorted[round_ % len(self._sorted)]
        anchored = self._anchored(round_)
        active: set[PublicKey] = set()
        recent_authors: list[PublicKey] = []
        for _blk_round, author, signers in anchored:
            if author is not None:
                active.add(author)
                recent_authors.append(author)
            active.update(signers)
        if not active:
            # Boot (or post-restart) fallback: deterministic everywhere.
            return self._sorted[round_ % len(self._sorted)]
        excluded = (
            set(recent_authors[-self.exclude :]) if self.exclude else set()
        )
        eligible = sorted(
            (pk for pk in active if pk not in excluded),
            key=lambda pk: pk.data,
        )
        if not eligible:  # degenerate single-participant window
            eligible = sorted(active, key=lambda pk: pk.data)
        h = hashlib.sha512(_U64.pack(round_)).digest()
        return eligible[int.from_bytes(h[:8], "little") % len(eligible)]


class ScheduledLeaderElector:
    """A fixed ``{round: leader}`` override with round-robin fallback —
    the per-round leader-assignment control of the Twins methodology
    (Bano et al.): the adversary scripts exactly who leads each round,
    instead of waiting for rotation to land where the attack needs it.

    Strict like round-robin (the schedule is global and deterministic,
    so all instances consulting it agree), stateless (``update`` /
    ``note_round_entry`` are no-ops), and safe to share across the
    simulated instances of one world. Not reachable from production
    config on purpose: it exists for ``sim.twins`` adversary
    enumeration, where ``SimWorld(leader_schedule=...)`` installs it.
    """

    lenient = False

    def __init__(
        self, committee: Committee, schedule: dict[Round, PublicKey]
    ) -> None:
        self.committee = committee
        self._sorted = committee.sorted_keys()
        self._schedule = dict(schedule)

    def get_leader(self, round_: Round) -> PublicKey:
        pk = self._schedule.get(round_)
        if pk is not None:
            return pk
        return self._sorted[round_ % len(self._sorted)]

    def update(self, block) -> None:
        pass

    def note_round_entry(self, round_: Round, via_tc: bool) -> None:
        pass

    def gate_active(self, round_: Round) -> bool:
        return True


def make_elector(committee: Committee, kind: str):
    if kind == "reputation":
        return ReputationLeaderElector(committee)
    if kind in ("round-robin", "rr", ""):
        return RRLeaderElector(committee)
    raise ValueError(f"unknown leader_elector {kind!r}")


LeaderElector = RRLeaderElector
