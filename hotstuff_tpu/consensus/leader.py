"""Round-robin leader election over sorted public keys (reference
``consensus/src/leader.rs:16-20``)."""

from __future__ import annotations

from hotstuff_tpu.crypto import PublicKey

from .config import Committee, Round


class RRLeaderElector:
    def __init__(self, committee: Committee) -> None:
        self.committee = committee
        self._sorted = committee.sorted_keys()

    def get_leader(self, round_: Round) -> PublicKey:
        return self._sorted[round_ % len(self._sorted)]


LeaderElector = RRLeaderElector
