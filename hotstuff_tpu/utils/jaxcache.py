"""Persistent XLA compilation cache, keyed under the repo.

Cold-compiling the crypto mega-kernels costs tens of seconds (worst
observed ~400 s when the device tunnel is slow); the persistent cache
makes every later process start pay a disk read instead. Used by
``bench.py``, the test suite conftest, and the node's TPU backend.

The cache is per-backend (TPU executables and CPU executables hash
differently), so tests (CPU) and bench (TPU) coexist in one directory.
"""

from __future__ import annotations

import hashlib
import os
import platform

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), ".jax_cache")

_enabled = False


def host_fingerprint() -> str:
    """Short stable id of this host's CPU feature set.

    XLA CPU executables are AOT-compiled for the build host's ISA
    extensions; loading an entry produced under a different feature set
    (e.g. AVX-512 code on an AVX2 box after the bench environment moves
    hosts) SIGILLs/segfaults the interpreter — observed live in round 2.
    Keying the cache directory by the feature flags makes a wrong-host
    cache invisible instead of lethal. Hash input: the cpuinfo ``flags``
    line (ISA extensions) + machine arch; kernel version and core count
    deliberately excluded (they don't change codegen)."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):  # x86 / arm
                    flags = line.split(":", 1)[1].strip()
                    break
    except OSError:  # non-Linux: arch alone still partitions usefully
        pass
    digest = hashlib.sha256(
        f"{platform.machine()}|{flags}".encode()
    ).hexdigest()[:12]
    return f"host-{digest}"


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """Idempotently enable JAX's persistent compilation cache.

    Returns the cache directory in use. Safe to call before or after the
    backend initializes; must be called before the first ``jit`` compile
    to benefit that compile.
    """
    global _enabled
    cache_dir = cache_dir or os.environ.get("HOTSTUFF_JAX_CACHE", _DEFAULT_DIR)
    # Entries compiled under a different CPU feature set can SIGILL on
    # load: partition by host fingerprint (see ``host_fingerprint``).
    cache_dir = os.path.join(cache_dir, host_fingerprint())
    if _enabled:
        return cache_dir
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache everything: the kernels here are few and large, so there is no
    # benefit to the default size/time thresholds.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled = True
    return cache_dir
