from .serde import Encoder, Decoder, SerdeError

__all__ = ["Encoder", "Decoder", "SerdeError"]
