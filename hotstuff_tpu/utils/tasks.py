"""Actor-task lifecycle helpers.

Every long-lived actor task should attach :func:`log_task_death` so an
unhandled exception is surfaced loudly instead of vanishing into an
un-awaited task (the asyncio analog of the reference's panic-on-join
behavior for crashed tokio tasks).
"""

from __future__ import annotations

import asyncio
import logging

log = logging.getLogger("hotstuff")


def log_task_death(task: asyncio.Task) -> None:
    """Done-callback: surface unexpected actor-task death."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        log.critical(
            "task %s died: %s: %s", task.get_name(), type(exc).__name__, exc
        )
