"""Shared stake-weighted ACK-quorum waiting.

Both back-pressure points — the mempool QuorumWaiter (batch dissemination,
reference ``mempool/src/quorum_waiter.rs:80-102``) and the consensus
Proposer (block dissemination, reference ``consensus/src/proposer.rs:105-121``)
— wait until ReliableSender ACK handlers representing 2f+1 stake resolve.
"""

from __future__ import annotations

import asyncio


async def _waiter(handler: asyncio.Future, stake: int) -> int:
    """Resolve to the handler's stake once ACKed; 0 if cancelled."""
    try:
        await handler
        return stake
    except asyncio.CancelledError:
        return 0


async def wait_for_ack_quorum(
    handlers: list[tuple[object, asyncio.Future]],
    stake_of,
    own_stake: int,
    threshold: int,
) -> tuple[bool, dict[asyncio.Task, asyncio.Future]]:
    """Wait until ACKed stake (plus ``own_stake``) reaches ``threshold``.

    ``handlers``: (name, CancelHandler) pairs; ``stake_of(name)`` -> stake.
    Returns (reached, remaining) where ``remaining`` maps still-pending
    waiter tasks to their underlying handler futures — the caller decides
    whether to cancel them or grant extra dissemination time.
    """
    waiters = {
        asyncio.ensure_future(_waiter(h, stake_of(name))): h for name, h in handlers
    }
    total = own_stake
    pending = set(waiters)
    while total < threshold and pending:
        done, pending = await asyncio.wait(
            pending, return_when=asyncio.FIRST_COMPLETED
        )
        for t in done:
            total += t.result()
    return total >= threshold, {t: waiters[t] for t in pending}


def cancel_remaining(remaining: dict[asyncio.Task, asyncio.Future]) -> None:
    """Cancel both the waiter tasks and their handlers (stops the
    ReliableSender replaying those messages)."""
    for task, handler in remaining.items():
        handler.cancel()
        task.cancel()
