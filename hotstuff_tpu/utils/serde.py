"""Deterministic binary wire codec.

The reference serializes every wire message with bincode (little-endian,
length-prefixed vectors) over length-delimited TCP frames (reference
``network/src/receiver.rs:20-27``, ``mempool/src/mempool.rs:29-33``). We use
our own equally-simple format — explicit, deterministic, and safe to decode
from untrusted peers (no pickle):

- integers: fixed-width little-endian (``u8``/``u32``/``u64``)
- byte strings: ``u32`` length prefix + raw bytes
- sequences: ``u32`` count prefix + elements
- enums: ``u8`` tag + variant payload
- options: ``u8`` 0/1 + payload

Determinism matters: signatures cover SHA-512 digests of serialized content,
so encoding must be canonical (one byte string per value).
"""

from __future__ import annotations

import struct


class SerdeError(Exception):
    """Raised on malformed input from the wire."""


_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# Upper bound on any length prefix we will allocate for; guards against
# memory-exhaustion from malformed/byzantine frames.
MAX_LEN = 64 * 1024 * 1024


class Encoder:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u8(self, v: int) -> "Encoder":
        self._parts.append(_U8.pack(v))
        return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(_U32.pack(v))
        return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(_U64.pack(v))
        return self

    def raw(self, b: bytes) -> "Encoder":
        """Fixed-size field: no length prefix (e.g. 32-byte digests)."""
        self._parts.append(b)
        return self

    def bytes(self, b: bytes) -> "Encoder":
        self._parts.append(_U32.pack(len(b)))
        self._parts.append(b)
        return self

    def seq(self, items, write_item) -> "Encoder":
        self._parts.append(_U32.pack(len(items)))
        for it in items:
            write_item(self, it)
        return self

    def option(self, value, write_value) -> "Encoder":
        if value is None:
            self._parts.append(b"\x00")
        else:
            self._parts.append(b"\x01")
            write_value(self, value)
        return self

    def finish(self) -> bytes:
        return b"".join(self._parts)


class Decoder:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if n < 0 or self._pos + n > len(self._buf):
            raise SerdeError(f"short read: need {n} bytes at offset {self._pos}")
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return _U8.unpack(self._take(1))[0]

    def u32(self) -> int:
        return _U32.unpack(self._take(4))[0]

    def u64(self) -> int:
        return _U64.unpack(self._take(8))[0]

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def bytes(self) -> bytes:
        n = self.u32()
        if n > MAX_LEN:
            raise SerdeError(f"length prefix {n} exceeds MAX_LEN")
        return self._take(n)

    def seq(self, read_item) -> list:
        n = self.u32()
        if n > MAX_LEN:
            raise SerdeError(f"sequence count {n} exceeds MAX_LEN")
        return [read_item(self) for _ in range(n)]

    def option(self, read_value):
        tag = self.u8()
        if tag == 0:
            return None
        if tag == 1:
            return read_value(self)
        raise SerdeError(f"bad option tag {tag}")

    def finish(self) -> None:
        """Assert the whole buffer was consumed (canonical encodings only)."""
        if self._pos != len(self._buf):
            raise SerdeError(
                f"trailing garbage: {len(self._buf) - self._pos} bytes unread"
            )
