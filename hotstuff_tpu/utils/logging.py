"""Logging setup emitting the reference harness's log format.

The benchmark measurement system is regex-scraping of timestamped log lines
(reference ``benchmark/benchmark/logs.py:90-141``); the expected shape is
env_logger's: ``[2021-06-01T07:58:01.845Z INFO module] message`` with
millisecond UTC timestamps and WARN (not WARNING) level names. Keeping this
exact format means the reference harness could parse our logs unchanged.
"""

from __future__ import annotations

import logging
import sys
import time

_LEVELS = {0: logging.ERROR, 1: logging.WARNING, 2: logging.INFO, 3: logging.DEBUG}


class _EnvLoggerFormatter(logging.Formatter):
    converter = time.gmtime

    def format(self, record: logging.LogRecord) -> str:
        level = {"WARNING": "WARN", "CRITICAL": "ERROR"}.get(
            record.levelname, record.levelname
        )
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", self.converter(record.created))
        ms = int(record.msecs)
        return f"[{ts}.{ms:03d}Z {level} {record.name}] {record.getMessage()}"


def setup_logging(verbosity: int = 2, stream=None) -> None:
    """verbosity: 0=error 1=warn 2=info 3+=debug (reference -v flag
    semantics, ``node/src/main.rs:61-71``)."""
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(_EnvLoggerFormatter())
    root = logging.getLogger()
    root.handlers.clear()
    root.addHandler(handler)
    root.setLevel(_LEVELS.get(verbosity, logging.DEBUG))
