"""GF(2^255-19) arithmetic in radix-2^13 limbs on int32 — VPU-native.

Representation: a field element is ``int32[..., 20]``, limb k weighing
2^(13k); 20x13 = 260 bits of headroom over the 255-bit field. Loose limbs
(< 2^13 + small slack) are the working form; ``canonical`` produces the
unique reduced form for comparisons/serialization.

Why radix 13: products of 13-bit limbs are <= 2^26 and a 20-term schoolbook
column sums to < 2^31, so multiplication never leaves native int32 — no
64-bit emulation anywhere (TPU VPU has no native 64-bit path). The fold of
limbs >= 20 multiplies by 19*2^5 = 608 (2^260 = 2^5 * 2^255 = 2^5 * 19 mod p),
applied only after a carry pass so the products stay small.

Verified bit-exact against the pure-Python RFC 8032 oracle
(``hotstuff_tpu.crypto.ed25519_ref``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1
P = 2**255 - 19

# p and 2p in canonical radix-13 limbs (int32).
P_LIMBS = np.array(
    [8173] + [8191] * 18 + [255], dtype=np.int32
)
TWO_P_LIMBS = (2 * P_LIMBS.astype(np.int64)).astype(np.int32)

# Fold factor for limbs >= 20: 2^260 ≡ 19 * 32 (mod p).
FOLD = 19 * 32


def _int_to_limbs(x: int) -> np.ndarray:
    return np.array([(x >> (RADIX * k)) & MASK for k in range(NLIMB)], dtype=np.int32)


def _limbs_to_int(a) -> int:
    a = np.asarray(a)
    return sum(int(a[..., k]) << (RADIX * k) for k in range(NLIMB)) % P


# Curve constant d and sqrt(-1), as module-level limb constants.
D_INT = (-121665 * pow(121666, P - 2, P)) % P
D2_INT = (2 * D_INT) % P
SQRT_M1_INT = pow(2, (P - 1) // 4, P)

D_LIMBS = _int_to_limbs(D_INT)
D2_LIMBS = _int_to_limbs(D2_INT)
SQRT_M1_LIMBS = _int_to_limbs(SQRT_M1_INT)
ONE_LIMBS = _int_to_limbs(1)
ZERO_LIMBS = _int_to_limbs(0)


def fe_from_int(x: int, batch_shape=()) -> jnp.ndarray:
    limbs = _int_to_limbs(x % P)
    return jnp.broadcast_to(jnp.asarray(limbs), (*batch_shape, NLIMB))


def fe_from_bytes(data: np.ndarray) -> np.ndarray:
    """uint8[..., 32] little-endian -> int32[..., 20] limbs (host-side).

    The top bit (the compression sign bit) must be cleared by the caller.
    Vectorized via 64-bit word windows (bit-unpacking was ~5 ms at 4k
    lanes; this is ~0.1 ms).
    """
    data = np.asarray(data, dtype=np.uint8)
    # Pad to 40 bytes so every 13-bit window fits inside one aligned u64
    # load starting at the window's byte.
    padded = np.concatenate(
        [data, np.zeros((*data.shape[:-1], 8), dtype=np.uint8)], axis=-1
    )
    out = np.empty((*data.shape[:-1], NLIMB), dtype=np.int32)
    flat = padded.reshape(-1, 40)
    for k in range(NLIMB):
        bit = RADIX * k
        byte, off = bit // 8, bit % 8
        words = flat[:, byte : byte + 8].copy().view("<u8")[:, 0]
        out.reshape(-1, NLIMB)[:, k] = ((words >> off) & MASK).astype(np.int32)
    return out


def fe_to_bytes(limbs: np.ndarray) -> np.ndarray:
    """Canonical int32[..., 20] -> uint8[..., 32] little-endian (host-side)."""
    limbs = np.asarray(limbs)
    batch = limbs.shape[:-1]
    out = np.zeros((*batch, 32), dtype=np.uint8)
    flat = limbs.reshape(-1, NLIMB)
    oflat = out.reshape(-1, 32)
    for i in range(flat.shape[0]):
        val = sum(int(flat[i, k]) << (RADIX * k) for k in range(NLIMB)) % P
        oflat[i] = np.frombuffer(val.to_bytes(32, "little"), dtype=np.uint8)
    return out


# ---------------------------------------------------------------------------
# Core arithmetic. All functions take/return int32[..., 20].
# ---------------------------------------------------------------------------


LOOSE_SLACK = FOLD  # working limbs are < 2^13 + 608 after carry passes


def _carry_pass(a: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry pass with wraparound fold: every limb sheds its
    >=2^13 part to its neighbor simultaneously; the top limb's carry folds
    to limb 0 with factor 608. Fully elementwise — no sequential scan, so
    XLA fuses whole chains of field ops into a few kernels (the sequential
    carry scan was a ~300x slowdown on TPU)."""
    c = a >> RADIX
    return (a & MASK) + jnp.concatenate(
        [c[..., -1:] * FOLD, c[..., :-1]], axis=-1
    )


def carry(a: jnp.ndarray) -> jnp.ndarray:
    """Normalize to loose limbs < 2^13 + 608. Input limbs in [0, 2^31).

    Three parallel passes: pass 1 leaves limbs < 2^13 + 2^18 (+ the fold on
    limb 0 < 2^27.3); pass 2 < 2^13 + 2^14.3; pass 3 < 2^13 + 608. Loose
    limbs of this size keep schoolbook columns < 20 * (2^13+608)^2 < 2^31.
    """
    return _carry_pass(_carry_pass(_carry_pass(a)))


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b + 2p (keeps limbs non-negative for carried inputs)."""
    return carry(a + jnp.asarray(TWO_P_LIMBS) - b)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return carry(jnp.asarray(TWO_P_LIMBS) - a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook 20x20 -> 39 columns, carry, fold >=20 by 608, carry.

    Columns are sums of <= 20 products <= 2^26 each: < 2^31, int32-safe.
    """
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    cols = jnp.zeros((*batch, 2 * NLIMB - 1), dtype=jnp.int32)
    for i in range(NLIMB):
        cols = cols.at[..., i : i + NLIMB].add(a[..., i : i + 1] * b)

    # One parallel carry pass over the 39 columns (no wraparound: the top
    # carry becomes virtual column 39). Columns < 2^31 -> < 2^13 + 2^18.
    c = cols >> RADIX
    cols = (cols & MASK).at[..., 1:].add(c[..., :-1])
    c39 = c[..., -1:]  # < 2^18

    # Fold columns 20..38 and the virtual column 39 down by 608
    # (2^(13k) = 608 * 2^(13(k-20)) mod p for k >= 20). All terms
    # < 608 * (2^13 + 2^18) < 2^28: int32-safe.
    high = jnp.concatenate([cols[..., NLIMB:], c39], axis=-1)  # 20 limbs
    folded = cols[..., :NLIMB] + high * FOLD
    # Limbs < 2^28: three more passes normalize to loose form.
    return carry(folded)


def square(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def pow_const(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e for a fixed public exponent (square-and-multiply as a lax.scan
    over the exponent bits LSB-first, keeping the compiled graph one
    square+multiply regardless of exponent size — verification-only, no
    secret exponents, so variable-time is fine)."""
    assert e > 0
    bits = jnp.asarray(
        np.array([(e >> k) & 1 for k in range(e.bit_length())], dtype=np.int32)
    )

    def step(state, bit):
        result, base = state
        result = select(bit.astype(jnp.bool_), mul(result, base), result)
        base = square(base)
        return (result, base), None

    # Derive the init carry from ``a`` (a*0 + 1) so its sharding variance
    # matches inside shard_map bodies (scan requires carry types to agree).
    one = a * 0 + jnp.asarray(ONE_LIMBS)
    (result, _), _ = lax.scan(step, (one, a), bits)
    return result


def inv(a: jnp.ndarray) -> jnp.ndarray:
    return pow_const(a, P - 2)


def canonical(a: jnp.ndarray) -> jnp.ndarray:
    """Fully reduced form in [0, p).

    After carry passes the value is < 2^260 (up to ~54p): fold the bits at
    and above 2^255 (limb 19 holds weights 2^247..2^259; its bits >= 8 are
    the overflow) back as *19, twice; the value is then < 2^255 + 19 and a
    single conditional subtract of p canonicalizes.
    """
    a = carry(carry(a))
    for _ in range(2):
        hi = a[..., 19] >> 8
        a = a.at[..., 19].set(a[..., 19] & 0xFF)
        a = a.at[..., 0].add(hi * 19)
        a = carry(a)
    ge = _geq_p(a)
    return jnp.where(ge[..., None], _sub_exact(a, jnp.asarray(P_LIMBS)), a)


def _geq_p(a: jnp.ndarray) -> jnp.ndarray:
    """a >= p for carried inputs (limbs < 2^13)."""
    p_limbs = jnp.asarray(P_LIMBS)
    # Lexicographic compare from the top limb down.
    gt = jnp.zeros(a.shape[:-1], dtype=jnp.bool_)
    eq = jnp.ones(a.shape[:-1], dtype=jnp.bool_)
    for k in range(NLIMB - 1, -1, -1):
        gt = gt | (eq & (a[..., k] > p_limbs[k]))
        eq = eq & (a[..., k] == p_limbs[k])
    return gt | eq


def _sub_exact(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b with borrow propagation; requires a >= b (both carried)."""
    diff = a - b

    def step(borrow, limb):
        t = limb - borrow
        new_borrow = (t < 0).astype(jnp.int32)
        return new_borrow, t + (new_borrow << RADIX)

    _, limbs = lax.scan(step, jnp.zeros_like(diff[..., 0]), jnp.moveaxis(diff, -1, 0))
    return jnp.moveaxis(limbs, 0, -1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field equality (canonicalizes both sides)."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """mask ? a : b, with mask shaped [...]."""
    return jnp.where(mask[..., None], a, b)


def sqrt_ratio(u: jnp.ndarray, v: jnp.ndarray, root_fn=None):
    """(was_square, sqrt(u/v)) — the decompression square root.

    Computes r = u * v^3 * (u * v^7)^((p-5)/8); then r^2 * v in {u, -u}
    decides the branch, fixing r by sqrt(-1) when needed (RFC 8032
    section 5.1.3 / curve25519 folklore). ``root_fn(u, v)`` overrides the
    candidate-root computation (the Pallas kernel on TPU).
    """
    if root_fn is not None:
        r = root_fn(u, v)
    else:
        v3 = mul(square(v), v)
        v7 = mul(square(v3), v)
        r = mul(mul(u, v3), pow_const(mul(u, v7), (P - 5) // 8))
    check = mul(square(r), v)
    u_neg = neg(u)
    correct = eq(check, u)
    flipped = eq(check, u_neg)
    r = select(flipped, mul(r, jnp.asarray(SQRT_M1_LIMBS)), r)
    return correct | flipped, r


def parity(a: jnp.ndarray) -> jnp.ndarray:
    """Low bit of the canonical value (the compression sign)."""
    return canonical(a)[..., 0] & 1
