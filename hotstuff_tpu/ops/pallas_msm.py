"""Pallas TPU kernels for the Ed25519 MSM.

Why a mega-kernel: the XLA lowering of the MSM issues ~200 kernels per
window x 64 windows; at ~20µs launch overhead on this platform that is
~0.5 s/batch of pure dispatch. These kernels keep the per-point tables, the
window loop, and the lane tree-reduction resident in VMEM, so one batch is
TWO kernel launches (block partial sums + combine/Horner).

In-kernel layout is limb-major / lane-minor ([..., 20, LANES]): the batch
lanes land on the VPU's 128-wide minor dimension at full utilization
(batch-minor [m, 20] layouts use 20/128 lanes). Field arithmetic is the
same radix-2^13 int32 scheme as ``ops.field``, with the limb axis at -2.

Kernel A (grid over lane blocks): builds the 16-entry point table for its
block, then for each of the 64 radix-16 windows one-hot-selects each
lane's multiple and tree-reduces the block to one point — [64] window
partial sums per block.

Kernel B (single step): point-adds the per-block partials and combines the
64 window sums with a Horner loop (4 doublings + 1 add per window).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import field as fe

RADIX = fe.RADIX
MASK = fe.MASK
FOLD = fe.FOLD
NLIMB = fe.NLIMB

# Field constants needed inside kernels (pallas forbids captured array
# constants, so they enter as inputs; row 0 = 2p, row 1 = 2d). Two layouts,
# avoiding in-kernel transposes: [2, 20, 1] (limb-major) and [2, 1, 20]
# (limbs-minor).
_CONSTS = np.stack(
    [np.asarray(fe.TWO_P_LIMBS), np.asarray(fe.D2_LIMBS)]
).astype(np.int32)
CONSTS_CM = _CONSTS[:, :, None]
CONSTS_LM = _CONSTS[:, None, :]

DEFAULT_BLOCK = 512
N_WINDOWS = 64
TABLE = 16


# -- field arithmetic with the limb axis at -2 (lanes minor) ---------------


def _carry_pass(a):
    c = a >> RADIX
    return (a & MASK) + jnp.concatenate(
        [c[..., -1:, :] * FOLD, c[..., :-1, :]], axis=-2
    )


def _carry(a):
    return _carry_pass(_carry_pass(_carry_pass(a)))


def _add(a, b):
    return _carry(a + b)


def _sub(a, b, two_p):
    return _carry(a + two_p - b)


def _mul(a, b):
    # Schoolbook columns as a sum of shifted partial products. No .at[].add:
    # scatter-add has no Pallas TPU lowering — pads/concats do.
    nd = a.ndim
    cols = None
    for i in range(NLIMB):
        prod = a[..., i : i + 1, :] * b  # [..., 20, L]
        pad = [(0, 0)] * (nd - 2) + [(i, NLIMB - 1 - i), (0, 0)]
        shifted = jnp.pad(prod, pad)
        cols = shifted if cols is None else cols + shifted
    c = cols >> RADIX
    zero_row = jnp.zeros_like(c[..., :1, :])
    cols = (cols & MASK) + jnp.concatenate([zero_row, c[..., :-1, :]], axis=-2)
    c39 = c[..., -1:, :]
    high = jnp.concatenate([cols[..., NLIMB:, :], c39], axis=-2)
    return _carry(cols[..., :NLIMB, :] + high * FOLD)


# -- limbs-MINOR variants (batch leading, limb axis -1) --------------------
# Used by the tiny combine kernel: [64, 20] / [1, 20] shapes tile to a few
# KB of VMEM, whereas a trailing 1-lane layout pads 128x and OOMs VMEM.


def _carry_pass_lm(a):
    c = a >> RADIX
    zero = jnp.zeros_like(c[..., :1])
    return (a & MASK) + jnp.concatenate([c[..., -1:] * FOLD + zero, c[..., :-1]], axis=-1)


def _carry_lm(a):
    return _carry_pass_lm(_carry_pass_lm(_carry_pass_lm(a)))


def _add_lm(a, b):
    return _carry_lm(a + b)


def _sub_lm(a, b, two_p):
    return _carry_lm(a + two_p - b)


def _mul_lm(a, b):
    nd = a.ndim
    cols = None
    for i in range(NLIMB):
        prod = a[..., i : i + 1] * b
        pad = [(0, 0)] * (nd - 1) + [(i, NLIMB - 1 - i)]
        shifted = jnp.pad(prod, pad)
        cols = shifted if cols is None else cols + shifted
    c = cols >> RADIX
    zero = jnp.zeros_like(c[..., :1])
    cols = (cols & MASK) + jnp.concatenate([zero, c[..., :-1]], axis=-1)
    c39 = c[..., -1:]
    high = jnp.concatenate([cols[..., NLIMB:], c39], axis=-1)
    return _carry_lm(cols[..., :NLIMB] + high * FOLD)


def _padd_lm(p, q, two_p, d2):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = _mul_lm(_sub_lm(y1, x1, two_p), _sub_lm(y2, x2, two_p))
    b = _mul_lm(_add_lm(y1, x1), _add_lm(y2, x2))
    c = _mul_lm(_mul_lm(t1, d2), t2)
    d = _mul_lm(_add_lm(z1, z1), z2)
    e, f, g, h = (
        _sub_lm(b, a, two_p),
        _sub_lm(d, c, two_p),
        _add_lm(d, c),
        _add_lm(b, a),
    )
    return (_mul_lm(e, f), _mul_lm(g, h), _mul_lm(f, g), _mul_lm(e, h))


def _pdouble_lm(p, two_p):
    x1, y1, z1, _ = p
    a = _mul_lm(x1, x1)
    b = _mul_lm(y1, y1)
    zz = _mul_lm(z1, z1)
    c = _add_lm(zz, zz)
    h = _add_lm(a, b)
    xy = _add_lm(x1, y1)
    e = _sub_lm(h, _mul_lm(xy, xy), two_p)
    g = _sub_lm(a, b, two_p)
    f = _add_lm(c, g)
    return (_mul_lm(e, f), _mul_lm(g, h), _mul_lm(f, g), _mul_lm(e, h))


# -- point ops on (x, y, z, t) tuples of [..., 20, L] ----------------------


def _padd(p, q, two_p, d2):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = _mul(_sub(y1, x1, two_p), _sub(y2, x2, two_p))
    b = _mul(_add(y1, x1), _add(y2, x2))
    c = _mul(_mul(t1, d2), t2)
    d = _mul(_add(z1, z1), z2)
    e, f, g, h = (
        _sub(b, a, two_p),
        _sub(d, c, two_p),
        _add(d, c),
        _add(b, a),
    )
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _pdouble(p, two_p):
    x1, y1, z1, _ = p
    a = _mul(x1, x1)
    b = _mul(y1, y1)
    zz = _mul(z1, z1)
    c = _add(zz, zz)
    h = _add(a, b)
    xy = _add(x1, y1)
    e = _sub(h, _mul(xy, xy), two_p)
    g = _sub(a, b, two_p)
    f = _add(c, g)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _one_limbs(lanes: int):
    """The field element 1 as [20, lanes], built from an iota (no captured
    array constants allowed in pallas kernels)."""
    idx = jax.lax.broadcasted_iota(jnp.int32, (NLIMB, lanes), 0)
    return jnp.where(idx == 0, 1, 0).astype(jnp.int32)


# -- sqrt-pow kernel: w^((p-5)/8) for batched decompression ----------------
# (p-5)/8 = 2^252 - 3 = 4*(2^250 - 1) + 1. Computes t = w^(2^250-1) by an
# addition chain on all-ones exponents (f(a+b) = f(a)^(2^b) * f(b)), then
# squares twice and multiplies by w. ~250 squarings, all VMEM-resident —
# replaces a 253-iteration XLA scan (253 kernel launches).


def _sqk(x, k):
    """x^(2^k) by k in-kernel squarings."""
    return jax.lax.fori_loop(0, k, lambda _, v: _mul(v, v), x)


def _pow_p58(w):
    """w^(2^252 - 3) on [20, L] arrays."""
    f1 = w  # 2^1 - 1
    f2 = _mul(_sqk(f1, 1), f1)
    f4 = _mul(_sqk(f2, 2), f2)
    f5 = _mul(_sqk(f4, 1), f1)
    f10 = _mul(_sqk(f5, 5), f5)
    f20 = _mul(_sqk(f10, 10), f10)
    f40 = _mul(_sqk(f20, 20), f20)
    f80 = _mul(_sqk(f40, 40), f40)
    f160 = _mul(_sqk(f80, 80), f80)
    f240 = _mul(_sqk(f160, 80), f80)
    f250 = _mul(_sqk(f240, 10), f10)
    return _mul(_sqk(f250, 2), w)


def _sqrt_pow_kernel(u, v, r):
    """r = u * v^3 * (u*v^7)^((p-5)/8) — the decompression root candidate."""
    uu, vv = u[:], v[:]
    v2 = _mul(vv, vv)
    v3 = _mul(v2, vv)
    v7 = _mul(_mul(v3, v3), vv)
    w = _mul(uu, v7)
    r[:] = _mul(_mul(uu, v3), _pow_p58(w))


@functools.lru_cache(maxsize=16)
def _build_sqrt(m: int, block: int):
    grid = m // block
    limb_spec = pl.BlockSpec((NLIMB, block), lambda b: (0, b))

    call = pl.pallas_call(
        _sqrt_pow_kernel,
        grid=(grid,),
        in_specs=[limb_spec] * 2,
        out_specs=limb_spec,
        out_shape=jax.ShapeDtypeStruct((NLIMB, m), jnp.int32),
    )

    @jax.jit
    def run(u, v):
        # [m, 20] batch-minor <-> [20, m] limb-major at the boundary.
        return call(u.T, v.T).T

    return run


def sqrt_pow(u: jnp.ndarray, v: jnp.ndarray, block: int | None = None):
    """u * v^3 * (u v^7)^((p-5)/8) for [m, 20] inputs (m power of two)."""
    m = u.shape[0]
    if block is None:
        block = min(DEFAULT_BLOCK, m)
    if block != m and block % 128 != 0:
        block = m
    return _build_sqrt(m, block)(u, v)


# -- Kernel A (signed): per-block window partial sums, 9-entry table --------

TABLE_SIGNED = 9  # multiples 0..8; negative digits negate the selection

# Per-window tree-reduction stops at this lane width inside the window
# loop; the tails of ALL windows are then reduced together in a few
# full-width passes. Rationale: tree levels narrower than a vreg are
# instruction-issue-bound (a padd costs the same instruction count at
# width 8 as at width 128), and the window loop used to pay log2(block)
# narrow levels x 64 windows; batching the tails pays log2(TAIL) levels
# ONCE at n_windows*TAIL width (~40% of the old MSM time, per the round-1
# roadmap analysis).
TAIL = 16


def _neg_fe(x, two_p):
    """-x mod p on [20, L] loose limbs (2p - x, carried)."""
    return _carry(two_p - x)


def _make_partials_kernel_signed(n_windows: int, block: int):
    tail = min(TAIL, block)

    def kernel(
        consts, px, py, pz, pt, digits_ref, wx, wy, wz, wt,
        tx, ty, tz, tt, bx, by, bz, bt,
    ):
        two_p, d2 = consts[0], consts[1]
        # 9-entry table: T[0] = identity, T[d] = T[d-1] + P (7 adds vs 14
        # for the unsigned 16-entry table).
        zero = jnp.zeros((NLIMB, block), dtype=jnp.int32)
        one = _one_limbs(block)
        tx[0], ty[0], tz[0], tt[0] = zero, one, one, zero
        tx[1], ty[1], tz[1], tt[1] = px[:], py[:], pz[:], pt[:]
        for d in range(2, TABLE_SIGNED):
            nx, ny, nz, nt = _padd(
                (tx[d - 1], ty[d - 1], tz[d - 1], tt[d - 1]),
                (px[:], py[:], pz[:], pt[:]),
                two_p,
                d2,
            )
            tx[d], ty[d], tz[d], tt[d] = nx, ny, nz, nt

        def window(w, _):
            dg = digits_ref[w]  # [block], signed in [-8, 8]
            mag = jnp.abs(dg)
            selx = jnp.zeros((NLIMB, block), dtype=jnp.int32)
            sely = jnp.zeros((NLIMB, block), dtype=jnp.int32)
            selz = jnp.zeros((NLIMB, block), dtype=jnp.int32)
            selt = jnp.zeros((NLIMB, block), dtype=jnp.int32)
            for d in range(TABLE_SIGNED):
                m = (mag == d)[None, :]
                selx = jnp.where(m, tx[d], selx)
                sely = jnp.where(m, ty[d], sely)
                selz = jnp.where(m, tz[d], selz)
                selt = jnp.where(m, tt[d], selt)
            negm = (dg < 0)[None, :]
            selx = jnp.where(negm, _neg_fe(selx, two_p), selx)
            selt = jnp.where(negm, _neg_fe(selt, two_p), selt)
            cur = (selx, sely, selz, selt)
            half = block // 2
            while half >= tail:  # stop at TAIL lanes; batch the rest
                cur = _padd(
                    tuple(c[:, :half] for c in cur),
                    tuple(c[:, half : 2 * half] for c in cur),
                    two_p,
                    d2,
                )
                half //= 2
            # Stage this window's TAIL-wide partial at lanes
            # [w*tail, (w+1)*tail) of the cross-window buffer.
            sl = pl.ds(w * tail, tail)
            cx, cy, cz, ct = cur
            bx[:, sl], by[:, sl], bz[:, sl], bt[:, sl] = (
                cx[:, :tail],
                cy[:, :tail],
                cz[:, :tail],
                ct[:, :tail],
            )
            return 0

        jax.lax.fori_loop(0, n_windows, window, 0)

        # Cross-window tail reduction: log2(tail) passes over the FULL
        # [20, n_windows*tail] buffer. Lane w*tail+j pairs with lane
        # w*tail+j+half via a lane-axis rotate; only lanes with
        # j + half < tail are meaningful, and the final window sums land
        # at lanes w*tail. 2D shapes only (Mosaic-safe).
        cur = (bx[:, :], by[:, :], bz[:, :], bt[:, :])
        half = tail // 2
        while half >= 1:
            shifted = tuple(
                jnp.concatenate([c[:, half:], c[:, :half]], axis=1) for c in cur
            )
            cur = _padd(cur, shifted, two_p, d2)
            half //= 2
        rx, ry, rz, rt = cur
        for w in range(n_windows):
            wx[0, w], wy[0, w], wz[0, w], wt[0, w] = (
                rx[:, w * tail],
                ry[:, w * tail],
                rz[:, w * tail],
                rt[:, w * tail],
            )

    return kernel


# -- Kernel A: per-block window partial sums --------------------------------


def _partials_kernel(
    consts, px, py, pz, pt, digits_ref, wx, wy, wz, wt, tx, ty, tz, tt
):
    block = px.shape[-1]
    two_p, d2 = consts[0], consts[1]
    # Build the 16-entry table: T[0] = identity, T[d] = T[d-1] + P.
    zero = jnp.zeros((NLIMB, block), dtype=jnp.int32)
    one = _one_limbs(block)
    tx[0], ty[0], tz[0], tt[0] = zero, one, one, zero
    tx[1], ty[1], tz[1], tt[1] = px[:], py[:], pz[:], pt[:]
    for d in range(2, TABLE):
        nx, ny, nz, nt = _padd(
            (tx[d - 1], ty[d - 1], tz[d - 1], tt[d - 1]),
            (px[:], py[:], pz[:], pt[:]),
            two_p,
            d2,
        )
        tx[d], ty[d], tz[d], tt[d] = nx, ny, nz, nt

    def window(w, _):
        dg = digits_ref[w]  # [block]
        selx = jnp.zeros((NLIMB, block), dtype=jnp.int32)
        sely = jnp.zeros((NLIMB, block), dtype=jnp.int32)
        selz = jnp.zeros((NLIMB, block), dtype=jnp.int32)
        selt = jnp.zeros((NLIMB, block), dtype=jnp.int32)
        for d in range(TABLE):
            m = (dg == d)[None, :]
            selx = jnp.where(m, tx[d], selx)
            sely = jnp.where(m, ty[d], sely)
            selz = jnp.where(m, tz[d], selz)
            selt = jnp.where(m, tt[d], selt)
        cur = (selx, sely, selz, selt)
        half = block // 2
        while half >= 1:
            cur = _padd(
                tuple(c[:, :half] for c in cur),
                tuple(c[:, half : 2 * half] for c in cur),
                two_p,
                d2,
            )
            half //= 2
        cx, cy, cz, ct = cur  # [20, 1]
        wx[0, w], wy[0, w], wz[0, w], wt[0, w] = cx[:, 0], cy[:, 0], cz[:, 0], ct[:, 0]
        return 0

    jax.lax.fori_loop(0, N_WINDOWS, window, 0)


# -- Kernel B: combine block partials + Horner over windows ----------------


def _make_combine_kernel(n_windows: int):
    def kernel(consts, wx, wy, wz, wt, ox, oy, oz, ot, sx, sy, sz, st):
        nblocks = wx.shape[0]
        two_p_lm, d2_lm = consts[0], consts[1]  # [1, 20] limbs-minor
        # Sum the per-block window partials in limbs-minor layout
        # ([n_windows, 20]).
        cur = (wx[0], wy[0], wz[0], wt[0])
        for g in range(1, nblocks):
            cur = _padd_lm(cur, (wx[g], wy[g], wz[g], wt[g]), two_p_lm, d2_lm)
        # Stage the combined window sums in scratch: dynamic indexing is only
        # lowerable on refs, not on computed values.
        sx[:], sy[:], sz[:], st[:] = cur

        # Horner over windows, MSB-first: S = 16*S + W[w]; states are [1, 20].
        def step(w, s):
            for _ in range(4):
                s = _pdouble_lm(s, two_p_lm)
            ww = (
                sx[pl.ds(w, 1)],
                sy[pl.ds(w, 1)],
                sz[pl.ds(w, 1)],
                st[pl.ds(w, 1)],
            )
            return _padd_lm(s, ww, two_p_lm, d2_lm)

        s0 = (sx[0:1], sy[0:1], sz[0:1], st[0:1])  # [1, 20]
        rx, ry, rz, rt = jax.lax.fori_loop(1, n_windows, step, s0)
        ox[:], oy[:], oz[:], ot[:] = rx, ry, rz, rt

    return kernel


_combine_kernel = _make_combine_kernel(N_WINDOWS)


# -- host wrapper -----------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _build_partials(m: int, block: int):
    grid = m // block
    const_spec = pl.BlockSpec((2, NLIMB, 1), lambda b: (0, 0, 0))
    limb_spec = pl.BlockSpec((NLIMB, block), lambda b: (0, b))
    digit_spec = pl.BlockSpec((N_WINDOWS, block), lambda b: (0, b))
    wsum_spec = pl.BlockSpec((1, N_WINDOWS, NLIMB), lambda b: (b, 0, 0))
    wsum_shape = jax.ShapeDtypeStruct((grid, N_WINDOWS, NLIMB), jnp.int32)

    return pl.pallas_call(
        _partials_kernel,
        grid=(grid,),
        in_specs=[const_spec] + [limb_spec] * 4 + [digit_spec],
        out_specs=[wsum_spec] * 4,
        out_shape=[wsum_shape] * 4,
        scratch_shapes=[pltpu.VMEM((TABLE, NLIMB, block), jnp.int32)] * 4,
    )


@functools.lru_cache(maxsize=16)
def _build_combine():
    return pl.pallas_call(
        _combine_kernel,
        out_shape=[jax.ShapeDtypeStruct((1, NLIMB), jnp.int32)] * 4,
        scratch_shapes=[pltpu.VMEM((N_WINDOWS, NLIMB), jnp.int32)] * 4,
    )


@functools.lru_cache(maxsize=16)
def _build(m: int, block: int):
    partials = _build_partials(m, block)
    combine = _build_combine()

    @jax.jit
    def run(points, digits):
        # points [m, 4, 20] -> limb-major [20, m] per coordinate.
        coords = jnp.moveaxis(points, 0, -1)  # [4, 20, m]
        wx, wy, wz, wt = partials(
            jnp.asarray(CONSTS_CM), coords[0], coords[1], coords[2], coords[3], digits
        )
        ox, oy, oz, ot = combine(jnp.asarray(CONSTS_LM), wx, wy, wz, wt)
        # Back to the [4, 20] stacked layout of ops.curve.
        return jnp.stack([ox[0], oy[0], oz[0], ot[0]])

    return run


def msm(points: jnp.ndarray, digits: jnp.ndarray, block: int | None = None):
    """Drop-in replacement for ``curve.msm`` backed by the Pallas kernels.

    points: [m, 4, 20] (m a power of two), digits: [64, m].
    """
    m = points.shape[0]
    if block is None:
        block = min(DEFAULT_BLOCK, m)
    # Pallas TPU blocking: the lane dimension must be 128-divisible unless
    # the block covers the whole array.
    if block != m and block % 128 != 0:
        block = m
    assert m % block == 0
    return _build(m, block)(points, digits)


# -- signed-digit variant ---------------------------------------------------

DEFAULT_BLOCK_SIGNED = 1024  # 9-entry table: ~3 MB VMEM at 1024 lanes


@functools.lru_cache(maxsize=32)
def _build_signed(m: int, block: int, n_windows: int):
    grid = m // block
    const_spec = pl.BlockSpec((2, NLIMB, 1), lambda b: (0, 0, 0))
    limb_spec = pl.BlockSpec((NLIMB, block), lambda b: (0, b))
    digit_spec = pl.BlockSpec((n_windows, block), lambda b: (0, b))
    wsum_spec = pl.BlockSpec((1, n_windows, NLIMB), lambda b: (b, 0, 0))
    wsum_shape = jax.ShapeDtypeStruct((grid, n_windows, NLIMB), jnp.int32)

    tail = min(TAIL, block)
    partials = pl.pallas_call(
        _make_partials_kernel_signed(n_windows, block),
        grid=(grid,),
        in_specs=[const_spec] + [limb_spec] * 4 + [digit_spec],
        out_specs=[wsum_spec] * 4,
        out_shape=[wsum_shape] * 4,
        scratch_shapes=[pltpu.VMEM((TABLE_SIGNED, NLIMB, block), jnp.int32)] * 4
        + [pltpu.VMEM((NLIMB, n_windows * tail), jnp.int32)] * 4,
    )

    combine = pl.pallas_call(
        _make_combine_kernel(n_windows),
        out_shape=[jax.ShapeDtypeStruct((1, NLIMB), jnp.int32)] * 4,
        scratch_shapes=[pltpu.VMEM((n_windows, NLIMB), jnp.int32)] * 4,
    )

    @jax.jit
    def run(points, digits):
        coords = jnp.moveaxis(points, 0, -1)  # [4, 20, m]
        wx, wy, wz, wt = partials(
            jnp.asarray(CONSTS_CM), coords[0], coords[1], coords[2], coords[3], digits
        )
        ox, oy, oz, ot = combine(jnp.asarray(CONSTS_LM), wx, wy, wz, wt)
        return jnp.stack([ox[0], oy[0], oz[0], ot[0]])

    return run


def msm_signed(points: jnp.ndarray, digits: jnp.ndarray, block: int | None = None):
    """Pallas MSM over SIGNED radix-16 digits (``curve.msm_signed``
    semantics): 9-entry tables + in-kernel conditional negation, window
    count taken from ``digits.shape[0]`` (33 for RLC lanes, 64 for mod-L).
    """
    m = points.shape[0]
    n_windows = digits.shape[0]
    if block is None:
        block = min(DEFAULT_BLOCK_SIGNED, m)
    if block != m and block % 128 != 0:
        block = m
    assert m % block == 0
    return _build_signed(m, block, n_windows)(points, digits)
