"""Device kernels (JAX) for the crypto hot path.

This is the TPU-native replacement for the reference's CPU crypto
(ed25519-dalek batch verification, ``crypto/src/lib.rs:206-219``): GF(2^255-19)
limb arithmetic on the VPU, Edwards25519 point operations in extended
coordinates, batched point decompression, and a shared-doubling windowed
multi-scalar multiplication evaluating the random-linear-combination batch
verification equation in one device call.

Design notes (TPU-first):
- Field elements are 20 limbs of 13 bits in ``int32``: schoolbook products
  are <= 2^26 and 20-term column sums < 2^31, so the whole multiplier runs
  in native int32 on the 8x128 VPU with no 64-bit emulation.
- All control flow is static: fixed 64 radix-16 windows via ``lax.scan``,
  identity-padded power-of-two batches, masked selects instead of branches.
- The batch dimension is the parallel axis — one verification batch maps to
  [lanes, 20] arrays; multi-chip sharding splits lanes across a Mesh and
  combines per-device partial MSM accumulators (``hotstuff_tpu.parallel``).
"""
