"""Batched SHA-512 on device.

64-bit words are emulated as (hi, lo) uint32 pairs — the TPU has no native
64-bit integer path. The batch dimension (many messages hashed in
parallel) is the lane axis; blocks chain through a ``lax.scan``; the 80
rounds and message-schedule extension are unrolled in the scan body.

Protocol fit (reference uses SHA-512 truncated to 32 B for every digest,
``crypto/src/lib.rs``, ``mempool/src/processor.rs:30``): the host keeps
hashlib for latency-bound single digests; this kernel serves
throughput-bound regimes — thousands of per-signature challenge hashes or
batch digests at committee scale (BASELINE.json config 3).

Bit-exact against hashlib (property-tested).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# Round constants (FIPS 180-4) as (hi, lo) uint32.
_K64 = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F, 0xE9B5DBA58189DBBC,
    0x3956C25BF348B538, 0x59F111F1B605D019, 0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118,
    0xD807AA98A3030242, 0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235, 0xC19BF174CF692694,
    0xE49B69C19EF14AD2, 0xEFBE4786384F25E3, 0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65,
    0x2DE92C6F592B0275, 0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F, 0xBF597FC7BEEF0EE4,
    0xC6E00BF33DA88FC2, 0xD5A79147930AA725, 0x06CA6351E003826F, 0x142929670A0E6E70,
    0x27B70A8546D22FFC, 0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6, 0x92722C851482353B,
    0xA2BFE8A14CF10364, 0xA81A664BBC423001, 0xC24B8B70D0F89791, 0xC76C51A30654BE30,
    0xD192E819D6EF5218, 0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99, 0x34B0BCB5E19B48A8,
    0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB, 0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3,
    0x748F82EE5DEFB2FC, 0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915, 0xC67178F2E372532B,
    0xCA273ECEEA26619C, 0xD186B8C721C0C207, 0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178,
    0x06F067AA72176FBA, 0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC, 0x431D67C49C100D4C,
    0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A, 0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
_H0 = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]

_K_HI = np.array([k >> 32 for k in _K64], dtype=np.uint32)
_K_LO = np.array([k & 0xFFFFFFFF for k in _K64], dtype=np.uint32)
_H0_HI = np.array([h >> 32 for h in _H0], dtype=np.uint32)
_H0_LO = np.array([h & 0xFFFFFFFF for h in _H0], dtype=np.uint32)


# -- 64-bit ops on (hi, lo) uint32 pairs -----------------------------------


def _add(a, b):
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(jnp.uint32)
    return (a[0] + b[0] + carry, lo)


def _xor(a, b):
    return (a[0] ^ b[0], a[1] ^ b[1])


def _and(a, b):
    return (a[0] & b[0], a[1] & b[1])


def _not(a):
    return (~a[0], ~a[1])


def _rotr(a, n):
    hi, lo = a
    if n == 32:
        return (lo, hi)
    if n > 32:
        hi, lo = lo, hi
        n -= 32
    n = jnp.uint32(n)
    m = jnp.uint32(32) - n
    return ((hi >> n) | (lo << m), (lo >> n) | (hi << m))


def _shr(a, n):
    hi, lo = a
    assert 0 < n < 32
    n = jnp.uint32(n)
    m = jnp.uint32(32) - n
    return (hi >> n, (lo >> n) | (hi << m))


def _compress(state, block):
    """One SHA-512 compression: state 8x(hi,lo) [lanes], block 16x(hi,lo).

    Both the message-schedule extension and the 80 rounds run as lax.scans
    (a 16-slot rolling window for the schedule) — unrolling them produced
    multi-minute XLA compiles.
    """
    ring_hi = jnp.stack([w[0] for w in block])  # [16, lanes]
    ring_lo = jnp.stack([w[1] for w in block])

    def extend(ring, _):
        rhi, rlo = ring
        w15 = (rhi[1], rlo[1])  # t-15
        w7 = (rhi[9], rlo[9])  # t-7
        w2 = (rhi[14], rlo[14])  # t-2
        w16 = (rhi[0], rlo[0])  # t-16
        s0 = _xor(_xor(_rotr(w15, 1), _rotr(w15, 8)), _shr(w15, 7))
        s1 = _xor(_xor(_rotr(w2, 19), _rotr(w2, 61)), _shr(w2, 6))
        new = _add(_add(w16, s0), _add(w7, s1))
        rhi = jnp.concatenate([rhi[1:], new[0][None]])
        rlo = jnp.concatenate([rlo[1:], new[1][None]])
        return (rhi, rlo), new

    _, extended = lax.scan(extend, (ring_hi, ring_lo), None, length=64)
    w_hi = jnp.concatenate([ring_hi, extended[0]])  # [80, lanes]
    w_lo = jnp.concatenate([ring_lo, extended[1]])

    def round_step(carry, inputs):
        a, b, c, d, e, f, g, h = carry
        k_hi, k_lo, wt_hi, wt_lo = inputs
        k = (k_hi, k_lo)
        wt = (wt_hi, wt_lo)
        s1 = _xor(_xor(_rotr(e, 14), _rotr(e, 18)), _rotr(e, 41))
        ch = _xor(_and(e, f), _and(_not(e), g))
        t1 = _add(_add(_add(h, s1), _add(ch, k)), wt)
        s0 = _xor(_xor(_rotr(a, 28), _rotr(a, 34)), _rotr(a, 39))
        maj = _xor(_xor(_and(a, b), _and(a, c)), _and(b, c))
        t2 = _add(s0, maj)
        return (_add(t1, t2), a, b, c, _add(d, t1), e, f, g), None

    k_hi = jnp.asarray(_K_HI)[:, None] + jnp.zeros_like(w_hi)
    k_lo = jnp.asarray(_K_LO)[:, None] + jnp.zeros_like(w_lo)
    final, _ = lax.scan(round_step, state, (k_hi, k_lo, w_hi, w_lo))
    return tuple(_add(s, n) for s, n in zip(state, final))


@functools.lru_cache(maxsize=8)
def _compiled(nblocks: int):
    @jax.jit
    def run(blocks_hi, blocks_lo):  # [n, nblocks, 16] uint32 each
        n = blocks_hi.shape[0]
        state = tuple(
            (
                jnp.full((n,), np.uint32(_H0_HI[i]), dtype=jnp.uint32),
                jnp.full((n,), np.uint32(_H0_LO[i]), dtype=jnp.uint32),
            )
            for i in range(8)
        )

        def body(st, blk):
            bhi, blo = blk  # [n, 16]
            words = tuple((bhi[:, j], blo[:, j]) for j in range(16))
            return _compress(st, words), None

        state, _ = lax.scan(
            body,
            state,
            (jnp.moveaxis(blocks_hi, 1, 0), jnp.moveaxis(blocks_lo, 1, 0)),
        )
        # [n, 8] hi/lo -> caller assembles bytes.
        return (
            jnp.stack([s[0] for s in state], axis=1),
            jnp.stack([s[1] for s in state], axis=1),
        )

    return run


def _pad_messages(msgs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """FIPS 180-4 padding; all messages must produce the same block count."""
    length = len(msgs[0])
    assert all(len(m) == length for m in msgs), "equal-length batches only"
    total = length + 17  # 0x80 + 16-byte length field
    nblocks = -(-total // 128)
    padded = np.zeros((len(msgs), nblocks * 128), dtype=np.uint8)
    data = np.frombuffer(b"".join(msgs), dtype=np.uint8).reshape(len(msgs), length)
    padded[:, :length] = data
    padded[:, length] = 0x80
    bitlen = length * 8
    padded[:, -16:] = np.frombuffer(
        bitlen.to_bytes(16, "big"), dtype=np.uint8
    )
    # Big-endian 64-bit words as (hi, lo) uint32.
    words = padded.reshape(len(msgs), nblocks, 16, 8)
    hi = (
        (words[..., 0].astype(np.uint32) << 24)
        | (words[..., 1].astype(np.uint32) << 16)
        | (words[..., 2].astype(np.uint32) << 8)
        | words[..., 3].astype(np.uint32)
    )
    lo = (
        (words[..., 4].astype(np.uint32) << 24)
        | (words[..., 5].astype(np.uint32) << 16)
        | (words[..., 6].astype(np.uint32) << 8)
        | words[..., 7].astype(np.uint32)
    )
    return hi, lo


def sha512_batch(msgs: list[bytes]) -> list[bytes]:
    """SHA-512 of equal-length messages, batched on device."""
    hi, lo = _pad_messages(msgs)
    out_hi, out_lo = _compiled(hi.shape[1])(jnp.asarray(hi), jnp.asarray(lo))
    out_hi = np.asarray(out_hi)
    out_lo = np.asarray(out_lo)
    n = len(msgs)
    out = np.zeros((n, 8, 8), dtype=np.uint8)
    for shift, idx in ((24, 0), (16, 1), (8, 2), (0, 3)):
        out[:, :, idx] = (out_hi >> shift).astype(np.uint8)
        out[:, :, idx + 4] = (out_lo >> shift).astype(np.uint8)
    return [bytes(row.reshape(64)) for row in out]


def sha512_32_batch(msgs: list[bytes]) -> list[bytes]:
    """Protocol digests: SHA-512 truncated to 32 bytes (reference digest
    convention)."""
    return [d[:32] for d in sha512_batch(msgs)]
