"""Device batch verification: the random-linear-combination equation as one
jitted device call.

Checks (dalek ``verify_batch``-equivalent semantics of reference
``crypto/src/lib.rs:206-219``):

    8 * [ (-sum z_i s_i mod L) * B + sum z_i * R_i + sum (z_i h_i mod L) * A_i ] == O

with fresh random 128-bit z_i. Host side does the byte parsing, strictness
checks (canonical s < L, canonical y), SHA-512 challenges and mod-L scalar
arithmetic (tiny integer work); the device does all curve math: batched
point decompression of every R_i/A_i and the shared-doubling MSM.

Lanes are padded to a power of two with identity encodings so compiled
shapes are reused across batch sizes.
"""

from __future__ import annotations

import functools
import hashlib
import secrets

import numpy as np

import jax
import jax.numpy as jnp

from hotstuff_tpu.crypto.ed25519_ref import G, L, P, point_compress

from . import curve as cv
from . import field as fe

_B_ENC = point_compress(G)
_IDENTITY_ENC = (1).to_bytes(32, "little")  # y=1, sign 0
_HALF_MASK = (1 << 255) - 1


@functools.lru_cache(maxsize=16)
def _compiled(m: int):
    """Jitted decompress+MSM+cofactor-check for a padded lane count m."""

    @jax.jit
    def run(y_limbs, signs, digits):
        ok, pts = cv.decompress(y_limbs, signs)
        acc = cv.msm(pts, digits)
        zero = cv.is_identity(cv.mul_by_cofactor(acc[None, ...]))[0]
        return jnp.all(ok) & zero

    return run


def _pad_to_pow2(n: int, minimum: int = 4) -> int:
    m = minimum
    while m < n:
        m *= 2
    return m


def _digits_np(scalar_bytes: np.ndarray) -> np.ndarray:
    """uint8[m, 32] little-endian scalars -> int32[64, m] radix-16 digits,
    MSB-first (vectorized host prep: ~µs for thousands of lanes)."""
    low = (scalar_bytes & 0x0F).astype(np.int32)
    high = (scalar_bytes >> 4).astype(np.int32)
    lsb_first = np.empty((scalar_bytes.shape[0], 64), dtype=np.int32)
    lsb_first[:, 0::2] = low
    lsb_first[:, 1::2] = high
    return lsb_first[:, ::-1].T.copy()  # MSB-first, [64, m]


def prepare_batch(msgs, pubs, sigs, _rng=None):
    """Host-side prep: strictness checks, challenges, RLC scalars, limb/digit
    arrays. Returns (y_limbs, signs, digits, m_padded) or None if the batch
    is rejected host-side."""
    randbits = _rng.getrandbits if _rng is not None else secrets.randbits

    encodings: list[bytes] = []
    scalars: list[int] = []
    b_coeff = 0
    for msg, pub, sig in zip(msgs, pubs, sigs):
        if len(sig) != 64 or len(pub) != 32:
            return None
        r_enc, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= L:  # non-canonical s: reject (RFC 8032 / dalek)
            return None
        # Reject non-canonical y encodings host-side (y >= p).
        if (int.from_bytes(pub, "little") & _HALF_MASK) >= P:
            return None
        if (int.from_bytes(r_enc, "little") & _HALF_MASK) >= P:
            return None
        z = randbits(128) | (1 << 127)
        h = int.from_bytes(hashlib.sha512(r_enc + pub + msg).digest(), "little") % L
        b_coeff = (b_coeff + z * s) % L
        encodings.append(r_enc)
        scalars.append(z)
        encodings.append(pub)
        scalars.append(z * h % L)
    encodings.append(_B_ENC)
    scalars.append((-b_coeff) % L)

    m = _pad_to_pow2(len(encodings))
    pad = m - len(encodings)
    encodings.extend([_IDENTITY_ENC] * pad)
    scalars.extend([0] * pad)

    data = np.stack([np.frombuffer(e, dtype=np.uint8) for e in encodings])
    signs = (data[:, 31] >> 7).astype(np.int32)
    y_bytes = data.copy()
    y_bytes[:, 31] &= 0x7F
    y_limbs = fe.fe_from_bytes(y_bytes)
    scalar_bytes = np.stack(
        [np.frombuffer(s.to_bytes(32, "little"), dtype=np.uint8) for s in scalars]
    )
    digits = _digits_np(scalar_bytes)
    return y_limbs, signs, digits, m


def pad_prepared(y_limbs, signs, digits, target: int):
    """Grow a prepared batch to ``target`` lanes with identity encodings."""
    m = y_limbs.shape[0]
    extra = target - m
    id_limbs = fe.fe_from_bytes(
        np.frombuffer(_IDENTITY_ENC, dtype=np.uint8)[None, :]
    )
    y_limbs = np.concatenate([y_limbs, np.repeat(id_limbs, extra, axis=0)])
    signs = np.concatenate([signs, np.zeros(extra, dtype=np.int32)])
    digits = np.concatenate(
        [digits, np.zeros((digits.shape[0], extra), dtype=np.int32)], axis=1
    )
    return y_limbs, signs, digits


def verify_batch_device(msgs, pubs, sigs, _rng=None) -> bool:
    """msgs/pubs/sigs: equal-length lists of bytes. True iff the whole batch
    is valid under cofactored semantics."""
    if len(msgs) == 0:
        return True
    prepared = prepare_batch(msgs, pubs, sigs, _rng=_rng)
    if prepared is None:
        return False
    y_limbs, signs, digits, m = prepared
    result = _compiled(m)(
        jnp.asarray(y_limbs), jnp.asarray(signs), jnp.asarray(digits)
    )
    return bool(result)
