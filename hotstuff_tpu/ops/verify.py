"""Device batch verification: the random-linear-combination equation as one
jitted device call.

Checks (dalek ``verify_batch``-equivalent semantics of reference
``crypto/src/lib.rs:206-219``):

    8 * [ (-sum z_i s_i mod L) * B + sum z_i * R_i + sum (z_i h_i mod L) * A_i ] == O

with fresh random 128-bit z_i. Host side does the byte parsing, strictness
checks (canonical s < L, canonical y), SHA-512 challenges and mod-L scalar
arithmetic (tiny integer work); the device does all curve math: batched
point decompression of every R_i/A_i and the shared-doubling MSM.

Lanes are padded to a power of two with identity encodings so compiled
shapes are reused across batch sizes.
"""

from __future__ import annotations

import functools
import hashlib
import secrets

import numpy as np

import jax
import jax.numpy as jnp

from hotstuff_tpu.crypto.ed25519_ref import G, L, P, point_compress

from . import curve as cv
from . import field as fe

_B_ENC = point_compress(G)
_IDENTITY_ENC = (1).to_bytes(32, "little")  # y=1, sign 0
_HALF_MASK = (1 << 255) - 1


def _enc_to_y_limbs(enc):
    """int32[m, 32] little-endian encoding bytes (sign bit pre-cleared from
    byte 31) -> y limbs int32[m, 20]: 13-bit windows over a 3-byte read
    (13 + 7 <= 21 bits), with the sign bit's contribution cleared from the
    top limb (bit 255 = limb 19 bit 8)."""
    limbs = []
    for k in range(fe.NLIMB):
        bit = fe.RADIX * k
        byte, off = bit // 8, bit % 8
        window = enc[:, byte]
        window = window + (enc[:, byte + 1] << 8 if byte + 1 < 32 else 0)
        if byte + 2 < 32:
            window = window + (enc[:, byte + 2] << 16)
        limbs.append((window >> off) & fe.MASK)
    y_limbs = jnp.stack(limbs, axis=-1)
    return y_limbs.at[:, fe.NLIMB - 1].set(y_limbs[:, fe.NLIMB - 1] & 0xFF)


def _unpack_device(packed):
    """Device-side unpacking of the [m, 65] uint8 batch layout:
    bytes 0..31 point encoding (LE), 32..63 RLC scalar (LE), 64 sign.

    One packed array means ONE host->device transfer per batch — on this
    platform every transfer costs a full tunnel round trip regardless of
    size, so the old 3-array layout tripled the floor.
    """
    b = packed.astype(jnp.int32)
    y_limbs = _enc_to_y_limbs(b[:, :32])
    signs = b[:, 64]
    # Radix-16 digits, MSB-first: digit w = nibble 63-w of the scalar.
    sc = b[:, 32:64]
    digit_rows = []
    for w in range(64):
        nib = 63 - w
        byte = sc[:, nib // 2]
        digit_rows.append((byte >> 4) & 0xF if nib % 2 else byte & 0xF)
    digits = jnp.stack(digit_rows, axis=0)
    return y_limbs, signs, digits


def _kernels():
    """(root_fn, msm_fn) for the current backend: the Pallas mega-kernels on
    TPU (the XLA lowering is kernel-launch-bound there: ~46x slower), plain
    XLA elsewhere. Override with HOTSTUFF_MSM=pallas|xla."""
    import os

    pref = os.environ.get("HOTSTUFF_MSM", "auto")
    # Pallas kernels are TPU-only (pltpu VMEM scratch); every other backend
    # (cpu, gpu, ...) takes the portable XLA lowering.
    use_pallas = pref == "pallas" or (
        pref == "auto" and jax.default_backend() == "tpu"
    )
    if use_pallas:
        from . import pallas_msm as pm

        return pm.sqrt_pow, pm.msm
    return None, cv.msm


@functools.lru_cache(maxsize=16)
def _compiled(m: int):
    """Jitted unpack+decompress+MSM+cofactor-check for a padded lane count
    m. Takes the single packed uint8 [m, 65] batch array."""
    root_fn, msm_fn = _kernels()

    @jax.jit
    def run(packed):
        y_limbs, signs, digits = _unpack_device(packed)
        ok, pts = cv.decompress(y_limbs, signs, root_fn=root_fn)
        acc = msm_fn(pts, digits)
        zero = cv.is_identity(cv.mul_by_cofactor(acc[None, ...]))[0]
        return jnp.all(ok) & zero

    return run


def _pad_to_pow2(n: int, minimum: int = 4) -> int:
    m = minimum
    while m < n:
        m *= 2
    return m


def prepare_batch(msgs, pubs, sigs, _rng=None):
    """Host-side prep: strictness checks, challenges, RLC scalars, and the
    packed uint8 batch array. Returns ``(packed, m_padded)`` where
    ``packed`` is uint8[m, 65] (bytes 0..31 point encoding with the sign
    bit cleared, 32..63 scalar, 64 sign) — see ``_unpack_device`` — or
    None if the batch is rejected host-side."""
    randbits = _rng.getrandbits if _rng is not None else secrets.randbits

    encodings: list[bytes] = []
    scalars: list[int] = []
    b_coeff = 0
    for msg, pub, sig in zip(msgs, pubs, sigs):
        if len(sig) != 64 or len(pub) != 32:
            return None
        r_enc, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= L:  # non-canonical s: reject (RFC 8032 / dalek)
            return None
        # Reject non-canonical y encodings host-side (y >= p).
        if (int.from_bytes(pub, "little") & _HALF_MASK) >= P:
            return None
        if (int.from_bytes(r_enc, "little") & _HALF_MASK) >= P:
            return None
        z = randbits(128) | (1 << 127)
        h = int.from_bytes(hashlib.sha512(r_enc + pub + msg).digest(), "little") % L
        b_coeff = (b_coeff + z * s) % L
        encodings.append(r_enc)
        scalars.append(z)
        encodings.append(pub)
        scalars.append(z * h % L)
    encodings.append(_B_ENC)
    scalars.append((-b_coeff) % L)

    m = _pad_to_pow2(len(encodings))
    pad = m - len(encodings)
    encodings.extend([_IDENTITY_ENC] * pad)
    scalars.extend([0] * pad)

    data = np.frombuffer(b"".join(encodings), dtype=np.uint8).reshape(-1, 32)
    scalar_bytes = np.frombuffer(
        b"".join(s.to_bytes(32, "little") for s in scalars), dtype=np.uint8
    ).reshape(-1, 32)
    packed = np.empty((m, 65), dtype=np.uint8)
    packed[:, :32] = data
    packed[:, 31] &= 0x7F  # sign bit moved to its own byte
    packed[:, 32:64] = scalar_bytes
    packed[:, 64] = data[:, 31] >> 7
    return packed, m


def pad_prepared(packed: np.ndarray, target: int):
    """Grow a prepared batch to ``target`` lanes with identity encodings
    (zero scalars)."""
    m = packed.shape[0]
    extra = target - m
    pad = np.zeros((extra, 65), dtype=np.uint8)
    pad[:, :32] = np.frombuffer(_IDENTITY_ENC, dtype=np.uint8)
    return np.concatenate([packed, pad])


def verify_batch_device(msgs, pubs, sigs, _rng=None) -> bool:
    """msgs/pubs/sigs: equal-length lists of bytes. True iff the whole batch
    is valid under cofactored semantics."""
    if len(msgs) == 0:
        return True
    prepared = prepare_batch(msgs, pubs, sigs, _rng=_rng)
    if prepared is None:
        return False
    packed, m = prepared
    return bool(_compiled(m)(jnp.asarray(packed)))


# ---------------------------------------------------------------------------
# v2: committee point cache + signed digits + narrow R-lane windows.
#
# The committee is static per epoch, so the A_i points (validator public
# keys) decompress ONCE onto the device and stay resident; per batch only
# the R_i points (one per signature, fresh each time) pay the sqrt-chain.
# Scalars ship as host-recoded SIGNED radix-16 digits; the R-lane group's
# 128-bit RLC coefficients need only 33 windows vs 64 for the mod-L
# A/B-lane scalars. Together: ~2x less decompression, 9-entry tables, and
# half the window loop for half the lanes.
# ---------------------------------------------------------------------------

import threading

N_WINDOWS_RLC = 33  # 128-bit z (top bit set) + signed-recode carry
N_WINDOWS_FULL = 64  # mod-L scalars

_ROW_WIDTH = 66  # 32 enc + 33 digits + 1 sign (fresh) / 64 digits + 2 row (cached)


def _signed_msm_fn():
    """Signed-digit MSM for the current backend (pallas on TPU, XLA else)."""
    import os

    pref = os.environ.get("HOTSTUFF_MSM", "auto")
    use_pallas = pref == "pallas" or (
        pref == "auto" and jax.default_backend() == "tpu"
    )
    if use_pallas:
        from . import pallas_msm as pm

        return pm.msm_signed
    return cv.msm_signed


@functools.lru_cache(maxsize=32)
def _compiled_decompress(k: int):
    """Jitted decompress of k packed encodings ([k, 33]: 32 enc + sign)."""
    root_fn, _ = _kernels()

    @jax.jit
    def run(packed):
        b = packed.astype(jnp.int32)
        y_limbs = _enc_to_y_limbs(b[:, :32])
        return cv.decompress(y_limbs, b[:, 32], root_fn=root_fn)

    return run


class CacheFull(RuntimeError):
    """The device point cache hit its 16-bit row-index ceiling."""


class DevicePointCache:
    """Device-resident decompressed-point cache keyed by 32-byte encodings.

    Row 0 is always the Ed25519 base point. Thread-safe; grows by doubling
    (each capacity is a distinct compiled gather shape, so growth is rare
    and bounded). Invalid encodings (non-points) are remembered host-side
    so batches naming them fail fast without a device call.
    """

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = max(16, capacity)
        self._rows: dict[bytes, int] = {_B_ENC: 0}
        self._next_row = 1  # rows are never reused, even for failed inserts
        self._invalid: set[bytes] = set()
        self._lock = threading.Lock()
        arr = np.zeros((self.capacity, 4, 20), dtype=np.int32)
        # identity rows everywhere so stray gathers stay on-curve
        arr[:] = cv.IDENTITY
        arr[0] = cv.BASE_POINT
        self.array = jnp.asarray(arr)

    def lookup(self, enc: bytes):
        return self._rows.get(enc)

    def ensure(self, encs) -> bool:
        """Decompress-and-insert any unknown encodings. Returns False if any
        encoding is known-invalid or fails decompression."""
        with self._lock:
            fresh = []
            for e in dict.fromkeys(encs):  # dedup, keep order
                if len(e) != 32 or e in self._invalid:
                    return False
                if e not in self._rows:
                    # host-side canonicality (y < p), mirroring prepare_batch
                    if (int.from_bytes(e, "little") & _HALF_MASK) >= P:
                        self._invalid.add(e)
                        return False
                    fresh.append(e)
            if not fresh:
                return True
            while self._next_row + len(fresh) > self.capacity:
                self._grow()
            k = _pad_to_pow2(len(fresh))
            packed = np.zeros((k, 33), dtype=np.uint8)
            for i, e in enumerate(fresh):
                row = np.frombuffer(e, dtype=np.uint8)
                packed[i, :32] = row
                packed[i, 31] &= 0x7F
                packed[i, 32] = row[31] >> 7
            ok, pts = _compiled_decompress(k)(jnp.asarray(packed))
            ok = np.asarray(ok)
            n = len(fresh)
            # Only the successfully-decompressed points land in the array,
            # each on a never-before-used row: a failed insert can never
            # alias or overwrite a registered key's row.
            valid = [i for i in range(n) if ok[i]]
            if valid:
                rows = list(range(self._next_row, self._next_row + len(valid)))
                self._next_row += len(valid)
                self.array = self.array.at[jnp.asarray(rows)].set(
                    pts[jnp.asarray(valid)]
                )
                for r, i in zip(rows, valid):
                    self._rows[fresh[i]] = r
            all_ok = True
            for i, e in enumerate(fresh):
                if not ok[i]:
                    self._invalid.add(e)
                    all_ok = False
            return all_ok

    def _grow(self) -> None:
        new_cap = self.capacity * 2
        if new_cap > 65536:  # row indices ship as 16 bits
            raise CacheFull("point cache cannot exceed 65536 rows")
        arr = np.zeros((new_cap, 4, 20), dtype=np.int32)
        arr[:] = cv.IDENTITY
        arr[: self.capacity] = np.asarray(self.array)
        self.capacity = new_cap
        self.array = jnp.asarray(arr)


@functools.lru_cache(maxsize=64)
def _compiled_cached(mf: int, mc: int, cap: int):
    """Jitted verify for a (fresh-lanes, cached-lanes) split batch.

    Input ``packed``: uint8[mf + mc, 66]. Fresh rows: 32 enc bytes, 33
    biased signed digits (d+8), sign. Cached rows: 64 biased digits, row
    index (lo, hi). ``cache_arr``: int32[cap, 4, 20].
    """
    root_fn, _ = _kernels()
    msm_signed = _signed_msm_fn()

    @jax.jit
    def run(packed, cache_arr):
        b = packed.astype(jnp.int32)
        fresh, cached = b[:mf], b[mf:]
        y_limbs = _enc_to_y_limbs(fresh[:, :32])
        ok_f, pts_f = cv.decompress(y_limbs, fresh[:, 65], root_fn=root_fn)
        digits_f = fresh[:, 32:65].T - 8  # [33, mf] signed

        rows = cached[:, 64] | (cached[:, 65] << 8)
        pts_c = jnp.take(cache_arr, rows, axis=0)  # [mc, 4, 20]
        digits_c = cached[:, :64].T - 8  # [64, mc] signed

        acc = cv.point_add(msm_signed(pts_f, digits_f), msm_signed(pts_c, digits_c))
        zero = cv.is_identity(cv.mul_by_cofactor(acc[None, ...]))[0]
        return jnp.all(ok_f) & zero

    return run


def prepare_batch_cached(msgs, pubs, sigs, cache: DevicePointCache, _rng=None):
    """Host prep for the cached path. Returns ``(packed, mf, mc)`` or None
    if the batch is rejected host-side (non-canonical encodings, invalid
    cached keys)."""
    randbits = _rng.getrandbits if _rng is not None else secrets.randbits

    # Length checks BEFORE cache.ensure: a wrong-length pub inside ensure
    # would surface as a numpy shape error (read upstream as an
    # infrastructure outage), not the rejection prepare_batch returns.
    for pub, sig in zip(pubs, sigs):
        if len(sig) != 64 or len(pub) != 32:
            return None

    if not cache.ensure(pubs):
        return None

    n = len(msgs)
    r_encs: list[bytes] = []
    z_bytes = np.zeros((n, 32), dtype=np.uint8)
    rows: list[int] = []
    full_scalars: list[int] = []
    b_coeff = 0
    for i, (msg, pub, sig) in enumerate(zip(msgs, pubs, sigs)):
        r_enc, s_bytes = sig[:32], sig[32:]  # lengths validated above
        s = int.from_bytes(s_bytes, "little")
        if s >= L:
            return None
        if (int.from_bytes(r_enc, "little") & _HALF_MASK) >= P:
            return None
        z = randbits(128) | (1 << 127)
        h = int.from_bytes(hashlib.sha512(r_enc + pub + msg).digest(), "little") % L
        b_coeff = (b_coeff + z * s) % L
        r_encs.append(r_enc)
        z_bytes[i, :16] = np.frombuffer(z.to_bytes(16, "little"), dtype=np.uint8)
        rows.append(cache.lookup(pub))
        full_scalars.append(z * h % L)
    rows.append(0)  # base point row
    full_scalars.append((-b_coeff) % L)

    mf = _pad_to_pow2(n)
    mc = _pad_to_pow2(n + 1)

    digits_f = cv.signed_digits_from_bytes(z_bytes, N_WINDOWS_RLC)  # [33, n]
    sc_bytes = np.frombuffer(
        b"".join(s.to_bytes(32, "little") for s in full_scalars), dtype=np.uint8
    ).reshape(-1, 32)
    digits_c = cv.signed_digits_from_bytes(sc_bytes, N_WINDOWS_FULL)  # [64, n+1]

    packed = np.zeros((mf + mc, _ROW_WIDTH), dtype=np.uint8)
    enc_arr = np.frombuffer(b"".join(r_encs), dtype=np.uint8).reshape(n, 32)
    packed[:n, :32] = enc_arr
    packed[:n, 31] &= 0x7F
    packed[:n, 32:65] = (digits_f.T + 8).astype(np.uint8)
    packed[:n, 65] = enc_arr[:, 31] >> 7
    packed[n:mf, 0] = 1  # identity encoding (y=1, sign 0), zero digits
    packed[n:mf, 32:65] = 8  # biased zero digits

    c = packed[mf:]
    c[: n + 1, :64] = (digits_c.T + 8).astype(np.uint8)
    row_arr = np.asarray(rows, dtype=np.uint32)
    c[: n + 1, 64] = (row_arr & 0xFF).astype(np.uint8)
    c[: n + 1, 65] = (row_arr >> 8).astype(np.uint8)
    c[n + 1 :, :64] = 8  # biased zero digits, row 0 (B * 0 = identity)
    return packed, mf, mc


def pad_prepared_cached(packed, mf: int, mc: int, mf2: int, mc2: int):
    """Grow a ``prepare_batch_cached`` layout to (mf2, mc2) lanes with
    neutral rows (identity encodings / zero digits on row 0), preserving
    the verdict. Used by the sharded mesh path to give every device an
    equal power-of-two shard of each group."""
    out = np.zeros((mf2 + mc2, _ROW_WIDTH), dtype=np.uint8)
    out[:mf] = packed[:mf]
    out[mf:mf2, 0] = 1  # identity encoding (y=1, sign 0)
    out[mf:mf2, 32:65] = 8  # biased zero digits
    out[mf2 : mf2 + mc] = packed[mf:]
    out[mf2 + mc :, :64] = 8  # biased zero digits, row 0 (B * 0 = identity)
    return out


def verify_batch_device_cached(
    msgs, pubs, sigs, cache: DevicePointCache, _rng=None
) -> bool:
    """Cached-committee variant of ``verify_batch_device`` — the node's
    steady-state QC path (same cofactored acceptance set)."""
    if len(msgs) == 0:
        return True
    prepared = prepare_batch_cached(msgs, pubs, sigs, cache, _rng=_rng)
    if prepared is None:
        return False
    packed, mf, mc = prepared
    run = _compiled_cached(mf, mc, cache.capacity)
    return bool(run(jnp.asarray(packed), cache.array))
