"""Device batch verification: the random-linear-combination equation as one
jitted device call.

Checks (dalek ``verify_batch``-equivalent semantics of reference
``crypto/src/lib.rs:206-219``):

    8 * [ (-sum z_i s_i mod L) * B + sum z_i * R_i + sum (z_i h_i mod L) * A_i ] == O

with fresh random 128-bit z_i. Host side does the byte parsing, strictness
checks (canonical s < L, canonical y), SHA-512 challenges and mod-L scalar
arithmetic (tiny integer work); the device does all curve math: batched
point decompression of every R_i/A_i and the shared-doubling MSM.

Lanes are padded to a power of two with identity encodings so compiled
shapes are reused across batch sizes.
"""

from __future__ import annotations

import functools
import hashlib
import secrets

import numpy as np

import jax
import jax.numpy as jnp

from hotstuff_tpu.crypto.ed25519_ref import G, L, P, point_compress

from . import curve as cv
from . import field as fe

_B_ENC = point_compress(G)
_IDENTITY_ENC = (1).to_bytes(32, "little")  # y=1, sign 0
_HALF_MASK = (1 << 255) - 1


def _unpack_device(packed):
    """Device-side unpacking of the [m, 65] uint8 batch layout:
    bytes 0..31 point encoding (LE), 32..63 RLC scalar (LE), 64 sign.

    One packed array means ONE host->device transfer per batch — on this
    platform every transfer costs a full tunnel round trip regardless of
    size, so the old 3-array layout tripled the floor.
    """
    b = packed.astype(jnp.int32)
    enc = b[:, :32]
    # y limbs: 13-bit windows over a 3-byte read (13+7 <= 21 bits).
    limbs = []
    for k in range(fe.NLIMB):
        bit = fe.RADIX * k
        byte, off = bit // 8, bit % 8
        window = enc[:, byte]
        window = window + (enc[:, byte + 1] << 8 if byte + 1 < 32 else 0)
        if byte + 2 < 32:
            window = window + (enc[:, byte + 2] << 16)
        limbs.append((window >> off) & fe.MASK)
    y_limbs = jnp.stack(limbs, axis=-1)
    # Clear the sign bit's contribution from the top limb (bit 255 =
    # limb 19 bit 8).
    y_limbs = y_limbs.at[:, fe.NLIMB - 1].set(y_limbs[:, fe.NLIMB - 1] & 0xFF)
    signs = b[:, 64]
    # Radix-16 digits, MSB-first: digit w = nibble 63-w of the scalar.
    sc = b[:, 32:64]
    digit_rows = []
    for w in range(64):
        nib = 63 - w
        byte = sc[:, nib // 2]
        digit_rows.append((byte >> 4) & 0xF if nib % 2 else byte & 0xF)
    digits = jnp.stack(digit_rows, axis=0)
    return y_limbs, signs, digits


def _kernels():
    """(root_fn, msm_fn) for the current backend: the Pallas mega-kernels on
    TPU (the XLA lowering is kernel-launch-bound there: ~46x slower), plain
    XLA elsewhere. Override with HOTSTUFF_MSM=pallas|xla."""
    import os

    pref = os.environ.get("HOTSTUFF_MSM", "auto")
    # Pallas kernels are TPU-only (pltpu VMEM scratch); every other backend
    # (cpu, gpu, ...) takes the portable XLA lowering.
    use_pallas = pref == "pallas" or (
        pref == "auto" and jax.default_backend() == "tpu"
    )
    if use_pallas:
        from . import pallas_msm as pm

        return pm.sqrt_pow, pm.msm
    return None, cv.msm


@functools.lru_cache(maxsize=16)
def _compiled(m: int):
    """Jitted unpack+decompress+MSM+cofactor-check for a padded lane count
    m. Takes the single packed uint8 [m, 65] batch array."""
    root_fn, msm_fn = _kernels()

    @jax.jit
    def run(packed):
        y_limbs, signs, digits = _unpack_device(packed)
        ok, pts = cv.decompress(y_limbs, signs, root_fn=root_fn)
        acc = msm_fn(pts, digits)
        zero = cv.is_identity(cv.mul_by_cofactor(acc[None, ...]))[0]
        return jnp.all(ok) & zero

    return run


def _pad_to_pow2(n: int, minimum: int = 4) -> int:
    m = minimum
    while m < n:
        m *= 2
    return m


def prepare_batch(msgs, pubs, sigs, _rng=None):
    """Host-side prep: strictness checks, challenges, RLC scalars, and the
    packed uint8 batch array. Returns ``(packed, m_padded)`` where
    ``packed`` is uint8[m, 65] (bytes 0..31 point encoding with the sign
    bit cleared, 32..63 scalar, 64 sign) — see ``_unpack_device`` — or
    None if the batch is rejected host-side."""
    randbits = _rng.getrandbits if _rng is not None else secrets.randbits

    encodings: list[bytes] = []
    scalars: list[int] = []
    b_coeff = 0
    for msg, pub, sig in zip(msgs, pubs, sigs):
        if len(sig) != 64 or len(pub) != 32:
            return None
        r_enc, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= L:  # non-canonical s: reject (RFC 8032 / dalek)
            return None
        # Reject non-canonical y encodings host-side (y >= p).
        if (int.from_bytes(pub, "little") & _HALF_MASK) >= P:
            return None
        if (int.from_bytes(r_enc, "little") & _HALF_MASK) >= P:
            return None
        z = randbits(128) | (1 << 127)
        h = int.from_bytes(hashlib.sha512(r_enc + pub + msg).digest(), "little") % L
        b_coeff = (b_coeff + z * s) % L
        encodings.append(r_enc)
        scalars.append(z)
        encodings.append(pub)
        scalars.append(z * h % L)
    encodings.append(_B_ENC)
    scalars.append((-b_coeff) % L)

    m = _pad_to_pow2(len(encodings))
    pad = m - len(encodings)
    encodings.extend([_IDENTITY_ENC] * pad)
    scalars.extend([0] * pad)

    data = np.frombuffer(b"".join(encodings), dtype=np.uint8).reshape(-1, 32)
    scalar_bytes = np.frombuffer(
        b"".join(s.to_bytes(32, "little") for s in scalars), dtype=np.uint8
    ).reshape(-1, 32)
    packed = np.empty((m, 65), dtype=np.uint8)
    packed[:, :32] = data
    packed[:, 31] &= 0x7F  # sign bit moved to its own byte
    packed[:, 32:64] = scalar_bytes
    packed[:, 64] = data[:, 31] >> 7
    return packed, m


def pad_prepared(packed: np.ndarray, target: int):
    """Grow a prepared batch to ``target`` lanes with identity encodings
    (zero scalars)."""
    m = packed.shape[0]
    extra = target - m
    pad = np.zeros((extra, 65), dtype=np.uint8)
    pad[:, :32] = np.frombuffer(_IDENTITY_ENC, dtype=np.uint8)
    return np.concatenate([packed, pad])


def verify_batch_device(msgs, pubs, sigs, _rng=None) -> bool:
    """msgs/pubs/sigs: equal-length lists of bytes. True iff the whole batch
    is valid under cofactored semantics."""
    if len(msgs) == 0:
        return True
    prepared = prepare_batch(msgs, pubs, sigs, _rng=_rng)
    if prepared is None:
        return False
    packed, m = prepared
    return bool(_compiled(m)(jnp.asarray(packed)))
