"""Edwards25519 point operations and MSM on device.

Points are ``int32[..., 4, 20]`` — stacked (X, Y, Z, T) extended homogeneous
coordinates (x = X/Z, y = Y/Z, xy = T/Z) on the a = -1 twisted Edwards
curve. Formulas: unified add-2008-hwcd-3 and dbl-2008-hwcd, the same
formulas the pure-Python oracle uses (``ed25519_ref.point_add/point_double``),
property-tested for bit-equality against it.

The MSM is the TPU replacement for dalek's Straus/Pippenger CPU multiscalar
(reference ``crypto/src/lib.rs:206-219`` batch verification): radix-16
windows, per-point 16-entry tables, one shared accumulator; per window the
digit-selected multiples are summed with an identity-padded binary tree
reduction across lanes — all lanes advance in lock-step on the VPU, control
flow is a single ``lax.scan`` over the 64 windows.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import field as fe

# Identity element (0, 1, 1, 0).
IDENTITY = np.stack(
    [fe.ZERO_LIMBS, fe.ONE_LIMBS, fe.ONE_LIMBS, fe.ZERO_LIMBS]
).astype(np.int32)

# Base point.
_BX = (
    15112221349535400772501151409588531511454012693041857206046113283949847762202
)
_BY = (
    46316835694926478169428394003475163141307993866256225615783033603165251855960
)
BASE_POINT = np.stack(
    [
        fe._int_to_limbs(_BX),
        fe._int_to_limbs(_BY),
        fe.ONE_LIMBS,
        fe._int_to_limbs(_BX * _BY % fe.P),
    ]
).astype(np.int32)

WINDOW_BITS = 4
N_WINDOWS = 64  # 256 bits / 4
TABLE = 1 << WINDOW_BITS


def identity(batch_shape=()) -> jnp.ndarray:
    return jnp.broadcast_to(jnp.asarray(IDENTITY), (*batch_shape, 4, 20))


def point_add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Unified addition (add-2008-hwcd-3, a = -1): works for doubling and
    identity operands — no branches, VPU-friendly."""
    x1, y1, z1, t1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    x2, y2, z2, t2 = q[..., 0, :], q[..., 1, :], q[..., 2, :], q[..., 3, :]
    a = fe.mul(fe.sub(y1, x1), fe.sub(y2, x2))
    b = fe.mul(fe.add(y1, x1), fe.add(y2, x2))
    c = fe.mul(fe.mul(t1, jnp.asarray(fe.D2_LIMBS)), t2)
    d = fe.mul(fe.add(z1, z1), z2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return jnp.stack(
        [fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h)], axis=-2
    )


def point_double(p: jnp.ndarray) -> jnp.ndarray:
    """Dedicated doubling (dbl-2008-hwcd): 4 squarings + 3 muls."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = fe.square(x1)
    b = fe.square(y1)
    c = fe.add(fe.square(z1), fe.square(z1))
    h = fe.add(a, b)
    e = fe.sub(h, fe.square(fe.add(x1, y1)))
    g = fe.sub(a, b)
    f = fe.add(c, g)
    return jnp.stack(
        [fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h)], axis=-2
    )


def point_select(mask: jnp.ndarray, p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """mask ? p : q with mask shaped [...]."""
    return jnp.where(mask[..., None, None], p, q)


def point_eq(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    x2, y2, z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    return fe.eq(fe.mul(x1, z2), fe.mul(x2, z1)) & fe.eq(
        fe.mul(y1, z2), fe.mul(y2, z1)
    )


def is_identity(p: jnp.ndarray) -> jnp.ndarray:
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    return fe.is_zero(x) & fe.eq(y, z)


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray, root_fn=None):
    """Batch point decompression: x^2 = (y^2-1)/(d y^2+1).

    ``y_limbs``: int32[..., 20] (the 255-bit y; the caller host-side rejects
    non-canonical y >= p and strips the sign bit); ``sign``: int32[...] in
    {0,1}. Returns (ok[...], point[..., 4, 20]). ``root_fn`` routes the
    heavy exponentiation to the Pallas kernel on TPU.
    """
    yy = fe.square(y_limbs)
    u = fe.sub(yy, fe.fe_from_int(1, yy.shape[:-1]))
    v = fe.add(fe.mul(yy, jnp.asarray(fe.D_LIMBS)), fe.fe_from_int(1, yy.shape[:-1]))
    ok, x = fe.sqrt_ratio(u, v, root_fn=root_fn)
    x = fe.canonical(x)
    flip = (x[..., 0] & 1) != sign
    x = fe.select(flip, fe.neg(x), x)
    # sign=1 with x=0 encodes no valid point (negating zero cannot fix the
    # parity) — matches dalek/RFC 8032 strict decoding.
    ok = ok & ~(fe.is_zero(x) & (sign == 1))
    point = jnp.stack(
        [x, y_limbs, fe.fe_from_int(1, yy.shape[:-1]), fe.mul(x, y_limbs)],
        axis=-2,
    )
    return ok, point


def to_affine_bytes(p) -> bytes:
    """Single point -> 32-byte compressed encoding (host-side, for tests)."""
    arr = jnp.asarray(p)
    zi = fe.inv(arr[..., 2, :])
    x = fe.canonical(fe.mul(arr[..., 0, :], zi))
    y = fe.canonical(fe.mul(arr[..., 1, :], zi))
    xb = fe.fe_to_bytes(np.asarray(x))
    yb = np.asarray(fe.fe_to_bytes(np.asarray(y)))
    yb[..., 31] |= (np.asarray(xb)[..., 0] & 1) << 7
    return bytes(yb.reshape(-1))


# ---------------------------------------------------------------------------
# Multi-scalar multiplication.
# ---------------------------------------------------------------------------


def scalars_to_digits(scalars: list[int]) -> np.ndarray:
    """256-bit scalars -> int32[N_WINDOWS, m] radix-16 digits, MSB-first."""
    m = len(scalars)
    out = np.zeros((N_WINDOWS, m), dtype=np.int32)
    for j, s in enumerate(scalars):
        for w in range(N_WINDOWS):
            out[w, j] = (s >> (WINDOW_BITS * (N_WINDOWS - 1 - w))) & (TABLE - 1)
    return out


def scalars_to_signed_digits(scalars: list[int], n_windows: int) -> np.ndarray:
    """Scalars -> int32[n_windows, m] SIGNED radix-16 digits in [-8, 8],
    MSB-first. sum_w d_w * 16^(n_windows-1-w) == s exactly; requires
    s < 16^n_windows / 2 so the final carry cannot overflow (mod-L scalars
    fit 64 windows, 128-bit RLC coefficients fit 33).

    Signed digits halve the device table (9 entries, 7 additions to build)
    and the per-window select (9 compares + a conditional negate — point
    negation is 2 cheap field negations), the same recoding trick dalek's
    radix-16 scalar_mul uses on CPU.
    """
    m = len(scalars)
    nibs = np.zeros((n_windows, m), dtype=np.int32)  # LSB-first here
    for j, s in enumerate(scalars):
        assert 2 * s < 1 << (4 * n_windows), "scalar too wide for window count"
        for w in range(n_windows):
            nibs[w, j] = (s >> (4 * w)) & 0xF
    carry = np.zeros(m, dtype=np.int32)
    for w in range(n_windows):
        d = nibs[w] + carry
        carry = (d > 8).astype(np.int32)
        nibs[w] = d - 16 * carry
    assert not carry.any(), "top-window carry (scalar too wide)"
    return nibs[::-1]  # MSB-first


def signed_digits_from_bytes(scalar_bytes: np.ndarray, n_windows: int) -> np.ndarray:
    """Vectorized ``scalars_to_signed_digits``: uint8[m, 32] little-endian
    scalars -> int32[n_windows, m] signed digits, MSB-first. The carry
    sweep is sequential over the n_windows windows but vectorized over all
    m lanes (the host hot path at 4096-lane batches)."""
    sb = np.asarray(scalar_bytes, dtype=np.uint8)
    m = sb.shape[0]
    lo = (sb & 0xF).astype(np.int32)
    hi = (sb >> 4).astype(np.int32)
    nibs = np.empty((64, m), dtype=np.int32)  # LSB-first
    nibs[0::2] = lo.T
    nibs[1::2] = hi.T
    assert not nibs[n_windows:].any(), "scalar too wide for window count"
    nibs = nibs[:n_windows]
    carry = np.zeros(m, dtype=np.int32)
    for w in range(n_windows):
        d = nibs[w] + carry
        carry = (d > 8).astype(np.int32)
        nibs[w] = d - 16 * carry
    assert not carry.any(), "top-window carry (scalar too wide)"
    return nibs[::-1]


def point_neg(p: jnp.ndarray) -> jnp.ndarray:
    """-(X : Y : Z : T) = (-X : Y : Z : -T)."""
    return jnp.stack(
        [fe.neg(p[..., 0, :]), p[..., 1, :], p[..., 2, :], fe.neg(p[..., 3, :])],
        axis=-2,
    )


def _build_table(points: jnp.ndarray) -> jnp.ndarray:
    """[m, 4, 20] -> [m, TABLE, 4, 20] with table[:, d] = d * P."""
    m = points.shape[0]
    entries = [identity((m,)), points]
    for _ in range(TABLE - 2):
        entries.append(point_add(entries[-1], points))
    return jnp.stack(entries, axis=1)


def _tree_reduce(points: jnp.ndarray) -> jnp.ndarray:
    """Sum [m, 4, 20] points (m a power of two) by pairwise reduction."""
    m = points.shape[0]
    assert m & (m - 1) == 0, "tree reduction needs power-of-two lanes"
    while m > 1:
        m //= 2
        points = point_add(points[:m], points[m:])
    return points[0]


def msm(points: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """sum_j e_j * P_j with shared doublings.

    ``points``: [m, 4, 20] (m a power of two; pad with the identity),
    ``digits``: [N_WINDOWS, m] radix-16 digits of the scalars, MSB-first.
    Returns a single point [4, 20].
    """
    table = _build_table(points)  # [m, 16, 4, 20]

    def body(acc, digit_row):
        acc = point_double(point_double(point_double(point_double(acc))))
        idx = digit_row[:, None, None, None]  # [m, 1, 1, 1]
        sel = jnp.take_along_axis(table, idx, axis=1)[:, 0]  # [m, 4, 20]
        acc = point_add(acc, _tree_reduce(sel))
        return acc, None

    # Init carry derived from the inputs so its sharding variance matches
    # inside shard_map bodies.
    init = points[0] * 0 + jnp.asarray(IDENTITY)
    acc, _ = lax.scan(body, init, digits)
    return acc


def _build_table_signed(points: jnp.ndarray) -> jnp.ndarray:
    """[m, 4, 20] -> [m, 9, 4, 20] with table[:, d] = d * P (d in 0..8)."""
    entries = [identity((points.shape[0],)), points]
    for _ in range(7):
        entries.append(point_add(entries[-1], points))
    return jnp.stack(entries, axis=1)


def msm_signed(points: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """``msm`` over SIGNED radix-16 digits (from
    ``scalars_to_signed_digits``): 9-entry tables + conditional negation.

    ``digits``: [n_windows, m] in [-8, 8], MSB-first; n_windows is free
    (33 for 128-bit RLC coefficients, 64 for mod-L scalars).
    """
    table = _build_table_signed(points)  # [m, 9, 4, 20]

    def body(acc, digit_row):
        acc = point_double(point_double(point_double(point_double(acc))))
        mag = jnp.abs(digit_row)[:, None, None, None]  # [m, 1, 1, 1]
        sel = jnp.take_along_axis(table, mag, axis=1)[:, 0]  # [m, 4, 20]
        sel = point_select(digit_row >= 0, sel, point_neg(sel))
        acc = point_add(acc, _tree_reduce(sel))
        return acc, None

    init = points[0] * 0 + jnp.asarray(IDENTITY)
    acc, _ = lax.scan(body, init, digits)
    return acc


def mul_by_cofactor(p: jnp.ndarray) -> jnp.ndarray:
    return point_double(point_double(point_double(p)))
