"""Faultline's injection points, kept import-light on purpose.

The network plane (``network/receiver.py``, ``simple_sender.py``,
``reliable_sender.py``, the native ctypes wrapper) imports THIS module
only — never the scenario/runtime machinery — so the disabled-path cost
is one module-global load per send/receive and the network package
acquires no new import-time dependencies.

``plane`` is the process's active :class:`~.runtime.FaultPlane` (None
when faultline is off — the overwhelmingly common case). ``NODE`` is the
sender identity: a contextvar so one process can host a whole committee
(each engine's actor tasks are spawned under its own value; tasks
inherit the context they were created in), with an env-var default for
one-node-per-process deployments (``HOTSTUFF_FAULTLINE_NODE``).
"""

from __future__ import annotations

import contextvars
import os

#: the active FaultPlane, or None (fast path). Set via runtime.install().
plane = None

#: sender identity for link resolution; see module docstring.
NODE: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "faultline_node", default=os.environ.get("HOTSTUFF_FAULTLINE_NODE")
)


def current_node() -> str | None:
    return NODE.get()


def active():
    """The installed plane, or None."""
    return plane
