"""Faultline policy plane: declarative, JSON-serializable fault scenarios.

A ``Scenario`` is data — a seed plus a virtual-time schedule of fault
*templates* (crash/restart of named nodes, partitions with healing,
per-link impairments, byzantine behaviors). ``Scenario.compile`` resolves
it against a concrete committee into a ``Schedule`` of fully-determined
``FaultEvent``s: every free choice a template leaves open (which node to
crash, which groups a partition cuts, how long an impairment lasts) is
drawn from an RNG derived ONLY from the scenario seed, so the same seed
always yields byte-identical schedules — ``Schedule.trace()`` is the
canonical replay trace whose equality across runs is the reproducibility
contract the chaos harness asserts.

Two layers of determinism:

- the SCHEDULE (what fires, when, against whom) is a pure function of
  ``(seed, node names)`` — replay-trace equality checks this;
- per-message coin flips (does THIS frame drop?) come from per-link RNG
  streams also derived from the seed (``link_rng``). They are
  deterministic given the same message sequence, but message counts vary
  run to run, so they are recorded as counters, not in the trace.

Virtual time: every event's ``at``/``until`` are seconds from scenario
start; the runtime anchors them to the loop clock at activation. No
wall-clock value ever enters the schedule.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass, field

__all__ = [
    "FaultEvent",
    "Scenario",
    "Schedule",
    "chaos_scenario",
    "link_rng",
    "BYZANTINE_BEHAVIORS",
]

#: behaviors the runtime/byzantine module knows how to drive.
#: ``batch_withhold`` is a DATA-PLANE behavior: the node receives worker
#: batches but never signs availability acks and never serves batch
#: requests — enacted inside the Conveyor worker handler (like
#: silent_leader, it needs no attack actor).
BYZANTINE_BEHAVIORS = (
    "equivocate",
    "stale_vote_flood",
    "silent_leader",
    "batch_withhold",
)

#: the pool seeded "?"-behavior draws come from. Frozen at the original
#: three: committed chaos seeds (3, 7, the detector ground-truth corpus)
#: must keep compiling to byte-identical schedules — new behaviors are
#: opt-in by name, never by lottery.
SEEDED_BEHAVIORS = BYZANTINE_BEHAVIORS[:3]

_KINDS = ("crash", "restart", "partition", "link", "byzantine")


def _seed_stream(seed: int, *tags: str) -> random.Random:
    """An RNG stream keyed by the scenario seed plus a string tag —
    independent streams for independent choices, all reproducible."""
    h = hashlib.sha256(
        ("%d|" % seed + "|".join(tags)).encode()
    ).digest()
    return random.Random(int.from_bytes(h[:8], "little"))


def link_rng(seed: int, src: str, dst: str) -> random.Random:
    """Per-directed-link RNG stream for message-level coin flips."""
    return _seed_stream(seed, "link", src, dst)


@dataclass(frozen=True)
class FaultEvent:
    """One fully-resolved fault action on the virtual timeline.

    ``at`` is the activation time (s from scenario start); ``until`` is
    the healing time for interval faults (None = never heals inside the
    scenario). ``params`` carries the kind-specific payload:

    - crash/restart: ``{"node": name}``
    - partition: ``{"groups": [[names...], ...]}``
    - link: ``{"src": name|"*", "dst": name|"*", "drop": p,
      "delay_ms": [lo, hi], "duplicate": p, "reorder": p}``
    - byzantine: ``{"node": name, "behavior": one of
      BYZANTINE_BEHAVIORS}``
    """

    at: float
    kind: str
    params: dict
    until: float | None = None

    def to_json(self) -> dict:
        d = {"at": self.at, "kind": self.kind, **self.params}
        if self.until is not None:
            d["until"] = self.until
        return d


@dataclass
class Schedule:
    """The compiled, deterministic fault sequence for one scenario run."""

    scenario: str
    seed: int
    nodes: list[str]
    events: list[FaultEvent] = field(default_factory=list)

    def trace(self) -> str:
        """Canonical JSON replay trace: identical seeds must produce
        identical traces (the harness asserts string equality)."""
        return json.dumps(
            {
                "schema": "faultline-trace-v1",
                "scenario": self.scenario,
                "seed": self.seed,
                "nodes": self.nodes,
                "events": [e.to_json() for e in self.events],
            },
            sort_keys=True,
            separators=(",", ":"),
        )

    def last_heal_time(self) -> float:
        """Virtual time after which the network is fault-free: liveness
        recovery is measured from here. Events that never heal (crash
        without restart) don't extend it — the checker instead excludes
        permanently-crashed nodes from the recovery set."""
        t = 0.0
        restarts: dict[str, float] = {}
        for e in self.events:
            if e.kind == "restart":
                restarts[e.params["node"]] = max(
                    restarts.get(e.params["node"], 0.0), e.at
                )
        for e in self.events:
            if e.kind == "crash":
                healed = restarts.get(e.params["node"])
                if healed is not None and healed >= e.at:
                    t = max(t, healed)
            elif e.until is not None:
                t = max(t, e.until)
            elif e.kind in ("partition", "link", "byzantine"):
                # Un-healing interval fault: treat activation as the last
                # disturbance; permanently-degraded links are the
                # scenario author's explicit choice.
                t = max(t, e.at)
        return t

    def crashed_forever(self) -> set[str]:
        """Nodes crashed and never restarted — excluded from liveness."""
        down: set[str] = set()
        for e in sorted(self.events, key=lambda e: e.at):
            if e.kind == "crash":
                down.add(e.params["node"])
            elif e.kind == "restart":
                down.discard(e.params["node"])
        return down


@dataclass
class Scenario:
    """Declarative scenario: JSON round-trippable, compiled per committee.

    ``events`` entries are dicts mirroring ``FaultEvent.to_json`` except
    that node-valued fields may be omitted or set to ``"?"`` — compile()
    then draws the target from the seed stream (seeded chaos). ``nodes``
    in templates are INDICES-or-names: integers index into the committee's
    sorted node-name list so scenarios stay committee-agnostic.
    """

    name: str
    seed: int
    duration_s: float
    events: list[dict] = field(default_factory=list)

    # -- (de)serialization ---------------------------------------------------

    def to_json(self) -> dict:
        return {
            "schema": "faultline-scenario-v1",
            "name": self.name,
            "seed": self.seed,
            "duration_s": self.duration_s,
            "events": self.events,
        }

    @classmethod
    def from_json(cls, data: dict) -> "Scenario":
        if data.get("schema") not in (None, "faultline-scenario-v1"):
            raise ValueError(f"unknown scenario schema {data.get('schema')!r}")
        return cls(
            name=data["name"],
            seed=int(data["seed"]),
            duration_s=float(data["duration_s"]),
            events=list(data.get("events", [])),
        )

    @classmethod
    def load(cls, path: str) -> "Scenario":
        with open(path) as f:
            return cls.from_json(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")

    # -- compilation ---------------------------------------------------------

    def _resolve_node(self, value, nodes: list[str], rng: random.Random) -> str:
        if value is None or value == "?":
            return rng.choice(nodes)
        if isinstance(value, int):
            return nodes[value % len(nodes)]
        if value == "*":
            return "*"
        if value not in nodes:
            raise ValueError(f"scenario names unknown node {value!r}")
        return value

    def compile(self, nodes: list[str]) -> Schedule:
        """Resolve templates against a concrete committee. All free
        choices come from seed-derived streams, so the result — including
        ``trace()`` — is a pure function of ``(scenario, nodes)``."""
        nodes = sorted(nodes)
        events: list[FaultEvent] = []
        for i, ev in enumerate(self.events):
            kind = ev.get("kind")
            if kind not in _KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
            # One independent stream per template slot: inserting an event
            # never re-rolls the choices of the events after it.
            rng = _seed_stream(self.seed, "event", str(i), str(kind))
            at = float(ev.get("at", 0.0))
            until = ev.get("until")
            until = None if until is None else float(until)
            if kind in ("crash", "restart"):
                params = {"node": self._resolve_node(ev.get("node"), nodes, rng)}
                if kind == "restart" and ev.get("wipe"):
                    # Cold rejoin (Lazarus): the node restarts with an
                    # EMPTY store and must recover via state sync. A
                    # plain boolean rider — no RNG draw — so committed
                    # scenarios keep byte-identical schedules.
                    params["wipe"] = True
            elif kind == "partition":
                groups = ev.get("groups")
                if groups is None:
                    # Seeded minority cut: isolate f = (n-1)//3 nodes.
                    f = max(1, (len(nodes) - 1) // 3)
                    cut = sorted(rng.sample(nodes, f))
                    groups = [cut, sorted(set(nodes) - set(cut))]
                else:
                    groups = [
                        sorted(
                            self._resolve_node(m, nodes, rng) for m in group
                        )
                        for group in groups
                    ]
                params = {"groups": groups}
            elif kind == "link":
                src = self._resolve_node(ev.get("src", "*"), nodes, rng)
                dst = self._resolve_node(ev.get("dst", "*"), nodes, rng)
                params = {
                    "src": src,
                    "dst": dst,
                    "drop": float(ev.get("drop", 0.0)),
                    "delay_ms": [
                        float(x) for x in ev.get("delay_ms", [0.0, 0.0])
                    ],
                    "duplicate": float(ev.get("duplicate", 0.0)),
                    "reorder": float(ev.get("reorder", 0.0)),
                    "side": str(ev.get("side", "send")),
                }
            else:  # byzantine
                behavior = ev.get("behavior") or rng.choice(SEEDED_BEHAVIORS)
                if behavior not in BYZANTINE_BEHAVIORS:
                    raise ValueError(f"unknown byzantine behavior {behavior!r}")
                params = {
                    "node": self._resolve_node(ev.get("node"), nodes, rng),
                    "behavior": behavior,
                }
            events.append(FaultEvent(at=at, kind=kind, params=params, until=until))
        events.sort(key=lambda e: (e.at, e.kind, json.dumps(e.params, sort_keys=True)))
        return Schedule(
            scenario=self.name, seed=self.seed, nodes=nodes, events=events
        )


def chaos_scenario(
    seed: int,
    duration_s: float = 20.0,
    *,
    crashes: int = 1,
    partitions: int = 1,
    byzantine: int = 1,
    links: int = 1,
    name: str | None = None,
) -> Scenario:
    """Seeded chaos: generate a scenario whose entire event list is drawn
    from the seed — the "one integer describes the whole storm" entry
    point. Faults activate inside the middle 60% of the run (warm-up and
    recovery tails stay clean so the checker can judge liveness), and
    every interval fault heals before ``0.8 * duration_s``."""
    rng = _seed_stream(seed, "chaos")
    lo, hi = 0.2 * duration_s, 0.6 * duration_s
    heal_by = 0.8 * duration_s
    events: list[dict] = []
    for _ in range(crashes):
        at = rng.uniform(lo, hi)
        down = rng.uniform(0.1, 0.3) * duration_s
        # The pair must hit the SAME node: draw one integer index here
        # (compile maps it modulo committee size) instead of two
        # independent "?" choices that would strand a crash unrestarted.
        victim = rng.randrange(1 << 16)
        events.append({"kind": "crash", "node": victim, "at": round(at, 3)})
        events.append(
            {"kind": "restart", "node": victim, "at": round(min(at + down, heal_by), 3)}
        )
    for _ in range(partitions):
        at = rng.uniform(lo, hi)
        events.append(
            {
                "kind": "partition",
                "at": round(at, 3),
                "until": round(min(at + rng.uniform(0.1, 0.25) * duration_s, heal_by), 3),
            }
        )
    for _ in range(links):
        at = rng.uniform(lo, hi)
        events.append(
            {
                "kind": "link",
                "src": "?",
                "dst": "*",
                "at": round(at, 3),
                "until": round(min(at + rng.uniform(0.1, 0.3) * duration_s, heal_by), 3),
                "drop": round(rng.uniform(0.05, 0.4), 3),
                "delay_ms": [5.0, round(rng.uniform(20.0, 80.0), 1)],
                "duplicate": round(rng.uniform(0.0, 0.1), 3),
                "reorder": round(rng.uniform(0.0, 0.1), 3),
            }
        )
    for _ in range(byzantine):
        at = rng.uniform(lo, hi)
        events.append(
            {
                "kind": "byzantine",
                "node": "?",
                "behavior": None,
                "at": round(at, 3),
                "until": round(min(at + rng.uniform(0.2, 0.4) * duration_s, heal_by), 3),
            }
        )
    # Drop the null behavior key (from_json/compile treat missing == None).
    for ev in events:
        if ev.get("behavior", "x") is None:
            del ev["behavior"]
    return Scenario(
        name=name or f"chaos-{seed}",
        seed=seed,
        duration_s=duration_s,
        events=events,
    )
