"""Faultline runtime: the FaultPlane that enacts a compiled Schedule.

The plane owns three things:

- **link filters** consulted by the network plane through
  :mod:`hotstuff_tpu.faultline.hooks` (one global load when disabled):
  partitions and per-link drop/delay/duplicate/reorder rules are applied
  on the SEND side (both endpoints of an in-process committee share the
  plane; per-process deployments each filter their own egress), plus an
  optional receive-side filter for ingress-NIC-style loss;
- **supervised actions** (crash, restart, byzantine on/off) which the
  plane cannot enact itself: the scenario runner polls
  :meth:`FaultPlane.poll_actions` and performs them against real engines
  / processes — the plane just keeps the deterministic clock and trace;
- **the replay trace + telemetry**: every applied transition is recorded
  with its SCHEDULED virtual time (never wall clock), and every injected
  message-level effect counts into ``faultline.injected.*`` metrics — a
  namespace reserved for the injection plane, so snapshots distinguish
  injected faults from organically occurring ones.

Message-level coin flips use per-link RNG streams derived from the
scenario seed (``policy.link_rng``): deterministic given the same message
sequence on a link.
"""

from __future__ import annotations

import logging
import time

from hotstuff_tpu import telemetry

from . import hooks
from .policy import Schedule, link_rng

log = logging.getLogger("faultline")

__all__ = ["FaultPlane", "install", "uninstall"]

#: wire tag of consensus proposals (consensus/messages.py TAG_PROPOSE) —
#: the frame class a silent leader suppresses. Kept as a literal so this
#: module never imports the consensus package.
_TAG_PROPOSE = 0


class _LinkRule:
    __slots__ = ("src", "dst", "drop", "delay_lo", "delay_hi", "duplicate",
                 "reorder", "side")

    def __init__(self, params: dict) -> None:
        self.src = params["src"]
        self.dst = params["dst"]
        self.drop = params.get("drop", 0.0)
        lo, hi = params.get("delay_ms", (0.0, 0.0))
        self.delay_lo = lo / 1e3
        self.delay_hi = hi / 1e3
        self.duplicate = params.get("duplicate", 0.0)
        self.reorder = params.get("reorder", 0.0)
        self.side = params.get("side", "send")

    def matches(self, src: str | None, dst: str | None) -> bool:
        if self.src != "*" and self.src != src:
            return False
        return self.dst == "*" or self.dst == dst


class FaultPlane:
    """Enacts one compiled :class:`~.policy.Schedule` against a committee.

    ``addr_to_node`` maps every network address fault injection should
    recognize to its node name; ``consensus_addrs`` is the subset whose
    frames carry consensus wire tags (silent-leader suppression only
    inspects those). The plane is inert until :meth:`start` anchors the
    virtual clock.
    """

    def __init__(
        self,
        schedule: Schedule,
        addr_to_node: dict[tuple[str, int], str],
        consensus_addrs: set[tuple[str, int]] | None = None,
        clock=time.monotonic,
    ) -> None:
        self.schedule = schedule
        # Injectable clock: virtual time is ``clock() - t0``. The real
        # planes keep the default monotonic clock; the simulation plane
        # passes its virtual clock so the SAME schedule machinery (and
        # the same per-link RNG streams) enacts faults at simulated
        # timestamps with zero real sleeping.
        self._clock = clock
        self.addr_to_node = dict(addr_to_node)
        self.consensus_addrs = (
            set(addr_to_node) if consensus_addrs is None else set(consensus_addrs)
        )
        self._t0: float | None = None
        self.started_wall: float | None = None
        # (time, is_heal, event) transitions in virtual-time order; heals
        # sort after activations at the same instant.
        self._transitions: list[tuple[float, int, object]] = []
        for ev in schedule.events:
            self._transitions.append((ev.at, 0, ev))
            if ev.until is not None:
                self._transitions.append((ev.until, 1, ev))
        self._transitions.sort(key=lambda t: (t[0], t[1]))
        self._cursor = 0
        # Active state.
        self._partitions: list[dict[str, int]] = []  # node -> group index
        self._links: list[_LinkRule] = []
        self._behaviors: dict[str, set[str]] = {}  # node -> active behaviors
        self._pending_actions: list[dict] = []  # for the supervisor
        self.applied: list[dict] = []  # replay-trace of applied transitions
        self._rngs: dict[tuple[str, str], object] = {}
        # Injection counters (plain ints for the verdict; telemetry
        # counters for the observability plane — no-ops when disabled).
        self.counts = {
            "send_drops": 0, "recv_drops": 0, "delays": 0, "duplicates": 0,
            "reorders": 0, "proposals_suppressed": 0, "events_applied": 0,
        }
        self._m = {
            k: telemetry.counter(f"faultline.injected.{k}") for k in self.counts
        }
        self._g_active = telemetry.gauge("faultline.active_faults")

    # -- clock / schedule ----------------------------------------------------

    def start(self, t0: float | None = None) -> "FaultPlane":
        self._t0 = self._clock() if t0 is None else t0
        # Wall-clock anchor of virtual time 0: consumers that correlate
        # schedule times with wall-stamped telemetry (the watchtower's
        # detector bench measures time-to-detection against fault
        # activation) read this instead of guessing.
        self.started_wall = time.time()
        return self

    def vnow(self) -> float:
        return 0.0 if self._t0 is None else self._clock() - self._t0

    def any_active(self) -> bool:
        """True while any fault is currently active (drives the
        RoundTrace fault annotation)."""
        return bool(self._partitions or self._links or self._behaviors)

    def _advance(self) -> None:
        if self._t0 is None:
            return
        now = self.vnow()
        while self._cursor < len(self._transitions):
            at, is_heal, ev = self._transitions[self._cursor]
            if at > now:
                break
            self._cursor += 1
            self._apply(ev, heal=bool(is_heal))

    def _apply(self, ev, heal: bool) -> None:
        kind = ev.kind
        self.counts["events_applied"] += 1
        self._m["events_applied"].inc()
        self.applied.append(
            {
                "t": ev.until if heal else ev.at,  # scheduled, not wall
                "kind": kind,
                "phase": "heal" if heal else "inject",
                **ev.params,
            }
        )
        if kind == "partition":
            membership = {
                node: gi
                for gi, group in enumerate(ev.params["groups"])
                for node in group
            }
            if heal:
                if membership in self._partitions:
                    self._partitions.remove(membership)
            else:
                self._partitions.append(membership)
        elif kind == "link":
            if heal:
                self._links = [
                    r for r in self._links
                    if (r.src, r.dst) != (ev.params["src"], ev.params["dst"])
                ]
            else:
                self._links.append(_LinkRule(ev.params))
        elif kind == "byzantine":
            node, behavior = ev.params["node"], ev.params["behavior"]
            if heal:
                self._behaviors.get(node, set()).discard(behavior)
                if not self._behaviors.get(node):
                    self._behaviors.pop(node, None)
            else:
                self._behaviors.setdefault(node, set()).add(behavior)
            # Attack-task behaviors need the supervisor; silent_leader is
            # enacted right here in the send filter and batch_withhold
            # inside the Conveyor worker handler.
            if behavior not in ("silent_leader", "batch_withhold"):
                self._pending_actions.append(
                    {"action": "byzantine_" + ("off" if heal else "on"),
                     "node": node, "behavior": behavior}
                )
        elif kind in ("crash", "restart"):
            action = {"action": kind, "node": ev.params["node"]}
            if ev.params.get("wipe"):
                action["wipe"] = True  # cold rejoin: restart on empty store
            self._pending_actions.append(action)
        self._g_active.set(
            len(self._partitions) + len(self._links)
            + sum(len(b) for b in self._behaviors.values())
        )
        # Flight-recorder context: injected transitions interleave with
        # the protocol events in the trace ring, so a postmortem shows
        # WHAT the committee was doing when each fault landed.
        telemetry.trace_event(
            "faultline", 0, f"{'heal' if heal else 'inject'}:{kind}"
        )
        log.info(
            "faultline %s %s %s (v=%.3fs)",
            "healed" if heal else "injected", kind, ev.params,
            ev.until if heal else ev.at,
        )

    def behavior_active(self, node: str, behavior: str) -> bool:
        """True while ``node`` is currently marked with ``behavior`` —
        the query surface for behaviors enacted at their call site
        (silent_leader in the send filter, batch_withhold in the
        Conveyor worker handler)."""
        self._advance()
        active = self._behaviors.get(node)
        return bool(active and behavior in active)

    def schedule_exhausted(self) -> bool:
        """True once every scheduled transition (activations AND heals)
        has been applied — i.e. virtual time has passed the whole
        schedule. The sim plane gates its early-exit on this so a run
        can never skip late faults by recovering quickly."""
        return self._cursor >= len(self._transitions)

    def poll_actions(self) -> list[dict]:
        """Supervised actions due now (crash/restart/byzantine on-off),
        in schedule order. The runner enacts them against real engines or
        processes; draining is destructive."""
        self._advance()
        due, self._pending_actions = self._pending_actions, []
        return due

    # -- link filters --------------------------------------------------------

    def _rng(self, src: str, dst: str):
        key = (src, dst)
        rng = self._rngs.get(key)
        if rng is None:
            rng = self._rngs[key] = link_rng(self.schedule.seed, src, dst)
        return rng

    def _partitioned(self, src: str, dst: str) -> bool:
        for membership in self._partitions:
            gs, gd = membership.get(src), membership.get(dst)
            if gs is not None and gd is not None and gs != gd:
                return True
        return False

    def filter_send(
        self,
        address: tuple[str, int],
        frame: bytes,
        payload_off: int = 0,
        src: str | None = None,
        dst: str | None = None,
    ):
        """Decide the fate of one outbound frame to ``address``.

        Returns None to deliver untouched (the fast path), or
        ``(action, delay_s, copies)`` with action ``"drop"``/``"deliver"``
        — the sender drops, or sends ``copies`` copies after ``delay_s``.
        ``frame`` begins its payload at ``payload_off`` (senders that
        pre-frame pass 4 to skip the length prefix); only the first
        payload byte is ever inspected (silent-leader suppression).

        ``src``/``dst`` override endpoint resolution (default: the
        contextvar sender identity and the address map). The simulation
        plane passes both explicitly — it has no sender tasks to carry a
        contextvar, and Twins runs route one address to several node
        INSTANCES that partition independently.
        """
        self._advance()
        if src is None:
            src = hooks.current_node()
        if src is None:
            return None  # external senders (clients) are never faulted
        if dst is None:
            dst = self.addr_to_node.get(address)
        if dst is None:
            return None
        behaviors = self._behaviors.get(src)
        if (
            behaviors
            and "silent_leader" in behaviors
            and address in self.consensus_addrs
            and len(frame) > payload_off
            and frame[payload_off] == _TAG_PROPOSE
        ):
            self.counts["proposals_suppressed"] += 1
            self._m["proposals_suppressed"].inc()
            return ("drop", 0.0, 0)
        if self._partitioned(src, dst):
            self.counts["send_drops"] += 1
            self._m["send_drops"].inc()
            return ("drop", 0.0, 0)
        if not self._links:
            return None
        delay = 0.0
        copies = 1
        touched = False
        for rule in self._links:
            if rule.side != "send" or not rule.matches(src, dst):
                continue
            rng = self._rng(src, dst)
            if rule.drop and rng.random() < rule.drop:
                self.counts["send_drops"] += 1
                self._m["send_drops"].inc()
                return ("drop", 0.0, 0)
            if rule.delay_hi > 0.0:
                delay += rng.uniform(rule.delay_lo, rule.delay_hi)
                touched = True
            if rule.duplicate and rng.random() < rule.duplicate:
                copies += 1
                touched = True
            if rule.reorder and rng.random() < rule.reorder:
                # Reordering on an in-order transport = holding this frame
                # past its successors: one extra delay quantum.
                delay += rule.delay_hi if rule.delay_hi > 0 else 0.01
                self.counts["reorders"] += 1
                self._m["reorders"].inc()
                touched = True
        if not touched:
            return None
        if delay > 0.0:
            self.counts["delays"] += 1
            self._m["delays"].inc()
        if copies > 1:
            self.counts["duplicates"] += copies - 1
            self._m["duplicates"].inc(copies - 1)
        return ("deliver", delay, copies)

    def filter_recv(self, address: tuple[str, int], dst: str | None = None):
        """Receive-side filter for the listener bound to ``address``:
        applies ``side: "recv"`` link rules whose dst is this node
        (ingress loss where the sender cannot be instrumented). Returns
        None (deliver) or ``("drop"|"deliver", delay_s)``. ``dst``
        overrides address-map resolution (see ``filter_send``)."""
        self._advance()
        if not self._links:
            return None
        if dst is None:
            dst = self.addr_to_node.get(address)
        if dst is None:
            return None
        for rule in self._links:
            if rule.side != "recv":
                continue
            if rule.dst != "*" and rule.dst != dst:
                continue
            rng = self._rng("*", dst)
            if rule.drop and rng.random() < rule.drop:
                self.counts["recv_drops"] += 1
                self._m["recv_drops"].inc()
                return ("drop", 0.0)
            if rule.delay_hi > 0.0:
                return ("deliver", rng.uniform(rule.delay_lo, rule.delay_hi))
        return None

    # -- verdict support -----------------------------------------------------

    def injection_summary(self) -> dict:
        return {"applied": list(self.applied), "counts": dict(self.counts)}


def install(plane: FaultPlane) -> FaultPlane:
    """Make ``plane`` the process's active fault plane (and annotate
    RoundTrace spans that close while faults are active)."""
    hooks.plane = plane
    telemetry.RoundTrace.fault_flag = staticmethod(plane.any_active)
    return plane


def uninstall() -> None:
    hooks.plane = None
    telemetry.RoundTrace.fault_flag = None
