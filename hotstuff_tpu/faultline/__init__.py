"""Faultline: deterministic fault injection with machine-checked verdicts.

Three planes (see ``docs/faultline.md``):

- **policy** — declarative, JSON-serializable scenarios whose entire
  fault schedule derives from one seed (``Scenario``, ``chaos_scenario``,
  ``Schedule.trace()`` as the replay-equality contract);
- **runtime** — the ``FaultPlane`` enacting partitions / per-link
  drop-delay-duplicate-reorder / byzantine behaviors through hooks in the
  network plane (asyncio and native C++ via ``hs_net_faults``), plus
  supervised crash/restart, every injection counted in
  ``faultline.injected.*`` telemetry and recorded to a replay trace;
- **checker** — post-run safety (no conflicting commits at a round across
  honest nodes) and liveness (commit growth resumes after the last heal)
  verdicts as plain JSON.

Entry points: ``benchmark/committee_scale.py --faults`` and
``benchmark/run_local.py --chaos`` (harness + LocalBench integration),
or programmatically ``faultline.run_scenario``.

Import discipline: the network plane imports ``faultline.hooks`` on its
own hot path, so this package initializer must stay dependency-light —
the harness/byzantine/checker layers (which import consensus, which
imports network) load lazily on first attribute access (PEP 562).
"""

from .policy import (
    BYZANTINE_BEHAVIORS,
    FaultEvent,
    Scenario,
    Schedule,
    chaos_scenario,
    link_rng,
)
from .runtime import FaultPlane, install, uninstall

__all__ = [
    "BYZANTINE_BEHAVIORS",
    "CommitRecord",
    "FaultEvent",
    "FaultPlane",
    "Scenario",
    "ScenarioRun",
    "Schedule",
    "VERDICT_SCHEMA",
    "chaos_scenario",
    "check",
    "check_availability",
    "install",
    "link_rng",
    "run_scenario",
    "uninstall",
]

_LAZY = {
    "CommitRecord": ("checker", "CommitRecord"),
    "check": ("checker", "check"),
    "check_availability": ("checker", "check_availability"),
    "VERDICT_SCHEMA": ("checker", "VERDICT_SCHEMA"),
    "ScenarioRun": ("harness", "ScenarioRun"),
    "run_scenario": ("harness", "run_scenario"),
    "ByzantineActor": ("byzantine", "ByzantineActor"),
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    module = importlib.import_module(f".{target[0]}", __name__)
    value = getattr(module, target[1])
    globals()[name] = value
    return value
