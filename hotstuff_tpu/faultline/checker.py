"""Faultline's safety/liveness checker: machine-checked verdicts.

Consumes the per-node commit streams a scenario run collected and the
compiled schedule, and emits a machine-readable verdict:

- **safety** — no two honest nodes committed different blocks at the same
  round (quorum intersection must hold under every injected fault), and
  no single node ever committed two different blocks at one round.
  Streams are NOT required to be round-monotonic: commit progress is
  persisted lazily (with the vote state), so a node crash-restarted
  between a commit and its next vote legitimately REPLAYS recent
  commits — at-least-once delivery the execution layer must absorb.
  What replay may never do is change a digest;
- **liveness** — commit height resumes growing after the last fault
  heals: every honest node that is still supposed to be alive gains at
  least ``min_recovery_commits`` commits with virtual time past the
  schedule's ``last_heal_time()``. Nodes crashed and never restarted are
  excluded (the scenario author chose to lose them).

The verdict is plain data (JSON-serializable) so CI lanes can gate on
``verdict["safety"]["ok"] and verdict["liveness"]["recovered"]`` without
parsing human text.
"""

from __future__ import annotations

from .policy import Schedule

__all__ = [
    "CommitRecord",
    "check",
    "check_availability",
    "check_frontier_availability",
    "VERDICT_SCHEMA",
]

VERDICT_SCHEMA = "faultline-verdict-v1"


class CommitRecord:
    """One committed block as observed by one node: ``(round, digest,
    t)`` with ``t`` in virtual scenario time."""

    __slots__ = ("round", "digest", "t")

    def __init__(self, round_: int, digest: bytes, t: float) -> None:
        self.round = round_
        self.digest = digest
        self.t = t


def check_availability(
    schedule: Schedule,
    committed: set,
    holders: dict,
    *,
    honest: set[str] | None = None,
) -> dict:
    """The Conveyor data-plane invariant: consensus never commits a
    batch digest lacking an availability certificate RESOLVABLE at f+1
    honest nodes — i.e. after the run, every committed batch digest must
    be held (store-resolvable) by at least f+1 honest nodes, so the
    2f+1-signed cert it was ordered under can always be honored.

    ``committed`` is the set of committed batch digests (any hashable
    form, typically hex); ``holders`` maps each digest to the set of
    node names whose store resolves it. ``honest`` defaults to every
    node the schedule never marked byzantine. Returns a plain-data
    verdict section (``{"ok", "f", "checked", "violations"}``) that
    harnesses merge into their run verdicts.
    """
    byzantine = {
        e.params["node"] for e in schedule.events if e.kind == "byzantine"
    }
    if honest is None:
        honest = set(schedule.nodes) - byzantine
    n = len(schedule.nodes)
    f = (n - 1) // 3
    required = f + 1
    violations = []
    for digest in sorted(committed):
        holding = sorted(h for h in holders.get(digest, ()) if h in honest)
        if len(holding) < required:
            violations.append(
                {
                    "type": "unresolvable_commit",
                    "digest": digest if isinstance(digest, str) else str(digest),
                    "honest_holders": holding,
                    "required": required,
                }
            )
    return {
        "ok": not violations,
        "f": f,
        "required_holders": required,
        "checked": len(committed),
        "violations": violations,
    }


def check_frontier_availability(
    schedule: Schedule,
    committed: set,
    resolvers: dict,
    floors: dict,
    *,
    honest: set[str] | None = None,
) -> dict:
    """The Lazarus truncation invariant: log compaction must never make
    a committed block unservable to a catching-up replica. After the
    run, every committed ``(round, digest)`` must be SERVABLE by at
    least f+1 honest nodes, where node X serves it iff

    - X's store still resolves ``digest`` (the block survives below or
      above X's truncation horizon), or
    - X's snapshot frontier round >= ``round`` (X truncated it, but its
      snapshot subsumes the block's state — a joiner syncing from X
      lands at or past the block and never needs it individually).

    ``committed`` is a set of ``(round, digest)`` pairs (digest in any
    hashable form); ``resolvers`` maps each digest to the set of node
    names whose store resolves it; ``floors`` maps node name to its
    snapshot frontier round (0/absent when the node never compacted).
    Returns a plain-data verdict section harnesses merge into their run
    verdicts.
    """
    byzantine = {
        e.params["node"] for e in schedule.events if e.kind == "byzantine"
    }
    if honest is None:
        honest = set(schedule.nodes) - byzantine
    n = len(schedule.nodes)
    f = (n - 1) // 3
    required = f + 1
    violations = []
    for round_, digest in sorted(
        committed, key=lambda rd: (rd[0], str(rd[1]))
    ):
        servers = sorted(
            node
            for node in honest
            if node in resolvers.get(digest, ())
            or floors.get(node, 0) >= round_
        )
        if len(servers) < required:
            violations.append(
                {
                    "type": "unservable_commit",
                    "round": round_,
                    "digest": (
                        digest.hex()
                        if isinstance(digest, (bytes, bytearray))
                        else str(digest)
                    ),
                    "honest_servers": servers,
                    "required": required,
                }
            )
    return {
        "ok": not violations,
        "f": f,
        "required_servers": required,
        "checked": len(committed),
        "floors": {k: floors[k] for k in sorted(floors)},
        "violations": violations,
    }


def check(
    schedule: Schedule,
    commits: dict[str, list[CommitRecord]],
    *,
    honest: set[str] | None = None,
    min_recovery_commits: int = 3,
    injections: dict | None = None,
) -> dict:
    """Judge one finished scenario run. ``commits`` maps node name to its
    commit stream in arrival order; ``honest`` defaults to every node the
    schedule never marked byzantine."""
    byzantine = {
        e.params["node"] for e in schedule.events if e.kind == "byzantine"
    }
    if honest is None:
        honest = set(schedule.nodes) - byzantine
    violations: list[dict] = []

    # Intra-node consistency: crash-recovery replay may repeat rounds
    # (see module docstring) but never with a different digest.
    for node in sorted(honest):
        seen: dict[int, bytes] = {}
        for rec in commits.get(node, []):
            prev = seen.get(rec.round)
            if prev is not None and prev != rec.digest:
                violations.append(
                    {
                        "type": "intra_node_conflict",
                        "node": node,
                        "round": rec.round,
                        "digests": [prev.hex(), rec.digest.hex()],
                    }
                )
            seen[rec.round] = rec.digest

    # Cross-node agreement: same round => same digest among honest nodes.
    by_round: dict[int, dict[bytes, list[str]]] = {}
    for node in sorted(honest):
        for rec in commits.get(node, []):
            by_round.setdefault(rec.round, {}).setdefault(
                rec.digest, []
            ).append(node)
    for round_, digests in sorted(by_round.items()):
        if len(digests) > 1:
            violations.append(
                {
                    "type": "conflicting_commit",
                    "round": round_,
                    "digests": {
                        d.hex(): sorted(nodes) for d, nodes in digests.items()
                    },
                }
            )

    # Liveness: commit growth after the last heal.
    heal_t = schedule.last_heal_time()
    expected_alive = sorted(
        (honest - schedule.crashed_forever())
    )
    post_heal = {
        node: sum(1 for rec in commits.get(node, []) if rec.t > heal_t)
        for node in expected_alive
    }
    laggards = sorted(
        n for n, c in post_heal.items() if c < min_recovery_commits
    )
    recovered = not laggards
    # Measured recovery cost: how long past the heal the SLOWEST
    # recovering node took to reach min_recovery_commits post-heal
    # commits (None unless every expected node got there). This is the
    # view-change/recovery number benchmarks report.
    recovery_s = None
    if recovered and expected_alive:
        per_node = []
        for node in expected_alive:
            times = sorted(
                rec.t for rec in commits.get(node, []) if rec.t > heal_t
            )
            k = max(min_recovery_commits, 1)
            if len(times) < k:
                per_node = []
                break
            per_node.append(times[k - 1] - heal_t)
        if per_node:
            recovery_s = round(max(per_node), 3)

    return {
        "schema": VERDICT_SCHEMA,
        "scenario": schedule.scenario,
        "seed": schedule.seed,
        "nodes": schedule.nodes,
        "byzantine": sorted(byzantine),
        "safety": {"ok": not violations, "violations": violations},
        "liveness": {
            "ok": recovered,
            "recovered": recovered,
            "heal_t": heal_t,
            "recovery_s": recovery_s,
            "min_recovery_commits": min_recovery_commits,
            "post_heal_commits": post_heal,
            "laggards": laggards,
        },
        "commits": {
            node: len(commits.get(node, [])) for node in sorted(schedule.nodes)
        },
        "injections": injections or {},
    }
