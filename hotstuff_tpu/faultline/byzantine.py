"""Active byzantine behaviors driven by the scenario runner.

Three behaviors (``policy.BYZANTINE_BEHAVIORS``):

- ``silent_leader`` needs no actor: the FaultPlane suppresses the node's
  outbound proposals at the link filter, so the node keeps voting and
  timing out but never proposes — the committee burns a timeout every
  time it elects the silent seat (the regime the reputation elector
  exists for).
- ``equivocate`` and ``stale_vote_flood`` are ACTOR behaviors: a task
  holding the byzantine seat's genuine key injects adversarial traffic
  through a real sender (so link faults apply to the attacker too). The
  honest committee must drop all of it at verification/round gates while
  continuing to commit — safety rests on quorum intersection, never on
  these frames being filtered early.

Actors observe only what a network adversary could (a round estimate
sampled from the runner), and every randomized choice draws from a
seed-derived stream so the attack sequence replays with the scenario.
"""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu.consensus.messages import (
    QC,
    Block,
    Vote,
    encode_propose,
    encode_vote,
)
from hotstuff_tpu.crypto import sha512_digest
from hotstuff_tpu.network import SimpleSender

from .policy import _seed_stream

log = logging.getLogger("faultline")

__all__ = ["ByzantineActor"]

_PERIOD_S = 0.05  # injection cadence; fast enough to pressure every round


class ByzantineActor:
    """One byzantine seat's attack task. ``round_source`` returns the
    adversary's current round estimate (the runner samples an honest
    core; a real attacker would read it off the wire)."""

    def __init__(
        self,
        committee,
        name,
        secret,
        behavior: str,
        seed: int,
        round_source,
    ) -> None:
        self.committee = committee
        self.name = name
        self.secret = secret
        self.behavior = behavior
        self.rng = _seed_stream(seed, "byzantine", behavior, str(name))
        self.round_source = round_source
        self.network = SimpleSender()
        self.sent = 0
        self._task: asyncio.Task | None = None

    def spawn(self) -> "ByzantineActor":
        runner = {
            "equivocate": self._equivocate,
            "stale_vote_flood": self._stale_vote_flood,
        }.get(self.behavior)
        if runner is None:
            raise ValueError(f"behavior {self.behavior!r} needs no actor")
        self._task = asyncio.create_task(
            runner(), name=f"byzantine_{self.behavior}"
        )
        return self

    async def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self.network.shutdown()

    def _peers(self):
        return [a for _, a in self.committee.broadcast_addresses(self.name)]

    async def _equivocate(self) -> None:
        """Equivocating proposer: two conflicting signed blocks for the
        same round, each half of the committee receiving a different one
        first (plus both broadcast, so everyone eventually sees the
        conflict). Honest cores must never commit either unless it earns
        a genuine quorum — which conflicting proposals cannot both do."""
        while True:
            round_ = self.round_source() + 1
            parent = sha512_digest(
                b"equivocation-parent", self.rng.randbytes(8)
            )
            fake_qc = QC(hash=parent, round=round_ - 1, votes=[])
            peers = self._peers()
            half = len(peers) // 2
            for salt, targets in (
                (b"a", peers[:half]),
                (b"b", peers[half:]),
            ):
                block = Block.new_from_key(
                    fake_qc,
                    None,
                    self.name,
                    round_,
                    [sha512_digest(b"equiv-payload-" + salt)],
                    self.secret,
                )
                self.network.broadcast(targets or peers, encode_propose(block))
                self.sent += 1
            await asyncio.sleep(_PERIOD_S)

    async def _stale_vote_flood(self) -> None:
        """Stale-vote flooder: bursts of genuine-key votes for rounds far
        behind the committee's progress — the traffic class the native
        pre-stage's round gate and the core's cheap round check must
        shed without paying signature verifications."""
        while True:
            current = self.round_source()
            peers = self._peers()
            for _ in range(8):
                stale_round = max(1, current - self.rng.randrange(1, 50))
                vote = Vote.new_from_key(
                    sha512_digest(b"stale", self.rng.randbytes(8)),
                    stale_round,
                    self.name,
                    self.secret,
                )
                self.network.broadcast(peers, encode_vote(vote))
                self.sent += 1
            await asyncio.sleep(_PERIOD_S)
