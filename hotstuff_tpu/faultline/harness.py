"""Faultline scenario runner: an in-process committee under scripted faults.

Boots an N-validator committee of full consensus engines over real
localhost TCP (the ``committee_scale --mode protocol`` testbed), installs
a :class:`~.runtime.FaultPlane` compiled from a scenario, enacts the
supervised schedule (engine crash/restart, byzantine actors), collects
every node's commit stream, and returns the checker's machine verdict
plus the canonical replay trace.

Determinism contract: the fault SCHEDULE — what fires, when, against
which node/link — is a pure function of the scenario seed (assert
``result["trace"]`` equality across runs). Wall-clock interleaving of
protocol messages is not replayed; the checker's invariants are exactly
the properties that must hold regardless of interleaving.

Virtual time anchors at the run's first full-committee commit (warm-up —
key generation, crypto backend compile, TCP dial-in — varies by machine
and must not eat the scenario's timeline).
"""

from __future__ import annotations

import asyncio
import logging
import time

from hotstuff_tpu import telemetry

from . import hooks
from .byzantine import ByzantineActor
from .checker import CommitRecord, check
from .policy import Scenario
from .runtime import FaultPlane, install, uninstall

log = logging.getLogger("faultline")

__all__ = ["run_scenario", "ScenarioRun"]

_POLL_S = 0.05  # supervisor cadence; schedule times stay seed-derived
_RECOVERY_POLL_S = 0.2  # recovery-tail probe cadence (wall, not scheduled)


def _node_name(i: int) -> str:
    return f"n{i:03d}"  # zero-padded so sorted() == index order


class _Engine:
    """One seat: key, store, live Consensus handle, commit collector."""

    def __init__(self, index, name, keypair, store):
        self.index = index
        self.name = name
        self.pk, self.sk = keypair
        self.store = store
        self.consensus = None
        self.tasks: list[asyncio.Task] = []
        self.crashed = False

    def core(self):
        """The engine's Core instance (the run coroutine's self)."""
        if self.consensus is None:
            return None
        frame = self.consensus.tasks[0].get_coro().cr_frame
        return frame.f_locals.get("self") if frame is not None else None


class ScenarioRun:
    """Mutable run state; ``execute`` drives it end to end."""

    def __init__(
        self,
        scenario: Scenario,
        n: int,
        *,
        base_port: int = 21000,
        timeout_delay: int = 1_000,
        leader_elector: str = "",
        min_recovery_commits: int = 3,
        recovery_timeout_s: float = 30.0,
        retention_rounds: int = 0,
        clock=time.monotonic,
    ) -> None:
        from hotstuff_tpu.consensus import Authority, Committee, Parameters
        from hotstuff_tpu.crypto import generate_keypair

        self.scenario = scenario
        self.n = n
        # Injectable clock for the harness's OWN deadlines (boot, the
        # recovery tail): defaults to wall time on the real planes; the
        # simulation reuses the checker but supplies virtual deadlines,
        # so no wall-clock value leaks into a simulated verdict.
        self._clock = clock
        self.names = [_node_name(i) for i in range(n)]
        self.schedule = scenario.compile(self.names)
        self.min_recovery_commits = min_recovery_commits
        self.recovery_timeout_s = recovery_timeout_s

        seed_bytes = scenario.seed.to_bytes(8, "little", signed=False)
        keypairs = [
            generate_keypair(seed=bytes([i]) * 24 + seed_bytes)[:2]
            for i in range(n)
        ]
        addresses = [("127.0.0.1", base_port + i) for i in range(n)]
        self.committee = Committee(
            authorities={
                pk: Authority(stake=1, address=addresses[i])
                for i, (pk, _) in enumerate(keypairs)
            }
        )
        self.params = Parameters(
            timeout_delay=timeout_delay,
            batch_vote_verification=True,
            leader_elector=leader_elector,
            retention_rounds=retention_rounds,
        )
        from hotstuff_tpu.store import Store

        self.engines = [
            _Engine(i, self.names[i], keypairs[i], Store())
            for i in range(n)
        ]
        self.plane = FaultPlane(
            self.schedule,
            {addresses[i]: self.names[i] for i in range(n)},
        )
        self.commits: dict[str, list[CommitRecord]] = {
            name: [] for name in self.names
        }
        self.actors: dict[tuple[str, str], ByzantineActor] = {}
        self._aux: list[asyncio.Task] = []

    # -- engine lifecycle ----------------------------------------------------

    async def _spawn_engine(self, eng: _Engine) -> None:
        from hotstuff_tpu.consensus import Consensus
        from hotstuff_tpu.crypto import SignatureService

        rx_mempool: asyncio.Queue = asyncio.Queue()
        tx_mempool: asyncio.Queue = asyncio.Queue()
        tx_commit: asyncio.Queue = asyncio.Queue()

        async def drain(q=tx_mempool):
            while True:
                await q.get()

        async def collect(q=tx_commit, name=eng.name):
            while True:
                blk = await q.get()
                self.commits[name].append(
                    CommitRecord(blk.round, blk.digest().data, self.plane.vnow())
                )

        # Everything the engine spawns inherits its faultline identity
        # (contextvars flow into create_task), so its senders resolve the
        # right source end of every link.
        token = hooks.NODE.set(eng.name)
        try:
            eng.consensus = await Consensus.spawn(
                eng.pk,
                self.committee,
                self.params,
                SignatureService(eng.sk),
                eng.store,
                rx_mempool,
                tx_mempool,
                tx_commit,
            )
            eng.tasks = [
                asyncio.create_task(drain()),
                asyncio.create_task(collect()),
            ]
        finally:
            hooks.NODE.reset(token)
        eng.crashed = False

    async def _crash_engine(self, eng: _Engine) -> None:
        """Unclean kill — cancel the actor tasks and yank the listeners,
        modeling a process crash. The store object survives (it is the
        node's disk), so a later restart exercises real state recovery."""
        if eng.consensus is None or eng.crashed:
            return
        c = eng.consensus
        for t in c.tasks:
            t.cancel()
        if c.synchronizer is not None:
            c.synchronizer.shutdown()
        if c.mempool_driver is not None:
            c.mempool_driver.shutdown()
        for r in c.receivers:
            server = getattr(r, "_server", None)
            if server is not None:  # asyncio transport: tear down unclean
                r._closing = True
                server.close()
                for task in list(r._conn_tasks):
                    task.cancel()
                for w in list(r._writers):
                    w.transport.abort()
            else:  # native transport: drop the listener id
                await r.shutdown()
        for t in eng.tasks:
            t.cancel()
        eng.consensus = None
        eng.crashed = True
        telemetry.counter("faultline.injected.crashes").inc()
        log.info("faultline crashed %s", eng.name)

    async def _restart_engine(self, eng: _Engine, wipe: bool = False) -> None:
        if not eng.crashed:
            return
        if wipe:
            # Cold rejoin (Lazarus): the node's disk is lost — replace
            # the store with a fresh empty one; the engine must recover
            # via state sync from its peers.
            from hotstuff_tpu.store import Store

            eng.store = Store()
        await self._spawn_engine(eng)
        telemetry.counter("faultline.injected.restarts").inc()
        log.info(
            "faultline restarted %s%s", eng.name, " (wiped)" if wipe else ""
        )

    # -- byzantine actors ----------------------------------------------------

    def _honest_round(self) -> int:
        rounds = [
            e.core().round
            for e in self.engines
            if not e.crashed and e.core() is not None
        ]
        return max(rounds, default=1)

    async def _enact(self, action: dict) -> None:
        node = action["node"]
        eng = self.engines[self.names.index(node)]
        if action["action"] == "crash":
            await self._crash_engine(eng)
        elif action["action"] == "restart":
            await self._restart_engine(eng, wipe=action.get("wipe", False))
        elif action["action"] == "byzantine_on":
            key = (node, action["behavior"])
            if key not in self.actors:
                token = hooks.NODE.set(node)
                try:
                    self.actors[key] = ByzantineActor(
                        self.committee,
                        eng.pk,
                        eng.sk,
                        action["behavior"],
                        self.scenario.seed,
                        self._honest_round,
                    ).spawn()
                finally:
                    hooks.NODE.reset(token)
                telemetry.counter("faultline.injected.byzantine_actors").inc()
        elif action["action"] == "byzantine_off":
            actor = self.actors.pop((node, action["behavior"]), None)
            if actor is not None:
                await actor.shutdown()

    # -- lazarus frontier probe ----------------------------------------------

    async def _probe_frontier_availability(self) -> dict:
        """Post-run audit for retention-armed runs: every committed
        block must still be servable (block bytes or subsuming snapshot)
        at f+1 honest live stores — truncation may bound disk, never
        availability."""
        from hotstuff_tpu.consensus.statesync import (
            SNAPSHOT_KEY,
            peek_frontier,
        )

        from .checker import check_frontier_availability

        committed: set = set()
        for recs in self.commits.values():
            for rec in recs:
                committed.add((rec.round, rec.digest))
        resolvers: dict = {}
        floors: dict[str, int] = {}
        for eng in self.engines:
            if eng.crashed:
                continue
            snap = await eng.store.read_meta(SNAPSHOT_KEY)
            if snap is not None:
                floors[eng.name] = peek_frontier(snap)[0]
            for _round, digest in committed:
                if await eng.store.read(digest) is not None:
                    resolvers.setdefault(digest, set()).add(eng.name)
        return check_frontier_availability(
            self.schedule, committed, resolvers, floors
        )

    # -- main drive ----------------------------------------------------------

    async def execute(self) -> dict:
        install(self.plane)
        try:
            return await self._execute_inner()
        finally:
            uninstall()
            for actor in self.actors.values():
                await actor.shutdown()
            for eng in self.engines:
                if eng.consensus is not None and not eng.crashed:
                    await eng.consensus.shutdown()
                for t in eng.tasks:
                    t.cancel()
            for t in self._aux:
                t.cancel()

    async def _execute_inner(self) -> dict:
        for eng in self.engines:
            await self._spawn_engine(eng)

        # Warm-up: anchor virtual time at the first full-committee
        # commit. The deadline scales with committee size: N engines in
        # one process dial N*(N-1) connections before the first proposal
        # can quorum (minutes at N=100 on one core).
        boot_deadline = self._clock() + max(120, 3 * self.n)
        while any(not self.commits[name] for name in self.names):
            if self._clock() > boot_deadline:
                raise RuntimeError("committee failed to reach first commit")
            await asyncio.sleep(0.1)
        self.plane.start()
        log.info(
            "faultline scenario %r (seed %d) armed on %d nodes",
            self.scenario.name, self.scenario.seed, self.n,
        )

        # Drive the schedule.
        while self.plane.vnow() < self.scenario.duration_s:
            for action in self.plane.poll_actions():
                await self._enact(action)
            await asyncio.sleep(_POLL_S)

        # Recovery tail: give the committee a bounded window to prove
        # post-heal commit growth before judging.
        heal_t = self.schedule.last_heal_time()
        expected = set(self.names) - self.schedule.crashed_forever() - {
            e.params["node"]
            for e in self.schedule.events
            if e.kind == "byzantine"
        }
        deadline = self._clock() + self.recovery_timeout_s
        while self._clock() < deadline:
            for action in self.plane.poll_actions():  # late heals
                await self._enact(action)
            if all(
                sum(1 for r in self.commits[n] if r.t > heal_t)
                >= self.min_recovery_commits
                for n in expected
            ):
                break
            await asyncio.sleep(_RECOVERY_POLL_S)

        verdict = check(
            self.schedule,
            self.commits,
            min_recovery_commits=self.min_recovery_commits,
            injections=self.plane.injection_summary(),
        )
        if self.params.retention_rounds > 0:
            verdict["frontier_availability"] = (
                await self._probe_frontier_availability()
            )
        flight_path = None
        if not (
            verdict["safety"]["ok"]
            and verdict["liveness"]["recovered"]
            and verdict.get("frontier_availability", {"ok": True})["ok"]
        ):
            # Checker failure => actionable postmortem, not just a
            # verdict: dump the flight recorder (the last ring of
            # protocol trace events across every in-process engine, plus
            # the registry state and the fault injection summary).
            flight_path = _dump_flight_for(self, verdict)
        return {
            "flight_record": flight_path,
            "verdict": verdict,
            "trace": self.schedule.trace(),
            "telemetry": telemetry.get_registry().snapshot(),
            # Raw per-node commit streams in virtual time — tests assert
            # window properties (e.g. silence while partitioned) the
            # aggregate verdict cannot express.
            "commit_streams": {
                name: [(rec.round, rec.t) for rec in recs]
                for name, recs in self.commits.items()
            },
        }


def _dump_flight_for(run: "ScenarioRun", verdict: dict) -> str | None:
    """Write the flight record for a failed run. Destination:
    ``HOTSTUFF_FLIGHT_DIR`` when set, else the system temp dir (a
    failing chaos TEST must not litter the working tree)."""
    if not telemetry.enabled():
        return None
    import os
    import tempfile

    directory = os.environ.get("HOTSTUFF_FLIGHT_DIR", tempfile.gettempdir())
    path = os.path.join(
        directory,
        f"flightrec-{run.scenario.name}-seed{run.scenario.seed}"
        f"-n{run.n}.json",
    )
    return telemetry.dump_flight_record(
        path,
        "checker_failure",
        telemetry.trace_buffer(),
        telemetry.get_registry(),
        extra={
            "verdict": verdict,
            "injections": run.plane.injection_summary(),
        },
    )


async def run_scenario(scenario: Scenario, n: int, **kwargs) -> dict:
    """Execute ``scenario`` on an ``n``-node in-process committee; returns
    ``{"verdict", "trace", "telemetry"}`` (see module docstring)."""
    return await ScenarioRun(scenario, n, **kwargs).execute()
