from .config import Committee, ConfigError, Parameters, Secret
from .node import Node

__all__ = ["Node", "Committee", "Parameters", "Secret", "ConfigError"]
