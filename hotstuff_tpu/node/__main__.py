"""Node CLI (reference ``node/src/main.rs:27-163``):

- ``keys --filename FILE``: generate a keypair file
- ``run --keys K --committee C --store DIR [--parameters P]``: run one node
- ``deploy --nodes N [--port P]``: in-process local testbed of N >= 4 nodes
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

from hotstuff_tpu.utils.logging import setup_logging

from .config import Committee, Parameters, Secret
from .node import Node

log = logging.getLogger("node")


def cmd_keys(args) -> None:
    Secret.new().write(args.filename)


async def _run_node(args) -> None:
    node = await Node.new(
        args.committee,
        args.keys,
        args.store,
        parameters_file=args.parameters,
        benchmark=True,
    )
    await node.analyze_block()


async def _deploy(nodes: int, base_port: int) -> None:
    """In-process local testbed (reference ``main.rs:103-163``): committee of
    N nodes on 127.0.0.1 with consensus/front/mempool port blocks."""
    import tempfile

    from hotstuff_tpu.consensus import Authority as CAuth
    from hotstuff_tpu.consensus import Committee as CCommittee
    from hotstuff_tpu.mempool import Authority as MAuth
    from hotstuff_tpu.mempool import Committee as MCommittee

    if nodes < 4:
        raise SystemExit("local testbeds require at least 4 nodes")
    secrets = [Secret.new() for _ in range(nodes)]
    consensus = CCommittee(
        authorities={
            s.name: CAuth(stake=1, address=("127.0.0.1", base_port + i))
            for i, s in enumerate(secrets)
        }
    )
    mempool = MCommittee(
        authorities={
            s.name: MAuth(
                stake=1,
                transactions_address=("127.0.0.1", base_port + 100 + i),
                mempool_address=("127.0.0.1", base_port + 200 + i),
            )
            for i, s in enumerate(secrets)
        }
    )
    tmp = tempfile.mkdtemp(prefix="hotstuff_deploy_")
    committee_file = f"{tmp}/committee.json"
    Committee(consensus, mempool).write(committee_file)
    started = []
    for i, s in enumerate(secrets):
        key_file = f"{tmp}/node_{i}.json"
        s.write(key_file)
        node = await Node.new(committee_file, key_file, f"{tmp}/db_{i}")
        started.append(node)
        print(f"Node {i} booted on 127.0.0.1:{base_port + 100 + i}")
    await asyncio.gather(*[n.analyze_block() for n in started])


def main() -> None:
    parser = argparse.ArgumentParser(
        prog="hotstuff_tpu.node",
        description="A TPU-accelerated implementation of 2-chain HotStuff.",
    )
    parser.add_argument("-v", action="count", default=2, dest="verbosity")
    sub = parser.add_subparsers(dest="command", required=True)

    p_keys = sub.add_parser("keys", help="generate a new keypair file")
    p_keys.add_argument("--filename", required=True)

    p_run = sub.add_parser("run", help="run a single node")
    p_run.add_argument("--keys", required=True)
    p_run.add_argument("--committee", required=True)
    p_run.add_argument("--store", required=True)
    p_run.add_argument("--parameters", default=None)

    p_deploy = sub.add_parser("deploy", help="in-process local testbed")
    p_deploy.add_argument("--nodes", type=int, required=True)
    p_deploy.add_argument("--port", type=int, default=25000)

    args = parser.parse_args()
    setup_logging(args.verbosity)

    try:
        if args.command == "keys":
            cmd_keys(args)
        elif args.command == "run":
            asyncio.run(_run_node(args))
        elif args.command == "deploy":
            asyncio.run(_deploy(args.nodes, args.port))
    except KeyboardInterrupt:
        sys.exit(0)


if __name__ == "__main__":
    main()
