"""Benchmark client: open-loop load generator (reference
``node/src/client.rs``).

Waits for all ``--nodes`` TCP ports then 2x timeout; sends ``rate`` tx/s in
50 ms bursts (PRECISION=20). Transactions are ``size`` bytes: sample txs
start with byte 0 + u64 BE counter (one per burst, used for e2e latency);
standard txs start with byte 1 + a random u64. Log lines are the
measurement interface (``client.rs:110,128-131``).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import random
import time

from hotstuff_tpu.network.receiver import write_frame
from hotstuff_tpu.utils.logging import setup_logging

log = logging.getLogger("client")

PRECISION = 20  # bursts per second
BURST_DURATION = 1.0 / PRECISION


async def wait_for_nodes(nodes: list[tuple[str, int]], timeout_ms: int) -> None:
    log.info("Waiting for all nodes to be online...")

    async def probe(addr):
        while True:
            try:
                _, w = await asyncio.open_connection(*addr)
                w.close()
                return
            except OSError:
                await asyncio.sleep(0.01)

    await asyncio.gather(*[probe(a) for a in nodes])
    log.info("Waiting for all nodes to be synchronized...")
    await asyncio.sleep(2 * timeout_ms / 1000)


async def run_client(
    target: tuple[str, int],
    size: int,
    rate: int,
    timeout_ms: int,
    nodes: list[tuple[str, int]],
    duration: float | None = None,
) -> None:
    log.info("Node address: %s:%d", *target)
    # NOTE: these exact log entries are parsed by the benchmark harness.
    log.info("Transactions size: %d B", size)
    log.info("Transactions rate: %d tx/s", rate)
    if size < 9:
        raise ValueError("transaction size must be at least 9 bytes")
    await wait_for_nodes(nodes, timeout_ms)

    _, writer = await asyncio.open_connection(*target)
    burst = max(rate // PRECISION, 1)
    counter = 0
    r = random.getrandbits(64)

    # NOTE: This log entry is used to compute performance.
    log.info("Start sending transactions")

    deadline = time.monotonic() + duration if duration else None
    next_burst = time.monotonic()
    filler = b"\x00" * (size - 9)
    try:
        while deadline is None or time.monotonic() < deadline:
            now = time.monotonic()
            if now < next_burst:
                await asyncio.sleep(next_burst - now)
            burst_start = time.monotonic()
            for x in range(burst):
                if x == counter % burst:
                    # NOTE: This log entry is used to compute performance.
                    log.info("Sending sample transaction %d", counter)
                    tx = b"\x00" + counter.to_bytes(8, "big") + filler
                else:
                    r = (r + 1) & 0xFFFFFFFFFFFFFFFF
                    tx = b"\x01" + r.to_bytes(8, "big") + filler
                write_frame(writer, tx)
            await writer.drain()
            if time.monotonic() - burst_start > BURST_DURATION:
                # NOTE: This log entry is used to compute performance.
                log.warning("Transaction rate too high for this client")
            counter += 1
            next_burst += BURST_DURATION
    except (ConnectionError, OSError) as e:
        log.warning("Failed to send transaction: %s", e)
    finally:
        writer.close()


def _parse_addr(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host, int(port))


def main() -> None:
    parser = argparse.ArgumentParser(description="Benchmark client for hotstuff_tpu nodes.")
    parser.add_argument("target", help="node transactions address ip:port")
    parser.add_argument("--size", type=int, required=True, help="tx size in bytes")
    parser.add_argument("--rate", type=int, required=True, help="tx/s to send")
    parser.add_argument("--timeout", type=int, required=True, help="node timeout (ms)")
    parser.add_argument("--nodes", nargs="*", default=[], help="addresses to await")
    parser.add_argument("--duration", type=float, default=None, help="stop after N s")
    args = parser.parse_args()
    setup_logging(2)
    asyncio.run(
        run_client(
            _parse_addr(args.target),
            args.size,
            args.rate,
            args.timeout,
            [_parse_addr(a) for a in args.nodes],
            duration=args.duration,
        )
    )


if __name__ == "__main__":
    main()
