"""Benchmark client: open-loop load generator (reference
``node/src/client.rs``).

Waits for all ``--nodes`` TCP ports then 2x timeout; sends ``rate`` tx/s in
50 ms bursts (PRECISION=20). Transactions are ``size`` bytes: sample txs
start with byte 0 + u64 BE counter (one per burst, used for e2e latency);
standard txs start with byte 1 + a random u64. Log lines are the
measurement interface (``client.rs:110,128-131``).

**Sharded mode** (``--shards a:p,b:p,...``): targets Conveyor worker
ingress ports instead of the legacy transactions port. Each burst is
pre-framed into one BUNDLE per shard (header: tx count + sample ids;
body: opaque length-prefixed tx blob), so the per-transaction Python
cost stays on this client and the node-side hot path handles whole
bundles. A reader task per shard counts the node's client-visible
``b"Shed"`` refusals (the back-pressure contract) and logs them —
the measurement interface gains ``Shed notifications: N``.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import random
import time

from hotstuff_tpu.network.receiver import write_frame
from hotstuff_tpu.utils.logging import setup_logging

log = logging.getLogger("client")

PRECISION = 20  # bursts per second
BURST_DURATION = 1.0 / PRECISION


async def wait_for_nodes(nodes: list[tuple[str, int]], timeout_ms: int) -> None:
    log.info("Waiting for all nodes to be online...")

    async def probe(addr):
        while True:
            try:
                _, w = await asyncio.open_connection(*addr)
                w.close()
                return
            except OSError:
                await asyncio.sleep(0.01)

    await asyncio.gather(*[probe(a) for a in nodes])
    log.info("Waiting for all nodes to be synchronized...")
    await asyncio.sleep(2 * timeout_ms / 1000)


async def run_client(
    target: tuple[str, int],
    size: int,
    rate: int,
    timeout_ms: int,
    nodes: list[tuple[str, int]],
    duration: float | None = None,
) -> None:
    log.info("Node address: %s:%d", *target)
    # NOTE: these exact log entries are parsed by the benchmark harness.
    log.info("Transactions size: %d B", size)
    log.info("Transactions rate: %d tx/s", rate)
    if size < 9:
        raise ValueError("transaction size must be at least 9 bytes")
    await wait_for_nodes(nodes, timeout_ms)

    _, writer = await asyncio.open_connection(*target)
    burst = max(rate // PRECISION, 1)
    counter = 0
    r = random.getrandbits(64)

    # NOTE: This log entry is used to compute performance.
    log.info("Start sending transactions")

    deadline = time.monotonic() + duration if duration else None
    next_burst = time.monotonic()
    filler = b"\x00" * (size - 9)
    try:
        while deadline is None or time.monotonic() < deadline:
            now = time.monotonic()
            if now < next_burst:
                await asyncio.sleep(next_burst - now)
            burst_start = time.monotonic()
            for x in range(burst):
                if x == counter % burst:
                    # NOTE: This log entry is used to compute performance.
                    log.info("Sending sample transaction %d", counter)
                    tx = b"\x00" + counter.to_bytes(8, "big") + filler
                else:
                    r = (r + 1) & 0xFFFFFFFFFFFFFFFF
                    tx = b"\x01" + r.to_bytes(8, "big") + filler
                write_frame(writer, tx)
            await writer.drain()
            if time.monotonic() - burst_start > BURST_DURATION:
                # NOTE: This log entry is used to compute performance.
                log.warning("Transaction rate too high for this client")
            counter += 1
            next_burst += BURST_DURATION
    except (ConnectionError, OSError) as e:
        log.warning("Failed to send transaction: %s", e)
    finally:
        writer.close()


def _make_bundler(size: int):
    """BUNDLE frame builder for worker ingress. Per-tx assembly fast
    path: only the 9 header bytes vary per transaction."""
    from hotstuff_tpu.mempool.dataplane.messages import TAG_TX_BUNDLE

    seq = random.getrandbits(63)
    filler = b"\x01" * (size - 9)
    prefix = size.to_bytes(4, "big") + b"\x01"
    sample_filler = b"\x00" * (size - 9)

    def bundle(n_txs: int, sample_id: int | None) -> bytes:
        nonlocal seq
        parts = []
        if sample_id is not None:
            parts.append(
                prefix[:4] + b"\x00" + sample_id.to_bytes(8, "big") + sample_filler
            )
            n_txs -= 1
        base = seq
        seq += n_txs
        parts.extend(
            prefix + (base + i).to_bytes(8, "big") + filler
            for i in range(n_txs)
        )
        blob = b"".join(parts)
        # Bundle header fields ride the serde codec = little-endian; the
        # per-tx length prefixes INSIDE the blob are big-endian (the
        # split_blob contract). The sample id is a raw u64 field (LE).
        head = (
            bytes([TAG_TX_BUNDLE])
            + (len(parts)).to_bytes(4, "little")
            + (1 if sample_id is not None else 0).to_bytes(4, "little")
            + (sample_id.to_bytes(8, "little") if sample_id is not None else b"")
        )
        return head + len(blob).to_bytes(4, "little") + blob

    return bundle


def _make_shed_counter(shed: list[int]):
    """Reader-task body counting the node's client-visible ``b"Shed"``
    refusals (the back-pressure contract) into the shared ``shed[0]``."""

    async def count_sheds(reader: asyncio.StreamReader) -> None:
        last_logged = 0.0
        try:
            while True:
                hdr = await reader.readexactly(4)
                frame = await reader.readexactly(int.from_bytes(hdr, "big"))
                if frame == b"Shed":
                    shed[0] += 1
                    now = time.monotonic()
                    if now - last_logged > 1.0:
                        last_logged = now
                        # NOTE: measurement interface (shed accounting).
                        log.warning("Shed notifications: %d", shed[0])
        except (asyncio.IncompleteReadError, ConnectionError):
            pass

    return count_sheds


async def run_sharded_client(
    shards: list[tuple[str, int]],
    size: int,
    rate: int,
    timeout_ms: int,
    nodes: list[tuple[str, int]],
    duration: float | None = None,
    coalesce_bytes: int = 0,
    coalesce_ms: float = 5.0,
) -> None:
    log.info("Worker shards: %s", ", ".join(f"{h}:{p}" for h, p in shards))
    # NOTE: these exact log entries are parsed by the benchmark harness.
    log.info("Transactions size: %d B", size)
    log.info("Transactions rate: %d tx/s", rate)
    if size < 9:
        raise ValueError("transaction size must be at least 9 bytes")
    if coalesce_bytes:
        log.info("Coalescing: %d B / %.1f ms", coalesce_bytes, coalesce_ms)
    await wait_for_nodes(nodes, timeout_ms)

    conns = [await asyncio.open_connection(*addr) for addr in shards]
    shed = [0]
    count_sheds = _make_shed_counter(shed)
    readers = [asyncio.create_task(count_sheds(r)) for r, _w in conns]

    burst = max(rate // PRECISION, 1)
    per_shard = max(burst // len(conns), 1)
    counter = 0
    bundle = _make_bundler(size)

    # Bundle coalescing: small bundles are staged per shard and packed
    # into one write, flushed when the staging buffer reaches
    # ``coalesce_bytes`` or its oldest bundle has waited ``coalesce_ms``
    # — the 512 B–1 KB regime stops paying a write (and a node-side
    # wakeup) per bundle. A bundle already at/over the byte bound is
    # written immediately. Off (the historic behavior) at bytes=0.
    coalesce = coalesce_bytes > 0
    coalesce_s = coalesce_ms / 1000.0
    pend: list[bytearray] = [bytearray() for _ in conns]
    pend_ts = [0.0] * len(conns)

    def stage(i: int, frame: bytes) -> None:
        framed = len(frame).to_bytes(4, "big") + frame
        if not coalesce:
            conns[i][1].write(framed)
            return
        if not pend[i]:
            pend_ts[i] = time.monotonic()
        pend[i] += framed
        if len(pend[i]) >= coalesce_bytes:
            conns[i][1].write(bytes(pend[i]))
            pend[i].clear()

    def flush_due(now: float) -> float | None:
        """Flush shards whose oldest staged bundle hit the latency bound;
        return the earliest outstanding deadline (None if none staged)."""
        earliest = None
        for i, p in enumerate(pend):
            if not p:
                continue
            dl = pend_ts[i] + coalesce_s
            if dl <= now:
                conns[i][1].write(bytes(p))
                p.clear()
            elif earliest is None or dl < earliest:
                earliest = dl
        return earliest

    # NOTE: This log entry is used to compute performance.
    log.info("Start sending transactions")
    deadline = time.monotonic() + duration if duration else None
    next_burst = time.monotonic()
    try:
        while deadline is None or time.monotonic() < deadline:
            now = time.monotonic()
            while now < next_burst:
                # Sleep to whichever comes first: the next burst or the
                # earliest coalescing deadline — the latency bound holds
                # even across the inter-burst gap.
                dl = flush_due(now) if coalesce else None
                target = next_burst if dl is None or dl >= next_burst else dl
                await asyncio.sleep(target - now)
                now = time.monotonic()
            burst_start = time.monotonic()
            sample_shard = counter % len(conns)
            for i in range(len(conns)):
                sample_id = counter if i == sample_shard else None
                if sample_id is not None:
                    # NOTE: This log entry is used to compute performance.
                    log.info("Sending sample transaction %d", counter)
                stage(i, bundle(per_shard, sample_id))
            if coalesce:
                flush_due(time.monotonic())
            for _r, writer in conns:
                await writer.drain()
            if time.monotonic() - burst_start > BURST_DURATION:
                # NOTE: This log entry is used to compute performance.
                log.warning("Transaction rate too high for this client")
            counter += 1
            next_burst += BURST_DURATION
    except (ConnectionError, OSError) as e:
        log.warning("Failed to send transaction: %s", e)
    finally:
        for i, p in enumerate(pend):
            if p:
                try:
                    conns[i][1].write(bytes(p))
                except (ConnectionError, OSError):
                    pass
        for t in readers:
            t.cancel()
        for _r, writer in conns:
            writer.close()
        if shed[0]:
            log.warning("Shed notifications: %d", shed[0])


async def run_fleet_client(
    shards: list[tuple[str, int]],
    size: int,
    rate: int,
    timeout_ms: int,
    nodes: list[tuple[str, int]],
    duration: float | None = None,
    fleet: int = 64,
    bundle_txs: int = 8,
    burst_every: float = 0.0,
    burst_len: float = 0.0,
    burst_x: float = 1.0,
    churn_s: float = 0.0,
) -> None:
    """Open-loop fleet: ``fleet`` concurrent connections round-robin over
    the worker shards, each arrival one small bundle of ``bundle_txs``
    transactions, with Poisson (exponential-gap) arrivals at the
    aggregate ``rate``. Unlike the closed-ish burst loop of
    ``run_sharded_client``, arrivals do NOT wait for back-pressure: a
    saturated front door shows up as shedding and tail latency, which is
    the point. Optional square-wave bursts (``burst_every``/``burst_len``
    windows at ``burst_x`` times the base rate) and connection churn
    (every ``churn_s`` seconds one connection is torn down and redialed)
    exercise watermarks under connection-scale dynamics."""
    log.info("Worker shards: %s", ", ".join(f"{h}:{p}" for h, p in shards))
    # NOTE: these exact log entries are parsed by the benchmark harness.
    log.info("Transactions size: %d B", size)
    log.info("Transactions rate: %d tx/s", rate)
    log.info("Fleet connections: %d", fleet)
    log.info("Fleet bundle: %d txs", bundle_txs)
    if size < 9:
        raise ValueError("transaction size must be at least 9 bytes")
    if fleet < 1:
        raise ValueError("fleet size must be at least 1")
    await wait_for_nodes(nodes, timeout_ms)

    shed = [0]
    count_sheds = _make_shed_counter(shed)
    conns: list[tuple[asyncio.StreamReader, asyncio.StreamWriter]] = []
    readers: list[asyncio.Task] = []
    for k in range(fleet):
        r, w = await asyncio.open_connection(*shards[k % len(shards)])
        conns.append((r, w))
        readers.append(asyncio.create_task(count_sheds(r)))

    churns = [0]
    churn_task = None
    if churn_s > 0:

        async def churn_loop() -> None:
            k = 0
            while True:
                await asyncio.sleep(churn_s)
                idx = k % fleet
                k += 1
                readers[idx].cancel()
                conns[idx][1].close()
                try:
                    nr, nw = await asyncio.open_connection(
                        *shards[idx % len(shards)]
                    )
                except OSError:
                    continue  # redial next cycle; sends skip dead conns
                conns[idx] = (nr, nw)
                readers[idx] = asyncio.create_task(count_sheds(nr))
                churns[0] += 1
                # NOTE: measurement interface (cumulative, logged per
                # event — the harness SIGTERMs clients, so an end-of-run
                # summary line would never be written).
                log.info("Connection churns: %d", churns[0])

        churn_task = asyncio.create_task(churn_loop())

    bundle = _make_bundler(size)
    arrival_rate = max(rate / max(bundle_txs, 1), 1e-9)  # bundles/s, fleet-wide
    counter = 0
    k = 0
    late_warned = 0.0
    # NOTE: This log entry is used to compute performance.
    log.info("Start sending transactions")
    start = time.monotonic()
    deadline = start + duration if duration else None
    next_arrival = start
    next_sample = start
    try:
        while deadline is None or time.monotonic() < deadline:
            now = time.monotonic()
            if now < next_arrival:
                await asyncio.sleep(next_arrival - now)
                now = time.monotonic()
            mult = 1.0
            if burst_every > 0 and (now - start) % burst_every < burst_len:
                mult = burst_x
            sample_id = None
            if now >= next_sample:
                sample_id = counter
                # NOTE: This log entry is used to compute performance.
                log.info("Sending sample transaction %d", counter)
                counter += 1
                next_sample += BURST_DURATION
            frame = bundle(bundle_txs, sample_id)
            _r, writer = conns[k % fleet]
            k += 1
            try:
                writer.write(len(frame).to_bytes(4, "big") + frame)
                await writer.drain()
            except (ConnectionError, OSError):
                pass  # mid-churn/broken conn: open-loop drops, never blocks
            if now - next_arrival > BURST_DURATION and now - late_warned > 1.0:
                late_warned = now
                # NOTE: This log entry is used to compute performance.
                log.warning("Transaction rate too high for this client")
            next_arrival += random.expovariate(arrival_rate * mult)
    finally:
        if churn_task is not None:
            churn_task.cancel()
        for t in readers:
            t.cancel()
        for _r, w in conns:
            w.close()
        if shed[0]:
            log.warning("Shed notifications: %d", shed[0])
        if churns[0]:
            log.info("Connection churns: %d", churns[0])


def _parse_addr(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host, int(port))


def main() -> None:
    parser = argparse.ArgumentParser(description="Benchmark client for hotstuff_tpu nodes.")
    parser.add_argument("target", help="node transactions address ip:port")
    parser.add_argument("--size", type=int, required=True, help="tx size in bytes")
    parser.add_argument("--rate", type=int, required=True, help="tx/s to send")
    parser.add_argument("--timeout", type=int, required=True, help="node timeout (ms)")
    parser.add_argument("--nodes", nargs="*", default=[], help="addresses to await")
    parser.add_argument("--duration", type=float, default=None, help="stop after N s")
    parser.add_argument(
        "--shards",
        default=None,
        help="comma-separated Conveyor worker ingress addresses; switches "
        "to sharded bundle mode (the positional target is ignored)",
    )
    parser.add_argument(
        "--coalesce-bytes",
        type=int,
        default=0,
        help="sharded mode: pack small bundles per shard into one write "
        "up to this many bytes (0 = off)",
    )
    parser.add_argument(
        "--coalesce-ms",
        type=float,
        default=5.0,
        help="sharded mode: max ms a staged bundle may wait before its "
        "coalesced write is flushed",
    )
    parser.add_argument(
        "--fleet",
        type=int,
        default=0,
        help="open-loop fleet mode: number of concurrent connections "
        "round-robin over --shards (0 = off)",
    )
    parser.add_argument(
        "--bundle-txs",
        type=int,
        default=8,
        help="fleet mode: transactions per bundle (arrival granularity)",
    )
    parser.add_argument(
        "--burst-every",
        type=float,
        default=0.0,
        help="fleet mode: burst window period in seconds (0 = steady)",
    )
    parser.add_argument(
        "--burst-len",
        type=float,
        default=0.0,
        help="fleet mode: burst window length in seconds",
    )
    parser.add_argument(
        "--burst-x",
        type=float,
        default=1.0,
        help="fleet mode: rate multiplier inside burst windows",
    )
    parser.add_argument(
        "--churn",
        type=float,
        default=0.0,
        help="fleet mode: redial one connection every N seconds (0 = off)",
    )
    args = parser.parse_args()
    setup_logging(2)
    if args.fleet:
        if not args.shards:
            parser.error("--fleet requires --shards")
        asyncio.run(
            run_fleet_client(
                [_parse_addr(a) for a in args.shards.split(",")],
                args.size,
                args.rate,
                args.timeout,
                [_parse_addr(a) for a in args.nodes],
                duration=args.duration,
                fleet=args.fleet,
                bundle_txs=args.bundle_txs,
                burst_every=args.burst_every,
                burst_len=args.burst_len,
                burst_x=args.burst_x,
                churn_s=args.churn,
            )
        )
        return
    if args.shards:
        asyncio.run(
            run_sharded_client(
                [_parse_addr(a) for a in args.shards.split(",")],
                args.size,
                args.rate,
                args.timeout,
                [_parse_addr(a) for a in args.nodes],
                duration=args.duration,
                coalesce_bytes=args.coalesce_bytes,
                coalesce_ms=args.coalesce_ms,
            )
        )
        return
    asyncio.run(
        run_client(
            _parse_addr(args.target),
            args.size,
            args.rate,
            args.timeout,
            [_parse_addr(a) for a in args.nodes],
            duration=args.duration,
        )
    )


if __name__ == "__main__":
    main()
