"""Benchmark client: open-loop load generator (reference
``node/src/client.rs``).

Waits for all ``--nodes`` TCP ports then 2x timeout; sends ``rate`` tx/s in
50 ms bursts (PRECISION=20). Transactions are ``size`` bytes: sample txs
start with byte 0 + u64 BE counter (one per burst, used for e2e latency);
standard txs start with byte 1 + a random u64. Log lines are the
measurement interface (``client.rs:110,128-131``).

**Sharded mode** (``--shards a:p,b:p,...``): targets Conveyor worker
ingress ports instead of the legacy transactions port. Each burst is
pre-framed into one BUNDLE per shard (header: tx count + sample ids;
body: opaque length-prefixed tx blob), so the per-transaction Python
cost stays on this client and the node-side hot path handles whole
bundles. A reader task per shard counts the node's client-visible
``b"Shed"`` refusals (the back-pressure contract) and logs them —
the measurement interface gains ``Shed notifications: N``.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import random
import time

from hotstuff_tpu.network.receiver import write_frame
from hotstuff_tpu.utils.logging import setup_logging

log = logging.getLogger("client")

PRECISION = 20  # bursts per second
BURST_DURATION = 1.0 / PRECISION


async def wait_for_nodes(nodes: list[tuple[str, int]], timeout_ms: int) -> None:
    log.info("Waiting for all nodes to be online...")

    async def probe(addr):
        while True:
            try:
                _, w = await asyncio.open_connection(*addr)
                w.close()
                return
            except OSError:
                await asyncio.sleep(0.01)

    await asyncio.gather(*[probe(a) for a in nodes])
    log.info("Waiting for all nodes to be synchronized...")
    await asyncio.sleep(2 * timeout_ms / 1000)


async def run_client(
    target: tuple[str, int],
    size: int,
    rate: int,
    timeout_ms: int,
    nodes: list[tuple[str, int]],
    duration: float | None = None,
) -> None:
    log.info("Node address: %s:%d", *target)
    # NOTE: these exact log entries are parsed by the benchmark harness.
    log.info("Transactions size: %d B", size)
    log.info("Transactions rate: %d tx/s", rate)
    if size < 9:
        raise ValueError("transaction size must be at least 9 bytes")
    await wait_for_nodes(nodes, timeout_ms)

    _, writer = await asyncio.open_connection(*target)
    burst = max(rate // PRECISION, 1)
    counter = 0
    r = random.getrandbits(64)

    # NOTE: This log entry is used to compute performance.
    log.info("Start sending transactions")

    deadline = time.monotonic() + duration if duration else None
    next_burst = time.monotonic()
    filler = b"\x00" * (size - 9)
    try:
        while deadline is None or time.monotonic() < deadline:
            now = time.monotonic()
            if now < next_burst:
                await asyncio.sleep(next_burst - now)
            burst_start = time.monotonic()
            for x in range(burst):
                if x == counter % burst:
                    # NOTE: This log entry is used to compute performance.
                    log.info("Sending sample transaction %d", counter)
                    tx = b"\x00" + counter.to_bytes(8, "big") + filler
                else:
                    r = (r + 1) & 0xFFFFFFFFFFFFFFFF
                    tx = b"\x01" + r.to_bytes(8, "big") + filler
                write_frame(writer, tx)
            await writer.drain()
            if time.monotonic() - burst_start > BURST_DURATION:
                # NOTE: This log entry is used to compute performance.
                log.warning("Transaction rate too high for this client")
            counter += 1
            next_burst += BURST_DURATION
    except (ConnectionError, OSError) as e:
        log.warning("Failed to send transaction: %s", e)
    finally:
        writer.close()


async def run_sharded_client(
    shards: list[tuple[str, int]],
    size: int,
    rate: int,
    timeout_ms: int,
    nodes: list[tuple[str, int]],
    duration: float | None = None,
) -> None:
    from hotstuff_tpu.mempool.dataplane.messages import TAG_TX_BUNDLE

    log.info("Worker shards: %s", ", ".join(f"{h}:{p}" for h, p in shards))
    # NOTE: these exact log entries are parsed by the benchmark harness.
    log.info("Transactions size: %d B", size)
    log.info("Transactions rate: %d tx/s", rate)
    if size < 9:
        raise ValueError("transaction size must be at least 9 bytes")
    await wait_for_nodes(nodes, timeout_ms)

    conns = [await asyncio.open_connection(*addr) for addr in shards]
    shed = [0]

    async def count_sheds(reader: asyncio.StreamReader) -> None:
        last_logged = 0.0
        try:
            while True:
                hdr = await reader.readexactly(4)
                frame = await reader.readexactly(int.from_bytes(hdr, "big"))
                if frame == b"Shed":
                    shed[0] += 1
                    now = time.monotonic()
                    if now - last_logged > 1.0:
                        last_logged = now
                        # NOTE: measurement interface (shed accounting).
                        log.warning("Shed notifications: %d", shed[0])
        except (asyncio.IncompleteReadError, ConnectionError):
            pass

    readers = [asyncio.create_task(count_sheds(r)) for r, _w in conns]

    burst = max(rate // PRECISION, 1)
    per_shard = max(burst // len(conns), 1)
    counter = 0
    seq = random.getrandbits(63)
    # Per-tx assembly fast path: only the 9 header bytes vary.
    filler = b"\x01" * (size - 9)
    prefix = size.to_bytes(4, "big") + b"\x01"
    sample_filler = b"\x00" * (size - 9)

    def bundle(n_txs: int, sample_id: int | None) -> bytes:
        nonlocal seq
        parts = []
        if sample_id is not None:
            parts.append(
                prefix[:4] + b"\x00" + sample_id.to_bytes(8, "big") + sample_filler
            )
            n_txs -= 1
        base = seq
        seq += n_txs
        parts.extend(
            prefix + (base + i).to_bytes(8, "big") + filler
            for i in range(n_txs)
        )
        blob = b"".join(parts)
        # Bundle header fields ride the serde codec = little-endian; the
        # per-tx length prefixes INSIDE the blob are big-endian (the
        # split_blob contract). The sample id is a raw u64 field (LE).
        head = (
            bytes([TAG_TX_BUNDLE])
            + (len(parts)).to_bytes(4, "little")
            + (1 if sample_id is not None else 0).to_bytes(4, "little")
            + (sample_id.to_bytes(8, "little") if sample_id is not None else b"")
        )
        return head + len(blob).to_bytes(4, "little") + blob

    # NOTE: This log entry is used to compute performance.
    log.info("Start sending transactions")
    deadline = time.monotonic() + duration if duration else None
    next_burst = time.monotonic()
    try:
        while deadline is None or time.monotonic() < deadline:
            now = time.monotonic()
            if now < next_burst:
                await asyncio.sleep(next_burst - now)
            burst_start = time.monotonic()
            sample_shard = counter % len(conns)
            for i, (_r, writer) in enumerate(conns):
                sample_id = counter if i == sample_shard else None
                if sample_id is not None:
                    # NOTE: This log entry is used to compute performance.
                    log.info("Sending sample transaction %d", counter)
                frame = bundle(per_shard, sample_id)
                writer.write(len(frame).to_bytes(4, "big") + frame)
            for _r, writer in conns:
                await writer.drain()
            if time.monotonic() - burst_start > BURST_DURATION:
                # NOTE: This log entry is used to compute performance.
                log.warning("Transaction rate too high for this client")
            counter += 1
            next_burst += BURST_DURATION
    except (ConnectionError, OSError) as e:
        log.warning("Failed to send transaction: %s", e)
    finally:
        for t in readers:
            t.cancel()
        for _r, writer in conns:
            writer.close()
        if shed[0]:
            log.warning("Shed notifications: %d", shed[0])


def _parse_addr(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host, int(port))


def main() -> None:
    parser = argparse.ArgumentParser(description="Benchmark client for hotstuff_tpu nodes.")
    parser.add_argument("target", help="node transactions address ip:port")
    parser.add_argument("--size", type=int, required=True, help="tx size in bytes")
    parser.add_argument("--rate", type=int, required=True, help="tx/s to send")
    parser.add_argument("--timeout", type=int, required=True, help="node timeout (ms)")
    parser.add_argument("--nodes", nargs="*", default=[], help="addresses to await")
    parser.add_argument("--duration", type=float, default=None, help="stop after N s")
    parser.add_argument(
        "--shards",
        default=None,
        help="comma-separated Conveyor worker ingress addresses; switches "
        "to sharded bundle mode (the positional target is ignored)",
    )
    args = parser.parse_args()
    setup_logging(2)
    if args.shards:
        asyncio.run(
            run_sharded_client(
                [_parse_addr(a) for a in args.shards.split(",")],
                args.size,
                args.rate,
                args.timeout,
                [_parse_addr(a) for a in args.nodes],
                duration=args.duration,
            )
        )
        return
    asyncio.run(
        run_client(
            _parse_addr(args.target),
            args.size,
            args.rate,
            args.timeout,
            [_parse_addr(a) for a in args.nodes],
            duration=args.duration,
        )
    )


if __name__ == "__main__":
    main()
