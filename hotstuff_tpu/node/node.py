"""Node composition root (reference ``node/src/node.rs:18-81``): read
committee + secret, open the store, start the signature service, spawn
Mempool and Consensus wired by three channel pairs, and consume the commit
stream (``analyze_block`` is the application/execution attach point)."""

from __future__ import annotations

import asyncio
import logging
import os

from hotstuff_tpu import telemetry
from hotstuff_tpu.consensus import Consensus
from hotstuff_tpu.crypto import SignatureService
from hotstuff_tpu.mempool import Mempool
from hotstuff_tpu.store import Store

from .config import Committee, Parameters, Secret

log = logging.getLogger("node")

CHANNEL_CAPACITY = 1_000


def _committee_node_names(committee: Committee) -> dict:
    """Deterministic scenario names for a committee's members: sort by
    consensus address and name positionally (``n000``...). Every process
    reading the same committee file derives the same mapping, so one
    scenario file coordinates a whole LocalBench/netns deployment."""
    ordered = sorted(
        committee.consensus.authorities.items(),
        key=lambda kv: kv[1].address,
    )
    return {pk: f"n{i:03d}" for i, (pk, _) in enumerate(ordered)}


def _install_faultline_from_env(committee: Committee, name) -> None:
    """``HOTSTUFF_FAULTLINE=<scenario.json>`` arms this process's fault
    plane: the scenario compiles against the committee-derived node names
    and the plane starts at process boot (virtual t=0 ≈ node boot; the
    few hundred ms of boot skew between processes is noise at scenario
    timescales). The node's own identity comes from its key."""
    scenario_path = os.environ.get("HOTSTUFF_FAULTLINE")
    if not scenario_path:
        return
    from hotstuff_tpu.faultline import FaultPlane, Scenario, hooks, install

    names = _committee_node_names(committee)
    addr_to_node: dict = {}
    consensus_addrs = set()
    for pk, auth in committee.consensus.authorities.items():
        addr_to_node[tuple(auth.address)] = names[pk]
        consensus_addrs.add(tuple(auth.address))
    for pk, auth in committee.mempool.authorities.items():
        addr_to_node[tuple(auth.mempool_address)] = names[pk]
        for w in auth.workers:
            # Conveyor worker ports: partitions/link faults apply to the
            # data plane's dissemination traffic too.
            addr_to_node[tuple(w.worker_address)] = names[pk]
    scenario = Scenario.load(scenario_path)
    schedule = scenario.compile(sorted(names.values()))
    plane = FaultPlane(schedule, addr_to_node, consensus_addrs)
    install(plane).start()
    hooks.NODE.set(names[name])
    log.info(
        "faultline armed from %s as %s (seed %d)",
        scenario_path, names[name], scenario.seed,
    )


class Node:
    def __init__(self) -> None:
        self.commit: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        self.mempool: Mempool | None = None
        self.consensus: Consensus | None = None
        self.store: Store | None = None
        self.telemetry_emitter: telemetry.TelemetryEmitter | None = None
        self.resolver_task: asyncio.Task | None = None  # Conveyor commit path
        self.crashed = False
        self._boot: tuple | None = None  # (secret, committee, parameters, benchmark)

    @classmethod
    async def new(
        cls,
        committee_file: str,
        key_file: str,
        store_path: str,
        parameters_file: str | None = None,
        benchmark: bool = False,
    ) -> "Node":
        self = cls()
        secret = Secret.read(key_file)
        committee = Committee.read(committee_file)
        parameters = (
            Parameters.read(parameters_file) if parameters_file else Parameters.default()
        )
        self.store = Store(store_path)
        self._boot = (secret, committee, parameters, benchmark)
        # Arm fault injection BEFORE any actor spawns: the faultline node
        # identity is a contextvar, and tasks inherit the context they
        # were created in.
        _install_faultline_from_env(committee, secret.name)

        signature_service = SignatureService(secret.secret)

        tx_consensus_to_mempool: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        tx_mempool_to_consensus: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)

        self.mempool = Mempool(
            secret.name,
            committee.mempool,
            parameters.mempool,
            self.store,
            tx_consensus_to_mempool,
            tx_mempool_to_consensus,
            benchmark=benchmark,
            signature_service=signature_service,
        )
        await self.mempool.spawn()

        # Conveyor commit path: consensus ordered digests it could prove
        # available, so committed blocks pass through the resolver (which
        # materializes any batch this node never received) before the
        # application sees them.
        commit_sink = self.commit
        if self.mempool.dataplane is not None:
            from hotstuff_tpu.mempool.dataplane import CommitResolver

            inner: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
            self.resolver_task = CommitResolver.spawn(
                self.store,
                inner,
                self.commit,
                tx_consensus_to_mempool,
                self.mempool.dataplane,
            )
            commit_sink = inner

        self.consensus = await Consensus.spawn(
            secret.name,
            committee.consensus,
            parameters.consensus,
            signature_service,
            self.store,
            tx_mempool_to_consensus,
            tx_consensus_to_mempool,
            commit_sink,
            benchmark=benchmark,
        )

        # Resource observability (RSS, on-disk store size, optional
        # tracemalloc) + the continuous sampling profiler. The resource
        # collector is registered whenever telemetry is on — it costs
        # nothing until a snapshot polls it; the profiler is opt-in via
        # HOTSTUFF_PYPROF=1 (HOTSTUFF_PYPROF_INTERVAL_MS tunes the
        # cadence) and its hotstuff-profile-v1 records ride the node's
        # snapshot stream via the emitter below.
        if telemetry.enabled():
            from hotstuff_tpu.telemetry import profiler as pyprof, resources

            resources.install(store_path=store_path)
            if os.environ.get("HOTSTUFF_PYPROF") and pyprof.active() is None:
                prof = pyprof.SamplingProfiler(
                    interval_ms=pyprof.env_interval_ms()
                )
                prof.start(mode="auto")
                telemetry.register_collector("profile", prof.collector)
                log.info(
                    "sampling profiler armed (%s mode, %.1f ms)",
                    prof.mode, prof.interval_ms,
                )

        # Telemetry snapshot stream (HOTSTUFF_TELEMETRY[_DIR]): periodic
        # JSON-lines snapshots plus a final one at shutdown —
        # benchmark/logs.py reads these alongside the regex log scrape.
        stream_path = telemetry.env_stream_path(str(secret.name))
        if telemetry.enabled() and stream_path is not None:
            self.telemetry_emitter = telemetry.TelemetryEmitter(
                telemetry.get_registry(),
                stream_path,
                node=str(secret.name),
                interval_s=telemetry.env_interval_s(),
                trace=telemetry.trace_buffer(),
                dtrace=telemetry.dtrace_buffer(),
            ).spawn()
            # Unclean teardown (SIGTERM from the local bench, atexit)
            # still flushes the final snapshot + trace tail and dumps the
            # flight record — without this the last interval of every
            # killed node's stream was lost.
            telemetry.arm_shutdown_flush(
                self.telemetry_emitter,
                flight_path=telemetry.env_flight_path(str(secret.name)),
            )

        log.info("Node %s successfully booted", secret.name)
        return self

    async def analyze_block(self) -> None:
        """Sink committed blocks — the execution-engine attach point
        (reference ``node/src/node.rs:76-80``)."""
        while True:
            await self.commit.get()

    # -- supervised crash/restart (the faultline contract) -------------------

    async def crash(self) -> None:
        """Kill the node the UNCLEAN way — cancel every actor task and
        yank the listeners, no graceful drains — modeling a process
        crash while keeping the store open (it is the node's disk, and
        the restart must exercise real recovery from persisted state:
        ``Core._restore_state`` round/vote/high_qc replay)."""
        if self.crashed:
            return
        if self.consensus is not None:
            for t in self.consensus.tasks:
                t.cancel()
            if self.consensus.synchronizer is not None:
                self.consensus.synchronizer.shutdown()
            if self.consensus.mempool_driver is not None:
                self.consensus.mempool_driver.shutdown()
            for r in self.consensus.receivers:
                server = getattr(r, "_server", None)
                if server is not None:  # asyncio transport: unclean
                    r._closing = True
                    server.close()
                    for task in list(r._conn_tasks):
                        task.cancel()
                    for w in list(r._writers):
                        w.transport.abort()
                else:  # native transport: release the listener id
                    await r.shutdown()
            self.consensus = None
        if self.mempool is not None:
            for t in self.mempool.tasks:
                t.cancel()
            if self.mempool.dataplane is not None:
                await self.mempool.dataplane.shutdown()
            for r in self.mempool.receivers:
                await r.shutdown()
            self.mempool = None
        if self.resolver_task is not None:
            self.resolver_task.cancel()
            self.resolver_task = None
        self.crashed = True
        telemetry.counter("faultline.injected.crashes").inc()
        if telemetry.enabled() and self._boot is not None:
            # Postmortem: the last ring of protocol events at the moment
            # of the (injected) crash, plus the registry state.
            flight_path = telemetry.env_flight_path(str(self._boot[0].name))
            if flight_path is not None:
                telemetry.dump_flight_record(
                    flight_path,
                    "node_crash",
                    telemetry.trace_buffer(),
                    telemetry.get_registry(),
                )
        log.warning("Node crashed (supervised)")

    async def restart(self) -> "Node":
        """Bring a crashed node back on the SAME store: consensus state
        (round, last vote, high QC) restores from the persisted record,
        exactly like a process restarting on its disk."""
        if not self.crashed:
            return self
        assert self._boot is not None, "restart() before new()"
        secret, committee, parameters, benchmark = self._boot
        signature_service = SignatureService(secret.secret)
        tx_consensus_to_mempool: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        tx_mempool_to_consensus: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        self.mempool = Mempool(
            secret.name,
            committee.mempool,
            parameters.mempool,
            self.store,
            tx_consensus_to_mempool,
            tx_mempool_to_consensus,
            benchmark=benchmark,
            signature_service=signature_service,
        )
        await self.mempool.spawn()
        commit_sink = self.commit
        if self.mempool.dataplane is not None:
            from hotstuff_tpu.mempool.dataplane import CommitResolver

            inner: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
            self.resolver_task = CommitResolver.spawn(
                self.store,
                inner,
                self.commit,
                tx_consensus_to_mempool,
                self.mempool.dataplane,
            )
            commit_sink = inner
        self.consensus = await Consensus.spawn(
            secret.name,
            committee.consensus,
            parameters.consensus,
            signature_service,
            self.store,
            tx_mempool_to_consensus,
            tx_consensus_to_mempool,
            commit_sink,
            benchmark=benchmark,
        )
        self.crashed = False
        telemetry.counter("faultline.injected.restarts").inc()
        log.info("Node restarted (supervised)")
        return self

    async def shutdown(self) -> None:
        if self.consensus is not None:
            await self.consensus.shutdown()
        if self.mempool is not None:
            await self.mempool.shutdown()
        if self.resolver_task is not None:
            self.resolver_task.cancel()
        if self.telemetry_emitter is not None:
            await self.telemetry_emitter.shutdown()
        if self.store is not None:
            self.store.close()
