"""Node composition root (reference ``node/src/node.rs:18-81``): read
committee + secret, open the store, start the signature service, spawn
Mempool and Consensus wired by three channel pairs, and consume the commit
stream (``analyze_block`` is the application/execution attach point)."""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu import telemetry
from hotstuff_tpu.consensus import Consensus
from hotstuff_tpu.crypto import SignatureService
from hotstuff_tpu.mempool import Mempool
from hotstuff_tpu.store import Store

from .config import Committee, Parameters, Secret

log = logging.getLogger("node")

CHANNEL_CAPACITY = 1_000


class Node:
    def __init__(self) -> None:
        self.commit: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        self.mempool: Mempool | None = None
        self.consensus: Consensus | None = None
        self.store: Store | None = None
        self.telemetry_emitter: telemetry.TelemetryEmitter | None = None

    @classmethod
    async def new(
        cls,
        committee_file: str,
        key_file: str,
        store_path: str,
        parameters_file: str | None = None,
        benchmark: bool = False,
    ) -> "Node":
        self = cls()
        secret = Secret.read(key_file)
        committee = Committee.read(committee_file)
        parameters = (
            Parameters.read(parameters_file) if parameters_file else Parameters.default()
        )
        self.store = Store(store_path)

        signature_service = SignatureService(secret.secret)

        tx_consensus_to_mempool: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        tx_mempool_to_consensus: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)

        self.mempool = Mempool(
            secret.name,
            committee.mempool,
            parameters.mempool,
            self.store,
            tx_consensus_to_mempool,
            tx_mempool_to_consensus,
            benchmark=benchmark,
        )
        await self.mempool.spawn()

        self.consensus = await Consensus.spawn(
            secret.name,
            committee.consensus,
            parameters.consensus,
            signature_service,
            self.store,
            tx_mempool_to_consensus,
            tx_consensus_to_mempool,
            self.commit,
            benchmark=benchmark,
        )

        # Telemetry snapshot stream (HOTSTUFF_TELEMETRY[_DIR]): periodic
        # JSON-lines snapshots plus a final one at shutdown —
        # benchmark/logs.py reads these alongside the regex log scrape.
        stream_path = telemetry.env_stream_path(str(secret.name))
        if telemetry.enabled() and stream_path is not None:
            self.telemetry_emitter = telemetry.TelemetryEmitter(
                telemetry.get_registry(),
                stream_path,
                node=str(secret.name),
                interval_s=telemetry.env_interval_s(),
            ).spawn()

        log.info("Node %s successfully booted", secret.name)
        return self

    async def analyze_block(self) -> None:
        """Sink committed blocks — the execution-engine attach point
        (reference ``node/src/node.rs:76-80``)."""
        while True:
            await self.commit.get()

    async def shutdown(self) -> None:
        if self.consensus is not None:
            await self.consensus.shutdown()
        if self.mempool is not None:
            await self.mempool.shutdown()
        if self.telemetry_emitter is not None:
            await self.telemetry_emitter.shutdown()
        if self.store is not None:
            self.store.close()
