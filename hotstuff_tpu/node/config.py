"""Node-level config files (reference ``node/src/config.rs``).

JSON schemas are byte-compatible with the reference benchmark harness's
committee/parameters/key builders (reference
``benchmark/benchmark/config.py:33-53``), so either harness can drive either
implementation:

- committee: ``{"consensus": {"authorities": {name: {name, stake, address}},
  "epoch"}, "mempool": {"authorities": {name: {name, stake,
  transactions_address, mempool_address}}, "epoch"}}`` with ``ip:port``
  strings.
- parameters: ``{"consensus": {...}, "mempool": {...}}``
- secret: ``{"name": <b64 pk>, "secret": <b64 seed>}``
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass

from hotstuff_tpu.consensus import Authority as ConsensusAuthority
from hotstuff_tpu.consensus import Committee as ConsensusCommittee
from hotstuff_tpu.consensus import Parameters as ConsensusParameters
from hotstuff_tpu.crypto import PublicKey, SecretKey, generate_keypair
from hotstuff_tpu.mempool import Authority as MempoolAuthority
from hotstuff_tpu.mempool import Committee as MempoolCommittee
from hotstuff_tpu.mempool import Parameters as MempoolParameters
from hotstuff_tpu.mempool import WorkerEntry


class ConfigError(Exception):
    pass


def _parse_addr(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host, int(port))


def _fmt_addr(a: tuple[str, int]) -> str:
    return f"{a[0]}:{a[1]}"


@dataclass
class Secret:
    name: PublicKey
    secret: SecretKey

    @classmethod
    def new(cls) -> "Secret":
        pk, sk = generate_keypair()
        return cls(pk, sk)

    @classmethod
    def default(cls) -> "Secret":
        """Fixed-seed key for tests (reference ``config.rs:73-79``)."""
        rng = random.Random(0)
        pk, sk = generate_keypair(seed=rng.randbytes(32))
        return cls(pk, sk)

    @classmethod
    def read(cls, path: str) -> "Secret":
        try:
            with open(path) as f:
                data = json.load(f)
            return cls(
                PublicKey.decode_base64(data["name"]),
                SecretKey.decode_base64(data["secret"]),
            )
        except (OSError, KeyError, ValueError) as e:
            raise ConfigError(f"failed to read config file '{path}': {e}") from e

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(
                {"name": self.name.encode_base64(), "secret": self.secret.encode_base64()},
                f,
                indent=4,
                sort_keys=True,
            )
            f.write("\n")


@dataclass
class Committee:
    consensus: ConsensusCommittee
    mempool: MempoolCommittee

    @classmethod
    def read(cls, path: str) -> "Committee":
        try:
            with open(path) as f:
                data = json.load(f)
            consensus = ConsensusCommittee(
                authorities={
                    PublicKey.decode_base64(a["name"]): ConsensusAuthority(
                        stake=int(a["stake"]), address=_parse_addr(a["address"])
                    )
                    for a in data["consensus"]["authorities"].values()
                },
                epoch=int(data["consensus"].get("epoch", 1)),
            )
            mempool = MempoolCommittee(
                authorities={
                    PublicKey.decode_base64(a["name"]): MempoolAuthority(
                        stake=int(a["stake"]),
                        transactions_address=_parse_addr(a["transactions_address"]),
                        mempool_address=_parse_addr(a["mempool_address"]),
                        # Conveyor worker shards: optional, so committee
                        # files from the reference harness parse unchanged.
                        workers=[
                            WorkerEntry(
                                transactions_address=_parse_addr(
                                    w["transactions_address"]
                                ),
                                worker_address=_parse_addr(w["worker_address"]),
                            )
                            for w in a.get("workers", [])
                        ],
                    )
                    for a in data["mempool"]["authorities"].values()
                },
                epoch=int(data["mempool"].get("epoch", 1)),
            )
            return cls(consensus, mempool)
        except (OSError, KeyError, ValueError) as e:
            raise ConfigError(f"failed to read config file '{path}': {e}") from e

    def write(self, path: str) -> None:
        data = {
            "consensus": {
                "authorities": {
                    pk.encode_base64(): {
                        "name": pk.encode_base64(),
                        "stake": a.stake,
                        "address": _fmt_addr(a.address),
                    }
                    for pk, a in self.consensus.authorities.items()
                },
                "epoch": self.consensus.epoch,
            },
            "mempool": {
                "authorities": {
                    pk.encode_base64(): {
                        "name": pk.encode_base64(),
                        "stake": a.stake,
                        "transactions_address": _fmt_addr(a.transactions_address),
                        "mempool_address": _fmt_addr(a.mempool_address),
                        # Emitted only when shards exist: files stay
                        # byte-compatible with the reference harness
                        # whenever the data plane is off.
                        **(
                            {
                                "workers": [
                                    {
                                        "transactions_address": _fmt_addr(
                                            w.transactions_address
                                        ),
                                        "worker_address": _fmt_addr(
                                            w.worker_address
                                        ),
                                    }
                                    for w in a.workers
                                ]
                            }
                            if a.workers
                            else {}
                        ),
                    }
                    for pk, a in self.mempool.authorities.items()
                },
                "epoch": self.mempool.epoch,
            },
        }
        with open(path, "w") as f:
            json.dump(data, f, indent=4, sort_keys=True)
            f.write("\n")


@dataclass
class Parameters:
    consensus: ConsensusParameters
    mempool: MempoolParameters

    @classmethod
    def default(cls) -> "Parameters":
        return cls(ConsensusParameters(), MempoolParameters())

    @classmethod
    def read(cls, path: str) -> "Parameters":
        try:
            with open(path) as f:
                data = json.load(f)
            c, m = data.get("consensus", {}), data.get("mempool", {})
            return cls(
                ConsensusParameters(
                    timeout_delay=int(c.get("timeout_delay", 5_000)),
                    sync_retry_delay=int(c.get("sync_retry_delay", 10_000)),
                    persist_sync=bool(c.get("persist_sync", False)),
                    batch_vote_verification=bool(
                        c.get("batch_vote_verification", False)
                    ),
                    leader_elector=str(
                        c.get("leader_elector", "round-robin")
                    ),
                    # Emit-side wire negotiation: decode always accepts
                    # both formats, so this is safe to flip per epoch.
                    wire_v2=bool(c.get("wire_v2", True)),
                    retention_rounds=int(c.get("retention_rounds", 0)),
                ),
                MempoolParameters(
                    gc_depth=int(m.get("gc_depth", 50)),
                    sync_retry_delay=int(m.get("sync_retry_delay", 5_000)),
                    sync_retry_nodes=int(m.get("sync_retry_nodes", 3)),
                    batch_size=int(m.get("batch_size", 500_000)),
                    max_batch_delay=int(m.get("max_batch_delay", 100)),
                    device_batch_digests=bool(m.get("device_batch_digests", False)),
                    workers=int(m.get("workers", 0)),
                    worker_ingress_capacity=int(
                        m.get("worker_ingress_capacity", 512)
                    ),
                    store_high_watermark=int(
                        m.get("store_high_watermark", 256)
                    ),
                    store_low_watermark=int(m.get("store_low_watermark", 128)),
                ),
            )
        except (OSError, ValueError) as e:
            raise ConfigError(f"failed to read config file '{path}': {e}") from e

    def write(self, path: str) -> None:
        data = {
            "consensus": {
                "timeout_delay": self.consensus.timeout_delay,
                "sync_retry_delay": self.consensus.sync_retry_delay,
                "persist_sync": self.consensus.persist_sync,
                "batch_vote_verification": (
                    self.consensus.batch_vote_verification
                ),
                "leader_elector": self.consensus.leader_elector,
                "wire_v2": self.consensus.wire_v2,
                "retention_rounds": self.consensus.retention_rounds,
            },
            "mempool": {
                "gc_depth": self.mempool.gc_depth,
                "sync_retry_delay": self.mempool.sync_retry_delay,
                "sync_retry_nodes": self.mempool.sync_retry_nodes,
                "batch_size": self.mempool.batch_size,
                "max_batch_delay": self.mempool.max_batch_delay,
                "device_batch_digests": self.mempool.device_batch_digests,
                "workers": self.mempool.workers,
                "worker_ingress_capacity": self.mempool.worker_ingress_capacity,
                "store_high_watermark": self.mempool.store_high_watermark,
                "store_low_watermark": self.mempool.store_low_watermark,
            },
        }
        with open(path, "w") as f:
            json.dump(data, f, indent=4, sort_keys=True)
            f.write("\n")
