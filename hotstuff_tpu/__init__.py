"""hotstuff_tpu — a TPU-native 2-chain HotStuff BFT framework.

A ground-up re-design of the capabilities of the reference Rust implementation
(asonnino/hotstuff, mounted read-only at /root/reference): a committee of
``N = 3f+1`` validators receives client transactions, batches them in a
mempool, and totally orders batch digests via 2-chain HotStuff consensus.

Architecture (TPU-first, not a port):

- **Protocol plane** (host): asyncio actor runtime — every component owns its
  state in a single task and communicates over bounded queues / TCP, mirroring
  the reference's tokio actor topology (reference ``node/src/node.rs:18-70``).
- **Crypto plane** (device): the hot path — SHA-512 digests and Ed25519
  quorum-certificate batch verification (reference ``crypto/src/lib.rs:206-219``,
  ``consensus/src/messages.rs:180-198``) — is a pluggable backend where
  ``backend=tpu`` routes to JAX kernels: GF(2^255-19) limb arithmetic on the
  VPU, shared-doubling multi-scalar multiplication for random-linear-combination
  batch verification, sharded across a ``jax.sharding.Mesh`` with the partial
  accumulators combined over ICI.

Layers (bottom-up, same decomposition as the reference workspace):
``crypto`` / ``ops`` (device kernels) / ``store`` / ``network`` / ``mempool`` /
``consensus`` / ``node``, plus the Python benchmark harness in ``benchmark/``.
"""

__version__ = "0.1.0"
