// Native log-structured KV engine for the hotstuff_tpu store.
//
// The reference wraps RocksDB behind a single-writer actor
// (store/src/lib.rs); this is the TPU-era equivalent for the runtime's
// native plane: an append-only log with an in-memory hash index, sharing
// the exact on-disk record format of the Python LogEngine
// (u32 klen, u32 vlen, key, value — little-endian), so the two engines
// are interchangeable on the same database directory.
//
// Concurrency model: one writer (the store actor / event loop). The C API
// is deliberately single-threaded, like the actor that owns it.
//
// Crash behavior: torn tail records are detected and dropped on replay;
// an optional fsync knob covers power-crash durability for meta records.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <fcntl.h>     // open (directory fsync)
#include <sys/stat.h>  // stat
#include <unistd.h>    // truncate, fsync, close

extern "C" {

struct HsStore {
    std::unordered_map<std::string, std::string> index;
    FILE* log = nullptr;
    std::string path;
    std::string error;
    // Writes arriving while a compaction rewrite runs on another thread
    // are mirrored here and appended to the tmp file at commit, so the
    // atomic replace never discards records the index already holds.
    bool compacting = false;
    std::vector<std::pair<std::string, std::string>> delta;
};

static int64_t file_bytes(const std::string& path) {
    struct stat st;
    if (::stat(path.c_str(), &st) != 0) return 0;
    return static_cast<int64_t>(st.st_size);
}

// Best-effort directory fsync, same discipline as the Python engine's
// MetaLog._fsync_dir: without it a rename can be lost on power failure.
static void fsync_dir(const std::string& file_path) {
    auto slash = file_path.find_last_of('/');
    std::string dir = slash == std::string::npos ? "." : file_path.substr(0, slash);
    int fd = ::open(dir.c_str(), O_RDONLY);
    if (fd < 0) return;
    ::fsync(fd);  // unsupported on some filesystems: best effort
    ::close(fd);
}

static bool replay(HsStore* s, const std::string& path) {
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) return true;  // fresh database
    std::fseek(f, 0, SEEK_END);
    long file_size = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    long valid_end = 0;  // offset just past the last complete record
    for (;;) {
        uint32_t hdr[2];
        size_t n = std::fread(hdr, 1, sizeof hdr, f);
        if (n < sizeof hdr) break;  // clean EOF or torn header: stop
        // Bound lengths by the remaining file size before allocating: a
        // torn header can decode to multi-GB lengths and bad_alloc must
        // not escape the C ABI.
        long remaining = file_size - std::ftell(f);
        if (remaining < 0 ||
            static_cast<uint64_t>(hdr[0]) + hdr[1] >
                static_cast<uint64_t>(remaining))
            break;  // torn record: stop
        std::string key(hdr[0], '\0'), val(hdr[1], '\0');
        if (std::fread(key.data(), 1, hdr[0], f) != hdr[0]) break;
        if (std::fread(val.data(), 1, hdr[1], f) != hdr[1]) break;
        s->index[std::move(key)] = std::move(val);
        valid_end = std::ftell(f);
    }
    std::fseek(f, 0, SEEK_END);
    long file_end = std::ftell(f);
    std::fclose(f);
    if (file_end > valid_end) {
        // Torn tail: truncate before reopening for append, or the next
        // replay would misparse records written after the garbage bytes.
        if (truncate(path.c_str(), valid_end) != 0) return false;
    }
    return true;
}

HsStore* hs_store_open(const char* log_path) {
    auto* s = new HsStore();
    s->path = log_path;
    // A crash between the compaction tmp write and its rename leaves a
    // stale ``store.log.tmp`` beside the (intact) live log; discard it so
    // a later compaction cannot surface a file mixing two generations.
    std::remove((s->path + ".tmp").c_str());
    if (!replay(s, log_path)) {
        delete s;
        return nullptr;
    }
    s->log = std::fopen(log_path, "ab");
    if (!s->log) {
        delete s;
        return nullptr;
    }
    return s;
}

int hs_store_put(HsStore* s, const uint8_t* key, uint32_t klen,
                 const uint8_t* val, uint32_t vlen) {
    if (!s->log) {
        // A failed compaction swap can leave no append handle (reopen
        // after rename failed): retry here instead of dereferencing null,
        // so one transient failure doesn't poison every later write.
        s->log = std::fopen(s->path.c_str(), "ab");
        if (!s->log) return -1;
    }
    uint32_t hdr[2] = {klen, vlen};
    if (std::fwrite(hdr, 1, sizeof hdr, s->log) != sizeof hdr) return -1;
    if (std::fwrite(key, 1, klen, s->log) != klen) return -1;
    if (std::fwrite(val, 1, vlen, s->log) != vlen) return -1;
    if (std::fflush(s->log) != 0) return -1;
    std::string k(reinterpret_cast<const char*>(key), klen);
    std::string v(reinterpret_cast<const char*>(val), vlen);
    if (s->compacting) s->delta.emplace_back(k, v);
    s->index[std::move(k)] = std::move(v);
    return 0;
}

// Two-phase read: hs_store_get returns the value length (or -1 if absent);
// hs_store_read copies it out. The value cannot disappear between the two
// calls because the owning actor is single-threaded.
int64_t hs_store_get(HsStore* s, const uint8_t* key, uint32_t klen) {
    auto it = s->index.find(std::string(reinterpret_cast<const char*>(key), klen));
    if (it == s->index.end()) return -1;
    return static_cast<int64_t>(it->second.size());
}

int hs_store_read(HsStore* s, const uint8_t* key, uint32_t klen, uint8_t* out,
                  uint32_t outlen) {
    auto it = s->index.find(std::string(reinterpret_cast<const char*>(key), klen));
    if (it == s->index.end()) return -1;
    if (it->second.size() > outlen) return -2;
    std::memcpy(out, it->second.data(), it->second.size());
    return 0;
}

uint64_t hs_store_size(HsStore* s) { return s->index.size(); }

// Phased compaction: rewrite the log without the dropped keys (and
// without superseded duplicate records), atomically: tmp + fsync + rename
// + directory fsync — the same crash discipline as the Python
// LogEngine.compact. A crash at any point leaves either the old complete
// log or the new complete log.
//
// Split into begin/write/commit so the expensive part — writing every
// retained record plus the fsync — can run on a caller-provided thread
// while the owning event loop keeps serving puts: ``begin`` (owner
// thread) deep-copies the retained records and arms the delta mirror in
// hs_store_put; ``write`` touches ONLY its state object, so it is safe on
// any thread; ``commit``/``abort`` (owner thread again) append the
// mirrored delta, swap the files, and always leave a usable append handle
// (or null, which hs_store_put re-opens lazily).

struct HsCompact {
    std::vector<std::pair<std::string, std::string>> items;  // retained
    std::unordered_set<std::string> drop;
    std::string tmp;
};

// ``blob`` packs the drop set as repeated (u32 klen, key) entries.
// Returns null if the blob is malformed or a compaction is in flight.
HsCompact* hs_store_compact_begin(HsStore* s, const uint8_t* blob,
                                  uint64_t blob_len) {
    if (s->compacting) return nullptr;
    std::unordered_set<std::string> drop;
    uint64_t pos = 0;
    while (pos + 4 <= blob_len) {
        uint32_t klen;
        std::memcpy(&klen, blob + pos, 4);
        pos += 4;
        if (pos + klen > blob_len) return nullptr;  // malformed drop set
        drop.emplace(reinterpret_cast<const char*>(blob + pos), klen);
        pos += klen;
    }
    if (pos != blob_len) return nullptr;
    auto* c = new HsCompact();
    c->tmp = s->path + ".tmp";
    c->items.reserve(s->index.size());
    // Deep copies: the write thread must never touch the live index —
    // concurrent puts may rehash it or overwrite a value in place.
    for (const auto& kv : s->index) {
        if (drop.count(kv.first)) continue;
        c->items.emplace_back(kv.first, kv.second);
    }
    c->drop = std::move(drop);
    s->compacting = true;
    s->delta.clear();
    return c;
}

// Write the retained snapshot to the tmp file (flush + fsync). Reads only
// ``c`` — safe on any thread. Returns 0 on success, -1 on error.
int hs_store_compact_write(HsCompact* c) {
    FILE* f = std::fopen(c->tmp.c_str(), "wb");
    if (!f) return -1;
    for (const auto& kv : c->items) {
        uint32_t hdr[2] = {static_cast<uint32_t>(kv.first.size()),
                           static_cast<uint32_t>(kv.second.size())};
        if (std::fwrite(hdr, 1, sizeof hdr, f) != sizeof hdr ||
            std::fwrite(kv.first.data(), 1, kv.first.size(), f) !=
                kv.first.size() ||
            std::fwrite(kv.second.data(), 1, kv.second.size(), f) !=
                kv.second.size()) {
            std::fclose(f);
            std::remove(c->tmp.c_str());
            return -1;
        }
    }
    if (std::fflush(f) != 0 || ::fsync(fileno(f)) != 0) {
        std::fclose(f);
        std::remove(c->tmp.c_str());
        return -1;
    }
    std::fclose(f);
    return 0;
}

// Discard an in-flight compaction (write failure or shutdown): the live
// log was never touched.
void hs_store_compact_abort(HsStore* s, HsCompact* c) {
    s->compacting = false;
    s->delta.clear();
    std::remove(c->tmp.c_str());
    delete c;
}

// Append the delta mirrored during the rewrite, atomically swap the logs,
// drop the dead keys. Returns bytes reclaimed, or -1 on error — the old
// log stays live on every failure path, and the append handle is restored
// (or lazily re-opened by the next hs_store_put).
int64_t hs_store_compact_commit(HsStore* s, HsCompact* c) {
    FILE* f = std::fopen(c->tmp.c_str(), "ab");
    if (!f) {
        hs_store_compact_abort(s, c);
        return -1;
    }
    for (const auto& kv : s->delta) {
        if (c->drop.count(kv.first)) continue;
        uint32_t hdr[2] = {static_cast<uint32_t>(kv.first.size()),
                           static_cast<uint32_t>(kv.second.size())};
        if (std::fwrite(hdr, 1, sizeof hdr, f) != sizeof hdr ||
            std::fwrite(kv.first.data(), 1, kv.first.size(), f) !=
                kv.first.size() ||
            std::fwrite(kv.second.data(), 1, kv.second.size(), f) !=
                kv.second.size()) {
            std::fclose(f);
            hs_store_compact_abort(s, c);
            return -1;
        }
    }
    if (std::fflush(f) != 0 || ::fsync(fileno(f)) != 0) {
        std::fclose(f);
        hs_store_compact_abort(s, c);
        return -1;
    }
    std::fclose(f);
    const int64_t before = file_bytes(s->path);
    std::fclose(s->log);
    s->log = nullptr;
    if (std::rename(c->tmp.c_str(), s->path.c_str()) != 0) {
        s->log = std::fopen(s->path.c_str(), "ab");  // old log survived
        hs_store_compact_abort(s, c);
        return -1;
    }
    fsync_dir(s->path);
    s->log = std::fopen(s->path.c_str(), "ab");  // null: put re-opens lazily
    for (const auto& k : c->drop) s->index.erase(k);
    s->compacting = false;
    s->delta.clear();
    delete c;
    const int64_t after = file_bytes(s->path);
    return before > after ? before - after : 0;
}

// One-shot convenience wrapper over the phases (same-thread callers).
int64_t hs_store_compact(HsStore* s, const uint8_t* blob, uint64_t blob_len) {
    HsCompact* c = hs_store_compact_begin(s, blob, blob_len);
    if (!c) return -1;
    if (hs_store_compact_write(c) != 0) {
        hs_store_compact_abort(s, c);
        return -1;
    }
    return hs_store_compact_commit(s, c);
}

void hs_store_close(HsStore* s) {
    if (s->log) std::fclose(s->log);
    delete s;
}

}  // extern "C"
