// Native log-structured KV engine for the hotstuff_tpu store.
//
// The reference wraps RocksDB behind a single-writer actor
// (store/src/lib.rs); this is the TPU-era equivalent for the runtime's
// native plane: an append-only log with an in-memory hash index, sharing
// the exact on-disk record format of the Python LogEngine
// (u32 klen, u32 vlen, key, value — little-endian), so the two engines
// are interchangeable on the same database directory.
//
// Concurrency model: one writer (the store actor / event loop). The C API
// is deliberately single-threaded, like the actor that owns it.
//
// Crash behavior: torn tail records are detected and dropped on replay;
// an optional fsync knob covers power-crash durability for meta records.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <unistd.h>  // truncate

extern "C" {

struct HsStore {
    std::unordered_map<std::string, std::string> index;
    FILE* log = nullptr;
    std::string error;
};

static bool replay(HsStore* s, const std::string& path) {
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) return true;  // fresh database
    std::fseek(f, 0, SEEK_END);
    long file_size = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    long valid_end = 0;  // offset just past the last complete record
    for (;;) {
        uint32_t hdr[2];
        size_t n = std::fread(hdr, 1, sizeof hdr, f);
        if (n < sizeof hdr) break;  // clean EOF or torn header: stop
        // Bound lengths by the remaining file size before allocating: a
        // torn header can decode to multi-GB lengths and bad_alloc must
        // not escape the C ABI.
        long remaining = file_size - std::ftell(f);
        if (remaining < 0 ||
            static_cast<uint64_t>(hdr[0]) + hdr[1] >
                static_cast<uint64_t>(remaining))
            break;  // torn record: stop
        std::string key(hdr[0], '\0'), val(hdr[1], '\0');
        if (std::fread(key.data(), 1, hdr[0], f) != hdr[0]) break;
        if (std::fread(val.data(), 1, hdr[1], f) != hdr[1]) break;
        s->index[std::move(key)] = std::move(val);
        valid_end = std::ftell(f);
    }
    std::fseek(f, 0, SEEK_END);
    long file_end = std::ftell(f);
    std::fclose(f);
    if (file_end > valid_end) {
        // Torn tail: truncate before reopening for append, or the next
        // replay would misparse records written after the garbage bytes.
        if (truncate(path.c_str(), valid_end) != 0) return false;
    }
    return true;
}

HsStore* hs_store_open(const char* log_path) {
    auto* s = new HsStore();
    if (!replay(s, log_path)) {
        delete s;
        return nullptr;
    }
    s->log = std::fopen(log_path, "ab");
    if (!s->log) {
        delete s;
        return nullptr;
    }
    return s;
}

int hs_store_put(HsStore* s, const uint8_t* key, uint32_t klen,
                 const uint8_t* val, uint32_t vlen) {
    uint32_t hdr[2] = {klen, vlen};
    if (std::fwrite(hdr, 1, sizeof hdr, s->log) != sizeof hdr) return -1;
    if (std::fwrite(key, 1, klen, s->log) != klen) return -1;
    if (std::fwrite(val, 1, vlen, s->log) != vlen) return -1;
    if (std::fflush(s->log) != 0) return -1;
    s->index[std::string(reinterpret_cast<const char*>(key), klen)] =
        std::string(reinterpret_cast<const char*>(val), vlen);
    return 0;
}

// Two-phase read: hs_store_get returns the value length (or -1 if absent);
// hs_store_read copies it out. The value cannot disappear between the two
// calls because the owning actor is single-threaded.
int64_t hs_store_get(HsStore* s, const uint8_t* key, uint32_t klen) {
    auto it = s->index.find(std::string(reinterpret_cast<const char*>(key), klen));
    if (it == s->index.end()) return -1;
    return static_cast<int64_t>(it->second.size());
}

int hs_store_read(HsStore* s, const uint8_t* key, uint32_t klen, uint8_t* out,
                  uint32_t outlen) {
    auto it = s->index.find(std::string(reinterpret_cast<const char*>(key), klen));
    if (it == s->index.end()) return -1;
    if (it->second.size() > outlen) return -2;
    std::memcpy(out, it->second.data(), it->second.size());
    return 0;
}

uint64_t hs_store_size(HsStore* s) { return s->index.size(); }

void hs_store_close(HsStore* s) {
    if (s->log) std::fclose(s->log);
    delete s;
}

}  // extern "C"
