"""ctypes binding for the native C++ KV engine.

Builds ``libhsstore.so`` lazily with g++ on first use (no pip/pybind11 in
the environment — plain ctypes over a C ABI, per the runtime's native-code
policy). Falls back to the Python LogEngine automatically if the toolchain
is unavailable (``store._default_engine``).

Interchangeable on disk with the Python engine: identical record format,
including torn-tail crash replay. Meta records share the Python engine's
``MetaLog`` append file (optional fsync) so both engines are drop-in for
consensus state persistence.
"""

from __future__ import annotations

import ctypes

import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "engine.cpp")
_LIB = os.path.join(_DIR, "libhsstore.so")


def _ensure_built() -> str:
    if (
        not os.path.exists(_LIB)
        or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
    ):
        tmp = f"{_LIB}.{os.getpid()}.tmp"  # concurrent builders must not collide
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, _LIB)
    return _LIB


_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(_ensure_built())
        lib.hs_store_open.restype = ctypes.c_void_p
        lib.hs_store_open.argtypes = [ctypes.c_char_p]
        lib.hs_store_put.restype = ctypes.c_int
        lib.hs_store_put.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.hs_store_get.restype = ctypes.c_int64
        lib.hs_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.hs_store_read.restype = ctypes.c_int
        lib.hs_store_read.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.hs_store_size.restype = ctypes.c_uint64
        lib.hs_store_size.argtypes = [ctypes.c_void_p]
        lib.hs_store_compact.restype = ctypes.c_int64
        lib.hs_store_compact.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.hs_store_compact_begin.restype = ctypes.c_void_p
        lib.hs_store_compact_begin.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.hs_store_compact_write.restype = ctypes.c_int
        lib.hs_store_compact_write.argtypes = [ctypes.c_void_p]
        lib.hs_store_compact_abort.restype = None
        lib.hs_store_compact_abort.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.hs_store_compact_commit.restype = ctypes.c_int64
        lib.hs_store_compact_commit.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.hs_store_close.restype = None
        lib.hs_store_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class NativeEngine:
    """Same interface as ``store.LogEngine``, backed by the C++ engine."""

    def __init__(self, path: str) -> None:
        lib = _load()
        os.makedirs(path, exist_ok=True)
        self._path = path
        self._handle = lib.hs_store_open(
            os.path.join(path, "store.log").encode()
        )
        if not self._handle:
            raise OSError(f"failed to open native store at {path}")
        self._lib = lib
        self._metalog = None  # lazily opened MetaLog

    def put(self, key: bytes, value: bytes) -> None:
        rc = self._lib.hs_store_put(self._handle, key, len(key), value, len(value))
        if rc != 0:
            raise OSError("native store write failed")

    def get(self, key: bytes) -> bytes | None:
        n = self._lib.hs_store_get(self._handle, key, len(key))
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(int(n))
        rc = self._lib.hs_store_read(self._handle, key, len(key), buf, int(n))
        if rc != 0:
            raise OSError("native store read failed")
        return buf.raw

    # -- phased compaction (see LogEngine for the contract) ---------------
    #
    # ``compact_begin`` (loop thread) deep-copies the retained records in
    # C and arms the put-delta mirror; ``compact_write`` touches only that
    # state, so Store.compact runs it on an executor thread — ctypes
    # releases the GIL for the call, so the rewrite runs truly concurrent
    # with the event loop; ``compact_commit`` (loop thread) appends the
    # mirrored delta, swaps the files, and restores the append handle.

    class _CompactState:
        __slots__ = ("ptr", "error")

        def __init__(self, ptr) -> None:
            self.ptr = ptr
            self.error = None

    def compact_begin(self, drop_keys) -> "_CompactState | None":
        import struct

        blob = b"".join(
            struct.pack("<I", len(k)) + bytes(k) for k in drop_keys
        )
        ptr = self._lib.hs_store_compact_begin(self._handle, blob, len(blob))
        if not ptr:
            return None  # compaction already in flight (or malformed set)
        return self._CompactState(ptr)

    def compact_write(self, state) -> bool:
        ok = self._lib.hs_store_compact_write(state.ptr) == 0
        if not ok:
            state.error = "native tmp rewrite failed"
        return ok

    def compact_abort(self, state) -> None:
        self._lib.hs_store_compact_abort(self._handle, state.ptr)
        state.ptr = None

    def compact_commit(self, state) -> int:
        freed = self._lib.hs_store_compact_commit(self._handle, state.ptr)
        state.ptr = None  # commit consumed (and freed) the state either way
        if freed < 0:
            raise OSError("native store compaction failed")
        return int(freed)

    def compact(self, drop_keys) -> int:
        """Drop ``drop_keys`` from the log and reclaim their space (atomic
        rewrite, same crash discipline as ``LogEngine.compact``). Returns
        bytes reclaimed; 0 if a compaction was already in flight."""
        state = self.compact_begin(drop_keys)
        if state is None:
            return 0
        if not self.compact_write(state):
            self.compact_abort(state)
            raise OSError("native store compaction failed")
        return self.compact_commit(state)

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(os.path.join(self._path, "store.log"))
        except OSError:
            return 0

    # Meta records: the same shared MetaLog append file as the Python
    # engine (with fallback reads of the legacy per-key replace files).
    @property
    def _meta_log(self):
        if self._metalog is None:
            from hotstuff_tpu.store import MetaLog

            self._metalog = MetaLog(self._path)
        return self._metalog

    def put_meta(self, key: bytes, value: bytes, sync: bool = False) -> None:
        self._meta_log.put(key, value, sync=sync)

    def get_meta(self, key: bytes) -> bytes | None:
        return self._meta_log.get(key)

    def close(self) -> None:
        if self._handle:
            self._lib.hs_store_close(self._handle)
            self._handle = None
        if self._metalog is not None:
            self._metalog.close()
            self._metalog = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
