"""Store layer: persistent KV with read-notification obligations.

Same contract as the reference store crate (``store/src/lib.rs:15-93``): a
single-writer actor exposing ``write``/``read``/``notify_read``, where
``notify_read`` registers an obligation fulfilled by a later ``write`` — the
core "wait until data arrives" primitive every synchronizer builds on
(reference ``store/src/lib.rs:29-56``).

The reference wraps RocksDB; we use a pluggable engine: a log-structured
Python engine by default (append-only WAL + in-memory index, replayed on
open) and a C++ native engine (``hotstuff_tpu.store.native``) when built.
Since the runtime is a single-threaded asyncio loop, actor serialization is
inherent — no queue hop is needed, which removes one channel round-trip from
the commit hot path while preserving the exact observable semantics.
"""

from __future__ import annotations

import asyncio
import os
import struct

__all__ = ["Store", "StoreError"]

_HDR = struct.Struct("<II")


class StoreError(Exception):
    pass


class LogEngine:
    """Append-only log + in-memory index.

    Record format: ``u32 klen, u32 vlen, key, value`` (little-endian).
    Buffered appends, flushed per write (no fsync — matches the reference's
    RocksDB usage, which never requests synchronous writes).

    Small frequently-overwritten records (consensus voting state) go through
    ``put_meta`` instead: a separate fixed-size file updated by atomic
    replace, so the append log never accumulates superseded versions, with
    optional fsync for power-crash durability.
    """

    def __init__(self, path: str) -> None:
        self._index: dict[bytes, bytes] = {}
        self._path = path
        os.makedirs(path, exist_ok=True)
        self._log_path = os.path.join(path, "store.log")
        self._replay()
        self._log = open(self._log_path, "ab")

    def _meta_path(self, key: bytes) -> str:
        import hashlib

        return os.path.join(self._path, "meta_" + hashlib.sha256(key).hexdigest()[:16])

    def put_meta(self, key: bytes, value: bytes, sync: bool = False) -> None:
        path = self._meta_path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
            if sync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)

    def get_meta(self, key: bytes) -> bytes | None:
        try:
            with open(self._meta_path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def _replay(self) -> None:
        if not os.path.exists(self._log_path):
            return
        with open(self._log_path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + _HDR.size <= len(data):
            klen, vlen = _HDR.unpack_from(data, pos)
            end = pos + _HDR.size + klen + vlen
            if end > len(data):
                break  # torn tail from a crash — drop it
            key = data[pos + _HDR.size : pos + _HDR.size + klen]
            value = data[pos + _HDR.size + klen : end]
            self._index[key] = value
            pos = end
        if pos < len(data):
            # Torn tail: truncate before reopening for append, or the next
            # replay would misparse records written after the garbage bytes.
            os.truncate(self._log_path, pos)

    def put(self, key: bytes, value: bytes) -> None:
        self._log.write(_HDR.pack(len(key), len(value)) + key + value)
        self._log.flush()
        self._index[key] = value

    def get(self, key: bytes) -> bytes | None:
        return self._index.get(key)

    def close(self) -> None:
        self._log.close()


class MemEngine:
    """Volatile engine for tests and throwaway deployments."""

    def __init__(self) -> None:
        self._index: dict[bytes, bytes] = {}
        self._meta: dict[bytes, bytes] = {}

    def put(self, key: bytes, value: bytes) -> None:
        self._index[key] = value

    def get(self, key: bytes) -> bytes | None:
        return self._index.get(key)

    def put_meta(self, key: bytes, value: bytes, sync: bool = False) -> None:
        self._meta[key] = value

    def get_meta(self, key: bytes) -> bytes | None:
        return self._meta.get(key)

    def close(self) -> None:
        pass


def _default_engine(path: str | None):
    if path is None:
        return MemEngine()
    try:
        from .native import NativeEngine

        return NativeEngine(path)
    except Exception:
        return LogEngine(path)


class Store:
    """Async KV handle (reference ``Store{new,read,write,notify_read}``,
    ``store/src/lib.rs:64-92``). Clonable by reference — share freely between
    actors on one loop."""

    def __init__(self, path: str | None = None, engine=None) -> None:
        self._engine = engine if engine is not None else _default_engine(path)
        self._obligations: dict[bytes, list[asyncio.Future]] = {}

    async def write(self, key: bytes, value: bytes) -> None:
        self._engine.put(key, value)
        waiters = self._obligations.pop(key, None)
        if waiters:
            for fut in waiters:
                if not fut.done():
                    fut.set_result(value)

    async def read(self, key: bytes) -> bytes | None:
        return self._engine.get(key)

    async def write_meta(self, key: bytes, value: bytes, sync: bool = False) -> None:
        """Small bounded record with overwrite semantics (no log growth);
        ``sync=True`` fsyncs for power-crash durability."""
        self._engine.put_meta(key, value, sync=sync)

    async def read_meta(self, key: bytes) -> bytes | None:
        return self._engine.get_meta(key)

    async def notify_read(self, key: bytes) -> bytes:
        """Return the value for ``key``, waiting for a future ``write`` if it
        is not yet present (reference ``StoreCommand::NotifyRead``,
        ``store/src/lib.rs:46-56``). Cancelling the awaiting task cleanly
        drops the obligation."""
        value = self._engine.get(key)
        if value is not None:
            return value
        fut: asyncio.Future[bytes] = asyncio.get_running_loop().create_future()
        self._obligations.setdefault(key, []).append(fut)
        try:
            return await fut
        finally:
            if fut.cancelled():
                waiters = self._obligations.get(key)
                if waiters and fut in waiters:
                    waiters.remove(fut)
                    if not waiters:
                        del self._obligations[key]

    def close(self) -> None:
        self._engine.close()
