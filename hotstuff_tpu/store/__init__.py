"""Store layer: persistent KV with read-notification obligations.

Same contract as the reference store crate (``store/src/lib.rs:15-93``): a
single-writer actor exposing ``write``/``read``/``notify_read``, where
``notify_read`` registers an obligation fulfilled by a later ``write`` — the
core "wait until data arrives" primitive every synchronizer builds on
(reference ``store/src/lib.rs:29-56``).

The reference wraps RocksDB; we use a pluggable engine: a log-structured
Python engine by default (append-only WAL + in-memory index, replayed on
open) and a C++ native engine (``hotstuff_tpu.store.native``) when built.
Since the runtime is a single-threaded asyncio loop, actor serialization is
inherent — no queue hop is needed, which removes one channel round-trip from
the commit hot path while preserving the exact observable semantics.
"""

from __future__ import annotations

import asyncio
import os
import struct

__all__ = ["Store", "StoreError"]

_HDR = struct.Struct("<II")


class StoreError(Exception):
    pass


class MetaLog:
    """Append-only log for small frequently-overwritten records (consensus
    voting state): ``u32 klen, u32 vlen, key, value`` records, LAST record
    per key wins on replay.

    The previous layout (one file per key, rewritten by atomic tmp+rename
    each update) cost an ``open`` + ``os.replace`` (~0.4 ms of syscalls) on
    every consensus state change — ~9% of a node's CPU on the single-core
    local bench, straight on the vote path. An append is two buffered
    writes. Torn tails truncate on replay like the data log; the file
    compacts in place (atomic replace) when superseded records dominate.
    ``sync=True`` additionally fsyncs for power-crash durability.

    Reads fall back to the legacy per-key ``meta_<hash>`` files so a node
    restarted across the layout change still recovers its voting state.
    """

    COMPACT_MIN_RECORDS = 4096

    def __init__(self, dir_path: str) -> None:
        self._dir = dir_path
        self._path = os.path.join(dir_path, "meta.log")
        self._meta: dict[bytes, bytes] = {}
        self._records = 0
        # A crash between the compaction tmp write and its os.replace
        # leaves a stale ``meta.log.tmp`` beside the (intact) live log.
        # It must be discarded on open: a LATER compaction would reuse
        # the name, and a crash inside ITS write window could then
        # surface a file mixing two generations of records.
        try:
            os.unlink(self._path + ".tmp")
        except OSError:
            pass
        self._replay()
        existed = os.path.exists(self._path)
        self._f = open(self._path, "ab")
        if not existed:
            # A durable (sync=True) put into a file whose directory entry
            # was never fsynced can vanish wholesale on power failure on
            # some filesystems: persist the creation itself.
            self._fsync_dir()

    def _fsync_dir(self) -> None:
        try:
            fd = os.open(self._dir, os.O_RDONLY)
        except OSError:
            return  # platform without directory fds: best effort
        try:
            os.fsync(fd)
        except OSError:
            pass  # directory fsync unsupported (NFS/FUSE): best effort
        finally:
            os.close(fd)

    def _replay(self) -> None:
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + _HDR.size <= len(data):
            klen, vlen = _HDR.unpack_from(data, pos)
            end = pos + _HDR.size + klen + vlen
            if end > len(data):
                break  # torn tail
            self._meta[data[pos + _HDR.size : pos + _HDR.size + klen]] = data[
                pos + _HDR.size + klen : end
            ]
            self._records += 1
            pos = end
        if pos < len(data):
            os.truncate(self._path, pos)

    def _legacy_path(self, key: bytes) -> str:
        import hashlib

        return os.path.join(
            self._dir, "meta_" + hashlib.sha256(key).hexdigest()[:16]
        )

    def put(self, key: bytes, value: bytes, sync: bool = False) -> None:
        # The in-memory map updates only after the write path completes: on
        # OSError (disk full, IO error) callers never observe a value that
        # may not survive restart; replay truncates any torn partial record.
        self._f.write(_HDR.pack(len(key), len(value)) + key + value)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())
        self._meta[key] = value
        self._records += 1
        if (
            self._records >= self.COMPACT_MIN_RECORDS
            and self._records >= 4 * len(self._meta)
        ):
            self._compact()

    def _compact(self) -> None:
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            for k, v in self._meta.items():
                f.write(_HDR.pack(len(k), len(v)) + k + v)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self._path)
        # Persist the rename: without a directory fsync the replace can be
        # lost on power failure, resurrecting the (deleted) old log.
        self._fsync_dir()
        self._f = open(self._path, "ab")
        self._records = len(self._meta)

    def get(self, key: bytes) -> bytes | None:
        value = self._meta.get(key)
        if value is not None:
            return value
        try:  # pre-MetaLog layout: one atomic-replace file per key
            with open(self._legacy_path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def close(self) -> None:
        self._f.close()


class LogEngine:
    """Append-only log + in-memory index.

    Record format: ``u32 klen, u32 vlen, key, value`` (little-endian).
    Buffered appends, flushed per write (no fsync — matches the reference's
    RocksDB usage, which never requests synchronous writes).

    Small frequently-overwritten records (consensus voting state) go through
    ``put_meta`` instead — a shared ``MetaLog`` append file, so the data log
    never accumulates superseded versions and a state update never pays a
    file rename."""

    def __init__(self, path: str) -> None:
        self._index: dict[bytes, bytes] = {}
        self._path = path
        os.makedirs(path, exist_ok=True)
        self._log_path = os.path.join(path, "store.log")
        try:  # stale compaction temp from a crash inside the replace window
            os.unlink(self._log_path + ".tmp")
        except OSError:
            pass
        self._replay()
        self._log = open(self._log_path, "ab")
        self._metalog = MetaLog(path)
        # Writes arriving while a compaction rewrite is in flight are
        # mirrored here and appended to the tmp file at commit, so the
        # atomic replace never discards records the index already holds.
        self._compacting = False
        self._delta: list[tuple[bytes, bytes]] = []

    def put_meta(self, key: bytes, value: bytes, sync: bool = False) -> None:
        self._metalog.put(key, value, sync=sync)

    def get_meta(self, key: bytes) -> bytes | None:
        return self._metalog.get(key)

    def _replay(self) -> None:
        if not os.path.exists(self._log_path):
            return
        with open(self._log_path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + _HDR.size <= len(data):
            klen, vlen = _HDR.unpack_from(data, pos)
            end = pos + _HDR.size + klen + vlen
            if end > len(data):
                break  # torn tail from a crash — drop it
            key = data[pos + _HDR.size : pos + _HDR.size + klen]
            value = data[pos + _HDR.size + klen : end]
            self._index[key] = value
            pos = end
        if pos < len(data):
            # Torn tail: truncate before reopening for append, or the next
            # replay would misparse records written after the garbage bytes.
            os.truncate(self._log_path, pos)

    def put(self, key: bytes, value: bytes) -> None:
        self._log.write(_HDR.pack(len(key), len(value)) + key + value)
        self._log.flush()
        self._index[key] = value
        if self._compacting:
            self._delta.append((key, value))

    def get(self, key: bytes) -> bytes | None:
        return self._index.get(key)

    # -- phased compaction ---------------------------------------------------
    #
    # Rewrite ``store.log`` without the dropped keys (and without superseded
    # duplicate records), atomically: tmp + fsync + ``os.replace`` +
    # directory fsync, same crash discipline as ``MetaLog._compact``. A
    # crash at any point leaves either the old complete log or the new
    # complete log.
    #
    # Split into begin/write/commit so the expensive part — writing the
    # retained records plus two fsyncs — can run OFF the event loop
    # (``Store.compact`` sends it to an executor): a synchronous rewrite
    # inside the commit path stalled consensus for the full copy, long
    # enough at large stores to push nodes into view changes. ``begin``
    # snapshots the index on the loop (reference copies, cheap) and arms
    # the write mirror; ``write`` touches only its state object, so it is
    # safe on any thread; ``commit`` appends the mirrored delta (small),
    # swaps the files, and restores a usable append handle on EVERY path —
    # a failed replace or reopen must never leave ``put`` with a closed
    # handle.

    class _CompactState:
        __slots__ = ("items", "drop", "tmp", "error")

        def __init__(self, items, drop, tmp):
            self.items = items
            self.drop = drop
            self.tmp = tmp
            self.error: OSError | None = None

    def compact_begin(self, drop_keys) -> "_CompactState | None":
        """Snapshot the retained records; ``None`` if a compaction is
        already in flight (the caller retries at the next trigger)."""
        if self._compacting:
            return None
        drop = set(drop_keys)
        items = [(k, v) for k, v in self._index.items() if k not in drop]
        self._compacting = True
        self._delta = []
        return self._CompactState(items, drop, self._log_path + ".tmp")

    def compact_write(self, state) -> bool:
        """Write the retained snapshot to the tmp file (flush + fsync).
        Reads only ``state`` — safe to run on an executor thread while the
        loop keeps appending to the live log."""
        try:
            with open(state.tmp, "wb") as f:
                for k, v in state.items:
                    f.write(_HDR.pack(len(k), len(v)) + k + v)
                f.flush()
                os.fsync(f.fileno())
            return True
        except OSError as e:
            state.error = e
            return False

    def compact_abort(self, state) -> None:
        """Discard an in-flight compaction (write failure or shutdown):
        the live log was never touched, so dropping the tmp file and the
        mirror restores the pre-compaction world exactly."""
        self._compacting = False
        self._delta = []
        try:
            os.unlink(state.tmp)
        except OSError:
            pass

    def compact_commit(self, state) -> int:
        """Append the delta mirrored during the rewrite, atomically swap
        the logs, and drop the dead keys from the index. Returns bytes
        reclaimed. On ANY failure the engine is left with an open append
        handle on whichever log file survived."""
        before = (
            os.path.getsize(self._log_path)
            if os.path.exists(self._log_path)
            else 0
        )
        replaced = False
        try:
            with open(state.tmp, "ab") as f:
                for k, v in self._delta:
                    if k in state.drop:
                        continue
                    f.write(_HDR.pack(len(k), len(v)) + k + v)
                f.flush()
                os.fsync(f.fileno())
            self._log.close()
            os.replace(state.tmp, self._log_path)
            replaced = True
            self._fsync_dir()
        finally:
            self._compacting = False
            self._delta = []
            if not replaced:
                try:
                    os.unlink(state.tmp)
                except OSError:
                    pass
            if self._log.closed:
                # Reopen whatever log is live: the new one after a
                # successful replace, the old (intact) one otherwise.
                self._log = open(self._log_path, "ab")
        for k in state.drop:
            self._index.pop(k, None)
        after = os.path.getsize(self._log_path)
        return max(0, before - after)

    def compact(self, drop_keys) -> int:
        """Synchronous convenience wrapper over the phases (tests, tools).
        Unknown keys are retained conservatively. Returns bytes reclaimed
        (0 if a compaction was already in flight or the rewrite failed —
        the old log stays live either way)."""
        state = self.compact_begin(drop_keys)
        if state is None:
            return 0
        if not self.compact_write(state):
            self.compact_abort(state)
            return 0
        return self.compact_commit(state)

    def _fsync_dir(self) -> None:
        self._metalog._fsync_dir()

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self._log_path)
        except OSError:
            return 0

    def close(self) -> None:
        self._log.close()
        self._metalog.close()


class MemEngine:
    """Volatile engine for tests and throwaway deployments."""

    def __init__(self) -> None:
        self._index: dict[bytes, bytes] = {}
        self._meta: dict[bytes, bytes] = {}

    def put(self, key: bytes, value: bytes) -> None:
        self._index[key] = value

    def get(self, key: bytes) -> bytes | None:
        return self._index.get(key)

    def put_meta(self, key: bytes, value: bytes, sync: bool = False) -> None:
        self._meta[key] = value

    def get_meta(self, key: bytes) -> bytes | None:
        return self._meta.get(key)

    def compact(self, drop_keys) -> int:
        freed = 0
        for k in drop_keys:
            v = self._index.pop(k, None)
            if v is not None:
                freed += len(k) + len(v) + _HDR.size
        return freed

    def close(self) -> None:
        pass


def _default_engine(path: str | None):
    if path is None:
        return MemEngine()
    try:
        from .native import NativeEngine

        return NativeEngine(path)
    except Exception:
        return LogEngine(path)


class Store:
    """Async KV handle (reference ``Store{new,read,write,notify_read}``,
    ``store/src/lib.rs:64-92``). Clonable by reference — share freely between
    actors on one loop."""

    def __init__(self, path: str | None = None, engine=None) -> None:
        self._engine = engine if engine is not None else _default_engine(path)
        self._obligations: dict[bytes, list[asyncio.Future]] = {}

    async def write(self, key: bytes, value: bytes) -> None:
        self._engine.put(key, value)
        waiters = self._obligations.pop(key, None)
        if waiters:
            for fut in waiters:
                if not fut.done():
                    fut.set_result(value)

    async def read(self, key: bytes) -> bytes | None:
        return self._engine.get(key)

    async def write_meta(self, key: bytes, value: bytes, sync: bool = False) -> None:
        """Small bounded record with overwrite semantics (no log growth);
        ``sync=True`` fsyncs for power-crash durability."""
        self._engine.put_meta(key, value, sync=sync)

    async def read_meta(self, key: bytes) -> bytes | None:
        return self._engine.get_meta(key)

    def compaction_offloaded(self) -> bool:
        """True when this store's engine runs the compaction rewrite off
        the event loop (the phased protocol below) — callers may then run
        ``compact`` as a background task; sync-only engines (the sim
        plane's MemEngine) should be awaited inline instead."""
        return hasattr(self._engine, "compact_begin")

    async def compact(self, drop_keys) -> int:
        """Drop ``drop_keys`` from the data log and reclaim their space
        (engines without compaction support are a no-op). Returns bytes
        reclaimed.

        Engines exposing the phased protocol (``compact_begin`` /
        ``compact_write`` / ``compact_commit``) run the bulk rewrite —
        the full retained-log copy plus its fsyncs — on the default
        executor, so the event loop (votes, timeouts) keeps running while
        the file is written; only the brief begin (index snapshot) and
        commit (delta append + atomic swap) run on the loop. Concurrent
        ``write``s during the rewrite are safe: the engine mirrors them
        into the tmp file at commit. Engines with only a synchronous
        ``compact`` (MemEngine: in-memory pops; the sim plane, which has
        no executor) run inline as before."""
        engine = self._engine
        begin = getattr(engine, "compact_begin", None)
        if begin is None:
            engine_compact = getattr(engine, "compact", None)
            if engine_compact is None:
                return 0
            return engine_compact(drop_keys)
        state = begin(drop_keys)
        if state is None:
            return 0  # a compaction is already in flight
        loop = asyncio.get_running_loop()
        fut = loop.run_in_executor(None, engine.compact_write, state)
        try:
            ok = await asyncio.shield(fut)
        except asyncio.CancelledError:
            # The rewrite thread cannot be interrupted: let it finish,
            # then discard its output — the live log was never touched.
            fut.add_done_callback(lambda _f: engine.compact_abort(state))
            raise
        if not ok:
            engine.compact_abort(state)
            raise StoreError(f"compaction rewrite failed: {state.error}")
        return engine.compact_commit(state)

    async def notify_read(self, key: bytes) -> bytes:
        """Return the value for ``key``, waiting for a future ``write`` if it
        is not yet present (reference ``StoreCommand::NotifyRead``,
        ``store/src/lib.rs:46-56``). Cancelling the awaiting task cleanly
        drops the obligation."""
        value = self._engine.get(key)
        if value is not None:
            return value
        fut: asyncio.Future[bytes] = asyncio.get_running_loop().create_future()
        self._obligations.setdefault(key, []).append(fut)
        try:
            return await fut
        finally:
            if fut.cancelled():
                waiters = self._obligations.get(key)
                if waiters and fut in waiters:
                    waiters.remove(fut)
                    if not waiters:
                        del self._obligations[key]

    def close(self) -> None:
        self._engine.close()
