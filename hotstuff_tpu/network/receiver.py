"""TCP receiver: accept loop + per-connection runners dispatching frames to a
user-supplied handler (reference ``network/src/receiver.rs:38-88``)."""

from __future__ import annotations

import asyncio
import logging
import struct

from hotstuff_tpu import telemetry
from hotstuff_tpu.faultline import hooks as _faultline

log = logging.getLogger("network")

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


class FrameError(Exception):
    pass


async def read_frame(reader: asyncio.StreamReader) -> bytes:
    hdr = await reader.readexactly(4)
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise FrameError(f"frame length {n} exceeds MAX_FRAME")
    return await reader.readexactly(n)


# Per-await read size for the batched ingress path. One chunk holds many
# small frames (the 512 B–1 KB bundle regime this path exists for) while
# bulk frames just span several chunks via the carryover buffer.
_READ_CHUNK = 256 * 1024


async def read_frames(reader: asyncio.StreamReader, buf: bytearray) -> list[bytes]:
    """Await at least one complete frame, then drain every complete frame
    already buffered — the asyncio mirror of the native plane's
    multi-frame-per-wakeup reads. ``buf`` carries partial-frame bytes
    across calls (caller-owned, initially empty). Returns ``[]`` on clean
    EOF; raises ``IncompleteReadError`` on EOF mid-frame and
    ``FrameError`` on an oversized length prefix."""
    frames: list[bytes] = []
    while True:
        off = 0
        n_buf = len(buf)
        while n_buf - off >= 4:
            (n,) = _LEN.unpack_from(buf, off)
            if n > MAX_FRAME:
                raise FrameError(f"frame length {n} exceeds MAX_FRAME")
            if n_buf - off - 4 < n:
                break
            frames.append(bytes(buf[off + 4 : off + 4 + n]))
            off += 4 + n
        if off:
            del buf[:off]
        if frames:
            return frames
        data = await reader.read(_READ_CHUNK)
        if not data:
            if buf:
                raise asyncio.IncompleteReadError(bytes(buf), None)
            return []
        buf += data


def write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(_LEN.pack(len(payload)) + payload)


class FramedWriter:
    """Reply-side of a connection handed to ``MessageHandler.dispatch`` —
    the channel receivers use to write ACKs back on the same socket
    (reference ``network/src/receiver.rs:20-27``)."""

    __slots__ = ("_writer",)

    def __init__(self, writer: asyncio.StreamWriter) -> None:
        self._writer = writer

    async def send(self, payload: bytes) -> None:
        write_frame(self._writer, payload)
        await self._writer.drain()


class MessageHandler:
    """Dispatch one frame; may await replies via ``writer.send``."""

    async def dispatch(self, writer: FramedWriter, message: bytes) -> None:
        raise NotImplementedError


class _AckedWriter:
    """Writer handed to handlers on auto-ack receivers: the ACK already
    went out when the frame was read, so the handler's own
    ``writer.send(b"Ack")`` is a no-op (a second ACK would mispair the
    sender's FIFO ACK accounting). Handlers only ever reply with the
    literal ACK frame."""

    __slots__ = ()

    async def send(self, payload: bytes) -> None:
        pass


class Receiver:
    """Listens on ``(host, port)``; spawns one runner task per connection.

    With ``auto_ack`` the runner writes the ACK frame the moment a frame
    is read, before dispatch — the sender's back-pressure signal means
    "received", not "processed", exactly as the reference handlers that
    ACK on their first line (``consensus.rs:144-153``,
    ``mempool.rs:224-237``)."""

    def __init__(
        self,
        address: tuple[str, int],
        handler: MessageHandler,
        auto_ack: bool = False,
    ) -> None:
        self.address = address
        self.handler = handler
        self.auto_ack = auto_ack
        self._server: asyncio.base_events.Server | None = None
        self._writers: set[asyncio.StreamWriter] = set()
        self._conn_tasks: set[asyncio.Task] = set()
        self._closing = False

    @classmethod
    async def spawn(
        cls,
        address: tuple[str, int],
        handler: MessageHandler,
        auto_ack: bool = False,
    ) -> "Receiver":
        self = cls(address, handler, auto_ack)
        host, port = address
        self._server = await asyncio.start_server(self._on_connection, host, port)
        log.debug("listening on %s:%d", host, port)
        return self

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        if self._closing:
            # Accepted in the race window between shutdown() snapshotting
            # _conn_tasks and this handler's first iteration: bail so
            # wait_closed() need not burn its timeout on us.
            writer.transport.abort()
            return
        peer = writer.get_extra_info("peername")
        framed = _AckedWriter() if self.auto_ack else FramedWriter(writer)
        self._writers.add(writer)
        self._conn_tasks.add(asyncio.current_task())
        m_frames = telemetry.counter("net.frames_in")
        m_bytes = telemetry.counter("net.bytes_in")
        dispatch_frames = getattr(self.handler, "dispatch_frames", None)
        buf = bytearray()
        try:
            while True:
                # Batched feed: every complete frame already buffered is
                # drained per wakeup (partial-frame carryover in ``buf``),
                # mirroring the native plane's EV_RECV_BATCH shape.
                frames = await read_frames(reader, buf)
                if not frames:
                    break  # clean EOF
                m_frames.inc(len(frames))
                m_bytes.inc(sum(len(f) + 4 for f in frames))
                # Faultline ingress filter (``side: "recv"`` link rules):
                # a dropped frame vanishes before the ACK — the sender
                # sees exactly what a lossy ingress NIC produces; a delay
                # stalls this in-order connection, as real queueing would.
                plane = _faultline.plane
                if plane is not None:
                    kept = []
                    for frame in frames:
                        plan = plane.filter_recv(self.address)
                        if plan is not None:
                            action, delay = plan
                            if delay > 0:
                                await asyncio.sleep(delay)
                            if action == "drop":
                                continue
                        kept.append(frame)
                    frames = kept
                    if not frames:
                        continue
                if self.auto_ack:
                    for _ in frames:
                        write_frame(writer, b"Ack")
                    # drain() keeps flow control: a peer that floods
                    # frames but never reads its ACKs pauses this read
                    # loop at the transport's high-water mark instead of
                    # growing the write buffer without bound.
                    await writer.drain()
                if dispatch_frames is not None and len(frames) > 1:
                    await dispatch_frames([(framed, f) for f in frames])
                else:
                    for frame in frames:
                        await self.handler.dispatch(framed, frame)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass  # peer went away — normal
        except FrameError as e:
            log.warning("bad frame from %s: %s", peer, e)
        except Exception:
            log.exception("handler error for peer %s", peer)
        finally:
            self._conn_tasks.discard(asyncio.current_task())
            self._writers.discard(writer)
            writer.close()

    async def shutdown(self) -> None:
        if self._server is not None:
            self._closing = True
            self._server.close()
            # Python 3.12's wait_closed() waits for every connection
            # HANDLER to return. Closing the writers is not enough: a
            # handler parked in ``handler.dispatch`` (e.g. awaiting a put
            # on the consensus queue after its consumer was cancelled)
            # never observes the closed socket and wait_closed() hangs the
            # whole node teardown (observed live: a 40-node testbed's
            # shutdown wedging on engine 7 while the survivors ground on).
            # Cancel the handler tasks outright — shutdown is terminal —
            # and ABORT the transports: a graceful close() first flushes
            # the write buffer, which never drains on a flow-controlled
            # connection, and wait_closed() counts attached transports.
            for t in list(self._conn_tasks):
                t.cancel()
            for w in list(self._writers):
                w.transport.abort()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5)
            except asyncio.TimeoutError:  # pragma: no cover - defensive
                log.error(
                    "receiver %s: wait_closed timed out; abandoning "
                    "%d lingering connection(s)",
                    self.address,
                    len(self._conn_tasks),
                )
