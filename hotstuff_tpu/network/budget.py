"""Process-wide budget for outgoing sender connections.

A full validator mesh is O(N^2) sockets, and every in-process TCP
connection costs TWO file descriptors (the client end plus the accepted
end). The reference sidesteps this by running one validator per machine
(`benchmark/benchmark/remote.py`); our single-host committee testbed
(`node deploy`, `benchmark.committee_scale --mode protocol`) materializes
the whole mesh in one process and hits RLIMIT_NOFILE near N=100:
connects fail with EMFILE, votes and proposals are lost, every node
times out, and the resulting Timeout broadcasts open even MORE
connections — a self-sustaining storm.

The budget caps live outgoing connections per process. Senders register
each connection and touch it on use; when the cap is exceeded the
least-recently-used IDLE connection (nothing queued, nothing un-ACKed)
is closed. Its owner transparently reconnects on next use, so above the
cap the mesh degrades to connection churn (~100 us/connect on loopback)
instead of collapsing. Round-robin leadership makes the working set —
recent leaders' broadcast fans plus current vote edges — much smaller
than the full mesh, so steady state stays under the cap with no churn
in practice.

The default cap leaves the other half of the fd space for the accepted
ends (worst case: every peer is in-process) plus stores, logs, and
listening sockets. Override with ``HOTSTUFF_CONN_BUDGET``.
"""

from __future__ import annotations

import asyncio
import logging
import os
from collections import OrderedDict
from typing import Protocol

log = logging.getLogger("network")


class _Evictable(Protocol):
    def evictable(self) -> bool: ...

    def evict(self) -> None: ...


def _default_cap() -> int:
    env = os.environ.get("HOTSTUFF_CONN_BUDGET")
    if env:
        try:
            return max(16, int(env))
        except ValueError:
            raise ValueError(
                f"HOTSTUFF_CONN_BUDGET must be an integer, got {env!r}"
            ) from None
    try:
        import resource

        soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    except Exception:  # pragma: no cover - non-POSIX
        return 4096
    if soft == getattr(resource, "RLIM_INFINITY", -1) or soft <= 0:
        return 16384
    # 35% outgoing; x2 for in-process accepted ends = 70% of the limit,
    # leaving headroom for stores, logs, listeners, and the interpreter.
    return max(128, int(soft * 0.35))


class ConnectionBudget:
    def __init__(self, cap: int | None = None) -> None:
        self.cap = cap if cap is not None else _default_cap()
        self._lru: OrderedDict[_Evictable, None] = OrderedDict()
        self._evictions = 0
        self._sweep_handle = None

    def __len__(self) -> int:
        return len(self._lru)

    @property
    def evictions(self) -> int:
        return self._evictions

    def register(self, conn: _Evictable) -> None:
        self._lru[conn] = None
        # A connection registers from its constructor, BEFORE its first
        # message is queued — its empty queue makes it look idle. Excluding
        # it from its own reap prevents self-eviction (which would strand
        # the message the caller is about to queue on a dead connection).
        self._reap(exclude=conn)

    def touch(self, conn: _Evictable) -> None:
        if conn in self._lru:
            self._lru.move_to_end(conn)

    def unregister(self, conn: _Evictable) -> None:
        self._lru.pop(conn, None)

    def _reap(self, exclude: _Evictable | None = None) -> None:
        if len(self._lru) <= self.cap:
            return
        # Oldest-first scan for idle victims. Busy connections (queued or
        # un-ACKed messages) are never evicted — over-budget operation is
        # transient and resolves as ACKs land.
        #
        # Evict a BATCH (the excess plus cap/8 slack), not just back to
        # the cap: a mesh whose potential connection count sits far above
        # the cap (N=100 one-process committee ≈ 20k sender ends vs a 7k
        # cap) otherwise re-enters this scan on EVERY register, and the
        # oldest-first walk over thousands of busy long-lived peers made
        # the scan itself the protocol's biggest CPU line (~30% of a
        # round, measured). With slack, one O(n) sweep buys cap/8
        # scan-free registers — amortized O(8) per connect.
        victims = []
        excess = len(self._lru) - self.cap + self.cap // 8
        for conn in self._lru:
            if conn is not exclude and conn.evictable():
                victims.append(conn)
                if len(victims) >= excess:
                    break
        for conn in victims:
            self._lru.pop(conn, None)
            conn.evict()
            self._evictions += 1
        if victims:
            log.debug(
                "connection budget: evicted %d idle (cap %d, evictions %d)",
                len(victims),
                self.cap,
                self._evictions,
            )
        if len(self._lru) > self.cap:
            # Everything over budget is currently busy (e.g. a burst of
            # sends queued before any delivery). Sweep again shortly —
            # connections become evictable as their queues drain and ACKs
            # land.
            self._schedule_sweep()

    def _schedule_sweep(self) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        if self._sweep_handle is not None:
            pending_loop, handle = self._sweep_handle
            # A handle from a CLOSED loop (the budget is process-global;
            # asyncio.run creates a fresh loop per benchmark/test run)
            # never fires — treating it as live would disable sweeps for
            # the rest of the process.
            if pending_loop is loop and not handle.cancelled():
                return
            handle.cancel()
            self._sweep_handle = None

        def sweep() -> None:
            self._sweep_handle = None
            self._reap()

        self._sweep_handle = (loop, loop.call_later(0.05, sweep))


#: Process-wide instance used by SimpleSender and ReliableSender.
BUDGET = ConnectionBudget()
