"""At-least-once sender (reference ``network/src/reliable_sender.rs``).

``send`` returns a ``CancelHandler`` — a future resolved with the peer's ACK
bytes. Per-peer connection tasks reconnect with exponential backoff (200 ms,
x2, capped 60 s) and replay every un-ACKed message across reconnects
(reference ``reliable_sender.rs:131,166,185-247``). Dropping/cancelling the
handler cancels the message: it is skipped on replay and its ACK discarded
(reference ``reliable_sender.rs:175,195-197``).
"""

from __future__ import annotations

import asyncio
import logging
import random
from collections import deque

from .receiver import read_frame, write_frame

log = logging.getLogger("network")

QUEUE_CAPACITY = 1_000
RETRY_DELAY_MS = 200
RETRY_CAP_MS = 60_000

CancelHandler = asyncio.Future  # resolves to the peer's ACK bytes


class _Connection:
    def __init__(self, address: tuple[str, int]) -> None:
        self.address = address
        self.queue: asyncio.Queue[tuple[bytes, CancelHandler]] = asyncio.Queue(
            QUEUE_CAPACITY
        )
        # Messages sent but not yet ACKed, FIFO; replayed on reconnect.
        self.pending: deque[tuple[bytes, CancelHandler]] = deque()
        self.task = asyncio.create_task(self._keep_alive())

    async def _keep_alive(self) -> None:
        host, port = self.address
        delay = RETRY_DELAY_MS
        while True:
            # While disconnected — including DURING the connect attempt,
            # which can block for the kernel SYN-retry timeout on a
            # blackholed peer — keep draining the queue into ``pending`` and
            # prune cancelled messages, so senders back-pressured by ``send``
            # are never blocked by a DEAD peer, only by a slow live one.
            # Callers that give up (e.g. the proposer after 2f+1 ACKs)
            # cancel their handlers, which frees the buffered slots here
            # (reference ``reliable_sender.rs:160-177`` selects over
            # connect-retry and channel drain the same way).
            drain = asyncio.create_task(self._drain_while_disconnected())
            try:
                while True:
                    try:
                        reader, writer = await asyncio.open_connection(host, port)
                        break
                    except OSError as e:
                        log.debug(
                            "retrying %s:%d in %dms: %s", host, port, delay, e
                        )
                        await asyncio.sleep(delay / 1000)
                        delay = min(delay * 2, RETRY_CAP_MS)
            finally:
                drain.cancel()
                try:
                    await drain
                except asyncio.CancelledError:
                    pass
            delay = RETRY_DELAY_MS
            try:
                await self._run(reader, writer)
            except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
                log.debug("connection to %s:%d dropped: %s", host, port, e)
            finally:
                writer.close()

    async def _drain_while_disconnected(self) -> None:
        drained = 0
        while True:
            item = await self.queue.get()
            self.pending.append(item)
            drained += 1
            # Amortized prune: a full deque rebuild per message would be
            # O(n^2) over a long outage; _run re-prunes on reconnect.
            if drained % 64 == 0:
                self.pending = deque(
                    (d, h) for d, h in self.pending if not h.cancelled()
                )

    async def _run(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        # Replay un-ACKed, un-cancelled messages from the previous connection.
        self.pending = deque(
            (d, h) for d, h in self.pending if not h.cancelled()
        )
        for data, _ in self.pending:
            write_frame(writer, data)
        await writer.drain()

        ack_task = asyncio.create_task(read_frame(reader))
        queue_task = asyncio.create_task(self.queue.get())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {ack_task, queue_task}, return_when=asyncio.FIRST_COMPLETED
                )
                if queue_task in done:
                    data, handler = queue_task.result()
                    queue_task = asyncio.create_task(self.queue.get())
                    if handler.cancelled():
                        continue
                    self.pending.append((data, handler))
                    write_frame(writer, data)
                    await writer.drain()
                if ack_task in done:
                    ack = ack_task.result()  # raises on disconnect
                    ack_task = asyncio.create_task(read_frame(reader))
                    # Pair the ACK with the oldest live pending message.
                    while self.pending:
                        _, handler = self.pending.popleft()
                        if handler.cancelled():
                            continue
                        handler.set_result(ack)
                        break
        finally:
            ack_task.cancel()
            queue_task.cancel()


class ReliableSender:
    def __init__(self) -> None:
        self._connections: dict[tuple[str, int], _Connection] = {}
        self._rng = random.Random()

    def _connection(self, address: tuple[str, int]) -> _Connection:
        conn = self._connections.get(address)
        if conn is None or conn.task.done():
            conn = _Connection(address)
            self._connections[address] = conn
        return conn

    async def send(self, address: tuple[str, int], data: bytes) -> CancelHandler:
        """Queue one frame for ``address``; the returned handler resolves
        with the peer's ACK bytes (reference ``reliable_sender.rs:60-72``).

        Awaits queue capacity: when a peer's channel is full the caller is
        back-pressured, never dropped — "reliable" messages must not vanish
        under load (the reference's ``send`` likewise awaits the channel)."""
        handler: CancelHandler = asyncio.get_running_loop().create_future()
        conn = self._connection(address)
        await conn.queue.put((data, handler))
        return handler

    async def broadcast(
        self, addresses: list[tuple[str, int]], data: bytes
    ) -> list[CancelHandler]:
        return [await self.send(addr, data) for addr in addresses]

    async def lucky_broadcast(
        self, addresses: list[tuple[str, int]], data: bytes, nodes: int
    ) -> list[CancelHandler]:
        """Reliably send to ``nodes`` randomly-picked addresses (reference
        ``reliable_sender.rs:91-100``)."""
        picked = self._rng.sample(addresses, min(nodes, len(addresses)))
        return [await self.send(addr, data) for addr in picked]

    def shutdown(self) -> None:
        for conn in self._connections.values():
            conn.task.cancel()
        self._connections.clear()
