"""At-least-once sender (reference ``network/src/reliable_sender.rs``).

``send`` returns a ``CancelHandler`` — a future resolved with the peer's ACK
bytes. Per-peer connection tasks reconnect with exponential backoff (200 ms,
x2, capped 60 s) and replay every un-ACKed message across reconnects
(reference ``reliable_sender.rs:131,166,185-247``). Dropping/cancelling the
handler cancels the message: it is skipped on replay and its ACK discarded
(reference ``reliable_sender.rs:175,195-197``).

Back-pressure model (deliberately tighter than the reference): ``send``
awaits capacity, and capacity is measured in LIVE (un-cancelled,
un-ACKed) messages buffered for the peer. A pump task always moves the
bounded send queue into the replay buffer — connected, disconnected, or
mid-connect — pruning cancelled messages as it goes. So:

- a SLOW live peer back-pressures its senders once ``PENDING_CAP`` live
  messages are outstanding (never dropped — the reference's
  ``reliable_sender.rs:60-72`` contract);
- a DEAD or byzantine-stalled peer cannot wedge anyone: callers that give
  up (the proposer/quorum-waiter after 2f+1 ACKs) cancel their handlers,
  which frees the buffered slots. The reference wedges in this case (its
  channel only drains while disconnected); here cancellation always
  reclaims capacity.
"""

from __future__ import annotations

import asyncio
import logging
import random
from collections import deque

from hotstuff_tpu import telemetry
from hotstuff_tpu.faultline import hooks as _faultline

from .budget import BUDGET
from .receiver import read_frame, write_frame

log = logging.getLogger("network")

QUEUE_CAPACITY = 1_000
PENDING_CAP = 1_000  # live messages buffered per peer before back-pressure
RETRY_DELAY_MS = 200
RETRY_CAP_MS = 60_000

CancelHandler = asyncio.Future  # resolves to the peer's ACK bytes


class _Connection:
    def __init__(self, address: tuple[str, int]) -> None:
        self.address = address
        self.queue: asyncio.Queue[tuple[bytes, CancelHandler]] = asyncio.Queue(
            QUEUE_CAPACITY
        )
        # Messages awaiting (re)transmission, FIFO; unbounded but pruned of
        # cancelled entries on reconnect. The LIVE count (un-cancelled,
        # un-ACKed, whether still pending or in flight on the current
        # socket) is tracked by ``self.live`` via handler done-callbacks,
        # and the pump stalls at PENDING_CAP live ones.
        self.pending: deque[tuple[bytes, CancelHandler]] = deque()
        self.live = 0
        self.capacity = asyncio.Event()
        self.capacity.set()
        self.new_work = asyncio.Event()
        self.evicted = False
        self.task = asyncio.create_task(self._keep_alive())
        self.pump_task = asyncio.create_task(self._pump())
        BUDGET.register(self)

    def evictable(self) -> bool:
        # Only a fully-drained connection may be closed: nothing queued,
        # nothing awaiting (re)transmission, nothing un-ACKed. Every
        # outstanding CancelHandler keeps the connection pinned, so the
        # at-least-once contract survives eviction.
        if self.live == 0 and self.pending:
            # live == 0 means every handler is done, and an entry sitting
            # in ``pending`` (un-transmitted, or reassembled after a
            # disconnect before its ACK) can only have completed by
            # cancellation — ACKed entries leave via the ack_loop. The
            # leftovers are all dead: without this, a cancelled message to
            # a crashed peer (whose _run never executes, so _prune never
            # runs) would pin the connection forever, exempting dead-peer
            # connections from the fd budget in exactly the storm regime
            # it exists for.
            self.pending.clear()
        return self.live == 0 and not self.pending and self.queue.empty()

    def evict(self) -> None:
        self.evicted = True
        self.task.cancel()
        self.pump_task.cancel()

    def _prune(self) -> None:
        self.pending = deque(
            (d, h) for d, h in self.pending if not h.cancelled()
        )

    def _on_handler_done(self, _fut) -> None:
        # ACKed or cancelled: either way the message stops counting against
        # the peer's live budget; wake the pump if it was stalled.
        self.live -= 1
        if self.live < PENDING_CAP:
            self.capacity.set()

    async def _pump(self) -> None:
        """Move the send queue into ``pending`` regardless of connection
        state. Stalls (propagating back-pressure to ``send``) only while
        PENDING_CAP LIVE messages are outstanding — pending OR written but
        un-ACKed — so a connected peer that reads frames without ACKing
        them is bounded exactly like a disconnected one. Completion
        callbacks (ACK or cancel) free slots and wake the pump; no
        polling."""
        while True:
            item = await self.queue.get()
            while self.live >= PENDING_CAP:
                self.capacity.clear()
                if self.live < PENDING_CAP:  # completion raced the clear
                    break
                await self.capacity.wait()
            data, handler = item
            if handler.cancelled():
                continue  # dead before it ever counted
            self.live += 1
            handler.add_done_callback(self._on_handler_done)
            self.pending.append(item)
            self.new_work.set()

    async def _keep_alive(self) -> None:
        host, port = self.address
        delay = RETRY_DELAY_MS
        try:
            while True:
                try:
                    reader, writer = await asyncio.open_connection(host, port)
                except OSError as e:
                    log.debug("retrying %s:%d in %dms: %s", host, port, delay, e)
                    await asyncio.sleep(delay / 1000)
                    delay = min(delay * 2, RETRY_CAP_MS)
                    continue
                delay = RETRY_DELAY_MS
                try:
                    await self._run(reader, writer)
                except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
                    log.debug("connection to %s:%d dropped: %s", host, port, e)
                finally:
                    writer.close()
        finally:
            BUDGET.unregister(self)

    async def _run(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._prune()
        # Sent but not yet ACKed on THIS connection; replayed on reconnect.
        inflight: deque[tuple[bytes, CancelHandler]] = deque()

        m_frames = telemetry.counter("net.frames_out")
        m_bytes = telemetry.counter("net.bytes_out")
        m_writes = telemetry.counter("net.writes")

        async def write_loop() -> None:
            while True:
                while self.pending:
                    data, handler = self.pending.popleft()
                    if handler.cancelled():
                        continue
                    inflight.append((data, handler))
                    write_frame(writer, data)
                    m_frames.inc()
                    m_bytes.inc(len(data) + 4)
                    m_writes.inc()
                    await writer.drain()
                self.new_work.clear()
                await self.new_work.wait()

        async def ack_loop() -> None:
            while True:
                ack = await read_frame(reader)  # raises on disconnect
                # Pair the ACK with the oldest live in-flight message.
                while inflight:
                    _, handler = inflight.popleft()
                    if handler.cancelled():
                        continue
                    handler.set_result(ack)
                    break

        write_task = asyncio.create_task(write_loop())
        ack_task = asyncio.create_task(ack_loop())
        try:
            done, _ = await asyncio.wait(
                {write_task, ack_task}, return_when=asyncio.FIRST_EXCEPTION
            )
            for t in done:
                t.result()  # re-raise the connection error
        finally:
            write_task.cancel()
            ack_task.cancel()
            # Neither child can run again before we await, so reassembling
            # synchronously here is race-free: un-ACKed messages precede
            # queued ones on the next connection.
            self.pending = deque([*inflight, *self.pending])
            # return_exceptions captures the CHILDREN's cancellation; if
            # the connection task itself is being cancelled (node
            # shutdown), the await re-raises OUR CancelledError — it must
            # propagate, or the task would absorb its own cancellation and
            # reconnect forever (wedging event-loop teardown).
            await asyncio.gather(write_task, ack_task, return_exceptions=True)


class ReliableSender:
    def __init__(self) -> None:
        self._connections: dict[tuple[str, int], _Connection] = {}
        self._rng = random.Random()
        self._delayed: set[asyncio.Task] = set()

    def _connection(self, address: tuple[str, int]) -> _Connection:
        conn = self._connections.get(address)
        if conn is None or conn.evicted or conn.task.done():
            conn = _Connection(address)
            self._connections[address] = conn
        return conn

    async def send(self, address: tuple[str, int], data: bytes) -> CancelHandler:
        """Queue one frame for ``address``; the returned handler resolves
        with the peer's ACK bytes (reference ``reliable_sender.rs:60-72``).

        Awaits capacity: when PENDING_CAP live messages are already
        buffered for the peer, the caller is back-pressured, never
        dropped. Cancelled handlers free capacity immediately, so only a
        slow LIVE peer (with callers awaiting its ACKs) ever delays
        anyone."""
        handler: CancelHandler = asyncio.get_running_loop().create_future()
        # Faultline link filter: a dropped reliable message models the
        # network eating the frame before any replay machinery could see
        # it — the ACK future stays pending forever, exactly what callers
        # observe from a dead peer (they cancel after their quorum).
        # Delays enqueue through a side task so the CALLER's latency and
        # back-pressure stay untouched; duplicates are a best-effort-
        # channel phenomenon and are not applied to reliable sends.
        plane = _faultline.plane
        if plane is not None:
            plan = plane.filter_send(address, data)
            if plan is not None:
                action, delay, _copies = plan
                if action == "drop":
                    return handler
                if delay > 0:

                    async def enqueue_later() -> None:
                        await asyncio.sleep(delay)
                        if handler.cancelled():
                            return
                        conn = self._connection(address)
                        await conn.queue.put((data, handler))
                        BUDGET.touch(conn)

                    task = asyncio.create_task(enqueue_later())
                    self._delayed.add(task)
                    task.add_done_callback(self._delayed.discard)
                    return handler
        conn = self._connection(address)
        await conn.queue.put((data, handler))
        BUDGET.touch(conn)
        return handler

    async def broadcast(
        self, addresses: list[tuple[str, int]], data: bytes
    ) -> list[CancelHandler]:
        return [await self.send(addr, data) for addr in addresses]

    async def lucky_broadcast(
        self, addresses: list[tuple[str, int]], data: bytes, nodes: int
    ) -> list[CancelHandler]:
        """Reliably send to ``nodes`` randomly-picked addresses (reference
        ``reliable_sender.rs:91-100``)."""
        picked = self._rng.sample(addresses, min(nodes, len(addresses)))
        return [await self.send(addr, data) for addr in picked]

    def shutdown(self) -> None:
        for conn in self._connections.values():
            conn.task.cancel()
            conn.pump_task.cancel()
        self._connections.clear()
        for task in self._delayed:
            task.cancel()
        self._delayed.clear()
