"""Network layer: framed TCP actors.

The protocol plane's transport (the "DCN plane" in TPU terms — XLA
collectives over ICI appear only inside the device crypto kernels, never for
protocol messages). Same three abstractions and wire behavior as the
reference network crate (``network/src/lib.rs:11-13``):

- ``Receiver`` + ``MessageHandler``: accept loop, one runner per connection,
  4-byte big-endian length-delimited frames, handler may write replies/ACKs
  on the same socket (reference ``network/src/receiver.rs:20-88``).
- ``SimpleSender``: best-effort, one connection task per peer, no retry
  (reference ``network/src/simple_sender.rs:22-143``).
- ``ReliableSender``: at-least-once with per-message ``CancelHandler``
  resolved by the peer's ACK; exponential-backoff reconnect with replay of
  un-ACKed messages (reference ``network/src/reliable_sender.rs:140-247``).
"""

import logging as _logging
import os as _os

from .receiver import MessageHandler, Receiver, FramedWriter, read_frame, write_frame
from .simple_sender import SimpleSender
from .reliable_sender import CancelHandler, ReliableSender

# HOTSTUFF_NET=native swaps all three abstractions for the C++ epoll
# transport (network/native/) — same APIs, same wire behavior, ~10x lower
# per-event host cost. Falls back to asyncio (with a warning) if the
# toolchain can't build/load the library, so the flag is always safe.
if _os.environ.get("HOTSTUFF_NET", "").lower() == "native":
    from . import native as _native

    if _native.available():
        Receiver = _native.NativeReceiver  # type: ignore[misc]
        SimpleSender = _native.NativeSimpleSender  # type: ignore[misc]
        ReliableSender = _native.NativeReliableSender  # type: ignore[misc]
    else:  # pragma: no cover - toolchain-dependent
        _logging.getLogger("network").warning(
            "HOTSTUFF_NET=native requested but the native transport is "
            "unavailable (g++ missing?); using the asyncio implementation"
        )

__all__ = [
    "MessageHandler",
    "Receiver",
    "FramedWriter",
    "SimpleSender",
    "ReliableSender",
    "CancelHandler",
    "read_frame",
    "write_frame",
]
