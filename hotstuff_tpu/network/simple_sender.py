"""Best-effort sender (reference ``network/src/simple_sender.rs:22-143``).

One connection task per peer holding a bounded queue; no retry — on socket
error the connection task dies and queued messages are dropped; the next
``send`` to that peer spawns a fresh connection. Replies from the peer are
read and discarded (keeps the socket's receive window drained).
"""

from __future__ import annotations

import asyncio
import logging
import random

import struct

from hotstuff_tpu import telemetry
from hotstuff_tpu.faultline import hooks as _faultline

from .budget import BUDGET
from .receiver import read_frame

log = logging.getLogger("network")

QUEUE_CAPACITY = 1_000
_LEN = struct.Struct(">I")
# Frames gathered into one write/drain round trip when the queue has a
# backlog (the asyncio analog of the native engine's writev batching).
_WRITE_BATCH = 64


class _Connection:
    """Holds PRE-FRAMED bytes: the length prefix is attached once by the
    sender (once per BROADCAST, not once per peer), and the write loop
    gathers every immediately-available frame into a single write+drain."""

    def __init__(self, address: tuple[str, int]) -> None:
        self.address = address
        self.queue: asyncio.Queue[bytes] = asyncio.Queue(QUEUE_CAPACITY)
        self.evicted = False
        self._writing = False
        self._m_frames = telemetry.counter("net.frames_out")
        self._m_bytes = telemetry.counter("net.bytes_out")
        self._m_writes = telemetry.counter("net.writes")
        self._m_drops = telemetry.counter("net.send_drops")
        self.task = asyncio.create_task(self._run())
        BUDGET.register(self)

    def evictable(self) -> bool:
        # ``_writing`` guards the frame popped from the queue but still in
        # ``drain()`` — cancelling mid-write would tear it on the wire.
        return self.queue.empty() and not self._writing

    def evict(self) -> None:
        # Best-effort channel: closing an idle connection loses nothing;
        # the owner spawns a fresh one on the next send.
        self.evicted = True
        self.task.cancel()

    async def _run(self) -> None:
        host, port = self.address
        try:
            try:
                reader, writer = await asyncio.open_connection(host, port)
            except OSError as e:
                log.debug("failed to connect to %s:%d: %s", host, port, e)
                return
            sink = asyncio.create_task(self._sink_replies(reader))
            try:
                while True:
                    data = await self.queue.get()
                    self._writing = True
                    writer.write(data)
                    nbytes = len(data)
                    # Gather the backlog: every already-queued frame rides
                    # the same drain (one flow-control round trip).
                    burst = 1
                    while burst < _WRITE_BATCH and not self.queue.empty():
                        chunk = self.queue.get_nowait()
                        writer.write(chunk)
                        nbytes += len(chunk)
                        burst += 1
                    self._m_frames.inc(burst)
                    self._m_bytes.inc(nbytes)
                    self._m_writes.inc()
                    await writer.drain()
                    self._writing = False
            except (ConnectionError, OSError) as e:
                log.debug("connection to %s:%d died: %s", host, port, e)
            finally:
                sink.cancel()
                writer.close()
        finally:
            BUDGET.unregister(self)

    async def _sink_replies(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                await read_frame(reader)
        except Exception:
            pass

    def try_send(self, data: bytes) -> bool:
        if self.evicted or self.task.done():
            return False
        try:
            self.queue.put_nowait(data)
            BUDGET.touch(self)
            return True
        except asyncio.QueueFull:
            log.warning("dropping message to %s: channel full", self.address)
            self._m_drops.inc()
            return True  # best-effort: dropped, but connection is alive


class SimpleSender:
    def __init__(self) -> None:
        self._connections: dict[tuple[str, int], _Connection] = {}
        self._rng = random.Random()

    def _send_framed(self, address: tuple[str, int], framed: bytes) -> None:
        # Faultline link filter (one module-global load when disabled):
        # the active FaultPlane may drop this frame, delay it, or fan it
        # out as duplicates — per-link, seeded, and counted.
        plane = _faultline.plane
        if plane is not None:
            plan = plane.filter_send(address, framed, payload_off=4)
            if plan is not None:
                action, delay, copies = plan
                if action == "drop":
                    return
                loop = asyncio.get_running_loop()
                for _ in range(copies):
                    loop.call_later(delay, self._deliver_framed, address, framed)
                return
        self._deliver_framed(address, framed)

    def _deliver_framed(self, address: tuple[str, int], framed: bytes) -> None:
        conn = self._connections.get(address)
        if conn is None or not conn.try_send(framed):
            conn = _Connection(address)
            self._connections[address] = conn
            conn.try_send(framed)

    def send(self, address: tuple[str, int], data: bytes) -> None:
        """Fire-and-forget one frame to ``address``."""
        self._send_framed(address, _LEN.pack(len(data)) + data)

    def broadcast(self, addresses: list[tuple[str, int]], data: bytes) -> None:
        # Shared encode: the wire frame is built ONCE and the same bytes
        # object is queued to every peer (previously each peer's write
        # loop re-attached the length prefix — one allocation+copy per
        # peer per broadcast, N² per round at committee scale).
        framed = _LEN.pack(len(data)) + data
        for addr in addresses:
            self._send_framed(addr, framed)

    def lucky_broadcast(
        self, addresses: list[tuple[str, int]], data: bytes, nodes: int
    ) -> None:
        """Send to ``nodes`` randomly-picked addresses (reference
        ``simple_sender.rs:76-85``) — the sync-retry gossip primitive."""
        picked = self._rng.sample(addresses, min(nodes, len(addresses)))
        framed = _LEN.pack(len(data)) + data
        for addr in picked:
            self._send_framed(addr, framed)

    def shutdown(self) -> None:
        for conn in self._connections.values():
            conn.task.cancel()
        self._connections.clear()
