"""ctypes binding for the native C++ DCN transport (``netcore.cpp``).

Same three abstractions as the asyncio implementation — Receiver +
MessageHandler, SimpleSender, ReliableSender — with identical wire
behavior (4-byte BE frames, handler-written ACKs, FIFO ACK pairing,
backoff replay; reference ``network/src/{receiver,simple_sender,
reliable_sender}.rs``). The hot path (socket IO, framing, reconnects)
runs on one C++ epoll thread; Python drains BATCHES of inbound events
through a packed buffer signalled by an eventfd that asyncio watches
with ``loop.add_reader``, so the per-frame Python cost is one dict
lookup and one queue put instead of asyncio's full transport/protocol
machinery (~15k events/s/core floor, docs/latency_profile.md).

Selection: ``HOTSTUFF_NET=native`` routes the package-level
``Receiver``/``SimpleSender``/``ReliableSender`` names here (see
``network/__init__``); the asyncio implementation remains the default
and the automatic fallback when the toolchain is unavailable.

Builds ``libhsnet.so`` lazily with g++ on first use (ctypes over a C
ABI — no pybind11 in this environment, per the native-code policy).
"""

from __future__ import annotations

import asyncio
import ctypes
import ipaddress
import logging
import os
import random
import socket
import struct
import subprocess
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from hotstuff_tpu.faultline import hooks as _faultline

log = logging.getLogger("network")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "netcore.cpp")
_LIB = os.path.join(_DIR, "libhsnet.so")

PENDING_CAP = 1_000  # live reliable messages per peer before back-pressure
# Per-listener budget of frames emitted by the C++ loop but not yet
# dispatched by Python: past HIGH the loop stops reading the listener's
# sockets (kernel-buffer back-pressure reaches the peer, like the asyncio
# receiver's one-frame-per-dispatch bound); once dispatch progress brings
# it back to LOW it resumes. Enforced loop-side because a local flood is
# fully in the kernel before the Python loop even runs. Bounds
# Python-side memory against a flooding peer; read at spawn time.
RECV_HIGH_WATER = 4_096
RECV_LOW_WATER = 512
# Dispatch-progress report granularity (frames per hs_net_consumed call).
_CONSUMED_BATCH = 32

# How long a failed hostname lookup suppresses further getaddrinfo
# attempts before the next send retries it. Doubles per consecutive
# failure up to the cap. Lookups run on a dedicated worker thread (the
# event loop never blocks on the resolver), so the cap can be SHORT: a
# peer whose name resolves again is back within a minute, not ten
# (round-5 advisor finding — the old 600 s cap meant a transient
# resolver outage cost a correct peer for up to 10 minutes).
_RESOLVE_RETRY_S = 15.0
_RESOLVE_RETRY_MAX_S = 60.0
# Sends parked per unresolved hostname while its lookup is in flight;
# beyond this they drop (best-effort semantics, same as a down peer).
_RESOLVE_PARK_CAP = 1024

_EV_RECV = 1
_EV_ACKED = 2
_EV_GONE = 3
_EV_VOTE_BATCH = 4
_EV_RECV_BATCH = 5

# EV_RECV_BATCH payload record header: u64 LE conn_id | u32 LE frame len
# (followed by the frame bytes). One batch event carries every frame a
# listener's connections produced during one C++ poll cycle.
_BATCH_REC = struct.Struct("<QI")

# Command-ring record layouts (hs_net_cmds_flush). Little-endian, fixed
# headers; see netcore.cpp for the authoritative spec.
_RING_LID_U64 = struct.Struct("<BQQ")  # op 1 (set_round) / 2 (consumed)
_RING_SEND_HDR = struct.Struct("<BHBI")  # op 3: port, host_len, payload_len
_RING_BCAST_HDR = struct.Struct("<BHI")  # op 4: addrs_len, payload_len
_RING_VF_HDR = struct.Struct("<BQI")  # op 5: listener_id, payload_len
_RING_REPLY_HDR = struct.Struct("<BQI")  # op 6: conn_id, payload_len
_RING_RSEND_HDR = struct.Struct("<BHBQI")  # op 7: port, host_len, msg_id, plen
_RING_OP_SET_ROUND = 1
_RING_OP_CONSUMED = 2
_RING_OP_SEND = 3
_RING_OP_BROADCAST = 4
_RING_OP_VOTE_FILTER = 5
_RING_OP_REPLY = 6
_RING_OP_SEND_RELIABLE = 7
# Payloads above this ride the direct ctypes call even when the ring is
# on: the ring buys one crossing per loop iteration, but every ring byte
# is copied twice more (Python append + C++ parse), so for bulk frames
# (dataplane batches, ~387 KB) the copies dominate the crossing saved.
# ACKs and votes/proposals sit far below it.
_RING_PAYLOAD_MAX = 64 * 1024

# Fixed Vote wire frame length (consensus/messages.py layout) — the unit
# EV_VOTE_BATCH payloads are sliced into.
VOTE_WIRE_LEN = 137

_HDR = struct.Struct("<BQQI")  # type, a, b, payload_len


def _ensure_built() -> str:
    if (
        not os.path.exists(_LIB)
        or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
    ):
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        subprocess.run(
            [
                "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                "-pthread", _SRC, "-o", tmp,
            ],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, _LIB)
    return _LIB


_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(_ensure_built())
        lib.hs_net_create.restype = ctypes.c_void_p
        lib.hs_net_create.argtypes = []
        lib.hs_net_destroy.restype = None
        lib.hs_net_destroy.argtypes = [ctypes.c_void_p]
        lib.hs_net_event_fd.restype = ctypes.c_int
        lib.hs_net_event_fd.argtypes = [ctypes.c_void_p]
        lib.hs_net_listen.restype = ctypes.c_int64
        lib.hs_net_listen.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int,
            ctypes.c_uint32, ctypes.c_uint32,
        ]
        lib.hs_net_consumed.restype = None
        lib.hs_net_consumed.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64
        ]
        lib.hs_net_set_vote_filter.restype = None
        lib.hs_net_set_vote_filter.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32
        ]
        lib.hs_net_set_round.restype = None
        lib.hs_net_set_round.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64
        ]
        lib.hs_net_broadcast.restype = None
        lib.hs_net_broadcast.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.hs_net_faults.restype = None
        lib.hs_net_faults.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32
        ]
        lib.hs_net_close_listener.restype = None
        lib.hs_net_close_listener.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.hs_net_send.restype = None
        lib.hs_net_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16,
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int, ctypes.c_uint64,
        ]
        lib.hs_net_cancel.restype = None
        lib.hs_net_cancel.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.hs_net_pause_listener.restype = None
        lib.hs_net_pause_listener.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int
        ]
        lib.hs_net_reply.restype = None
        lib.hs_net_reply.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32
        ]
        lib.hs_net_drain.restype = ctypes.c_int64
        lib.hs_net_drain.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32
        ]
        lib.hs_net_stats.restype = None
        lib.hs_net_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.hs_net_stats_ex.restype = ctypes.c_int
        lib.hs_net_stats_ex.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int
        ]
        lib.hs_net_cmds_flush.restype = ctypes.c_int64
        lib.hs_net_cmds_flush.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32
        ]
        # Make the hs_net_* boundary instrumentable: an active profiler
        # session wraps these entry points to count calls + wall ns (the
        # per-call ctypes/GIL toll); zero cost otherwise.
        from hotstuff_tpu.telemetry import profiler as _pyprof

        _pyprof.register_ctypes_lib(
            lib,
            "hs_net",
            [
                "hs_net_send", "hs_net_broadcast", "hs_net_set_round",
                "hs_net_consumed", "hs_net_reply", "hs_net_cancel",
                "hs_net_drain", "hs_net_set_vote_filter",
                "hs_net_cmds_flush",
            ],
        )
        _lib = lib
    return _lib


# hs_net_stats_ex field order (new fields append; indices never move).
# The last six are the poll-loop timing account (cumulative; snapshot
# deltas give rates/means): time inside epoll_wait vs dispatching
# events, and how long commands sat in the queue before the loop
# serviced them — the C++ half of the ctypes-boundary latency the
# sampling profiler measures on the Python side.
STATS_FIELDS = (
    "pending", "inflight", "cancelled", "out_conns", "in_conns",
    "votes_batched", "votes_dropped", "votes_dropped_dup",
    "frames_rx", "bytes_rx", "frames_tx", "bytes_tx",
    "writev_calls", "send_drops", "faults_dropped", "faults_delayed",
    "loop_polls", "poll_ns", "dispatch_ns",
    "cmds_serviced", "cmd_service_ns", "cmd_service_max_ns",
    # Batched-ingress account — dotted names so the stats collector
    # surfaces them as net.native.ingress.* gauges (docs/telemetry.md):
    # reads = recv() syscalls, frames = frames via EV_RECV_BATCH,
    # batches = batch events (frames/batches = frames per wakeup).
    "ingress.reads", "ingress.frames", "ingress.batches",
)

# Rate limit for the loop-side drop warnings (satellite: silent filtering
# must be diagnosable without a debugger, but a flood of drops must not
# become a flood of log lines).
_DROP_WARN_INTERVAL_S = 10.0


class NativeTransport:
    """Process-wide bridge to one C++ epoll context.

    Listener registrations and outgoing connections live for the process;
    the eventfd reader rebinds to whichever event loop is currently
    running (tests run many short loops)."""

    _instance: "NativeTransport | None" = None

    def __init__(self) -> None:
        self._lib = _load()
        self._ctx = self._lib.hs_net_create()
        self._efd = self._lib.hs_net_event_fd(self._ctx)
        self._buf = ctypes.create_string_buffer(4 * 1024 * 1024)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._next_msg_id = 1
        # listener_id -> (queue of (conn_id, frame), dispatch task owner)
        self._listeners: dict[int, "NativeReceiver"] = {}
        self._acks: dict[int, asyncio.Future] = {}
        # Bumped whenever the reader rebinds to a new event loop: senders
        # compare against it to reset per-peer back-pressure counters
        # whose futures were dropped with the old loop.
        self.generation = 0
        self._resolved: dict[str, str] = {}  # hostname -> IPv4 literal
        # hostname -> (monotonic deadline to retry a failed lookup,
        # backoff used for the NEXT failure). Negative results must not
        # be permanent — a resolver down at boot would cost a correct
        # peer for the whole process lifetime — but retries back off so
        # a persistently-bad name isn't looked up on every send.
        self._resolve_retry_at: dict[str, tuple[float, float]] = {}
        # getaddrinfo runs on this worker, NEVER on the event-loop thread
        # (a dropping resolver blocks ~10 s per call — with the short
        # 60 s retry cap that would stall the loop every minute). Sends
        # to a not-yet-resolved name park here and are flushed by the
        # worker (hs_net_send is thread-safe: the C++ command queue is
        # mutex-guarded).
        self._resolve_lock = threading.Lock()
        self._resolve_pool: ThreadPoolExecutor | None = None
        self._parked_sends: dict[str, list[tuple[int, bytes, bool, int]]] = {}
        # Drop diagnosability: last counters the rate-limited warning saw,
        # and the next time _on_events may poll stats for it.
        self._drop_warn_seen = {"filtered": 0, "send_drops": 0}
        self._drop_warn_at = 0.0
        self._drop_poll_at = time.monotonic() + _DROP_WARN_INTERVAL_S
        # Command ring: loop-thread callers append fixed-layout records
        # here instead of making one ctypes crossing (with its GIL
        # release/reacquire) per command; ONE hs_net_cmds_flush per
        # event-loop iteration ships the lot. At N=200 the per-round
        # hs_net_set_round/hs_net_send crossings alone were 85% of the
        # vote edge (results/profile-attribution-200.json) — the ring
        # collapses ~N crossings per round into one. Off-loop callers
        # (resolver worker, telemetry threads) keep the direct calls.
        self._ring_enabled = os.environ.get("HOTSTUFF_CMD_RING", "1") != "0"
        self._ring = bytearray()
        self._ring_records = 0
        self._ring_scheduled = False
        self._ring_metrics_live = None
        # Plain lifetime totals (tests/diagnostics; the telemetry mirror
        # only records when the plane is enabled).
        self.ring_flushes = 0
        self.ring_total_records = 0
        # Telemetry: the engine's counters surface as gauges behind the
        # registry's one snapshot call (collector polls stats() lazily).
        from hotstuff_tpu import telemetry

        telemetry.register_collector("net.native", self.stats)

    @classmethod
    def get(cls) -> "NativeTransport":
        if cls._instance is None:
            cls._instance = cls()
        inst = cls._instance
        inst._bind_loop()
        return inst

    @classmethod
    def get_if_live(cls) -> "NativeTransport | None":
        """The process transport if one exists, WITHOUT binding it to a
        loop — safe to call outside any event loop (tests/diagnostics)."""
        return cls._instance

    def _bind_loop(self) -> None:
        loop = asyncio.get_running_loop()
        if self._loop is loop:
            return
        prev = self._loop
        if prev is not None and not prev.is_closed():
            try:
                prev.remove_reader(self._efd)
            except Exception:  # noqa: BLE001 — loop may be tearing down
                pass
        # Records parked behind a dead loop's never-run flush callback
        # must not be lost (tests run many short loops): ship them now.
        if self._ring_records:
            self._flush_cmd_ring()
        # A previous loop is gone (tests): its futures can never be
        # awaited again. Cancel their ids in the C++ layer — otherwise the
        # orphaned inflight entries would FIFO-consume ACKs meant for new
        # messages on the same connection — and drop them here.
        for mid in self._acks:
            self._lib.hs_net_cancel(self._ctx, ctypes.c_uint64(mid))
        self._acks.clear()
        self.generation += 1
        self._loop = loop
        loop.add_reader(self._efd, self._on_events)

    # -- called by senders/receivers --

    def alloc_msg_id(self) -> int:
        mid = self._next_msg_id
        self._next_msg_id += 1
        return mid

    # -- command ring --

    def _ring_push(self, rec: bytes) -> bool:
        """Append one record to the command ring and make sure a flush is
        scheduled for the next event-loop iteration. Returns False when
        the caller must fall back to its direct ctypes call: ring
        disabled, no bound loop, or the calling thread is not the loop's
        (the ring buffer is loop-thread-only by design — a lock here
        would reintroduce the contention the ring removes)."""
        loop = self._loop
        if not self._ring_enabled or loop is None or loop.is_closed():
            return False
        try:
            if asyncio.get_running_loop() is not loop:
                return False
        except RuntimeError:
            return False
        self._ring += rec
        self._ring_records += 1
        if not self._ring_scheduled:
            self._ring_scheduled = True
            # call_soon lands AFTER the currently-draining ready batch:
            # every command appended during this loop iteration rides the
            # same flush.
            loop.call_soon(self._flush_cmd_ring)
        return True

    def _flush_cmd_ring(self) -> None:
        self._ring_scheduled = False
        n = self._ring_records
        if not n:
            return
        buf = bytes(self._ring)
        self._ring.clear()
        self._ring_records = 0
        self._lib.hs_net_cmds_flush(self._ctx, buf, len(buf))
        self.ring_flushes += 1
        self.ring_total_records += n
        from hotstuff_tpu import telemetry

        if self._ring_metrics_live != telemetry.enabled():
            self._ring_metrics_live = telemetry.enabled()
            self._g_ring_depth = telemetry.gauge("net.native.cmd_ring_depth")
            self._m_ring_flushes = telemetry.counter(
                "net.native.cmd_ring.flushes"
            )
            self._m_ring_records = telemetry.counter(
                "net.native.cmd_ring.records"
            )
        self._g_ring_depth.set(n)
        self._m_ring_flushes.inc()
        self._m_ring_records.inc(n)

    def _resolve_fast(self, host: str) -> str | None:
        """Non-blocking resolution: IPv4 literals and cached names only.
        Returns the literal, or None when the name is unknown (caller
        decides whether to park the send and kick the worker)."""
        cached = self._resolved.get(host)
        if cached is not None:
            return cached
        try:
            ipaddress.IPv4Address(host)
        except ValueError:
            return None
        self._resolved[host] = host
        return host

    def _resolve_blocking(self, host: str) -> str | None:
        """One getaddrinfo for ``host``, honoring the negative-cache
        backoff. BLOCKING — runs on the resolver worker (or synchronously
        at listen/startup time, where a stalled loop cannot exist yet).
        Failed lookups are cached only for ``_RESOLVE_RETRY_S`` seconds
        (doubling per consecutive failure, capped at 60 s): a transient
        resolver outage must not permanently cost a correct peer."""
        fast = self._resolve_fast(host)
        if fast is not None:
            return fast
        deadline, _ = self._resolve_retry_at.get(host, (0.0, 0.0))
        if time.monotonic() < deadline:
            return None  # negative entry still fresh: don't re-query
        try:
            infos = socket.getaddrinfo(
                host, None, socket.AF_INET, socket.SOCK_STREAM
            )
            addr = infos[0][4][0]
        except OSError as exc:
            _, backoff = self._resolve_retry_at.get(
                host, (0.0, _RESOLVE_RETRY_S)
            )
            log.warning(
                "native transport cannot resolve %r (%s): "
                "dropping sends to it for the next %ds", host, exc,
                int(backoff),
            )
            self._resolve_retry_at[host] = (
                time.monotonic() + backoff,
                min(backoff * 2, _RESOLVE_RETRY_MAX_S),
            )
            return None
        self._resolved[host] = addr
        self._resolve_retry_at.pop(host, None)  # reset failure backoff
        return addr

    def _park_send(
        self, host: str, port: int, data: bytes, reliable: bool, msg_id: int
    ) -> None:
        """Queue a send behind its hostname's in-flight lookup and make
        sure a worker lookup is scheduled. The worker flushes (or drops)
        the parked sends when the lookup settles."""
        with self._resolve_lock:
            parked = self._parked_sends.get(host)
            first = parked is None
            if first:
                parked = self._parked_sends[host] = []
            if len(parked) < _RESOLVE_PARK_CAP:
                parked.append((port, data, reliable, msg_id))
            if self._resolve_pool is None:
                self._resolve_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="hsnet-dns"
                )
        if first:
            self._resolve_pool.submit(self._resolve_and_flush, host)

    def _resolve_and_flush(self, host: str) -> None:
        # Worker thread. A still-backing-off name resolves to None and
        # its parked sends drop — observably a down peer, exactly the
        # asyncio transport's retry-forever behavior from the caller's
        # side (reliable ACK futures stay pending until cancelled).
        try:
            addr = self._resolve_blocking(host)
        except Exception:  # noqa: BLE001 — never kill the worker
            log.exception("resolver worker failed for %r", host)
            addr = None
        with self._resolve_lock:
            parked = self._parked_sends.pop(host, [])
        if addr is None:
            return
        for port, data, reliable, msg_id in parked:
            self._lib.hs_net_send(
                self._ctx, addr.encode(), ctypes.c_uint16(port),
                data, len(data), int(reliable), ctypes.c_uint64(msg_id),
            )

    def listen(
        self, receiver: "NativeReceiver", host: str, port: int, auto_ack: bool
    ) -> int:
        # Startup path: blocking resolution is fine (no live loop traffic
        # behind us) and listen errors must be synchronous.
        resolved = self._resolve_blocking(host)
        if resolved is None:
            raise OSError(f"cannot resolve listen address {host!r}")
        lid = self._lib.hs_net_listen(
            self._ctx, resolved.encode(), ctypes.c_uint16(port),
            int(auto_ack),
            ctypes.c_uint32(RECV_HIGH_WATER), ctypes.c_uint32(RECV_LOW_WATER),
        )
        if lid < 0:
            raise OSError(-lid, os.strerror(-lid))
        self._listeners[lid] = receiver
        return lid

    def consumed(self, lid: int, n: int) -> None:
        if self._ring_push(_RING_LID_U64.pack(_RING_OP_CONSUMED, lid, n)):
            return
        self._lib.hs_net_consumed(
            self._ctx, ctypes.c_uint64(lid), ctypes.c_uint64(n)
        )

    def close_listener(self, lid: int) -> None:
        self._listeners.pop(lid, None)
        self._lib.hs_net_close_listener(self._ctx, ctypes.c_uint64(lid))

    def pause_listener(self, lid: int, paused: bool) -> None:
        self._lib.hs_net_pause_listener(
            self._ctx, ctypes.c_uint64(lid), int(paused)
        )

    def set_vote_filter(self, lid: int, authors: list[bytes]) -> None:
        """Push the committee table down to the C++ vote pre-stage."""
        packed = b"".join(authors)
        assert len(packed) == 32 * len(authors), "authors must be 32-byte keys"
        if self._ring_push(
            _RING_VF_HDR.pack(_RING_OP_VOTE_FILTER, lid, len(packed)) + packed
        ):
            return
        self._lib.hs_net_set_vote_filter(
            self._ctx, ctypes.c_uint64(lid), packed, len(authors)
        )

    def set_round(self, lid: int, round_: int) -> None:
        if self._ring_push(_RING_LID_U64.pack(_RING_OP_SET_ROUND, lid, round_)):
            return
        self._lib.hs_net_set_round(
            self._ctx, ctypes.c_uint64(lid), ctypes.c_uint64(round_)
        )

    def set_faults(self, rules, seed: int = 0) -> None:
        """Install the engine's test-only per-peer fault table
        (``hs_net_faults``): ``rules`` maps ``(host, port)`` to
        ``(drop_ppm, delay_ms)``; an empty mapping clears it. Applies to
        best-effort frames only — the chaos plane's hook into the native
        egress path (broadcast coalescing, writev pump, vote fan-in)."""
        tokens = [f"seed:{seed}"] if seed else []
        for (host, port), (drop_ppm, delay_ms) in rules.items():
            resolved = self._resolve_fast(host) or host
            tokens.append(f"{resolved}:{port}:{int(drop_ppm)}:{int(delay_ms)}")
        spec = " ".join(tokens).encode()
        self._lib.hs_net_faults(self._ctx, spec, len(spec))

    def stats(self) -> dict[str, int]:
        """Loop-thread state snapshot (tests / telemetry / ops). One call
        exports every engine counter; also drives the rate-limited drop
        warnings (any periodic reader — telemetry emitter, event pump —
        keeps drop diagnosability alive)."""
        out = (ctypes.c_uint64 * len(STATS_FIELDS))()
        n = self._lib.hs_net_stats_ex(self._ctx, out, len(STATS_FIELDS))
        result = {name: out[i] for i, name in enumerate(STATS_FIELDS[:n])}
        self._warn_on_drops(result)
        return result

    def _warn_on_drops(self, stats: dict[str, int]) -> None:
        """Log (rate-limited) when the vote pre-stage starts FILTERING
        votes (seat/round rejections — dedup of identical resends is
        routine and only reported alongside) or per-peer back-pressure
        starts dropping best-effort sends. Without this, a misconfigured
        committee table or saturated peer silently eats frames that only
        a debugger attached to the C++ loop would reveal."""
        filtered = stats.get("votes_dropped", 0) - stats.get(
            "votes_dropped_dup", 0
        )
        send_drops = stats.get("send_drops", 0)
        seen = self._drop_warn_seen
        d_filtered = filtered - seen["filtered"]
        d_sends = send_drops - seen["send_drops"]
        if d_filtered <= 0 and d_sends <= 0:
            return
        now = time.monotonic()
        if now - self._drop_warn_at < _DROP_WARN_INTERVAL_S:
            return
        self._drop_warn_at = now
        seen["filtered"] = filtered
        seen["send_drops"] = send_drops
        if d_filtered > 0:
            log.warning(
                "native vote pre-stage filtered %d vote frame(s) since the "
                "last report (unknown seat or out-of-window round; %d "
                "identical-resend dedups total): check committee table / "
                "round sync if unexpected",
                d_filtered,
                stats.get("votes_dropped_dup", 0),
            )
        if d_sends > 0:
            log.warning(
                "native transport dropped %d best-effort send(s) at "
                "per-peer back-pressure caps since the last report "
                "(slow or dead peer)",
                d_sends,
            )

    def send(
        self, address: tuple[str, int], data: bytes,
        reliable: bool = False, msg_id: int = 0,
    ) -> None:
        host, port = address
        resolved = self._resolve_fast(host)
        if resolved is None:
            # Unknown hostname: park behind a worker-thread lookup (the
            # event loop must never block on getaddrinfo). If the name
            # stays bad the parked sends drop — observably a down peer;
            # reliable ACK futures stay pending until the caller cancels.
            self._park_send(host, port, data, reliable, msg_id)
            return
        if len(data) <= _RING_PAYLOAD_MAX:
            # Small frames ride the command ring — best-effort AND
            # reliable. Reliable sends were originally kept direct
            # ("proposals are one frame per round"), but the dataplane's
            # batch dissemination is reliable at rate: at large-frame
            # load the per-send crossing + loop wake was the measured
            # gap vs asyncio (benchmark/netplane_frames.py). The ACK
            # future is registered by the caller before this returns,
            # and ring flushes run before any subsequent drain of the
            # ACK event, so pairing is unchanged. Bulk frames above
            # _RING_PAYLOAD_MAX keep the direct call (copy-dominated).
            rhost = resolved.encode()
            if not reliable and msg_id == 0:
                rec = (
                    _RING_SEND_HDR.pack(
                        _RING_OP_SEND, port, len(rhost), len(data)
                    )
                    + rhost
                    + data
                )
            else:
                rec = (
                    _RING_RSEND_HDR.pack(
                        _RING_OP_SEND_RELIABLE, port, len(rhost),
                        msg_id, len(data),
                    )
                    + rhost
                    + data
                )
            if self._ring_push(rec):
                return
        self._lib.hs_net_send(
            self._ctx, resolved.encode(), ctypes.c_uint16(port),
            data, len(data), int(reliable), ctypes.c_uint64(msg_id),
        )

    def broadcast(
        self, addresses: list[tuple[str, int]], data: bytes
    ) -> None:
        """Best-effort fan-out: ONE command into the loop thread; the C++
        side builds the frame once and queues it per peer."""
        tokens = []
        for host, port in addresses:
            resolved = self._resolve_fast(host)
            if resolved is None:
                self._park_send(host, port, data, False, 0)
                continue
            tokens.append(f"{resolved}:{port}")
        if not tokens:
            return
        packed = " ".join(tokens).encode()
        # Ring record caps the address list at u16 (fits ~2,900 resolved
        # IPv4 peers); anything larger takes the direct call.
        if len(packed) <= 0xFFFF and self._ring_push(
            _RING_BCAST_HDR.pack(_RING_OP_BROADCAST, len(packed), len(data))
            + packed
            + data
        ):
            return
        self._lib.hs_net_broadcast(
            self._ctx, packed, len(packed), data, len(data)
        )

    def cancel(self, msg_id: int) -> None:
        self._lib.hs_net_cancel(self._ctx, ctypes.c_uint64(msg_id))

    def reply(self, conn_id: int, data: bytes) -> None:
        # ACKs are tiny and per-frame — the highest-frequency crossing on
        # a busy receiver; ride the ring (one flush per loop iteration).
        if len(data) <= _RING_PAYLOAD_MAX and self._ring_push(
            _RING_REPLY_HDR.pack(_RING_OP_REPLY, conn_id, len(data)) + data
        ):
            return
        self._lib.hs_net_reply(
            self._ctx, ctypes.c_uint64(conn_id), data, len(data)
        )

    # -- event pump --

    def _on_events(self) -> None:
        try:
            os.read(self._efd, 8)  # clear the signal
        except BlockingIOError:
            pass
        # Periodic drop check even when nothing else reads stats(): one
        # loop-thread round trip (microseconds) at most once per warning
        # interval, piggybacked on event activity.
        now = time.monotonic()
        if now >= self._drop_poll_at:
            self._drop_poll_at = now + _DROP_WARN_INTERVAL_S
            self.stats()
        while True:
            n = self._lib.hs_net_drain(self._ctx, self._buf, len(self._buf))
            if n < 0:
                # One event larger than the buffer: grow to fit and retry.
                self._buf = ctypes.create_string_buffer(-n)
                continue
            if n == 0:
                break
            view = memoryview(self._buf)[:n]
            off = 0
            while off < n:
                etype, a, b, plen = _HDR.unpack_from(view, off)
                off += _HDR.size
                payload = bytes(view[off : off + plen])
                off += plen
                if etype == _EV_RECV:
                    receiver = self._listeners.get(a)
                    if receiver is not None:
                        receiver._enqueue(b, payload)
                elif etype == _EV_RECV_BATCH:
                    receiver = self._listeners.get(a)
                    if receiver is not None:
                        receiver._enqueue_frames(b, payload)
                elif etype == _EV_VOTE_BATCH:
                    receiver = self._listeners.get(a)
                    if receiver is not None:
                        receiver._enqueue_votes(b, payload)
                elif etype == _EV_ACKED:
                    fut = self._acks.pop(a, None)
                    if fut is not None and not fut.done():
                        fut.set_result(payload)
                elif etype == _EV_GONE and b == 0:
                    # conn_id 0 marks the LISTENER itself gone (an
                    # add-listener stranded by engine shutdown closed the
                    # fd loop-side): drop the phantom id so Python stops
                    # tracking a listener that can never emit again.
                    self._listeners.pop(a, None)
                # _EV_GONE with a real conn_id: inbound connection
                # closed — nothing to do; receivers are connectionless
                # from Python's view.


class _NativeFramedWriter:
    """Reply channel handed to ``MessageHandler.dispatch`` — writes ACKs
    back on the inbound connection (via the C++ loop)."""

    __slots__ = ("_transport", "_conn_id")

    def __init__(self, transport: NativeTransport, conn_id: int) -> None:
        self._transport = transport
        self._conn_id = conn_id

    async def send(self, payload: bytes) -> None:
        self._transport.reply(self._conn_id, payload)


class _AckedWriter:
    """Writer for auto-ack listeners: the transport already ACKed on
    frame arrival, so the handler's own ``writer.send(b"Ack")`` must
    become a no-op (a second ACK would mispair the sender's FIFO ACK
    accounting). Handlers only ever reply with the literal ACK frame."""

    __slots__ = ()

    async def send(self, payload: bytes) -> None:
        pass


class NativeReceiver:
    """Drop-in for ``network.Receiver``: one dispatch task drains the
    inbound frame queue sequentially (actor semantics preserved).

    With a vote pre-stage configured (``configure_vote_prestage``), the
    C++ loop delivers pre-validated votes as aggregated batches; the
    dispatch task hands each batch to ``handler.dispatch_votes`` (falling
    back to per-frame ``dispatch`` for handlers without one).

    General inbound frames arrive the same way (EV_RECV_BATCH: one
    aggregated event per poll cycle, conn ids preserved per record); a
    handler exposing ``dispatch_frames(pairs)`` receives the whole
    ``[(writer, frame), ...]`` list per wakeup, others degrade to
    per-frame ``dispatch``."""

    def __init__(
        self, address: tuple[str, int], handler, auto_ack: bool = False
    ) -> None:
        self.address = address
        self.handler = handler
        self.auto_ack = auto_ack
        self._transport: NativeTransport | None = None
        self._lid: int | None = None
        # ("frame", conn_id, frame) | ("votes", count, packed_frames)
        self._queue: asyncio.Queue[tuple[str, int, bytes]] = asyncio.Queue()
        self._task: asyncio.Task | None = None

    @classmethod
    async def spawn(
        cls, address: tuple[str, int], handler, auto_ack: bool = False
    ) -> "NativeReceiver":
        self = cls(address, handler, auto_ack)
        self._transport = NativeTransport.get()
        host, port = address
        self._lid = self._transport.listen(self, host, port, auto_ack)
        self._task = asyncio.create_task(self._dispatch_loop())
        log.debug(
            "native listener on %s:%d%s",
            host, port, " (auto-ack)" if auto_ack else "",
        )
        return self

    def _enqueue(self, conn_id: int, frame: bytes) -> None:
        self._queue.put_nowait(("frame", conn_id, frame))

    def _enqueue_votes(self, count: int, packed: bytes) -> None:
        self._queue.put_nowait(("votes", count, packed))

    def _enqueue_frames(self, count: int, packed: bytes) -> None:
        """One poll cycle's aggregated general-ingress frames
        (EV_RECV_BATCH): ``packed`` is ``count`` records of
        ``[u64 conn_id | u32 len | frame]``. One queue put per cycle."""
        self._queue.put_nowait(("frames", count, packed))

    def configure_vote_prestage(self, authors: list[bytes]) -> None:
        """Enable the C++ vote pre-stage with the committee's 32-byte
        public keys (seat table). Votes are then length-validated,
        seat-checked, round-gated and deduped on the loop thread and
        delivered as aggregated batches — a filter only; full Signature
        verification stays in the consensus core."""
        self._transport.set_vote_filter(self._lid, authors)

    def set_round(self, round_: int) -> None:
        """Advance the pre-stage's stale-round cutoff (call on round
        advance; monotonic)."""
        self._transport.set_round(self._lid, round_)

    async def _dispatch_loop(self) -> None:
        acked = _AckedWriter()
        undisclosed = 0  # dispatched frames not yet reported to the loop
        while True:
            if undisclosed and (
                undisclosed >= _CONSUMED_BATCH or self._queue.empty()
            ):
                self._transport.consumed(self._lid, undisclosed)
                undisclosed = 0
            kind, a, payload = await self._queue.get()
            if kind == "votes":
                frames = [
                    payload[i : i + VOTE_WIRE_LEN]
                    for i in range(0, len(payload), VOTE_WIRE_LEN)
                ]
                dispatch_votes = getattr(self.handler, "dispatch_votes", None)
                try:
                    if dispatch_votes is not None:
                        await dispatch_votes(frames)
                    else:
                        # Handler without a batch path: degrade to the
                        # per-frame contract (votes only arrive on
                        # auto-ack listeners, so the writer is a no-op).
                        for frame in frames:
                            await self.handler.dispatch(acked, frame)
                except Exception:
                    log.exception(
                        "vote batch handler error (native receiver %s)",
                        self.address,
                    )
                undisclosed += len(frames)
                continue
            if kind == "frames":
                # Aggregated general ingress: decode the cycle's records,
                # hand the handler the whole list per wakeup
                # (``dispatch_frames``; per-frame ``dispatch`` fallback).
                batch: list[tuple[int, bytes]] = []
                off = 0
                end = len(payload)
                while off + 12 <= end:
                    cid, flen = _BATCH_REC.unpack_from(payload, off)
                    off += 12
                    batch.append((cid, payload[off : off + flen]))
                    off += flen
                plane = _faultline.plane
                if plane is not None:
                    kept: list[tuple[int, bytes]] = []
                    for cid, frame in batch:
                        plan = plane.filter_recv(self.address)
                        if plan is not None:
                            f_action, f_delay = plan
                            if f_delay > 0:
                                await asyncio.sleep(f_delay)
                            if f_action == "drop":
                                continue
                        kept.append((cid, frame))
                    batch = kept
                if batch:
                    writers: dict[int, object] = {}
                    pairs = []
                    for cid, frame in batch:
                        if self.auto_ack:
                            writer = acked
                        else:
                            writer = writers.get(cid)
                            if writer is None:
                                writer = writers[cid] = _NativeFramedWriter(
                                    self._transport, cid
                                )
                        pairs.append((writer, frame))
                    dispatch_frames = getattr(
                        self.handler, "dispatch_frames", None
                    )
                    try:
                        if dispatch_frames is not None:
                            await dispatch_frames(pairs)
                        else:
                            for writer, frame in pairs:
                                await self.handler.dispatch(writer, frame)
                    except Exception:
                        log.exception(
                            "frame batch handler error (native receiver %s)",
                            self.address,
                        )
                # The C++ budget charged every frame, dropped or not.
                undisclosed += a
                continue
            conn_id = a
            # Faultline ingress filter (``side: "recv"`` rules). The C++
            # loop already ACKed auto-ack frames on arrival, so a drop
            # here models app-level ingress loss (frame read off the
            # wire, then eaten before dispatch).
            plane = _faultline.plane
            if plane is not None:
                plan = plane.filter_recv(self.address)
                if plan is not None:
                    f_action, f_delay = plan
                    if f_delay > 0:
                        await asyncio.sleep(f_delay)
                    if f_action == "drop":
                        undisclosed += 1
                        continue
            writer = (
                acked if self.auto_ack
                else _NativeFramedWriter(self._transport, conn_id)
            )
            try:
                await self.handler.dispatch(writer, payload)
            except Exception:
                log.exception("handler error (native receiver %s)", self.address)
            undisclosed += 1

    async def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if self._transport is not None and self._lid is not None:
            self._transport.close_listener(self._lid)
            self._lid = None


class NativeSimpleSender:
    """Drop-in for ``network.SimpleSender`` (best-effort, fire-and-forget)."""

    def __init__(self) -> None:
        self._rng = random.Random()

    def send(self, address: tuple[str, int], data: bytes) -> None:
        transport = NativeTransport.get()
        plane = _faultline.plane
        if plane is not None:
            plan = plane.filter_send(address, data)
            if plan is not None:
                action, delay, copies = plan
                if action == "drop":
                    return
                loop = asyncio.get_running_loop()
                for _ in range(copies):
                    loop.call_later(delay, transport.send, address, data)
                return
        transport.send(address, data, reliable=False)

    def broadcast(self, addresses: list[tuple[str, int]], data: bytes) -> None:
        # Coalesced: one command into the loop thread, one frame build.
        transport = NativeTransport.get()
        plane = _faultline.plane
        if plane is not None:
            # Per-link faults split the fan-out: untouched peers keep the
            # coalesced single-command path; dropped peers vanish; delayed
            # or duplicated peers are re-issued individually.
            clean: list[tuple[str, int]] = []
            loop = None
            for addr in addresses:
                plan = plane.filter_send(addr, data)
                if plan is None:
                    clean.append(addr)
                    continue
                action, delay, copies = plan
                if action == "drop":
                    continue
                if loop is None:
                    loop = asyncio.get_running_loop()
                for _ in range(copies):
                    loop.call_later(delay, transport.send, addr, data)
            if clean:
                transport.broadcast(clean, data)
            return
        transport.broadcast(addresses, data)

    def lucky_broadcast(
        self, addresses: list[tuple[str, int]], data: bytes, nodes: int
    ) -> None:
        picked = self._rng.sample(addresses, min(nodes, len(addresses)))
        self.broadcast(picked, data)

    def shutdown(self) -> None:
        pass  # connections are owned by the process-wide transport


class NativeReliableSender:
    """Drop-in for ``network.ReliableSender``: ``send`` returns a future
    resolved with the peer's ACK bytes; cancellation propagates to the
    C++ layer (skipped on replay, ACK discarded). Back-pressure matches
    the asyncio implementation: at PENDING_CAP live (un-ACKed,
    un-cancelled) messages for a peer, ``send`` awaits capacity."""

    def __init__(self) -> None:
        self._rng = random.Random()
        self._live: dict[tuple[str, int], int] = {}
        self._capacity: dict[tuple[str, int], asyncio.Event] = {}
        self._generation = -1  # transport loop generation of the counters

    def _cap_event(self, address: tuple[str, int]) -> asyncio.Event:
        ev = self._capacity.get(address)
        if ev is None:
            ev = asyncio.Event()
            ev.set()
            self._capacity[address] = ev
        return ev

    async def send(self, address: tuple[str, int], data: bytes):
        transport = NativeTransport.get()
        if self._generation != transport.generation:
            # The transport rebound to a new event loop and dropped our
            # in-flight futures (their done-callbacks can never run on
            # the dead loop): rebuild the back-pressure state so orphaned
            # messages don't consume PENDING_CAP capacity forever.
            self._generation = transport.generation
            self._live.clear()
            self._capacity.clear()
        ev = self._cap_event(address)
        while self._live.get(address, 0) >= PENDING_CAP:
            ev.clear()
            if self._live.get(address, 0) < PENDING_CAP:
                break
            await ev.wait()
        # Faultline link filter: drops leave the ACK future pending
        # forever (what a dead peer looks like — callers cancel after
        # their quorum); delays reschedule the engine handoff without
        # touching the caller. Duplicates are not applied to reliable
        # sends (FIFO ACK pairing would mispair).
        delay = 0.0
        plane = _faultline.plane
        if plane is not None:
            plan = plane.filter_send(address, data)
            if plan is not None:
                action, delay, _copies = plan
                if action == "drop":
                    return asyncio.get_running_loop().create_future()
        msg_id = transport.alloc_msg_id()
        handler: asyncio.Future = asyncio.get_running_loop().create_future()
        self._live[address] = self._live.get(address, 0) + 1

        def on_done(fut: asyncio.Future, *, _addr=address, _mid=msg_id) -> None:
            self._live[_addr] = max(0, self._live.get(_addr, 0) - 1)
            if self._live[_addr] < PENDING_CAP:
                self._cap_event(_addr).set()
            if fut.cancelled():
                transport.cancel(_mid)
                transport._acks.pop(_mid, None)

        handler.add_done_callback(on_done)
        transport._acks[msg_id] = handler
        if delay > 0:
            asyncio.get_running_loop().call_later(
                delay, transport.send, address, data, True, msg_id
            )
        else:
            transport.send(address, data, reliable=True, msg_id=msg_id)
        return handler

    async def broadcast(self, addresses: list[tuple[str, int]], data: bytes):
        return [await self.send(addr, data) for addr in addresses]

    async def lucky_broadcast(
        self, addresses: list[tuple[str, int]], data: bytes, nodes: int
    ):
        picked = self._rng.sample(addresses, min(nodes, len(addresses)))
        return [await self.send(addr, data) for addr in picked]

    def shutdown(self) -> None:
        pass  # connections are owned by the process-wide transport


def available() -> bool:
    """True when the native transport can be built/loaded on this host."""
    try:
        _load()
        return True
    except Exception:  # noqa: BLE001 — any toolchain failure means "no"
        return False
