// Native DCN transport: epoll event loop + length-delimited framing +
// reliable-delivery bookkeeping, exposed over a C ABI for ctypes.
//
// Why: the protocol plane's measured floor is Python asyncio's event
// machinery (~15k events/s/core — docs/latency_profile.md). This moves
// the per-event hot path (socket IO, frame reassembly, ACK pairing,
// reconnect/replay) into one C++ epoll thread; Python sees BATCHES of
// events through a packed buffer + eventfd, so its per-frame cost drops
// to a dict lookup and a queue put.
//
// Semantics mirror the asyncio implementation (and the reference's
// network crate, network/src/{receiver,simple_sender,reliable_sender}.rs):
//   - frames: 4-byte big-endian length prefix (LengthDelimitedCodec)
//   - simple sends: best-effort, connection dies on error, next send
//     reconnects; replies read and discarded
//   - reliable sends: per-message id resolved by the peer's ACK bytes
//     (FIFO pairing, cancelled ids skipped), exponential backoff
//     200ms..2x..60s, un-ACKed frames replayed across reconnects
//   - receivers: inbound frames are events; replies (ACKs) are written
//     back on the same connection by command
//
// Threading: ONE loop thread per context. Python talks to it through a
// mutex-guarded command queue (woken by an eventfd) and reads results
// from a mutex-guarded event buffer (signalled by a second eventfd that
// asyncio watches with add_reader). All fds are nonblocking.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <stdlib.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <sys/uio.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr uint32_t MAX_FRAME = 64u * 1024u * 1024u;
constexpr size_t SIMPLE_QUEUE_CAP = 1000;   // frames; matches Python sender
// Per-wake read cap for inbound connections: without it one flooding
// peer's handle_inbound drains its entire kernel buffer in a single
// epoll round, starving other connections and letting a flood blow past
// the listener-pause back-pressure before the pause command is serviced.
// Level-triggered epoll re-fires for the remainder.
// Sized above the dataplane's bulk batch frames (~387 KB): a budget
// below one frame guarantees TWO epoll wakes per frame plus a partial-
// frame memmove on every erase, which is where the native plane lost to
// asyncio on large frames (ROADMAP 3a).
constexpr size_t READ_BATCH_CAP = 512 * 1024;
constexpr size_t READ_CHUNK = 64 * 1024;
constexpr int RETRY_DELAY_MS = 200;
constexpr int RETRY_CAP_MS = 60000;

uint64_t now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000 + uint64_t(ts.tv_nsec) / 1000000;
}

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

void frame_append(std::string& out, const uint8_t* data, uint32_t len) {
  char hdr[4] = {char(len >> 24), char(len >> 16), char(len >> 8), char(len)};
  out.append(hdr, 4);
  out.append(reinterpret_cast<const char*>(data), len);
}

// Event types surfaced to Python (see hs_net_drain record layout).
enum : uint8_t {
  EV_RECV = 1,    // a=listener_id, b=conn_id, payload=frame
  EV_ACKED = 2,   // a=msg_id, payload=ACK bytes
  EV_GONE = 3,    // a=listener_id, b=conn_id (inbound connection closed)
  // a=listener_id, b=vote count, payload=count fixed-layout vote frames
  // (the vote pre-stage): ONE Python wakeup per poll cycle for the whole
  // fan-in, not one per frame.
  EV_VOTE_BATCH = 4,
  // a=listener_id, b=frame count, payload=count records of
  //   [u64 LE conn_id | u32 LE len | len bytes]
  // — the general-ingress form of the vote pre-stage: every frame a
  // listener's connections produced during one poll cycle rides ONE
  // aggregated event, so the Python side pays one wakeup + one queue put
  // per cycle instead of one per frame (the small-frame ingress floor,
  // ROADMAP item 3 / PR 14's residual `ingress_wait`).
  EV_RECV_BATCH = 5,
};

// Fixed wire layout of a consensus Vote (consensus/messages.py):
//   u8 tag=1 | 32B block hash | u64 LE round | 32B author | 64B signature
// The pre-stage decodes round/author straight from these offsets; any
// frame that is not exactly this shape flows through the normal EV_RECV
// path and Python's full decoder.
constexpr size_t VOTE_WIRE_LEN = 137;
constexpr uint8_t VOTE_TAG = 1;
constexpr size_t VOTE_ROUND_OFF = 33;
constexpr size_t VOTE_AUTHOR_OFF = 41;
// Mirrors Core.MAX_ROUND_LOOKAHEAD: votes fabricated for far-future
// rounds are dropped before they can allocate dedupe state.
constexpr uint64_t VOTE_ROUND_LOOKAHEAD = 1000;

struct Event {
  uint8_t type;
  uint64_t a, b;
  std::string payload;
};

enum : uint8_t {
  CMD_SEND_SIMPLE = 1,   // addr, payload
  CMD_SEND_RELIABLE = 2, // addr, msg_id, payload
  CMD_CANCEL = 3,        // msg_id
  CMD_REPLY = 4,         // conn_id, payload
  CMD_ADD_LISTENER = 5,  // listener fd already bound+listening
  CMD_STOP = 6,
  CMD_CLOSE_LISTENER = 7,  // close listener + its inbound connections
  CMD_PAUSE_LISTENER = 8,  // stop reading inbound conns (back-pressure)
  CMD_RESUME_LISTENER = 9,
  CMD_STATS = 10,  // fill a StatsReq on the loop thread (tests/ops)
  CMD_CONSUMED = 11,  // Python dispatched n frames of a listener
  CMD_SET_VOTE_FILTER = 12,  // listener_id, payload = n*32 author keys
  CMD_SET_ROUND = 13,        // listener_id, count = stale-round cutoff
  CMD_BROADCAST = 14,        // host = "ip:port ip:port ...", payload once
  CMD_SET_FAULTS = 15,       // payload = fault spec (hs_net_faults)
};

// Loop-thread state snapshot, serviced as a command so no lock covers the
// hot maps. The requesting thread blocks until the loop fills it.
struct StatsReq {
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  uint64_t pending = 0;    // frames queued, not yet written
  uint64_t inflight = 0;   // written, awaiting ACK
  uint64_t cancelled = 0;  // parked cancel markers
  uint64_t out_conns = 0;
  uint64_t in_conns = 0;
  uint64_t votes_batched = 0;  // vote frames delivered via EV_VOTE_BATCH
  uint64_t votes_dropped = 0;  // vote frames dropped by the pre-stage
  // Extended fields (hs_net_stats_ex; the legacy 7-slot hs_net_stats
  // ignores them).
  uint64_t votes_dropped_dup = 0;  // subset of votes_dropped: identical resends
  uint64_t frames_rx = 0;   // inbound frames parsed (incl. pre-staged votes)
  uint64_t bytes_rx = 0;    // inbound bytes read off sockets
  uint64_t frames_tx = 0;   // outbound frames handed to the kernel
  uint64_t bytes_tx = 0;    // outbound bytes accepted by the kernel
  uint64_t writev_calls = 0;  // writev syscalls (frames_tx/writev_calls =
                              // the egress coalescing factor)
  uint64_t send_drops = 0;  // best-effort sends dropped at a peer's
                            // SIMPLE_QUEUE_CAP back-pressure bound
  uint64_t faults_dropped = 0;  // frames eaten by the hs_net_faults table
  uint64_t faults_delayed = 0;  // frames held by the hs_net_faults table
  // Poll-loop timing (the C++ side of every trace edge): where the loop
  // thread's wall time goes, and how long commands sit in the queue
  // before the loop services them. Cumulative ns + counts — readers
  // derive means/rates from snapshot deltas (telemetry collector).
  uint64_t loop_polls = 0;          // epoll_wait calls
  uint64_t poll_ns = 0;             // wall ns inside epoll_wait (idle+block)
  uint64_t dispatch_ns = 0;         // wall ns handling events/commands/flushes
  uint64_t cmds_serviced = 0;       // commands drained by run_commands
  uint64_t cmd_service_ns = 0;      // sum of enqueue->service latency
  uint64_t cmd_service_max_ns = 0;  // worst single command latency
  // Batched-ingress account (net.native.ingress.* in the catalog):
  // reads = successful recv() syscalls on inbound conns, frames = frames
  // delivered via EV_RECV_BATCH, batches = EV_RECV_BATCH events emitted.
  // frames/batches is the frames-per-wakeup coalescing factor;
  // frames/reads the parse yield per syscall.
  uint64_t ingress_reads = 0;
  uint64_t ingress_frames = 0;
  uint64_t ingress_batches = 0;
};

struct Command {
  uint8_t type;
  std::string host;
  uint16_t port = 0;
  uint64_t id = 0;  // msg_id / conn_id / listener_id
  int fd = -1;
  bool flag = false;  // ADD_LISTENER: auto_ack
  uint64_t count = 0;   // CONSUMED: frames; ADD_LISTENER: high<<32|low
  void* ptr = nullptr;  // STATS: StatsReq*
  uint64_t enq_ns = 0;  // stamped by push_cmd (cmd-queue service latency)
  std::string payload;
};

// A peer that sends frames but never reads its ACKs would grow the
// reply buffer without bound (a byzantine-facing listener must not leak
// memory on hostile traffic): past this cap the connection is dropped.
constexpr size_t IN_OUTBUF_CAP = 1u << 20;

struct InConn {
  int fd;
  uint64_t id;
  uint64_t listener_id;
  std::string inbuf;
  std::string outbuf;  // replies (ACKs)
  bool auto_ack = false;
  bool dead = false;
  bool paused = false;  // reads suspended; kernel buffer back-pressures peer
};

struct PendingMsg {
  uint64_t msg_id;  // 0 for simple frames
  std::string frame;  // already length-prefixed
};

struct OutConn {
  uint64_t key_hash;
  std::string host;
  uint16_t port;
  bool reliable;
  int fd = -1;
  bool connecting = false;
  std::string inbuf;   // ACK frames (reliable) / discarded replies (simple)
  std::string outbuf;  // bytes in the kernel-bound staging buffer
  // reliable: frames not yet written on the CURRENT socket (replayed);
  // simple: frames waiting for the connection to come up.
  std::deque<PendingMsg> pending;
  // reliable only: written on this socket, awaiting ACK (FIFO).
  std::deque<PendingMsg> inflight;
  int backoff_ms = RETRY_DELAY_MS;
  uint64_t next_retry_ms = 0;  // 0 = connect now
};

struct Listener {
  int fd = -1;
  bool auto_ack = false;
  bool cmd_paused = false;    // explicit hs_net_pause_listener
  bool flood_paused = false;  // outstanding-event budget exceeded
  // EV_RECV events emitted but not yet reported dispatched by Python.
  // The budget must live HERE, not in Python: the sender writes to the
  // kernel synchronously, so a flood is fully read and emitted before
  // the Python loop ever runs — a Python-side pause is always too late.
  uint64_t outstanding = 0;
  uint32_t high = 0;  // 0 = unbounded (no budget)
  uint32_t low = 0;
  bool paused() const { return cmd_paused || flood_paused; }

  // -- vote pre-stage (hs_net_set_vote_filter) --
  // The pre-stage is a FILTER, never a trust root: everything it admits
  // is re-checked (round, authority, signature) by the consensus core;
  // it may only drop frames the core would provably drop cheaply —
  // unknown seats, stale/far-future rounds, and byte-identical resends
  // of a seat's latest vote.
  bool vf_enabled = false;
  std::unordered_map<std::string, uint32_t> vf_seats;  // 32B key -> seat
  uint64_t vf_round = 0;  // stale cutoff, pushed down on round advance
  // round -> seat -> latest admitted vote frame (dedupe by identity);
  // ordered by round so advancing the cutoff GCs with an erase-range.
  std::map<uint64_t, std::unordered_map<uint32_t, std::string>> vf_seen;
  // Admitted votes accumulated during the current poll cycle, flushed as
  // ONE EV_VOTE_BATCH per cycle.
  std::string vote_buf;
  uint64_t vote_count = 0;

  // General inbound frames accumulated during the current poll cycle,
  // flushed as ONE EV_RECV_BATCH per cycle (records carry the conn_id so
  // reply channels survive aggregation).
  std::string ingress_buf;
  uint64_t ingress_count = 0;
};

// A single EV_RECV_BATCH payload is flushed early past this size so the
// Python drain buffer doesn't have to grow toward the per-cycle inbound
// bound (conns x READ_BATCH_CAP); the event stays "one per cycle" in the
// common case and degrades to a handful under extreme bulk.
constexpr size_t INGRESS_FLUSH_CAP = 2u * 1024u * 1024u;

// Test-only per-peer fault injection (hs_net_faults): chaos scenarios
// must also exercise the native egress path (broadcast coalescing, the
// writev pump, the vote fan-in it feeds) under loss and latency. Rules
// apply to BEST-EFFORT frames only (simple sends and broadcasts): the
// reliable path's replay machinery gives injected loss there different
// semantics, and the Python fault plane already covers it.
struct PeerFault {
  uint32_t drop_ppm = 0;   // parts-per-million drop probability
  uint32_t delay_ms = 0;   // fixed hold before the frame enters the queue
  uint64_t rng = 0;        // per-peer xorshift stream (seeded, replayable)
};

struct DelayedFrame {
  std::string host;
  uint16_t port;
  std::string frame;  // already length-prefixed
};

struct AddrKey {
  std::string host;
  uint16_t port;
  bool reliable;
  bool operator==(const AddrKey& o) const {
    return port == o.port && reliable == o.reliable && host == o.host;
  }
};
struct AddrKeyHash {
  size_t operator()(const AddrKey& k) const {
    return std::hash<std::string>()(k.host) ^ (size_t(k.port) << 1) ^
           (k.reliable ? 0x9e3779b9u : 0);
  }
};

class NetCore {
 public:
  NetCore() {
    epfd_ = epoll_create1(EPOLL_CLOEXEC);
    cmd_efd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    out_efd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = TAG_CMD;
    epoll_ctl(epfd_, EPOLL_CTL_ADD, cmd_efd_, &ev);
    thread_ = std::thread([this] { loop(); });
  }

  // Destroy contract: no other thread may be INSIDE any hs_net_* call
  // (including the synchronous hs_net_stats/hs_net_stats_ex) when
  // destroy begins — ctypes callers must sequence destroy after their
  // last call returns. The narrower race — a push_cmd that took cmd_mu_
  // BEFORE destroy but would have signalled the eventfd after the
  // destructor closed it — is closed structurally: wake() runs while
  // cmd_mu_ is still held (see push_cmd), and the destructor itself
  // acquires cmd_mu_ below, so any in-flight enqueue has fully finished
  // (wake included) before CMD_STOP is even queued, and cmd_efd_ is
  // closed only after thread_.join().
  ~NetCore() {
    {
      std::lock_guard<std::mutex> g(cmd_mu_);
      Command c;
      c.type = CMD_STOP;
      commands_.push_back(std::move(c));
      wake();
    }
    thread_.join();
    for (auto& [id, c] : in_conns_) close(c.fd);
    for (auto& [k, c] : out_conns_) {
      if (c.fd >= 0) close(c.fd);
    }
    for (auto& [id, l] : listeners_) close(l.fd);
    close(epfd_);
    close(cmd_efd_);
    close(out_efd_);
  }

  int out_event_fd() const { return out_efd_; }

  // Called from the Python thread: bind+listen synchronously (errors are
  // immediate), hand the fd to the loop. With auto_ack, the loop thread
  // writes an "Ack" frame back the moment a frame arrives — the sender's
  // back-pressure signal no longer waits for the receiving PROCESS to be
  // scheduled (handlers ACK before processing anyway, so semantics
  // match; reference consensus.rs:144-153, mempool.rs:224-237).
  int64_t listen_on(const char* host, uint16_t port, bool auto_ack,
                    uint32_t high_water, uint32_t low_water) {
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (fd < 0) return -errno;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      close(fd);
      return -EINVAL;
    }
    if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0 ||
        ::listen(fd, 1024) < 0) {
      int e = errno;
      close(fd);
      return -e;
    }
    uint64_t id = next_listener_id_++;
    Command c;
    c.type = CMD_ADD_LISTENER;
    c.fd = fd;
    c.id = id;
    c.flag = auto_ack;
    c.count = (uint64_t(high_water) << 32) | uint64_t(low_water);
    if (!push_cmd(std::move(c))) {
      // Loop already shut down: the listener would never be registered.
      close(fd);
      return -ESHUTDOWN;
    }
    return int64_t(id);
  }

  // Returns false once the loop thread has stopped accepting commands
  // (CMD_STOP processed): a command pushed after that would never be
  // serviced, which matters for synchronous requests (CMD_STATS) whose
  // caller blocks on completion.
  //
  // wake() runs UNDER cmd_mu_, not after it: released-then-wake left a
  // window where a thread enqueuing just before destroy could write to a
  // cmd_efd_ the destructor had already closed (or the kernel had
  // reused). With the signal inside the critical section, the destructor
  // — which must take cmd_mu_ to enqueue CMD_STOP — cannot proceed until
  // any in-flight enqueue+wake has fully completed.
  bool push_cmd(Command&& c) {
    c.enq_ns = now_ns();
    std::lock_guard<std::mutex> g(cmd_mu_);
    if (!accepting_) return false;
    commands_.push_back(std::move(c));
    wake();
    return true;
  }

  // Bulk enqueue for the command ring (hs_net_cmds_flush): a whole
  // event-loop iteration's commands take ONE mutex acquisition and ONE
  // eventfd wake instead of one ctypes crossing + lock + wake each.
  // Same cmd_mu_ contract and enq_ns stamping as push_cmd, so the
  // cmd_service_* counters price ring-delivered commands identically.
  bool push_cmds(std::deque<Command>&& cmds) {
    uint64_t t = now_ns();
    for (auto& c : cmds) c.enq_ns = t;
    std::lock_guard<std::mutex> g(cmd_mu_);
    if (!accepting_) return false;
    for (auto& c : cmds) commands_.push_back(std::move(c));
    wake();
    return true;
  }

  // Drain events into a packed buffer:
  //   [u8 type][u64 a][u64 b][u32 len][len bytes] ...
  // Returns bytes written (0 = nothing pending).
  int64_t drain(uint8_t* buf, uint32_t cap) {
    std::lock_guard<std::mutex> g(ev_mu_);
    size_t off = 0;
    while (!events_.empty()) {
      Event& e = events_.front();
      size_t need = 1 + 8 + 8 + 4 + e.payload.size();
      if (need > cap && off == 0) {
        // A single event larger than the caller's buffer: report the
        // required size as a negative count so Python can grow and
        // retry (frames go up to MAX_FRAME).
        return -int64_t(need);
      }
      if (off + need > cap) break;
      buf[off++] = e.type;
      memcpy(buf + off, &e.a, 8);
      off += 8;
      memcpy(buf + off, &e.b, 8);
      off += 8;
      uint32_t len = uint32_t(e.payload.size());
      memcpy(buf + off, &len, 4);
      off += 4;
      memcpy(buf + off, e.payload.data(), len);
      off += len;
      events_.pop_front();
    }
    if (events_.empty()) {
      // All consumed: clear the coalescing flag so the next emit
      // re-signals the eventfd (the caller loops on drain until 0, so
      // partial drains need no re-arm).
      out_signaled_.store(false, std::memory_order_release);
    }
    return int64_t(off);
  }

 private:
  static constexpr uint64_t TAG_CMD = ~0ull;
  // epoll tags: listeners get 1<<62 | idx; inbound conns 1<<61 | id;
  // outbound conns 1<<60 | key-slot.
  static constexpr uint64_t TAG_LISTENER = 1ull << 62;
  static constexpr uint64_t TAG_IN = 1ull << 61;
  static constexpr uint64_t TAG_OUT = 1ull << 60;

  // Both signals are coalesced through an atomic flag: a burst of
  // commands (or events) costs ONE eventfd syscall, not one per item.
  void wake() {
    if (!cmd_signaled_.exchange(true, std::memory_order_acq_rel)) {
      uint64_t one = 1;
      (void)!write(cmd_efd_, &one, 8);
    }
  }

  void signal_out() {
    if (!out_signaled_.exchange(true, std::memory_order_acq_rel)) {
      uint64_t one = 1;
      (void)!write(out_efd_, &one, 8);
    }
  }

  void emit(Event&& e) {
    {
      std::lock_guard<std::mutex> g(ev_mu_);
      events_.push_back(std::move(e));
    }
    signal_out();
  }

  void loop() {
    std::vector<epoll_event> evs(256);
    while (!stop_) {
      int timeout = next_timeout();
      uint64_t t_poll = now_ns();
      int n = epoll_wait(epfd_, evs.data(), int(evs.size()), timeout);
      uint64_t t_wake = now_ns();
      loop_polls_++;
      poll_ns_ += t_wake - t_poll;
      uint64_t now = now_ms();
      for (int i = 0; i < n; i++) {
        uint64_t tag = evs[i].data.u64;
        uint32_t flags = evs[i].events;
        if (tag == TAG_CMD) {
          uint64_t junk;
          while (read(cmd_efd_, &junk, 8) == 8) {
          }
          // Clear BEFORE swapping the queue: a producer enqueueing after
          // the swap sees the flag false and re-signals.
          cmd_signaled_.store(false, std::memory_order_release);
          run_commands();
        } else if (tag & TAG_LISTENER) {
          accept_all(tag & ~TAG_LISTENER);
        } else if (tag & TAG_IN) {
          handle_inbound(tag & ~TAG_IN, flags);
        } else if (tag & TAG_OUT) {
          handle_outbound(tag & ~TAG_OUT, flags);
        }
      }
      flush_vote_batches();
      flush_ingress_batches();
      flush_delayed_frames(now);
      // Reconnect timers: disconnected reliable connections redial on
      // their backoff schedule whether or not traffic is queued (the
      // reference's keep_alive loop does the same).
      for (auto& [key, c] : out_conns_) {
        if (c.fd < 0 && c.reliable && c.next_retry_ms <= now) {
          start_connect(c);
        }
      }
      dispatch_ns_ += now_ns() - t_wake;
    }
    // Stop accepting, then complete any synchronous requests that were
    // enqueued before the flag flipped — without this a caller blocked
    // in hs_net_stats would wait forever once the loop thread exits.
    std::deque<Command> stranded;
    {
      std::lock_guard<std::mutex> g(cmd_mu_);
      accepting_ = false;
      stranded.swap(commands_);
    }
    for (auto& c : stranded) {
      if (c.type == CMD_STATS) {
        auto* s = static_cast<StatsReq*>(c.ptr);
        std::lock_guard<std::mutex> g(s->mu);
        s->done = true;  // zeros: the loop is gone, nothing is live
        s->cv.notify_one();
      } else if (c.type == CMD_ADD_LISTENER && c.fd >= 0) {
        // listen_on bound it; nobody else will close it. Its caller
        // already holds a "valid" listener id, so closing the fd alone
        // would leave Python tracking a phantom listener forever. Emit
        // an EV_GONE with conn_id 0 — the "listener itself is gone"
        // marker (real inbound conn ids start at 1) — so the wrapper
        // drops the id from its table. The event buffer and out_efd_
        // outlive the loop thread (closed only in the destructor), so
        // a caller still draining picks it up.
        close(c.fd);
        emit(Event{EV_GONE, c.id, 0, {}});
      }
    }
  }

  int next_timeout() {
    uint64_t now = now_ms();
    int64_t best = -1;
    for (auto& [key, c] : out_conns_) {
      if (c.fd < 0 && c.reliable) {
        int64_t d = int64_t(c.next_retry_ms) - int64_t(now);
        if (d < 0) d = 0;
        if (best < 0 || d < best) best = d;
      }
    }
    if (!delayed_frames_.empty()) {
      int64_t d = int64_t(delayed_frames_.begin()->first) - int64_t(now);
      if (d < 0) d = 0;
      if (best < 0 || d < best) best = d;
    }
    return int(best);
  }

  void run_commands() {
    std::deque<Command> cmds;
    {
      std::lock_guard<std::mutex> g(cmd_mu_);
      cmds.swap(commands_);
    }
    if (!cmds.empty()) {
      // Queue-service latency: how long each command waited between the
      // caller's push_cmd and this drain — the ctypes boundary's loop-
      // side half (the Python side is accounted by the sampling
      // profiler's ctypes wrappers).
      uint64_t t_service = now_ns();
      for (auto& c : cmds) {
        uint64_t waited = t_service > c.enq_ns ? t_service - c.enq_ns : 0;
        cmd_service_ns_ += waited;
        if (waited > cmd_service_max_ns_) cmd_service_max_ns_ = waited;
      }
      cmds_serviced_ += cmds.size();
    }
    for (auto& c : cmds) {
      switch (c.type) {
        case CMD_STOP:
          stop_ = true;
          break;
        case CMD_ADD_LISTENER: {
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.u64 = TAG_LISTENER | c.id;
          Listener& l = listeners_[c.id];
          l.fd = c.fd;
          l.auto_ack = c.flag;
          l.high = uint32_t(c.count >> 32);
          l.low = uint32_t(c.count & 0xffffffffu);
          epoll_ctl(epfd_, EPOLL_CTL_ADD, c.fd, &ev);
          break;
        }
        case CMD_CLOSE_LISTENER: {
          auto it = listeners_.find(c.id);
          if (it != listeners_.end()) {
            epoll_ctl(epfd_, EPOLL_CTL_DEL, it->second.fd, nullptr);
            close(it->second.fd);
            listeners_.erase(it);
          }
          std::vector<uint64_t> doomed;
          for (auto& [cid, conn] : in_conns_) {
            if (conn.listener_id == c.id) doomed.push_back(cid);
          }
          for (uint64_t cid : doomed) {
            auto cit = in_conns_.find(cid);
            if (cit != in_conns_.end()) {
              close(cit->second.fd);
              in_conns_.erase(cit);
            }
          }
          break;
        }
        case CMD_SEND_SIMPLE:
          send_simple(c.host, c.port, c.payload);
          break;
        case CMD_BROADCAST:
          broadcast_simple(c.host, c.payload);
          break;
        case CMD_SET_FAULTS:
          set_faults(c.payload);
          break;
        case CMD_SET_VOTE_FILTER: {
          auto it = listeners_.find(c.id);
          if (it != listeners_.end()) {
            Listener& l = it->second;
            l.vf_seats.clear();
            l.vf_seen.clear();
            for (size_t i = 0; i + 32 <= c.payload.size(); i += 32) {
              l.vf_seats.emplace(c.payload.substr(i, 32), uint32_t(i / 32));
            }
            l.vf_enabled = !l.vf_seats.empty();
          }
          break;
        }
        case CMD_SET_ROUND: {
          auto it = listeners_.find(c.id);
          if (it != listeners_.end()) {
            Listener& l = it->second;
            if (c.count > l.vf_round) {
              l.vf_round = c.count;
              // GC dedupe state for rounds now below the cutoff.
              l.vf_seen.erase(l.vf_seen.begin(),
                              l.vf_seen.lower_bound(c.count));
            }
          }
          break;
        }
        case CMD_SEND_RELIABLE:
          send_reliable(c.host, c.port, c.id, c.payload);
          break;
        case CMD_CANCEL: {
          // Reclaim immediately instead of parking the id: queued frames
          // for a permanently-down peer are only pruned in pump_out,
          // which never runs while disconnected — meanwhile the Python
          // side releases its back-pressure slot on cancellation and
          // keeps queueing, so pending/cancelled_ would grow without
          // bound (one proposal+vote per round per crashed peer). Erase
          // the frame from every pending queue now; only messages
          // already WRITTEN on a live socket (inflight) still need the
          // cancelled_ marker for FIFO ACK pairing. A cancel racing an
          // already-drained ACK matches neither and is dropped outright.
          // msg_ids are unique: stop at the first hit (found in pending
          // implies not inflight and vice versa).
          bool found_pending = false;
          bool still_inflight = false;
          for (auto& [key, oc] : out_conns_) {
            if (!oc.reliable) continue;
            for (auto it = oc.pending.begin(); it != oc.pending.end(); ++it) {
              if (it->msg_id == c.id) {
                oc.pending.erase(it);
                found_pending = true;
                break;
              }
            }
            if (found_pending) break;
            for (auto& m : oc.inflight) {
              if (m.msg_id == c.id) {
                still_inflight = true;
                break;
              }
            }
            if (still_inflight) break;
          }
          if (still_inflight) cancelled_.insert(c.id);
          break;
        }
        case CMD_PAUSE_LISTENER:
        case CMD_RESUME_LISTENER: {
          auto it = listeners_.find(c.id);
          if (it != listeners_.end()) {
            it->second.cmd_paused = (c.type == CMD_PAUSE_LISTENER);
            apply_listener_pause(c.id, it->second);
          }
          break;
        }
        case CMD_CONSUMED: {
          auto it = listeners_.find(c.id);
          if (it != listeners_.end()) {
            Listener& l = it->second;
            l.outstanding -= std::min(l.outstanding, c.count);
            if (l.flood_paused && l.outstanding <= l.low) {
              l.flood_paused = false;
              apply_listener_pause(c.id, l);
            }
          }
          break;
        }
        case CMD_STATS: {
          auto* s = static_cast<StatsReq*>(c.ptr);
          for (auto& [key, oc] : out_conns_) {
            s->pending += oc.pending.size();
            s->inflight += oc.inflight.size();
          }
          s->cancelled = cancelled_.size();
          s->out_conns = out_conns_.size();
          s->in_conns = in_conns_.size();
          s->votes_batched = votes_batched_;
          s->votes_dropped = votes_dropped_;
          s->votes_dropped_dup = votes_dropped_dup_;
          s->frames_rx = frames_rx_;
          s->bytes_rx = bytes_rx_;
          s->frames_tx = frames_tx_;
          s->bytes_tx = bytes_tx_;
          s->writev_calls = writev_calls_;
          s->send_drops = send_drops_;
          s->faults_dropped = faults_dropped_;
          s->faults_delayed = faults_delayed_;
          s->loop_polls = loop_polls_;
          s->poll_ns = poll_ns_;
          s->dispatch_ns = dispatch_ns_;
          s->cmds_serviced = cmds_serviced_;
          s->cmd_service_ns = cmd_service_ns_;
          s->cmd_service_max_ns = cmd_service_max_ns_;
          s->ingress_reads = ingress_reads_;
          s->ingress_frames = ingress_frames_;
          s->ingress_batches = ingress_batches_;
          {
            // notify under the lock: after the unlock the waiter may
            // (spurious wakeup) observe done and destroy the
            // stack-allocated request, leaving notify_one dangling.
            std::lock_guard<std::mutex> g(s->mu);
            s->done = true;
            s->cv.notify_one();
          }
          break;
        }
        case CMD_REPLY: {
          auto it = in_conns_.find(c.id);
          if (it != in_conns_.end() && !it->second.dead) {
            frame_append(it->second.outbuf,
                         reinterpret_cast<const uint8_t*>(c.payload.data()),
                         uint32_t(c.payload.size()));
            flush_inbound(it->second);
          }
          break;
        }
      }
    }
  }

  // ---- inbound ----

  // Sync every inbound connection's epoll interest with the listener's
  // effective pause state. While paused no socket is read, so the kernel
  // buffer fills and TCP flow control reaches the peer — the same bound
  // the asyncio receiver gets from reading one frame per dispatch.
  // Level-triggered epoll re-fires EPOLLIN on resume for buffered bytes.
  void apply_listener_pause(uint64_t listener_id, Listener& l) {
    bool pause = l.paused();
    for (auto& [cid, conn] : in_conns_) {
      if (conn.listener_id != listener_id || conn.paused == pause) continue;
      conn.paused = pause;
      epoll_event ev{};
      ev.events = (pause ? 0u : uint32_t(EPOLLIN)) |
                  (conn.outbuf.empty() ? 0u : uint32_t(EPOLLOUT));
      ev.data.u64 = TAG_IN | cid;
      epoll_ctl(epfd_, EPOLL_CTL_MOD, conn.fd, &ev);
    }
  }

  void accept_all(uint64_t listener_id) {
    auto lit = listeners_.find(listener_id);
    if (lit == listeners_.end()) return;
    int lfd = lit->second.fd;
    while (true) {
      int fd = accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK | SOCK_CLOEXEC);
      if (fd < 0) break;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      uint64_t id = next_conn_id_++;
      InConn& c = in_conns_[id];
      c.fd = fd;
      c.id = id;
      c.listener_id = listener_id;
      c.auto_ack = lit->second.auto_ack;
      c.paused = lit->second.paused();
      epoll_event ev{};
      ev.events = c.paused ? 0u : uint32_t(EPOLLIN);
      ev.data.u64 = TAG_IN | id;
      epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
    }
  }

  void drop_inbound(uint64_t id) {
    auto it = in_conns_.find(id);
    if (it == in_conns_.end()) return;
    emit(Event{EV_GONE, it->second.listener_id, id, {}});
    close(it->second.fd);
    in_conns_.erase(it);
  }

  void handle_inbound(uint64_t id, uint32_t flags) {
    auto it = in_conns_.find(id);
    if (it == in_conns_.end()) return;
    InConn& c = it->second;
    if (flags & (EPOLLERR | EPOLLHUP)) {
      drop_inbound(id);
      return;
    }
    if (flags & EPOLLIN) {
      // Read everything available, REMEMBERING eof/error instead of
      // acting on it: when a one-shot peer's final frame and its FIN
      // coalesce into one epoll wake (routine on loopback), dropping
      // the connection before parsing would silently discard that
      // frame. Parse first, drop after.
      bool conn_gone = false;
      size_t got = 0;
      while (got < READ_BATCH_CAP) {
        // Read straight into inbuf's tail — staging through a stack
        // buffer costs an extra pass over every received byte, which
        // dominates at bulk-frame sizes.
        size_t old = c.inbuf.size();
        c.inbuf.resize(old + READ_CHUNK);
        ssize_t r = read(c.fd, &c.inbuf[old], READ_CHUNK);
        if (r > 0) {
          c.inbuf.resize(old + size_t(r));
          got += size_t(r);
          bytes_rx_ += uint64_t(r);
          ingress_reads_++;
        } else {
          c.inbuf.resize(old);
          if (r == 0 || (errno != EAGAIN && errno != EWOULDBLOCK))
            conn_gone = true;
          break;
        }
      }
      // Reassemble frames, charging each against the listener's
      // outstanding-event budget: past high-water, reads stop until
      // Python reports dispatch progress (CMD_CONSUMED). Frames already
      // buffered in inbuf still parse — the bound is high + one read
      // batch, never the whole flood.
      Listener* l = nullptr;
      auto lit = listeners_.find(c.listener_id);
      if (lit != listeners_.end()) l = &lit->second;
      size_t off = 0;
      while (c.inbuf.size() - off >= 4) {
        const uint8_t* p = reinterpret_cast<const uint8_t*>(c.inbuf.data()) + off;
        uint32_t len = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                       (uint32_t(p[2]) << 8) | uint32_t(p[3]);
        if (len > MAX_FRAME) {
          drop_inbound(id);
          return;
        }
        if (c.inbuf.size() - off - 4 < len) break;
        frames_rx_++;
        bool charge = true;
        if (l != nullptr && l->vf_enabled && len == VOTE_WIRE_LEN &&
            uint8_t(c.inbuf[off + 4]) == VOTE_TAG) {
          charge = prestage_vote(*l, c.inbuf.data() + off + 4);
        } else if (l != nullptr) {
          // Accumulate into the listener's per-cycle batch instead of
          // emitting per frame: the whole cycle's ingress costs Python
          // one wakeup (flush_ingress_batches, same shape as votes).
          char rec[12];
          memcpy(rec, &id, 8);        // u64 LE conn_id (header struct <QI)
          memcpy(rec + 8, &len, 4);   // u32 LE frame length
          l->ingress_buf.append(rec, 12);
          l->ingress_buf.append(c.inbuf.data() + off + 4, len);
          l->ingress_count++;
          ingress_frames_++;
          if (l->ingress_buf.size() >= INGRESS_FLUSH_CAP) {
            flush_ingress(c.listener_id, *l);
          }
        } else {
          emit(Event{EV_RECV, c.listener_id, id,
                     c.inbuf.substr(off + 4, len)});
        }
        if (c.auto_ack) {
          // ACK every frame — including pre-stage drops: the asyncio
          // receiver ACKs before its (Python-side) drop too, so sender
          // back-pressure accounting is transport-independent.
          frame_append(c.outbuf, reinterpret_cast<const uint8_t*>("Ack"), 3);
        }
        off += 4 + len;
        if (charge && l != nullptr && l->high != 0) {
          l->outstanding++;
          if (!l->flood_paused && l->outstanding >= l->high) {
            l->flood_paused = true;
            apply_listener_pause(c.listener_id, *l);
          }
        }
      }
      if (off) c.inbuf.erase(0, off);
      if (conn_gone) {
        drop_inbound(id);  // frames above were parsed first
        return;
      }
      if (!c.outbuf.empty()) {
        flush_inbound(c);
        return;  // flush_inbound may have dropped the connection
      }
    }
    if (flags & EPOLLOUT) flush_inbound(c);
  }

  // Classify one vote frame (VOTE_WIRE_LEN bytes at ``frame``) against
  // the listener's committee table. Admitted votes accumulate in the
  // listener's per-cycle batch buffer; returns true iff the frame was
  // admitted (and should charge the outstanding-event budget). Drops are
  // exactly the core's cheap pre-verification drops: unknown seat, round
  // below the pushed-down cutoff or beyond the lookahead bound, and a
  // byte-identical resend of the seat's latest admitted vote. A DIFFERENT
  // payload for an occupied seat always passes through — spoof/
  // equivocation arbitration (individual verification, re-seat, ejection)
  // stays in the core, where the Signature semantics live.
  bool prestage_vote(Listener& l, const char* frame) {
    uint64_t round;
    memcpy(&round, frame + VOTE_ROUND_OFF, 8);  // wire is little-endian
    auto seat_it = l.vf_seats.find(std::string(frame + VOTE_AUTHOR_OFF, 32));
    if (seat_it == l.vf_seats.end() || round < l.vf_round ||
        round > l.vf_round + VOTE_ROUND_LOOKAHEAD) {
      votes_dropped_++;
      return false;
    }
    auto& seat_map = l.vf_seen[round];
    auto prev = seat_map.find(seat_it->second);
    if (prev != seat_map.end() &&
        prev->second.compare(0, VOTE_WIRE_LEN, frame, VOTE_WIRE_LEN) == 0) {
      votes_dropped_++;  // identical resend of this seat's latest vote
      votes_dropped_dup_++;
      return false;
    }
    seat_map[seat_it->second] = std::string(frame, VOTE_WIRE_LEN);
    l.vote_buf.append(frame, VOTE_WIRE_LEN);
    l.vote_count++;
    votes_batched_++;
    return true;
  }

  // One aggregated event per listener per poll cycle: the whole vote
  // fan-in of the cycle costs Python a single wakeup + decode loop.
  void flush_vote_batches() {
    for (auto& [lid, l] : listeners_) {
      if (l.vote_count == 0) continue;
      emit(Event{EV_VOTE_BATCH, lid, l.vote_count, std::move(l.vote_buf)});
      l.vote_buf.clear();  // moved-from: reset to a known state
      l.vote_count = 0;
    }
  }

  void flush_ingress(uint64_t lid, Listener& l) {
    if (l.ingress_count == 0) return;
    ingress_batches_++;
    emit(Event{EV_RECV_BATCH, lid, l.ingress_count,
               std::move(l.ingress_buf)});
    l.ingress_buf.clear();  // moved-from: reset to a known state
    l.ingress_count = 0;
  }

  // The general-ingress mirror of flush_vote_batches: every frame parsed
  // this cycle reaches Python as one aggregated event per listener.
  void flush_ingress_batches() {
    for (auto& [lid, l] : listeners_) flush_ingress(lid, l);
  }

  void flush_inbound(InConn& c) {
    if (c.outbuf.size() > IN_OUTBUF_CAP) {
      drop_inbound(c.id);  // peer reads nothing: hostile or dead
      return;
    }
    while (!c.outbuf.empty()) {
      ssize_t w = write(c.fd, c.outbuf.data(), c.outbuf.size());
      if (w > 0) {
        c.outbuf.erase(0, size_t(w));
      } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        break;
      } else {
        drop_inbound(c.id);
        return;
      }
    }
    epoll_event ev{};
    ev.events = (c.paused ? 0u : uint32_t(EPOLLIN)) |
                (c.outbuf.empty() ? 0u : uint32_t(EPOLLOUT));
    ev.data.u64 = TAG_IN | c.id;
    epoll_ctl(epfd_, EPOLL_CTL_MOD, c.fd, &ev);
  }

  // ---- fault injection (hs_net_faults) ----

  static uint64_t xorshift64(uint64_t& s) {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }

  // Parse the fault spec (loop thread): whitespace-separated tokens,
  // "seed:<u64>" or "<ip>:<port>:<drop_ppm>:<delay_ms>". An empty spec
  // clears the table. Per-peer RNG streams derive from the seed and the
  // peer key, so the same seed + same frame sequence replays the same
  // drop pattern.
  void set_faults(const std::string& spec) {
    fault_peers_.clear();
    uint64_t seed = 0x9e3779b97f4a7c15ull;
    size_t pos = 0;
    while (pos < spec.size()) {
      size_t end = spec.find(' ', pos);
      if (end == std::string::npos) end = spec.size();
      std::string tok = spec.substr(pos, end - pos);
      pos = end + 1;
      if (tok.empty()) continue;
      if (tok.rfind("seed:", 0) == 0) {
        seed = strtoull(tok.c_str() + 5, nullptr, 10);
        continue;
      }
      // ip:port:drop_ppm:delay_ms (rightmost-first split keeps IPv4 ':'
      // out of the picture — hosts here are dotted quads).
      size_t p3 = tok.rfind(':');
      size_t p2 = p3 == std::string::npos ? p3 : tok.rfind(':', p3 - 1);
      size_t p1 = p2 == std::string::npos ? p2 : tok.rfind(':', p2 - 1);
      if (p1 == std::string::npos || p1 == 0) continue;
      std::string peer = tok.substr(0, p2);  // "ip:port"
      PeerFault f;
      f.drop_ppm = uint32_t(strtoul(tok.c_str() + p2 + 1, nullptr, 10));
      f.delay_ms = uint32_t(strtoul(tok.c_str() + p3 + 1, nullptr, 10));
      f.rng = (seed ^ std::hash<std::string>()(peer)) | 1;  // nonzero
      fault_peers_[peer] = f;
    }
  }

  // True when the fault table consumed the frame (dropped, or parked for
  // delayed delivery). Best-effort frames only — callers on the reliable
  // path never consult this (replay semantics would turn injected loss
  // into duplicate delivery, which the Python fault plane models
  // explicitly instead).
  bool fault_intercept(const std::string& host, uint16_t port,
                       const std::string& frame) {
    if (fault_peers_.empty()) return false;
    std::string peer = host + ":" + std::to_string(port);
    auto it = fault_peers_.find(peer);
    if (it == fault_peers_.end()) return false;
    PeerFault& f = it->second;
    if (f.drop_ppm != 0 && xorshift64(f.rng) % 1000000u < f.drop_ppm) {
      faults_dropped_++;
      return true;
    }
    if (f.delay_ms != 0) {
      delayed_frames_.emplace(now_ms() + f.delay_ms,
                              DelayedFrame{host, port, frame});
      faults_delayed_++;
      return true;
    }
    return false;
  }

  void flush_delayed_frames(uint64_t now) {
    while (!delayed_frames_.empty() &&
           delayed_frames_.begin()->first <= now) {
      DelayedFrame df = std::move(delayed_frames_.begin()->second);
      delayed_frames_.erase(delayed_frames_.begin());
      OutConn& c = out_conn(df.host, df.port, false);
      if (c.pending.size() >= SIMPLE_QUEUE_CAP) {
        send_drops_++;
        continue;
      }
      PendingMsg m;
      m.msg_id = 0;
      m.frame = std::move(df.frame);
      c.pending.push_back(std::move(m));
      if (c.fd < 0 && !c.connecting) start_connect(c);
      if (c.fd >= 0 && !c.connecting) pump_out(c);
    }
  }

  // ---- outbound ----

  OutConn& out_conn(const std::string& host, uint16_t port, bool reliable) {
    AddrKey key{host, port, reliable};
    auto it = out_conns_.find(key);
    if (it == out_conns_.end()) {
      uint64_t slot = next_out_slot_++;
      OutConn& c = out_conns_[key];
      c.key_hash = slot;
      c.host = host;
      c.port = port;
      c.reliable = reliable;
      out_by_slot_[slot] = key;
      return c;
    }
    return it->second;
  }

  void send_simple(const std::string& host, uint16_t port,
                   const std::string& payload) {
    PendingMsg m;
    m.msg_id = 0;
    frame_append(m.frame, reinterpret_cast<const uint8_t*>(payload.data()),
                 uint32_t(payload.size()));
    if (fault_intercept(host, port, m.frame)) return;
    OutConn& c = out_conn(host, port, false);
    if (c.pending.size() >= SIMPLE_QUEUE_CAP) {  // best-effort drop
      send_drops_++;
      return;
    }
    c.pending.push_back(std::move(m));
    if (c.fd < 0 && !c.connecting) start_connect(c);
    if (c.fd >= 0 && !c.connecting) pump_out(c);
  }

  // One command for a whole best-effort broadcast: the frame is built
  // ONCE (length prefix + payload) and queued per peer, instead of one
  // Python->C crossing and one frame_append per peer. ``addrs`` is
  // space-separated "ip:port" tokens (resolved by the Python side).
  void broadcast_simple(const std::string& addrs, const std::string& payload) {
    std::string frame;
    frame_append(frame, reinterpret_cast<const uint8_t*>(payload.data()),
                 uint32_t(payload.size()));
    size_t pos = 0;
    while (pos < addrs.size()) {
      size_t sp = addrs.find(' ', pos);
      if (sp == std::string::npos) sp = addrs.size();
      size_t colon = addrs.rfind(':', sp);
      if (colon != std::string::npos && colon > pos) {
        std::string host = addrs.substr(pos, colon - pos);
        uint16_t port =
            uint16_t(strtoul(addrs.c_str() + colon + 1, nullptr, 10));
        if (fault_intercept(host, port, frame)) {
          pos = sp + 1;
          continue;
        }
        OutConn& c = out_conn(host, port, false);
        if (c.pending.size() < SIMPLE_QUEUE_CAP) {
          PendingMsg m;
          m.msg_id = 0;
          m.frame = frame;  // shared encode: one build, N queued copies
          c.pending.push_back(std::move(m));
          if (c.fd < 0 && !c.connecting) start_connect(c);
          if (c.fd >= 0 && !c.connecting) pump_out(c);
        } else {
          send_drops_++;
        }
      }
      pos = sp + 1;
    }
  }

  void send_reliable(const std::string& host, uint16_t port, uint64_t msg_id,
                     const std::string& payload) {
    OutConn& c = out_conn(host, port, true);
    PendingMsg m;
    m.msg_id = msg_id;
    frame_append(m.frame, reinterpret_cast<const uint8_t*>(payload.data()),
                 uint32_t(payload.size()));
    c.pending.push_back(std::move(m));
    if (c.fd < 0 && !c.connecting && c.next_retry_ms <= now_ms()) {
      start_connect(c);
    }
    if (c.fd >= 0 && !c.connecting) pump_out(c);
  }

  void start_connect(OutConn& c) {
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (fd < 0) {
      conn_failed(c);
      return;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(c.port);
    if (inet_pton(AF_INET, c.host.c_str(), &addr.sin_addr) != 1) {
      close(fd);
      conn_failed(c);
      return;
    }
    int r = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr);
    if (r < 0 && errno != EINPROGRESS) {
      close(fd);
      conn_failed(c);
      return;
    }
    c.fd = fd;
    c.connecting = (r < 0);
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.u64 = TAG_OUT | c.key_hash;
    epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
    if (!c.connecting) on_connected(c);
  }

  void on_connected(OutConn& c) {
    c.connecting = false;
    c.backoff_ms = RETRY_DELAY_MS;
    if (c.reliable && !c.inflight.empty()) {
      // Replay un-ACKed frames ahead of queued ones.
      for (auto it = c.inflight.rbegin(); it != c.inflight.rend(); ++it) {
        c.pending.push_front(std::move(*it));
      }
      c.inflight.clear();
    }
    c.outbuf.clear();
    pump_out(c);
  }

  void conn_failed(OutConn& c) {
    if (c.fd >= 0) {
      epoll_ctl(epfd_, EPOLL_CTL_DEL, c.fd, nullptr);
      close(c.fd);
      c.fd = -1;
    }
    c.connecting = false;
    c.outbuf.clear();
    c.inbuf.clear();
    if (c.reliable) {
      // FIFO pairing on this socket is over: cancelled inflight messages
      // need neither replay nor their cancelled_ marker.
      for (auto it = c.inflight.begin(); it != c.inflight.end();) {
        if (it->msg_id != 0 && cancelled_.erase(it->msg_id)) {
          it = c.inflight.erase(it);
        } else {
          ++it;
        }
      }
      c.next_retry_ms = now_ms() + uint64_t(c.backoff_ms);
      c.backoff_ms = std::min(c.backoff_ms * 2, RETRY_CAP_MS);
    } else {
      // Best-effort: queued frames die with the connection. The entry
      // stays in the map (bounded by distinct peer addresses) so callers
      // holding a reference across this call never dangle; the next send
      // reconnects through it.
      c.pending.clear();
    }
  }

  // Gathered write: the leftover staging buffer plus up to IOV_FRAMES
  // pending frames go out in ONE writev per round trip — pre-serialized
  // frames are never copied into a contiguous buffer on the happy path
  // (only a short write's partial frame leaves a remainder in outbuf).
  // Reliable frames enter ``inflight`` exactly when their bytes reach the
  // socket, preserving FIFO ACK pairing across partial writes.
  static constexpr int IOV_FRAMES = 63;

  void pump_out(OutConn& c) {
    while (true) {
      iovec iov[IOV_FRAMES + 1];
      int iovcnt = 0;
      size_t planned = 0;
      if (!c.outbuf.empty()) {
        iov[iovcnt++] = {c.outbuf.data(), c.outbuf.size()};
        planned += c.outbuf.size();
      }
      std::vector<PendingMsg> staged;
      while (!c.pending.empty() && iovcnt + int(staged.size()) <= IOV_FRAMES &&
             planned < (1u << 20)) {
        PendingMsg m = std::move(c.pending.front());
        c.pending.pop_front();
        if (m.msg_id && cancelled_.count(m.msg_id)) {
          cancelled_.erase(m.msg_id);
          continue;
        }
        planned += m.frame.size();
        staged.push_back(std::move(m));
      }
      for (size_t i = 0; i < staged.size(); i++) {
        iov[iovcnt++] = {staged[i].frame.data(), staged[i].frame.size()};
      }
      if (planned == 0) break;
      ssize_t w = writev(c.fd, iov, iovcnt);
      if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        w = 0;
      } else if (w < 0) {
        // Put the staged frames back for conn_failed's replay accounting
        // (reliable) / drop (simple) — none of their bytes were written.
        for (auto it = staged.rbegin(); it != staged.rend(); ++it) {
          c.pending.push_front(std::move(*it));
        }
        conn_failed(c);
        return;
      }
      if (w > 0) {
        writev_calls_++;
        bytes_tx_ += uint64_t(w);
      }
      size_t remaining = size_t(w);
      if (!c.outbuf.empty()) {
        size_t take = std::min(remaining, c.outbuf.size());
        c.outbuf.erase(0, take);
        remaining -= take;
      }
      size_t i = 0;
      for (; i < staged.size(); i++) {
        if (c.outbuf.empty() && remaining >= staged[i].frame.size()) {
          remaining -= staged[i].frame.size();
          frames_tx_++;
          if (c.reliable) c.inflight.push_back(std::move(staged[i]));
          continue;
        }
        break;
      }
      if (i < staged.size()) {
        if (c.outbuf.empty() && remaining > 0) {
          // Partially written frame: its unwritten tail becomes the new
          // staging buffer; the frame itself is on the wire (inflight).
          c.outbuf.assign(staged[i].frame, remaining,
                          staged[i].frame.size() - remaining);
          frames_tx_++;  // dispatched: its tail drains via outbuf
          if (c.reliable) c.inflight.push_back(std::move(staged[i]));
          i++;
        }
        // Untouched frames return to the queue front, order preserved.
        for (size_t j = staged.size(); j > i; j--) {
          c.pending.push_front(std::move(staged[j - 1]));
        }
      }
      if (size_t(w) < planned) break;  // kernel buffer full: wait for EPOLLOUT
      if (c.pending.empty()) break;
    }
    epoll_event ev{};
    ev.events = EPOLLIN |
                ((c.outbuf.empty() && c.pending.empty()) ? 0u : uint32_t(EPOLLOUT));
    ev.data.u64 = TAG_OUT | c.key_hash;
    epoll_ctl(epfd_, EPOLL_CTL_MOD, c.fd, &ev);
  }

  void handle_outbound(uint64_t slot, uint32_t flags) {
    auto kit = out_by_slot_.find(slot);
    if (kit == out_by_slot_.end()) return;
    auto cit = out_conns_.find(kit->second);
    if (cit == out_conns_.end()) return;
    OutConn& c = cit->second;
    if (c.connecting) {
      int err = 0;
      socklen_t len = sizeof err;
      getsockopt(c.fd, SOL_SOCKET, SO_ERROR, &err, &len);
      if (err != 0 || (flags & (EPOLLERR | EPOLLHUP))) {
        conn_failed(c);
        return;
      }
      on_connected(c);
      return;
    }
    if (flags & (EPOLLERR | EPOLLHUP)) {
      conn_failed(c);
      return;
    }
    if (flags & EPOLLIN) {
      // As in handle_inbound: parse BEFORE acting on eof/error. A peer
      // that writes its ACK and closes (one-shot servers; restarting
      // nodes) routinely delivers data+FIN in one epoll wake on
      // loopback — failing the connection first would discard the ACK,
      // leave the message "un-ACKed", and replay it forever against a
      // listener that no longer exists.
      bool conn_gone = false;
      char buf[16 * 1024];
      while (true) {
        ssize_t r = read(c.fd, buf, sizeof buf);
        if (r > 0) {
          if (c.reliable) {
            c.inbuf.append(buf, size_t(r));
          }  // simple: replies discarded
        } else if (r == 0 ||
                   (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
          conn_gone = true;
          break;
        } else {
          break;
        }
      }
      if (c.reliable) {
        size_t off = 0;
        while (c.inbuf.size() - off >= 4) {
          const uint8_t* p =
              reinterpret_cast<const uint8_t*>(c.inbuf.data()) + off;
          uint32_t len = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
          if (len > MAX_FRAME) {
            conn_failed(c);
            return;
          }
          if (c.inbuf.size() - off - 4 < len) break;
          std::string ack = c.inbuf.substr(off + 4, len);
          off += 4 + len;
          // FIFO-pair with the oldest non-cancelled in-flight message
          // (reference reliable_sender.rs ack_loop semantics).
          while (!c.inflight.empty()) {
            PendingMsg m = std::move(c.inflight.front());
            c.inflight.pop_front();
            if (cancelled_.count(m.msg_id)) {
              cancelled_.erase(m.msg_id);
              continue;
            }
            emit(Event{EV_ACKED, m.msg_id, 0, std::move(ack)});
            break;
          }
        }
        if (off) c.inbuf.erase(0, off);
      }
      if (conn_gone) {
        conn_failed(c);  // ACKs above were paired first
        return;
      }
    }
    if (flags & EPOLLOUT) pump_out(c);
  }

  int epfd_;
  int cmd_efd_;
  int out_efd_;
  std::thread thread_;
  bool stop_ = false;
  std::atomic<bool> cmd_signaled_{false};
  std::atomic<bool> out_signaled_{false};

  std::mutex cmd_mu_;
  std::deque<Command> commands_;
  bool accepting_ = true;  // guarded by cmd_mu_; false once loop() exits

  std::mutex ev_mu_;
  std::deque<Event> events_;

  uint64_t next_listener_id_ = 1;
  uint64_t next_conn_id_ = 1;
  uint64_t next_out_slot_ = 1;
  uint64_t votes_batched_ = 0;  // loop thread only
  uint64_t votes_dropped_ = 0;
  uint64_t votes_dropped_dup_ = 0;
  uint64_t frames_rx_ = 0;
  uint64_t bytes_rx_ = 0;
  uint64_t frames_tx_ = 0;
  uint64_t bytes_tx_ = 0;
  uint64_t writev_calls_ = 0;
  uint64_t send_drops_ = 0;
  uint64_t faults_dropped_ = 0;
  uint64_t faults_delayed_ = 0;
  uint64_t loop_polls_ = 0;  // poll-loop timing (all loop thread only)
  uint64_t poll_ns_ = 0;
  uint64_t dispatch_ns_ = 0;
  uint64_t cmds_serviced_ = 0;
  uint64_t cmd_service_ns_ = 0;
  uint64_t cmd_service_max_ns_ = 0;
  uint64_t ingress_reads_ = 0;  // batched-ingress account (loop thread)
  uint64_t ingress_frames_ = 0;
  uint64_t ingress_batches_ = 0;

  std::unordered_map<uint64_t, Listener> listeners_;  // loop thread only
  std::unordered_map<uint64_t, InConn> in_conns_;
  std::unordered_map<AddrKey, OutConn, AddrKeyHash> out_conns_;
  std::unordered_map<uint64_t, AddrKey> out_by_slot_;
  std::unordered_set<uint64_t> cancelled_;
  // hs_net_faults state (loop thread only).
  std::unordered_map<std::string, PeerFault> fault_peers_;
  std::multimap<uint64_t, DelayedFrame> delayed_frames_;  // release_ms
};

}  // namespace

extern "C" {

void* hs_net_create() { return new NetCore(); }

void hs_net_destroy(void* ctx) { delete static_cast<NetCore*>(ctx); }

int hs_net_event_fd(void* ctx) {
  return static_cast<NetCore*>(ctx)->out_event_fd();
}

// high_water/low_water bound the listener's emitted-but-undispatched
// event count (0 = unbounded): past high the loop stops reading the
// listener's sockets until hs_net_consumed reports progress below low.
int64_t hs_net_listen(void* ctx, const char* host, uint16_t port,
                      int auto_ack, uint32_t high_water,
                      uint32_t low_water) {
  return static_cast<NetCore*>(ctx)->listen_on(host, port, auto_ack != 0,
                                               high_water, low_water);
}

void hs_net_consumed(void* ctx, uint64_t listener_id, uint64_t n) {
  Command c;
  c.type = CMD_CONSUMED;
  c.id = listener_id;
  c.count = n;
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

void hs_net_send(void* ctx, const char* host, uint16_t port,
                 const uint8_t* data, uint32_t len, int reliable,
                 uint64_t msg_id) {
  Command c;
  c.type = reliable ? CMD_SEND_RELIABLE : CMD_SEND_SIMPLE;
  c.host = host;
  c.port = port;
  c.id = msg_id;
  c.payload.assign(reinterpret_cast<const char*>(data), len);
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

// Install (or clear, with n_authors=0) the vote pre-stage on a listener:
// ``authors`` is n_authors*32 bytes of committee public keys. Frames that
// match the fixed Vote wire layout are then length-validated, decoded,
// seat-checked, round-gated and deduped on the loop thread, and admitted
// votes reach Python as ONE EV_VOTE_BATCH per poll cycle.
void hs_net_set_vote_filter(void* ctx, uint64_t listener_id,
                            const uint8_t* authors, uint32_t n_authors) {
  Command c;
  c.type = CMD_SET_VOTE_FILTER;
  c.id = listener_id;
  if (authors != nullptr && n_authors > 0) {
    c.payload.assign(reinterpret_cast<const char*>(authors),
                     size_t(n_authors) * 32);
  }
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

// Advance the pre-stage's stale-round cutoff (monotonic; lower values
// are ignored). Also GCs dedupe state for rounds below the cutoff.
void hs_net_set_round(void* ctx, uint64_t listener_id, uint64_t round) {
  Command c;
  c.type = CMD_SET_ROUND;
  c.id = listener_id;
  c.count = round;
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

// Best-effort broadcast: one command, one frame build, N peer queues.
// ``addrs``/``addrs_len``: space-separated "ip:port" tokens.
void hs_net_broadcast(void* ctx, const char* addrs, uint32_t addrs_len,
                      const uint8_t* data, uint32_t len) {
  Command c;
  c.type = CMD_BROADCAST;
  c.host.assign(addrs, addrs_len);
  c.payload.assign(reinterpret_cast<const char*>(data), len);
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

// Test-only per-peer fault-injection table (the chaos plane's native
// hook): ``spec`` is whitespace-separated tokens — "seed:<u64>" and
// "<ip>:<port>:<drop_ppm>:<delay_ms>" — replacing the whole table; an
// empty spec clears it. Rules affect best-effort frames only (simple
// sends + broadcasts). Never enable in production deployments.
void hs_net_faults(void* ctx, const char* spec, uint32_t spec_len) {
  Command c;
  c.type = CMD_SET_FAULTS;
  c.payload.assign(spec, spec_len);
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

// Command ring flush: ``buf`` holds ``len`` bytes of fixed-layout
// little-endian records appended by the Python side over one event-loop
// iteration, decoded here into ordinary commands and enqueued under ONE
// cmd_mu_ acquisition + ONE eventfd wake. This is the batched form of
// the per-call entry points above — at committee scale the Python loop
// was paying ~N GIL re-acquisitions per round for hs_net_set_round /
// hs_net_send / hs_net_consumed crossings alone (85% of the N=200 vote
// edge, per the committed profile); the ring collapses them into one
// crossing per loop iteration. Record layouts (all integers LE):
//   op=1 SET_ROUND:       u8 op | u64 listener_id | u64 round
//   op=2 CONSUMED:        u8 op | u64 listener_id | u64 n
//   op=3 SEND_SIMPLE:     u8 op | u16 port | u8 host_len | u32 payload_len
//                         | host | payload
//   op=4 BROADCAST:       u8 op | u16 addrs_len | u32 payload_len
//                         | addrs ("ip:port ip:port ...") | payload
//   op=5 SET_VOTE_FILTER: u8 op | u64 listener_id | u32 payload_len
//                         | n*32B author keys
//   op=6 REPLY:           u8 op | u64 conn_id | u32 payload_len | payload
//   op=7 SEND_RELIABLE:   u8 op | u16 port | u8 host_len | u64 msg_id
//                         | u32 payload_len | host | payload
// A malformed record ends the parse (the Python side is the only
// producer; truncation can only mean a caller bug, and enqueueing a
// half-parsed tail would be worse than dropping it). Returns the number
// of records enqueued, or -1 when the loop has shut down.
int64_t hs_net_cmds_flush(void* ctx, const uint8_t* buf, uint32_t len) {
  std::deque<Command> cmds;
  uint32_t off = 0;
  auto rd_u16 = [&](uint32_t at) {
    uint16_t v;
    memcpy(&v, buf + at, 2);
    return v;
  };
  auto rd_u32 = [&](uint32_t at) {
    uint32_t v;
    memcpy(&v, buf + at, 4);
    return v;
  };
  auto rd_u64 = [&](uint32_t at) {
    uint64_t v;
    memcpy(&v, buf + at, 8);
    return v;
  };
  while (off < len) {
    uint8_t op = buf[off];
    Command c;
    if ((op == 1 || op == 2) && off + 17 <= len) {
      c.type = (op == 1) ? CMD_SET_ROUND : CMD_CONSUMED;
      c.id = rd_u64(off + 1);
      c.count = rd_u64(off + 9);
      off += 17;
    } else if (op == 3 && off + 8 <= len) {
      uint16_t port = rd_u16(off + 1);
      uint8_t hlen = buf[off + 3];
      uint32_t plen = rd_u32(off + 4);
      if (off + 8 + hlen + uint64_t(plen) > len) break;
      c.type = CMD_SEND_SIMPLE;
      c.host.assign(reinterpret_cast<const char*>(buf + off + 8), hlen);
      c.port = port;
      c.payload.assign(
          reinterpret_cast<const char*>(buf + off + 8 + hlen), plen);
      off += 8 + hlen + plen;
    } else if (op == 4 && off + 7 <= len) {
      uint16_t alen = rd_u16(off + 1);
      uint32_t plen = rd_u32(off + 3);
      if (off + 7 + alen + uint64_t(plen) > len) break;
      c.type = CMD_BROADCAST;
      c.host.assign(reinterpret_cast<const char*>(buf + off + 7), alen);
      c.payload.assign(
          reinterpret_cast<const char*>(buf + off + 7 + alen), plen);
      off += 7 + alen + plen;
    } else if (op == 5 && off + 13 <= len) {
      uint32_t plen = rd_u32(off + 9);
      if (off + 13 + uint64_t(plen) > len) break;
      c.type = CMD_SET_VOTE_FILTER;
      c.id = rd_u64(off + 1);
      c.payload.assign(
          reinterpret_cast<const char*>(buf + off + 13), plen);
      off += 13 + plen;
    } else if (op == 6 && off + 13 <= len) {
      uint32_t plen = rd_u32(off + 9);
      if (off + 13 + uint64_t(plen) > len) break;
      c.type = CMD_REPLY;
      c.id = rd_u64(off + 1);
      c.payload.assign(
          reinterpret_cast<const char*>(buf + off + 13), plen);
      off += 13 + plen;
    } else if (op == 7 && off + 16 <= len) {
      uint16_t port = rd_u16(off + 1);
      uint8_t hlen = buf[off + 3];
      uint64_t msg_id = rd_u64(off + 4);
      uint32_t plen = rd_u32(off + 12);
      if (off + 16 + hlen + uint64_t(plen) > len) break;
      c.type = CMD_SEND_RELIABLE;
      c.host.assign(reinterpret_cast<const char*>(buf + off + 16), hlen);
      c.port = port;
      c.id = msg_id;
      c.payload.assign(
          reinterpret_cast<const char*>(buf + off + 16 + hlen), plen);
      off += 16 + hlen + plen;
    } else {
      break;  // unknown op or truncated record: stop
    }
    cmds.push_back(std::move(c));
  }
  int64_t n = int64_t(cmds.size());
  if (n == 0) return 0;
  if (!static_cast<NetCore*>(ctx)->push_cmds(std::move(cmds))) return -1;
  return n;
}

void hs_net_close_listener(void* ctx, uint64_t listener_id) {
  Command c;
  c.type = CMD_CLOSE_LISTENER;
  c.id = listener_id;
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

void hs_net_pause_listener(void* ctx, uint64_t listener_id, int paused) {
  Command c;
  c.type = paused ? CMD_PAUSE_LISTENER : CMD_RESUME_LISTENER;
  c.id = listener_id;
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

void hs_net_cancel(void* ctx, uint64_t msg_id) {
  Command c;
  c.type = CMD_CANCEL;
  c.id = msg_id;
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

void hs_net_reply(void* ctx, uint64_t conn_id, const uint8_t* data,
                  uint32_t len) {
  Command c;
  c.type = CMD_REPLY;
  c.id = conn_id;
  c.payload.assign(reinterpret_cast<const char*>(data), len);
  static_cast<NetCore*>(ctx)->push_cmd(std::move(c));
}

int64_t hs_net_drain(void* ctx, uint8_t* buf, uint32_t cap) {
  return static_cast<NetCore*>(ctx)->drain(buf, cap);
}

// out[7] = {pending, inflight, cancelled, out_conns, in_conns,
// votes_batched, votes_dropped}. Blocks until the loop thread services
// the request (microseconds when live).
//
// Destroy contract (applies to hs_net_stats_ex too): this call must not
// race hs_net_destroy — the caller blocks on loop-thread servicing, and
// a context freed mid-wait is a use-after-free no in-library ordering
// can repair. The ctypes wrapper sequences destroy after every other
// call has returned; a call that merely LOSES the race to shutdown (the
// loop already exited but the context is alive) safely returns zeros
// via the push_cmd(false) path below.
void hs_net_stats(void* ctx, uint64_t* out) {
  StatsReq req;
  Command c;
  c.type = CMD_STATS;
  c.ptr = &req;
  if (!static_cast<NetCore*>(ctx)->push_cmd(std::move(c))) {
    // Loop thread already exited: report zeros instead of blocking on a
    // request nothing will ever service.
    for (int i = 0; i < 7; i++) out[i] = 0;
    return;
  }
  std::unique_lock<std::mutex> lk(req.mu);
  req.cv.wait(lk, [&] { return req.done; });
  out[0] = req.pending;
  out[1] = req.inflight;
  out[2] = req.cancelled;
  out[3] = req.out_conns;
  out[4] = req.in_conns;
  out[5] = req.votes_batched;
  out[6] = req.votes_dropped;
}

// Extended snapshot: fills up to ``cap`` slots in the order
// {pending, inflight, cancelled, out_conns, in_conns, votes_batched,
//  votes_dropped, votes_dropped_dup, frames_rx, bytes_rx, frames_tx,
//  bytes_tx, writev_calls, send_drops, faults_dropped, faults_delayed,
//  loop_polls, poll_ns, dispatch_ns, cmds_serviced, cmd_service_ns,
//  cmd_service_max_ns, ingress_reads, ingress_frames, ingress_batches}
// and returns the number filled (new fields append, existing indices
// never move — callers probe the return value instead of pinning a
// struct version). Same loop-thread servicing — and the same
// no-race-with-destroy contract — as hs_net_stats.
int hs_net_stats_ex(void* ctx, uint64_t* out, int cap) {
  constexpr int N_FIELDS = 25;
  if (out == nullptr || cap <= 0) return 0;
  StatsReq req;
  Command c;
  c.type = CMD_STATS;
  c.ptr = &req;
  if (!static_cast<NetCore*>(ctx)->push_cmd(std::move(c))) {
    for (int i = 0; i < cap; i++) out[i] = 0;
    return cap < N_FIELDS ? cap : N_FIELDS;
  }
  std::unique_lock<std::mutex> lk(req.mu);
  req.cv.wait(lk, [&] { return req.done; });
  const uint64_t fields[N_FIELDS] = {
      req.pending,       req.inflight,     req.cancelled,
      req.out_conns,     req.in_conns,     req.votes_batched,
      req.votes_dropped, req.votes_dropped_dup, req.frames_rx,
      req.bytes_rx,      req.frames_tx,    req.bytes_tx,
      req.writev_calls,  req.send_drops,   req.faults_dropped,
      req.faults_delayed, req.loop_polls,  req.poll_ns,
      req.dispatch_ns,   req.cmds_serviced, req.cmd_service_ns,
      req.cmd_service_max_ns, req.ingress_reads, req.ingress_frames,
      req.ingress_batches,
  };
  int n = cap < N_FIELDS ? cap : N_FIELDS;
  for (int i = 0; i < n; i++) out[i] = fields[i];
  return n;
}

}  // extern "C"
