"""Mempool configuration (reference ``mempool/src/config.rs``).

The mempool keeps its own committee type with its own address space — two
addresses per node: ``transactions_address`` for clients and
``mempool_address`` for peer mempools (reference ``mempool/src/config.rs:50-64``).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from hotstuff_tpu.crypto import PublicKey

log = logging.getLogger("mempool")

Stake = int


@dataclass
class Parameters:
    """Defaults match the reference (``mempool/src/config.rs:24-34``)."""

    gc_depth: int = 50  # rounds
    sync_retry_delay: int = 5_000  # ms
    sync_retry_nodes: int = 3  # number of nodes
    batch_size: int = 500_000  # bytes
    max_batch_delay: int = 100  # ms
    # -- Conveyor data plane (mempool/dataplane/) ---------------------------
    # Worker shards per node: 0 disables the data plane entirely (the
    # legacy BatchMaker path always runs); >0 spawns min(workers,
    # committee-declared worker entries) shards, each with its own
    # client-ingress port, peer port, bounded ingress queue and
    # availability-cert pipeline.
    workers: int = 0
    # Per-worker ingress bound, in client BUNDLES (a bundle is one client
    # frame of many transactions). Arrivals beyond it are shed with a
    # client-visible b"Shed" reply.
    worker_ingress_capacity: int = 512
    # Store-depth watermarks, in sealed-but-uncommitted batches per node:
    # sealing gates at >= high and resumes at <= low (hysteresis).
    store_high_watermark: int = 256
    store_low_watermark: int = 128
    # Route concurrent batch digests (SHA-512/32) through the device kernel
    # (``ops.sha512``) instead of per-batch host hashing — the BASELINE
    # config-3 regime (committee-scale digest throughput). Off by default:
    # at small committees a lone batch is latency-bound and host hashing
    # wins.
    device_batch_digests: bool = False

    def log(self) -> None:
        # These log entries are picked up by the benchmark log parser
        # (reference ``mempool/src/config.rs:37-44``).
        log.info("Garbage collection depth set to %d rounds", self.gc_depth)
        log.info("Sync retry delay set to %d ms", self.sync_retry_delay)
        log.info("Sync retry nodes set to %d nodes", self.sync_retry_nodes)
        log.info("Batch size set to %d B", self.batch_size)
        log.info("Max batch delay set to %d ms", self.max_batch_delay)


@dataclass
class WorkerEntry:
    """One worker shard's address pair: ``transactions_address`` faces
    clients, ``worker_address`` faces peer workers (batch dissemination,
    acks, certs, batch requests)."""

    transactions_address: tuple[str, int]
    worker_address: tuple[str, int]


@dataclass
class Authority:
    stake: Stake
    transactions_address: tuple[str, int]
    mempool_address: tuple[str, int]
    # Conveyor worker shards (optional; absent = legacy single-lane
    # mempool). Worker ``w`` of every node disseminates to worker ``w``
    # of every peer, so entries pair up positionally across the
    # committee.
    workers: list[WorkerEntry] = field(default_factory=list)


@dataclass
class Committee:
    authorities: dict[PublicKey, Authority]
    epoch: int = 1

    def size(self) -> int:
        return len(self.authorities)

    def stake(self, name: PublicKey) -> Stake:
        a = self.authorities.get(name)
        return a.stake if a else 0

    def total_stake(self) -> Stake:
        return sum(a.stake for a in self.authorities.values())

    def quorum_threshold(self) -> Stake:
        # 2f+1 out of N=3f+1 by stake (reference ``mempool/src/config.rs:90-95``).
        return 2 * self.total_stake() // 3 + 1

    def transactions_address(self, name: PublicKey) -> tuple[str, int] | None:
        a = self.authorities.get(name)
        return a.transactions_address if a else None

    def mempool_address(self, name: PublicKey) -> tuple[str, int] | None:
        a = self.authorities.get(name)
        return a.mempool_address if a else None

    def broadcast_addresses(self, name: PublicKey) -> list[tuple[PublicKey, tuple[str, int]]]:
        """(name, mempool_address) of every node except ``name``."""
        return [
            (pk, a.mempool_address)
            for pk, a in self.authorities.items()
            if pk != name
        ]

    # -- Conveyor worker shards ---------------------------------------------

    def workers_of(self, name: PublicKey) -> list["WorkerEntry"]:
        a = self.authorities.get(name)
        return a.workers if a else []

    def worker_peers(
        self, name: PublicKey, worker_id: int
    ) -> list[tuple[PublicKey, tuple[str, int]]]:
        """(peer, worker_address) of every OTHER node's worker shard
        ``worker_id`` — the dissemination fan-out set for our shard
        ``worker_id``. Peers without that shard are skipped (a mixed
        committee degrades to the peers that have it)."""
        return [
            (pk, a.workers[worker_id].worker_address)
            for pk, a in self.authorities.items()
            if pk != name and worker_id < len(a.workers)
        ]

    def worker_address(
        self, name: PublicKey, worker_id: int
    ) -> tuple[str, int] | None:
        a = self.authorities.get(name)
        if a is None or worker_id >= len(a.workers):
            return None
        return a.workers[worker_id].worker_address
