"""Mempool root: wires all mempool actors and network receivers (reference
``mempool/src/mempool.rs:58-245``).

Two receivers: client transactions on ``transactions_address`` and peer
messages on ``mempool_address`` (both rebound to 0.0.0.0, reference
``mempool.rs:119,166``). Peer ``Batch`` messages are ACKed then routed to a
Processor; ``BatchRequest``s go to the Helper.
"""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu.crypto import PublicKey, sha512_digest
from hotstuff_tpu.network import MessageHandler, Receiver
from hotstuff_tpu.store import Store
from hotstuff_tpu.utils.serde import SerdeError

# Conveyor batch frames are recognizable from their first byte (tags
# start at 16, disjoint from the legacy mempool tags) — resolved here as
# a constant so the per-frame dispatch pays no module lookup.
from .dataplane.messages import TAG_BATCH as _DP_TAG_BATCH

from . import messages
from .batch_maker import BatchMaker
from .config import Committee, Parameters
from .helper import Helper
from .processor import Processor
from .quorum_waiter import QuorumWaiter
from .synchronizer import Synchronizer

log = logging.getLogger("mempool")

CHANNEL_CAPACITY = 1_000


class TxReceiverHandler(MessageHandler):
    """Client transactions: one-way, no ACK (reference ``mempool.rs:196-214``)."""

    def __init__(self, tx_batch_maker: asyncio.Queue) -> None:
        self.tx_batch_maker = tx_batch_maker

    async def dispatch(self, writer, message: bytes) -> None:
        await self.tx_batch_maker.put(message)


class MempoolReceiverHandler(MessageHandler):
    """Peer messages: ACK batches then route (reference ``mempool.rs:217-245``)."""

    def __init__(
        self,
        tx_processor: asyncio.Queue,
        tx_helper: asyncio.Queue,
        store: Store | None = None,
    ) -> None:
        self.tx_processor = tx_processor
        self.tx_helper = tx_helper
        self.store = store

    async def dispatch(self, writer, message: bytes) -> None:
        if (
            message
            and message[0] == _DP_TAG_BATCH
            and self.store is not None
        ):
            # A Conveyor worker batch served raw through the legacy sync
            # path (the helper sends stored frames verbatim): store it
            # under its digest — that fulfils any notify_read obligation
            # the availability gate or the commit resolver registered.
            # No digest is re-emitted to consensus: the batch is being
            # fetched precisely because it is already ordered or
            # verifying.
            await writer.send(b"Ack")
            digest = sha512_digest(message)
            await self.store.write(digest.data, message)
            return
        try:
            kind, payload = messages.decode(message)
        except SerdeError as e:
            log.warning("failed to decode mempool message: %s", e)
            return
        if kind == "batch":
            # ACK first so the sender stops retransmitting, then store the
            # raw serialized message (reference ``mempool.rs:224-237``).
            await writer.send(b"Ack")
            await self.tx_processor.put(message)
        else:  # batch_request
            digests, requestor = payload
            await self.tx_helper.put((digests, requestor))


class Mempool:
    """Composition root (reference ``Mempool::spawn``, ``mempool.rs:58-91``)."""

    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        parameters: Parameters,
        store: Store,
        rx_consensus: asyncio.Queue,  # ConsensusMempoolMessage (Synchronize/Cleanup)
        tx_consensus: asyncio.Queue,  # batch digests out to consensus
        benchmark: bool = False,
        signature_service=None,  # required for the Conveyor data plane
    ) -> None:
        self.name = name
        self.committee = committee
        self.parameters = parameters
        self.store = store
        self.rx_consensus = rx_consensus
        self.tx_consensus = tx_consensus
        self.benchmark = benchmark
        self.signature_service = signature_service
        self.tasks: list[asyncio.Task] = []
        self.receivers: list[Receiver] = []
        self.dataplane = None  # Conveyor worker shards (spawned on demand)

    async def spawn(self) -> "Mempool":
        self.parameters.log()

        tx_batch_maker: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        tx_quorum_waiter: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        tx_own_processor: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        tx_peer_processor: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)
        tx_helper: asyncio.Queue = asyncio.Queue(CHANNEL_CAPACITY)

        # Mempool synchronizer answering consensus sync/cleanup commands.
        self.tasks.append(
            Synchronizer.spawn(
                self.name,
                self.committee,
                self.store,
                self.parameters.gc_depth,
                self.parameters.sync_retry_delay,
                self.parameters.sync_retry_nodes,
                self.rx_consensus,
            )
        )

        # Client transaction intake -> batch maker.
        tx_address = self.committee.transactions_address(self.name)
        assert tx_address is not None, "our key is not in the committee"
        self.receivers.append(
            await Receiver.spawn(
                ("0.0.0.0", tx_address[1]), TxReceiverHandler(tx_batch_maker)
            )
        )
        self.tasks.append(
            BatchMaker.spawn(
                self.parameters.batch_size,
                self.parameters.max_batch_delay,
                tx_batch_maker,
                tx_quorum_waiter,
                self.committee.broadcast_addresses(self.name),
                benchmark=self.benchmark,
            )
        )
        self.tasks.append(
            QuorumWaiter.spawn(
                self.committee, self.name, tx_quorum_waiter, tx_own_processor
            )
        )
        # Own batches: hash, store, digest to consensus.
        self.tasks.append(
            Processor.spawn(
                self.store,
                tx_own_processor,
                self.tx_consensus,
                device_digests=self.parameters.device_batch_digests,
            )
        )

        # Peer messages: batches + batch requests.
        mp_address = self.committee.mempool_address(self.name)
        assert mp_address is not None
        # auto_ack: batch ACKs (the 2f+1 dissemination quorum) go out on
        # frame arrival instead of after this process gets scheduled;
        # batch_request senders use SimpleSender and discard the reply.
        self.receivers.append(
            await Receiver.spawn(
                ("0.0.0.0", mp_address[1]),
                MempoolReceiverHandler(
                    tx_peer_processor, tx_helper, store=self.store
                ),
                auto_ack=True,
            )
        )
        # Peer batches: hash, store, digest to consensus.
        self.tasks.append(
            Processor.spawn(
                self.store,
                tx_peer_processor,
                self.tx_consensus,
                device_digests=self.parameters.device_batch_digests,
            )
        )
        self.tasks.append(Helper.spawn(self.committee, self.store, tx_helper))

        # Conveyor data plane: worker shards with availability certs.
        if (
            self.parameters.workers > 0
            and self.committee.workers_of(self.name)
        ):
            if self.signature_service is None:
                raise ValueError(
                    "the Conveyor data plane needs a signature service "
                    "(availability acks are signed)"
                )
            from .dataplane import DataPlane

            self.dataplane = await DataPlane(
                self.name,
                self.committee,
                self.parameters,
                self.store,
                self.signature_service,
                self.tx_consensus,
                benchmark=self.benchmark,
            ).spawn()

        log.info(
            "Mempool successfully booted on %s", mp_address[0]
        )
        return self

    async def shutdown(self) -> None:
        for t in self.tasks:
            t.cancel()
        if self.dataplane is not None:
            await self.dataplane.shutdown()
        for r in self.receivers:
            await r.shutdown()
