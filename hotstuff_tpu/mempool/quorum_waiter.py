"""QuorumWaiter: hold each batch until 2f+1 stake has ACKed its dissemination
(reference ``mempool/src/quorum_waiter.rs``).

Own stake counts toward the quorum (``quorum_waiter.rs:92-102``). After
quorum, the remaining (slow-node) handlers get up to 500 ms extra
dissemination time in a bounded background set (``quorum_waiter.rs:18-21``).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass

from hotstuff_tpu.crypto import PublicKey

from .config import Committee

log = logging.getLogger("mempool")

DISSEMINATION_DEADLINE = 0.5  # s — extra time for the f slowest nodes
DISSEMINATION_QUEUE_MAX = 10_000


@dataclass
class QuorumWaiterMessage:
    batch: bytes  # serialized MempoolMessage::Batch
    handlers: list[tuple[PublicKey, asyncio.Future]]


class QuorumWaiter:
    def __init__(
        self,
        committee: Committee,
        name: PublicKey,
        rx_message: asyncio.Queue,
        tx_batch: asyncio.Queue,
    ) -> None:
        self.committee = committee
        self.stake = committee.stake(name)
        self.rx_message = rx_message
        self.tx_batch = tx_batch
        self._background: set[asyncio.Task] = set()

    @classmethod
    def spawn(cls, *args, **kwargs) -> asyncio.Task:
        self = cls(*args, **kwargs)
        return asyncio.create_task(self._run(), name="quorum_waiter")

    async def _run(self) -> None:
        from hotstuff_tpu.utils.quorum import cancel_remaining, wait_for_ack_quorum

        while True:
            msg: QuorumWaiterMessage = await self.rx_message.get()
            reached, remaining = await wait_for_ack_quorum(
                msg.handlers,
                self.committee.stake,
                self.stake,  # our own batch counts for our stake
                self.committee.quorum_threshold(),
            )
            if reached:
                await self.tx_batch.put(msg.batch)
            else:
                log.warning("batch dissemination failed to reach quorum")
            # Let the f slowest nodes keep receiving for a bounded grace
            # period instead of cancelling their retransmissions immediately
            # (reference ``quorum_waiter.rs:104-122``).
            if remaining and len(self._background) < DISSEMINATION_QUEUE_MAX:
                task = asyncio.create_task(self._linger(remaining))
                self._background.add(task)
                task.add_done_callback(self._background.discard)
            elif remaining:
                cancel_remaining(remaining)

    @staticmethod
    async def _linger(remaining: dict[asyncio.Task, asyncio.Future]) -> None:
        """Give slow peers DISSEMINATION_DEADLINE more, then cancel their
        handlers so the ReliableSender stops replaying those messages."""
        try:
            await asyncio.wait_for(
                asyncio.gather(*remaining), DISSEMINATION_DEADLINE
            )
        except asyncio.TimeoutError:
            for handler in remaining.values():
                if not handler.done():
                    handler.cancel()
