"""Conveyor wire messages: the worker-sharded data plane's frame formats.

Design rule: the node-side hot path never touches individual
transactions. Clients pre-frame their transactions into **bundles**
whose header carries the tx count and the benchmark sample ids, and
whose body is an opaque length-prefixed blob. A worker seals a batch by
CONCATENATING bundle blobs — the tx bytes flow client → batch frame →
peer store as unparsed slices (the data-plane face of PR 2's
writev-coalescing egress and PR 8's zero-copy decode discipline), and
per-transaction Python cost stays on the client.

Frames on the worker ports:

- ``TxBundle`` (client → worker ingress): header + opaque tx blob.
- ``WorkerBatch`` (worker → peer workers): a sealed batch; its DIGEST is
  SHA-512/32 of the entire serialized frame, so storing the raw frame
  under its digest needs no re-encode.
- ``BatchAck`` (peer worker → disseminating worker, as the framed reply
  on the batch connection): a SIGNATURE over the domain-separated ack
  digest — the unit availability certificates are made of.
- ``Cert`` (worker → peers, best-effort broadcast): 2f+1 acks bound to
  one digest. Two wire formats, mirroring consensus wire v2: v1 repeats
  ``(pk, sig)`` pairs; v2 names signers as a seat BITMAP over the
  mempool committee's sorted key order plus concatenated signatures.
- ``BatchRequest``: digest list + requestor, served from the store.

Certs are persisted under ``cert_key(digest)`` so the consensus
availability gate (``consensus/mempool_driver.py``) can vote on a block
whose batches it never received — ordering needs the proof of
availability, not the bytes.
"""

from __future__ import annotations

from hotstuff_tpu.crypto import Digest, PublicKey, Signature, sha512_digest
from hotstuff_tpu.utils.serde import Decoder, Encoder, SerdeError

# Tags start at 16: disjoint from the legacy mempool tags (0, 1) AND the
# consensus tags, so a dataplane frame routed through the legacy mempool
# port (the synchronizer's batch-fetch path serves stored frames raw) is
# recognizable from its first byte.
TAG_TX_BUNDLE = 16
TAG_BATCH = 17
TAG_ACK = 18
TAG_CERT = 19
TAG_CERT_V2 = 20
TAG_BATCH_REQUEST = 21

#: store-key prefix for availability certificates (batches live under
#: their bare 32-byte digest, exactly like the legacy mempool path).
CERT_KEY_PREFIX = b"dpc:"


def cert_key(digest_data: bytes) -> bytes:
    return CERT_KEY_PREFIX + digest_data


def ack_digest(digest: Digest) -> Digest:
    """What a batch ack signs: domain-separated from every consensus
    digest so an availability ack can never be replayed as a vote."""
    return sha512_digest(b"conveyor-ack-v1", digest.data)


# -- client bundles ----------------------------------------------------------


def encode_bundle(txs: list[bytes], sample_ids: list[int] | None = None) -> bytes:
    """Client-side bundle builder (the slow, per-tx path lives HERE, on
    the load generator). ``sample_ids`` defaults to scanning ``txs`` for
    the benchmark sample prefix."""
    if sample_ids is None:
        sample_ids = [
            int.from_bytes(tx[1:9], "big")
            for tx in txs
            if tx[:1] == b"\x00" and len(tx) > 8
        ]
    enc = Encoder().u8(TAG_TX_BUNDLE).u32(len(txs)).u32(len(sample_ids))
    for s in sample_ids:
        enc.u64(s)
    blob = b"".join(
        len(tx).to_bytes(4, "big") + tx for tx in txs
    )
    enc.bytes(blob)
    return enc.finish()


def decode_bundle(data: bytes) -> tuple[int, list[int], bytes]:
    """(n_txs, sample_ids, blob). Raises SerdeError on malformed input."""
    dec = Decoder(data)
    if dec.u8() != TAG_TX_BUNDLE:
        raise SerdeError("not a tx bundle")
    n_txs = dec.u32()
    n_samples = dec.u32()
    if n_samples > n_txs:
        raise SerdeError("bundle claims more samples than txs")
    samples = [dec.u64() for _ in range(n_samples)]
    blob = dec.bytes()
    dec.finish()
    return n_txs, samples, blob


def split_blob(blob: bytes) -> list[bytes]:
    """Materialize the individual transactions of a bundle/batch blob —
    the execution/commit-resolution path, never the ingest hot path."""
    txs = []
    pos = 0
    n = len(blob)
    while pos < n:
        if pos + 4 > n:
            raise SerdeError("truncated tx length prefix in blob")
        tx_len = int.from_bytes(blob[pos : pos + 4], "big")
        pos += 4
        if pos + tx_len > n:
            raise SerdeError("truncated tx in blob")
        txs.append(blob[pos : pos + tx_len])
        pos += tx_len
    return txs


# -- worker batches ----------------------------------------------------------


def encode_worker_batch(
    worker_id: int, n_txs: int, sample_ids: list[int], blob: bytes
) -> bytes:
    enc = Encoder().u8(TAG_BATCH).u32(worker_id).u32(n_txs).u32(len(sample_ids))
    for s in sample_ids:
        enc.u64(s)
    enc.bytes(blob)
    return enc.finish()


def decode_worker_batch(data: bytes) -> tuple[int, int, list[int], bytes]:
    """(worker_id, n_txs, sample_ids, blob)."""
    dec = Decoder(data)
    if dec.u8() != TAG_BATCH:
        raise SerdeError("not a worker batch")
    worker_id = dec.u32()
    n_txs = dec.u32()
    n_samples = dec.u32()
    if n_samples > n_txs:
        raise SerdeError("batch claims more samples than txs")
    samples = [dec.u64() for _ in range(n_samples)]
    blob = dec.bytes()
    dec.finish()
    return worker_id, n_txs, samples, blob


def batch_tx_bytes(n_txs: int, blob: bytes) -> int:
    """Transaction payload bytes of a batch blob (minus the per-tx length
    prefixes) — the size the ``Batch d contains N B`` contract reports,
    matching the legacy BatchMaker's sum-of-tx-lengths."""
    return len(blob) - 4 * n_txs


# -- acks --------------------------------------------------------------------


def encode_ack(digest: Digest, signer: PublicKey, signature: Signature) -> bytes:
    return (
        Encoder()
        .u8(TAG_ACK)
        .raw(digest.data)
        .raw(signer.data)
        .raw(signature.data)
        .finish()
    )


def decode_ack(data: bytes) -> tuple[Digest, PublicKey, Signature]:
    dec = Decoder(data)
    if dec.u8() != TAG_ACK:
        raise SerdeError("not a batch ack")
    digest = Digest(dec.raw(32))
    signer = PublicKey(dec.raw(32))
    signature = Signature(dec.raw(64))
    dec.finish()
    return digest, signer, signature


# -- batch requests ----------------------------------------------------------


def encode_batch_request(digests: list[Digest], requestor: PublicKey) -> bytes:
    return (
        Encoder()
        .u8(TAG_BATCH_REQUEST)
        .seq(digests, lambda e, d: e.raw(d.data))
        .raw(requestor.data)
        .finish()
    )


def decode_batch_request(data: bytes) -> tuple[list[Digest], PublicKey]:
    dec = Decoder(data)
    if dec.u8() != TAG_BATCH_REQUEST:
        raise SerdeError("not a batch request")
    digests = dec.seq(lambda d: Digest(d.raw(32)))
    requestor = PublicKey(dec.raw(32))
    dec.finish()
    return digests, requestor


def peek_tag(data: bytes) -> int:
    if not data:
        raise SerdeError("empty frame")
    return data[0]
