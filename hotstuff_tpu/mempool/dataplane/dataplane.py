"""Conveyor composition root: spawn worker shards, track store depth,
resolve committed digests back to batches.

``DataPlane`` owns the per-node worker set plus the shared store-depth
:class:`~.backpressure.Watermark`: every sealed batch raises the depth,
every committed (or evicted) digest lowers it, and the watermark gates
every worker's batcher — one signal, all shards.

``CommitResolver`` sits between the consensus commit stream and the
application: consensus ordered DIGESTS it could prove available, so the
commit path must materialize the bytes. Batches already local (the
common case — this node was in the cert quorum or received the batch
anyway) resolve from the worker store for free; missing ones trigger
the mempool synchronizer's fetch path and the block is held until the
store notify_read obligation fires. Blocks always flow downstream in
commit order.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict

from hotstuff_tpu import telemetry
from hotstuff_tpu.crypto import PublicKey, SignatureService
from hotstuff_tpu.store import Store

from ..config import Committee, Parameters
from ..synchronizer import Synchronize
from .backpressure import Watermark
from .worker import Worker

log = logging.getLogger("mempool")

#: outstanding (sealed, uncommitted) digests tracked for depth; beyond
#: this the oldest is evicted (its depth contribution released) so a
#: digest that never commits cannot pin the watermark forever.
OUTSTANDING_CAP = 8192

#: how long the resolver waits for a missing batch before forwarding the
#: block anyway (counted — the availability invariant says this should
#: never fire with <= f faults; the checker would flag the run).
RESOLVE_TIMEOUT_S = 60.0


class DataPlane:
    """Per-node worker-shard set (see module docstring)."""

    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        parameters: Parameters,
        store: Store,
        signature_service: SignatureService,
        tx_consensus: asyncio.Queue,
        benchmark: bool = False,
    ) -> None:
        self.name = name
        self.committee = committee
        self.parameters = parameters
        self.store = store
        self.signature_service = signature_service
        self.tx_consensus = tx_consensus
        self.benchmark = benchmark
        self.watermark = Watermark(
            parameters.store_high_watermark, parameters.store_low_watermark
        )
        self.workers: list[Worker] = []
        # Sealed-but-uncommitted digests, insertion-ordered for eviction.
        self._outstanding: OrderedDict = OrderedDict()

    @property
    def n_workers(self) -> int:
        declared = len(self.committee.workers_of(self.name))
        return min(self.parameters.workers, declared)

    async def spawn(self) -> "DataPlane":
        for wid in range(self.n_workers):
            worker = Worker(
                self.name,
                wid,
                self.committee,
                self.parameters,
                self.store,
                self.signature_service,
                self.tx_consensus,
                self.watermark,
                on_sealed=self._note_sealed,
                benchmark=self.benchmark,
            )
            self.workers.append(await worker.spawn())
        log.info("Conveyor data plane booted with %d worker(s)", len(self.workers))
        return self

    # -- depth bookkeeping ---------------------------------------------------

    def _note_sealed(self, digest) -> None:
        if digest in self._outstanding:
            return
        # Value must be a non-None sentinel: note_committed distinguishes
        # a hit from a miss via pop(d, None).
        self._outstanding[digest] = True
        self.watermark.adjust(1)
        if len(self._outstanding) > OUTSTANDING_CAP:
            self._outstanding.popitem(last=False)
            self.watermark.adjust(-1)

    def note_committed(self, digests) -> None:
        """Commit feedback from the resolver: committed digests release
        their depth contribution."""
        for d in digests:
            if self._outstanding.pop(d, None) is not None:
                self.watermark.adjust(-1)

    async def shutdown(self) -> None:
        for w in self.workers:
            await w.shutdown()


class CommitResolver:
    """Digest → batch resolution on the commit path (module docstring)."""

    def __init__(
        self,
        store: Store,
        rx_commit: asyncio.Queue,
        tx_out: asyncio.Queue,
        tx_mempool: asyncio.Queue,
        dataplane: DataPlane | None = None,
    ) -> None:
        self.store = store
        self.rx_commit = rx_commit
        self.tx_out = tx_out
        self.tx_mempool = tx_mempool
        self.dataplane = dataplane
        self._m_resolved = telemetry.counter("mempool.resolver.batches_resolved")
        self._m_fetched = telemetry.counter("mempool.resolver.batches_fetched")
        self._m_unresolved = telemetry.counter("mempool.resolver.unresolved")
        self._h_wait = telemetry.histogram("mempool.resolver.fetch_wait_ms")
        # Lifeline node label: the dataplane knows whose commit stream
        # this is; a standalone resolver (tests) traces as "".
        self._node_label = repr(dataplane.name) if dataplane is not None else ""

    @classmethod
    def spawn(cls, *args, **kwargs) -> asyncio.Task:
        self = cls(*args, **kwargs)
        return asyncio.create_task(self._run(), name="commit_resolver")

    async def _run(self) -> None:
        while True:
            block = await self.rx_commit.get()
            if block.payload:
                await self._resolve(block)
                if self.dataplane is not None:
                    self.dataplane.note_committed(block.payload)
            await self.tx_out.put(block)

    def _trace_resolved(self, digests, detail: str) -> None:
        if not telemetry.dtrace_enabled():
            return
        for d in digests:
            # Lifeline terminal mark: the batch bytes are materialized on
            # this node's commit path (timeline closes here; a committed-
            # but-never-resolved batch leaves this edge open).
            telemetry.dtrace_event(
                self._node_label,
                telemetry.intern_label(d.data),
                "resolved",
                detail=detail,
            )

    async def _resolve(self, block) -> None:
        missing = [
            d for d in block.payload if await self.store.read(d.data) is None
        ]
        self._m_resolved.inc(len(block.payload) - len(missing))
        if not missing:
            self._trace_resolved(block.payload, "local")
            return
        # The certified quorum held the batch when it was ordered; pull it
        # through the mempool synchronizer's fetch/retry machinery.
        t0 = time.monotonic()
        await self.tx_mempool.put(Synchronize(missing, block.author))
        try:
            await asyncio.wait_for(
                asyncio.gather(
                    *[self.store.notify_read(d.data) for d in missing]
                ),
                RESOLVE_TIMEOUT_S,
            )
        except asyncio.TimeoutError:
            # Should be impossible with <= f faults (the availability
            # invariant); surfaced rather than wedging the commit stream.
            self._m_unresolved.inc(len(missing))
            log.error(
                "commit-path resolution timed out for %d batch(es) of %r",
                len(missing),
                block,
            )
            # The locally-present subset still resolved; the timed-out
            # digests leave their lifeline open (the attribution reports
            # the open edge, never invents a close).
            unresolved = set(missing)
            self._trace_resolved(
                [d for d in block.payload if d not in unresolved], "local"
            )
            return
        self._m_fetched.inc(len(missing))
        self._h_wait.observe((time.monotonic() - t0) * 1e3)
        missing_set = set(missing)
        self._trace_resolved(
            [d for d in block.payload if d not in missing_set], "local"
        )
        self._trace_resolved(missing, "fetched")
