"""Batch availability certificates: 2f+1 signed acks bound to a digest.

The Narwhal insight made concrete: once 2f+1 stake has SIGNED that it
holds a batch, quorum intersection guarantees at least f+1 HONEST nodes
hold it — so consensus may order the digest (and every replica may vote)
without possessing the bytes, and dissemination bandwidth leaves the
ordering critical path.

Two wire formats, mirroring the consensus plane's wire v2:

- **v1** (``TAG_CERT``): ``digest | u32 n | n * (pk 32B, sig 64B)`` —
  self-contained, committee-agnostic.
- **v2** (``TAG_CERT_V2``): ``digest | u32 n | seat-bitmap | n * sig`` —
  signers named as a bitmap over the mempool committee's sorted key
  order (:class:`WorkerSeatTable`), ~28% smaller at N=4 and asymptoting
  to half at large committees. Decode requires the seat table; both
  formats are always accepted, so the emit format can flip per epoch.

``AvailabilityCert.verify`` checks signer uniqueness, committee
membership, the stake quorum, and every signature over the
domain-separated ack digest. Certificates arriving off the wire are
verified BEFORE they are stored; the consensus availability gate then
only tests presence.
"""

from __future__ import annotations

from hotstuff_tpu.crypto import CryptoError, Digest, PublicKey, Signature
from hotstuff_tpu.utils.serde import Decoder, Encoder, SerdeError

from ..config import Committee
from .messages import TAG_CERT, TAG_CERT_V2, ack_digest

__all__ = ["AvailabilityCert", "CertCollector", "WorkerSeatTable", "CertError"]


class CertError(Exception):
    pass


class WorkerSeatTable:
    """Canonical seat numbering of the MEMPOOL committee: seat ``i`` is
    the ``i``-th public key in sorted order — the data plane's analog of
    the consensus ``SeatTable`` (same deterministic order on every node,
    so v2 certs name signers by bitmap)."""

    __slots__ = ("keys", "index", "nbytes")

    def __init__(self, keys) -> None:
        self.keys: list[PublicKey] = sorted(keys)
        self.index: dict[PublicKey, int] = {
            pk: i for i, pk in enumerate(self.keys)
        }
        self.nbytes = (len(self.keys) + 7) // 8

    def __len__(self) -> int:
        return len(self.keys)

    @classmethod
    def for_committee(cls, committee: Committee) -> "WorkerSeatTable":
        table = committee.__dict__.get("_worker_seat_table")
        if table is None:
            table = cls(committee.authorities.keys())
            committee.__dict__["_worker_seat_table"] = table
        return table


def _bitmap_seats(bitmap: bytes, n_seats: int) -> list[int]:
    seats = []
    for byte_i, byte in enumerate(bitmap):
        base = byte_i * 8
        while byte:
            low = byte & -byte
            seat = base + low.bit_length() - 1
            if seat >= n_seats:
                raise SerdeError(f"cert bitmap names unknown seat {seat}")
            seats.append(seat)
            byte ^= low
    return seats


def _seats_bitmap(seat_indices, nbytes: int) -> bytes:
    bits = bytearray(nbytes)
    for seat in seat_indices:
        bits[seat // 8] |= 1 << (seat % 8)
    return bytes(bits)


class AvailabilityCert:
    """An immutable (digest, signer→signature) binding."""

    __slots__ = ("digest", "pairs")

    def __init__(self, digest: Digest, pairs: list[tuple[PublicKey, Signature]]):
        self.digest = digest
        self.pairs = pairs

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, AvailabilityCert)
            and self.digest == other.digest
            and self.pairs == other.pairs
        )

    def signers(self) -> list[PublicKey]:
        return [pk for pk, _ in self.pairs]

    def verify(self, committee: Committee) -> None:
        """Raise CertError unless this is a valid 2f+1 availability
        certificate for ``committee``."""
        seen: set[PublicKey] = set()
        stake = 0
        for pk, _sig in self.pairs:
            if pk in seen:
                raise CertError(f"duplicate cert signer {pk}")
            seen.add(pk)
            s = committee.stake(pk)
            if s == 0:
                raise CertError(f"cert signer {pk} not in committee")
            stake += s
        if stake < committee.quorum_threshold():
            raise CertError(
                f"cert stake {stake} below quorum {committee.quorum_threshold()}"
            )
        signed = ack_digest(self.digest)
        for pk, sig in self.pairs:
            try:
                sig.verify(signed, pk)
            except CryptoError as e:
                raise CertError(f"bad cert signature from {pk}: {e}") from e

    # -- wire --------------------------------------------------------------

    def encode(self, seats: WorkerSeatTable | None = None) -> bytes:
        """v1 without ``seats``; v2 (seat bitmap + concatenated sigs)
        with. A signer missing from the table falls back to v1 — decode
        accepts both, so the fallback can never split a committee."""
        if seats is not None and all(pk in seats.index for pk, _ in self.pairs):
            ordered = sorted(
                ((seats.index[pk], sig) for pk, sig in self.pairs)
            )
            enc = (
                Encoder()
                .u8(TAG_CERT_V2)
                .raw(self.digest.data)
                .u32(len(ordered))
                .raw(_seats_bitmap([s for s, _ in ordered], seats.nbytes))
            )
            for _, sig in ordered:
                enc.raw(sig.data)
            return enc.finish()
        enc = Encoder().u8(TAG_CERT).raw(self.digest.data).u32(len(self.pairs))
        for pk, sig in self.pairs:
            enc.raw(pk.data)
            enc.raw(sig.data)
        return enc.finish()

    @classmethod
    def decode(
        cls, data: bytes, seats: WorkerSeatTable | None = None
    ) -> "AvailabilityCert":
        dec = Decoder(data)
        tag = dec.u8()
        if tag == TAG_CERT:
            digest = Digest(dec.raw(32))
            n = dec.u32()
            pairs = [
                (PublicKey(dec.raw(32)), Signature(dec.raw(64)))
                for _ in range(n)
            ]
            dec.finish()
            return cls(digest, pairs)
        if tag == TAG_CERT_V2:
            if seats is None:
                raise SerdeError("v2 cert without a seat table")
            digest = Digest(dec.raw(32))
            n = dec.u32()
            seat_list = _bitmap_seats(dec.raw(seats.nbytes), len(seats))
            if len(seat_list) != n:
                raise SerdeError(
                    f"cert bitmap popcount {len(seat_list)} != count {n}"
                )
            pairs = [
                (seats.keys[s], Signature(dec.raw(64))) for s in seat_list
            ]
            dec.finish()
            return cls(digest, pairs)
        raise SerdeError(f"unknown cert tag {tag}")


class CertCollector:
    """Accumulates verified acks for ONE batch until the stake quorum.

    The disseminating worker seeds it with its own signed ack (own stake
    counts, exactly like the reference QuorumWaiter), then feeds peer
    acks as their reply frames resolve; ``add_ack`` verifies signature +
    membership + digest binding and returns the finished certificate the
    moment accumulated stake reaches 2f+1."""

    def __init__(
        self,
        committee: Committee,
        digest: Digest,
        own: tuple[PublicKey, Signature] | None = None,
    ) -> None:
        self.committee = committee
        self.digest = digest
        self._signed = ack_digest(digest)
        self.pairs: list[tuple[PublicKey, Signature]] = []
        self.stake = 0
        self._seen: set[PublicKey] = set()
        self._done = False
        if own is not None:
            self.add_ack(*own)

    def add_ack(
        self, signer: PublicKey, signature: Signature
    ) -> AvailabilityCert | None:
        """Returns the certificate exactly once, at the ack that crosses
        the quorum; raises CertError on an invalid ack."""
        if self._done or signer in self._seen:
            return None  # post-quorum straggler / retransmit: harmless
        stake = self.committee.stake(signer)
        if stake == 0:
            raise CertError(f"ack signer {signer} not in committee")
        try:
            signature.verify(self._signed, signer)
        except CryptoError as e:
            raise CertError(f"bad ack signature from {signer}: {e}") from e
        self._seen.add(signer)
        self.pairs.append((signer, signature))
        self.stake += stake
        if self.stake >= self.committee.quorum_threshold():
            self._done = True
            return AvailabilityCert(self.digest, list(self.pairs))
        return None

    def complete(self) -> bool:
        return self.stake >= self.committee.quorum_threshold()
