"""One Conveyor worker shard: ingest → seal → disseminate → certify.

Each worker owns two listeners (client ingress + peer port) and three
loops:

- the **ingress handler** bounds arrivals (bundles beyond the queue
  capacity are shed with a client-visible ``b"Shed"`` reply) — the
  receive loop never blocks on a full queue;
- the **batcher** drains bundles into a batch (seal by size or delay,
  exactly the BatchMaker contract), gated by the store-depth watermark:
  while depth is above HIGH the batcher parks, ingress fills, and the
  edge sheds — graceful degradation instead of queue collapse;
- the **certifier** turns each sealed batch's signed ack replies into a
  :class:`~.certificate.AvailabilityCert` at 2f+1 stake, persists it,
  best-effort-broadcasts it to peer workers, and only THEN hands the
  digest to consensus — the primary orders digests the committee
  provably holds.

The peer handler is the receiving half: store the raw batch frame under
its digest and reply a SIGNED ack (the reply rides the dissemination
connection, pairing FIFO with the ReliableSender's in-flight frames);
verify-then-store incoming certs and feed their digests to our proposer
(any leader may order any certified batch, mirroring the reference
mempool's everyone-proposes-everything behavior); serve batch requests
from the store. A faultline ``batch_withhold`` byzantine node receives
batches but never acks and never serves — availability must rest on the
cert quorum, not on any individual peer's goodwill.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict

from hotstuff_tpu import telemetry
from hotstuff_tpu.crypto import PublicKey, SignatureService, sha512_digest
from hotstuff_tpu.faultline import hooks as _faultline
from hotstuff_tpu.network import MessageHandler, Receiver, ReliableSender, SimpleSender
from hotstuff_tpu.store import Store
from hotstuff_tpu.utils.serde import SerdeError

from ..config import Committee, Parameters
from . import messages
from .backpressure import BoundedIngress, Watermark
from .certificate import AvailabilityCert, CertCollector, CertError, WorkerSeatTable

log = logging.getLogger("mempool")

#: extra dissemination time granted to the f slowest peers after quorum
#: (mirrors the QuorumWaiter's linger contract).
LINGER_S = 0.5
#: bound on concurrently-certifying batches per worker.
CERTIFY_QUEUE_MAX = 10_000
#: recent-bundle dedup window (client retransmissions), per worker.
DEDUP_WINDOW = 4096


def _withholding() -> bool:
    """True while this node's faultline plane marks it batch-withholding."""
    plane = _faultline.plane
    if plane is None:
        return False
    node = _faultline.current_node()
    return node is not None and plane.behavior_active(node, "batch_withhold")


class IngressHandler(MessageHandler):
    """Client bundles: bound or shed, never block the read loop."""

    def __init__(self, ingress: BoundedIngress) -> None:
        self.ingress = ingress
        self._m_bundles = telemetry.counter("mempool.worker.ingress_bundles")
        self._m_txs = telemetry.counter("mempool.worker.ingress_tx")
        self._m_shed_b = telemetry.counter("mempool.worker.shed_bundles")
        self._m_shed_tx = telemetry.counter("mempool.worker.shed_tx")

    async def dispatch(self, writer, message: bytes) -> None:
        if not message or message[0] != messages.TAG_TX_BUNDLE:
            log.warning("non-bundle frame on worker ingress (tag %r)",
                        message[:1])
            return
        # Header peek only (serde ints are little-endian) — the hot path
        # never parses transactions.
        n_txs = int.from_bytes(message[1:5], "little")
        # Arrival stamp rides with the frame (perf_counter: the trace
        # timebase) so the seal site can back-date the batch's lifeline
        # ``ingress`` event — the hot path pays one clock read, no trace.
        if self.ingress.offer((time.perf_counter(), message)):
            self._m_bundles.inc()
            self._m_txs.inc(n_txs)
        else:
            self._m_shed_b.inc()
            self._m_shed_tx.inc(n_txs)
            # Client-visible shedding: the load generator reads these and
            # can adapt its offered rate.
            await writer.send(b"Shed")

    async def dispatch_frames(self, pairs) -> None:
        """Batched ingress (both transports hand one list of
        ``(writer, bundle)`` per wakeup): one clock read and one await
        point for the whole wakeup's bundles — the per-frame coroutine
        hop was most of the small-frame ``ingress_wait`` edge."""
        now = time.perf_counter()
        n_ok = tx_ok = n_shed = tx_shed = 0
        shed_writers = []
        for writer, message in pairs:
            if not message or message[0] != messages.TAG_TX_BUNDLE:
                log.warning("non-bundle frame on worker ingress (tag %r)",
                            message[:1])
                continue
            n_txs = int.from_bytes(message[1:5], "little")
            if self.ingress.offer((now, message)):
                n_ok += 1
                tx_ok += n_txs
            else:
                n_shed += 1
                tx_shed += n_txs
                shed_writers.append(writer)
        if n_ok:
            self._m_bundles.inc(n_ok)
            self._m_txs.inc(tx_ok)
        if n_shed:
            self._m_shed_b.inc(n_shed)
            self._m_shed_tx.inc(tx_shed)
            for writer in shed_writers:
                await writer.send(b"Shed")


class PeerWorkerHandler(MessageHandler):
    """Peer frames on the worker port: batches, certs, batch requests."""

    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        signature_service: SignatureService,
        tx_consensus: asyncio.Queue,
    ) -> None:
        self.name = name
        self.committee = committee
        self.store = store
        self.signature_service = signature_service
        self.tx_consensus = tx_consensus
        self.seats = WorkerSeatTable.for_committee(committee)
        self.helper_net = SimpleSender()
        self._m_batches = telemetry.counter("mempool.worker.batches_stored")
        self._m_bytes = telemetry.counter("mempool.worker.batch_bytes_in")
        self._m_certs = telemetry.counter("mempool.worker.certs_stored")
        self._m_bad_certs = telemetry.counter("mempool.worker.certs_rejected")
        self._m_withheld = telemetry.counter(
            "faultline.injected.acks_withheld"
        )
        self._node_label = repr(name)

    async def dispatch(self, writer, message: bytes) -> None:
        tag = message[0] if message else -1
        if tag == messages.TAG_BATCH:
            digest = sha512_digest(message)
            await self.store.write(digest.data, message)
            self._m_batches.inc()
            self._m_bytes.inc(len(message))
            if _withholding():
                # Byzantine availability attack: hold the bytes, withhold
                # the attestation. The sender's cert must come from the
                # honest remainder.
                self._m_withheld.inc()
                return
            sig = await self.signature_service.request_signature(
                messages.ack_digest(digest)
            )
            await writer.send(messages.encode_ack(digest, self.name, sig))
        elif tag in (messages.TAG_CERT, messages.TAG_CERT_V2):
            try:
                cert = AvailabilityCert.decode(message, self.seats)
            except SerdeError as e:
                log.warning("bad cert frame: %s", e)
                self._m_bad_certs.inc()
                return
            key = messages.cert_key(cert.digest.data)
            if await self.store.read(key) is not None:
                return  # known (and verified once already)
            try:
                cert.verify(self.committee)
            except CertError as e:
                log.warning("rejecting availability cert: %s", e)
                self._m_bad_certs.inc()
                return
            await self.store.write(key, message)
            self._m_certs.inc()
            # A certified digest is orderable by ANY leader: offer it to
            # our proposer too (committed duplicates are cleaned from
            # every proposer buffer on commit, reference behavior).
            await self.tx_consensus.put(cert.digest)
            if telemetry.dtrace_enabled():
                # Lifeline: a peer cert (wire v1 or v2 — decode handled
                # both above) put this digest into OUR proposer queue.
                telemetry.dtrace_event(
                    self._node_label,
                    telemetry.intern_label(cert.digest.data),
                    "enqueue",
                    detail="peer",
                )
        elif tag == messages.TAG_BATCH_REQUEST:
            try:
                digests, requestor = messages.decode_batch_request(message)
            except SerdeError as e:
                log.warning("bad batch request: %s", e)
                return
            if _withholding():
                self._m_withheld.inc()
                return
            address = self._requestor_address(requestor)
            if address is None:
                log.warning("batch request from unknown node %s", requestor)
                return
            for digest in digests:
                batch = await self.store.read(digest.data)
                if batch is not None:
                    self.helper_net.send(address, batch)
        else:
            log.warning("unknown worker frame tag %d", tag)

    def _requestor_address(self, requestor: PublicKey):
        # Prefer the requestor's worker-0 port; fall back to its legacy
        # mempool port (whose handler recognizes dataplane batch frames).
        addr = self.committee.worker_address(requestor, 0)
        return addr if addr is not None else self.committee.mempool_address(
            requestor
        )


class Worker:
    """One worker shard's actors; see module docstring."""

    def __init__(
        self,
        name: PublicKey,
        worker_id: int,
        committee: Committee,
        parameters: Parameters,
        store: Store,
        signature_service: SignatureService,
        tx_consensus: asyncio.Queue,
        watermark: Watermark,
        on_sealed=None,  # callback(digest) -> None: depth bookkeeping
        benchmark: bool = False,
    ) -> None:
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.parameters = parameters
        self.store = store
        self.signature_service = signature_service
        self.tx_consensus = tx_consensus
        self.watermark = watermark
        self.on_sealed = on_sealed
        self.benchmark = benchmark
        self.seats = WorkerSeatTable.for_committee(committee)
        self.ingress = BoundedIngress(parameters.worker_ingress_capacity)
        self.peers = committee.worker_peers(name, worker_id)
        self.network = ReliableSender()
        self.cert_network = SimpleSender()
        self.tasks: list[asyncio.Task] = []
        self.receivers: list[Receiver] = []
        self._certifiers: set[asyncio.Task] = set()
        self._dedup: OrderedDict[int, None] = OrderedDict()
        self._m_sealed = telemetry.counter("mempool.worker.batches_sealed")
        self._m_bytes_out = telemetry.counter("mempool.worker.batch_bytes_out")
        self._m_certs = telemetry.counter("mempool.worker.certs_formed")
        self._m_cert_fail = telemetry.counter("mempool.worker.certs_failed")
        self._m_acks = telemetry.counter("mempool.worker.acks_received")
        self._m_bad_acks = telemetry.counter("mempool.worker.acks_invalid")
        self._m_dedup = telemetry.counter("mempool.worker.dedup_hits")
        self._g_ingress = telemetry.gauge("mempool.worker.ingress_depth")
        self._h_ack = telemetry.histogram("mempool.worker.ack_latency_ms")
        # Lifeline events label their node once (repr is a base64 encode).
        self._node_label = repr(name)

    async def spawn(self) -> "Worker":
        entry = self.committee.workers_of(self.name)[self.worker_id]
        self.receivers.append(
            await Receiver.spawn(
                ("0.0.0.0", entry.transactions_address[1]),
                IngressHandler(self.ingress),
            )
        )
        self.receivers.append(
            await Receiver.spawn(
                ("0.0.0.0", entry.worker_address[1]),
                PeerWorkerHandler(
                    self.name,
                    self.committee,
                    self.store,
                    self.signature_service,
                    self.tx_consensus,
                ),
            )
        )
        self.tasks.append(
            asyncio.create_task(
                self._run_batcher(), name=f"worker{self.worker_id}_batcher"
            )
        )
        log.info(
            "Worker %d booted (ingress :%d, peers :%d)",
            self.worker_id,
            entry.transactions_address[1],
            entry.worker_address[1],
        )
        return self

    # -- batching ------------------------------------------------------------

    async def _run_batcher(self) -> None:
        batch_size = self.parameters.batch_size
        max_delay = self.parameters.max_batch_delay / 1000.0
        segments: list[bytes] = []
        n_txs = 0
        samples: list[int] = []
        size = 0
        first_arrival: float | None = None
        deadline = time.monotonic() + max_delay
        while True:
            # Back-pressure gate: while store depth is above HIGH, stop
            # consuming — ingress fills and sheds at the edge.
            await self.watermark.wait_ok()
            timeout = max(deadline - time.monotonic(), 0)
            try:
                arrived, frame = await asyncio.wait_for(
                    self.ingress.get(), timeout
                )
            except asyncio.TimeoutError:
                if segments:
                    await self._seal(
                        segments, n_txs, samples, size, first_arrival
                    )
                    segments, n_txs, samples, size = [], 0, [], 0
                    first_arrival = None
                deadline = time.monotonic() + max_delay
                continue
            try:
                bundle_txs, bundle_samples, blob = messages.decode_bundle(frame)
            except SerdeError as e:
                log.warning("dropping malformed bundle: %s", e)
                continue
            # Best-effort dedup of client retransmissions, at bundle
            # granularity (clients retry whole bundles).
            key = hash(blob)
            if key in self._dedup:
                self._m_dedup.inc()
                continue
            self._dedup[key] = None
            if len(self._dedup) > DEDUP_WINDOW:
                self._dedup.popitem(last=False)
            if first_arrival is None:
                first_arrival = arrived
            segments.append(blob)
            n_txs += bundle_txs
            samples.extend(bundle_samples)
            size += messages.batch_tx_bytes(bundle_txs, blob)
            if size >= batch_size:
                await self._seal(segments, n_txs, samples, size, first_arrival)
                segments, n_txs, samples, size = [], 0, [], 0
                first_arrival = None
                deadline = time.monotonic() + max_delay

    async def _seal(
        self,
        segments: list[bytes],
        n_txs: int,
        samples: list[int],
        size: int,
        first_arrival: float | None = None,
    ) -> None:
        serialized = messages.encode_worker_batch(
            self.worker_id, n_txs, samples, b"".join(segments)
        )
        digest = sha512_digest(serialized)
        await self.store.write(digest.data, serialized)
        self._m_sealed.inc()
        self._m_bytes_out.inc(len(serialized) * len(self.peers))
        batch_label = None
        if telemetry.enabled():
            self._g_ingress.set(self.ingress.qsize())
            telemetry.record_sealed(digest.data, size)
        if telemetry.dtrace_enabled():
            # Lifeline: the batch's timeline opens with the earliest
            # contributing bundle's arrival (back-dated — the ingress hot
            # path records nothing) and the seal instant. The seal detail
            # carries the shard, the sizes, and the leading sample ids so
            # the assembler can join client submit timestamps.
            batch_label = telemetry.intern_label(digest.data)
            if first_arrival is not None:
                telemetry.dtrace_event(
                    self._node_label, batch_label, "ingress", t=first_arrival
                )
            detail = f"w{self.worker_id}|{n_txs}tx|{size}B"
            if samples:
                detail += "|s" + ",".join(str(s) for s in samples[:4])
            telemetry.dtrace_event(
                self._node_label, batch_label, "seal", detail=detail
            )
        if self.benchmark:
            for tx_id in samples:
                # NOTE: benchmark measurement interface (same contract as
                # the legacy BatchMaker).
                log.info("Batch %s contains sample tx %d", digest, tx_id)
            log.info("Batch %s contains %d B", digest, size)
        if self.on_sealed is not None:
            self.on_sealed(digest)

        own_sig = await self.signature_service.request_signature(
            messages.ack_digest(digest)
        )
        collector = CertCollector(
            self.committee, digest, own=(self.name, own_sig)
        )
        handlers = [
            (pk, await self.network.send(addr, serialized))
            for pk, addr in self.peers
        ]
        if batch_label is not None:
            # Every dissemination frame is with the ReliableSender now;
            # first-ack minus this mark is the wire+store+sign edge.
            telemetry.dtrace_event(
                self._node_label, batch_label, "disseminate"
            )
        if len(self._certifiers) >= CERTIFY_QUEUE_MAX:
            log.warning("certifier queue full; dropping batch %s", digest)
            self._m_cert_fail.inc()
            for _, h in handlers:
                h.cancel()
            return
        task = asyncio.create_task(
            self._certify(
                digest, collector, handlers, time.monotonic(), batch_label
            )
        )
        self._certifiers.add(task)
        task.add_done_callback(self._certifiers.discard)

    # -- certification -------------------------------------------------------

    async def _certify(
        self,
        digest,
        collector: CertCollector,
        handlers: list,
        t0: float,
        label: str | None = None,
    ) -> None:
        pending = {h: pk for pk, h in handlers}
        traced = label is not None and telemetry.dtrace_enabled()
        first_ack_pending = traced
        cert: AvailabilityCert | None = (
            AvailabilityCert(digest, list(collector.pairs))
            if collector.complete()
            else None
        )
        while cert is None and pending:
            done, _ = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for fut in done:
                pending.pop(fut)
                if fut.cancelled():
                    continue
                try:
                    ack_d, signer, sig = messages.decode_ack(fut.result())
                    if ack_d != digest:
                        raise CertError("ack digest mismatch")
                    maybe = collector.add_ack(signer, sig)
                except (SerdeError, CertError, ValueError) as e:
                    log.warning("invalid batch ack: %s", e)
                    self._m_bad_acks.inc()
                    continue
                self._m_acks.inc()
                if first_ack_pending:
                    # One lifeline event for the FIRST verified seat ack
                    # only: the assembler's fan-in edge is first-ack →
                    # cert, and keeping the ack hot path to a single
                    # event holds the attached-plane overhead under the
                    # CI budget. The quorum size rides on the cert
                    # event's detail.
                    first_ack_pending = False
                    telemetry.dtrace_event(
                        self._node_label, label, "ack", detail=repr(signer)
                    )
                if maybe is not None:
                    cert = maybe
        if cert is None:
            log.warning("batch %s failed to reach an ack quorum", digest)
            self._m_cert_fail.inc()
            return
        self._h_ack.observe((time.monotonic() - t0) * 1e3)
        if traced:
            telemetry.dtrace_event(
                self._node_label, label, "cert",
                detail=f"a{len(cert.pairs)}",
            )
        encoded = cert.encode(self.seats)
        await self.store.write(messages.cert_key(digest.data), encoded)
        self._m_certs.inc()
        # Best-effort cert broadcast: lets peers vote on (and propose)
        # this digest without the batch; anyone who misses it falls back
        # to fetching the batch itself.
        for _pk, addr in self.peers:
            self.cert_network.send(addr, encoded)
        # Only now does the digest reach consensus: ordering is gated on
        # proven availability.
        await self.tx_consensus.put(digest)
        if traced:
            telemetry.dtrace_event(
                self._node_label, label, "enqueue", detail="own"
            )
        if pending:
            # Give the slow minority a bounded grace period, then stop
            # retransmitting to them (they can sync later).
            try:
                await asyncio.wait_for(
                    asyncio.gather(*pending, return_exceptions=True), LINGER_S
                )
            except asyncio.TimeoutError:
                for h in pending:
                    if not h.done():
                        h.cancel()

    async def shutdown(self) -> None:
        for t in self.tasks:
            t.cancel()
        for t in list(self._certifiers):
            t.cancel()
        for r in self.receivers:
            await r.shutdown()
