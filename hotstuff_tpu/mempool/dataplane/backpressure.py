"""Conveyor back-pressure: bounded ingress with client-visible shedding
and a store-depth watermark that throttles batch sealing.

The contract (ROADMAP item 3): under overload the system degrades
GRACEFULLY — latency rises, throughput plateaus, clients see explicit
shed signals — instead of queues growing until the process collapses.
Two mechanisms compose end to end:

- :class:`BoundedIngress` — the edge. Each worker's client-facing queue
  is bounded in BUNDLES; a full queue sheds the arriving bundle and the
  ingress handler replies ``b"Shed"`` on the client connection, so an
  adaptive load generator can observe exactly which portion of its offer
  was refused (client-visible shedding, not silent loss).
- :class:`Watermark` — the interior signal. Worker store depth (sealed
  batches not yet committed) crossing the HIGH watermark gates sealing;
  the ingress then fills and sheds at the edge. Sealing resumes only at
  the LOW watermark (hysteresis: no flapping at the boundary). The
  depth rides the ``mempool.worker.store_depth`` gauge and every
  transition counts into ``mempool.worker.throttle_events``.
"""

from __future__ import annotations

import asyncio

from hotstuff_tpu import telemetry

__all__ = ["BoundedIngress", "Watermark"]


class BoundedIngress:
    """Bounded FIFO with non-blocking producer side.

    ``offer`` never blocks the receive loop: it either enqueues or sheds
    (returns False). The consumer side is the usual awaitable ``get``.
    """

    def __init__(self, capacity: int) -> None:
        self._q: asyncio.Queue = asyncio.Queue(capacity)
        self.shed = 0  # bundles refused (telemetry mirrors per worker)

    def offer(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            self.shed += 1
            return False

    async def get(self):
        return await self._q.get()

    def get_nowait(self):
        return self._q.get_nowait()

    def qsize(self) -> int:
        return self._q.qsize()

    @property
    def capacity(self) -> int:
        return self._q.maxsize


class Watermark:
    """High/low hysteresis gate over a depth counter.

    States: ``ok`` (sealing allowed) and ``high`` (sealing gated).
    ``ok -> high`` at depth >= high; ``high -> ok`` at depth <= low.
    ``wait_ok`` parks the caller while gated.
    """

    def __init__(self, high: int, low: int, name: str = "mempool.worker") -> None:
        if low > high:
            raise ValueError(f"low watermark {low} above high {high}")
        self.high = high
        self.low = low
        self.depth = 0
        self.transitions = 0
        self._ok = asyncio.Event()
        self._ok.set()
        self._g_depth = telemetry.gauge(f"{name}.store_depth")
        self._m_throttle = telemetry.counter(f"{name}.throttle_events")

    @property
    def gated(self) -> bool:
        return not self._ok.is_set()

    def update(self, depth: int) -> None:
        self.depth = depth
        self._g_depth.set(depth)
        if not self.gated and depth >= self.high:
            self._ok.clear()
            self.transitions += 1
            self._m_throttle.inc()
        elif self.gated and depth <= self.low:
            self._ok.set()
            self.transitions += 1

    def adjust(self, delta: int) -> None:
        self.update(self.depth + delta)

    async def wait_ok(self) -> None:
        await self._ok.wait()
