"""Conveyor: a Narwhal-style worker-sharded data plane.

Separates batch dissemination from ordering (Danezis et al., "Narwhal
and Tusk" — asonnino's follow-up to the reference HotStuff codebase):
per-node worker shards batch client transactions independently,
disseminate batches to peer workers, collect 2f+1 signed availability
acks into a **batch availability certificate**, and hand only certified
digests to the primary. Consensus orders digests it can prove the
committee already holds; the commit path resolves digests back to
batches from the local worker store. Ingest bandwidth scales with the
worker count instead of riding the consensus critical path.
"""

from .backpressure import BoundedIngress, Watermark
from .certificate import (
    AvailabilityCert,
    CertCollector,
    CertError,
    WorkerSeatTable,
)
from .dataplane import CommitResolver, DataPlane
from .messages import ack_digest, cert_key
from .worker import IngressHandler, PeerWorkerHandler, Worker

__all__ = [
    "AvailabilityCert",
    "BoundedIngress",
    "CertCollector",
    "CertError",
    "CommitResolver",
    "DataPlane",
    "IngressHandler",
    "PeerWorkerHandler",
    "Watermark",
    "Worker",
    "WorkerSeatTable",
    "ack_digest",
    "cert_key",
]
