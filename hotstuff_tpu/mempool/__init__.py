"""Mempool layer: batch dissemination ahead of consensus (reference
``mempool/src/mempool.rs``).

Data-plane/control-plane split: bulk transaction data travels
mempool-to-mempool as batches; consensus orders only 32-byte digests
(reference ``batch_maker.rs:100-155``, ``consensus/src/messages.rs:22``).
"""

from .config import Authority, Committee, Parameters, WorkerEntry
from .mempool import Mempool
from .synchronizer import Cleanup, Synchronize

__all__ = [
    "Authority",
    "Committee",
    "Parameters",
    "WorkerEntry",
    "Mempool",
    "Synchronize",
    "Cleanup",
]
