"""Processor: hash batches (SHA-512/32), persist them, emit the digest to
consensus (reference ``mempool/src/processor.rs:18-38``). Spawned twice: once
for our own quorum-ACKed batches, once for batches received from peers."""

from __future__ import annotations

import asyncio

from hotstuff_tpu.crypto import sha512_digest
from hotstuff_tpu.store import Store


class Processor:
    @classmethod
    def spawn(
        cls, store: Store, rx_batch: asyncio.Queue, tx_digest: asyncio.Queue
    ) -> asyncio.Task:
        async def run():
            while True:
                batch: bytes = await rx_batch.get()
                digest = sha512_digest(batch)
                await store.write(digest.data, batch)
                await tx_digest.put(digest)

        return asyncio.create_task(run(), name="processor")
