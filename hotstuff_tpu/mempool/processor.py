"""Processor: hash batches (SHA-512/32), persist them, emit the digest to
consensus (reference ``mempool/src/processor.rs:18-38``). Spawned twice: once
for our own quorum-ACKed batches, once for batches received from peers.

With ``device_digests=True`` the processor greedily drains its input queue
and hashes all concurrently-pending batches in ONE device call
(``ops.sha512.sha512_32_batch`` — the batched SHA-512 TPU kernel), the
BASELINE config-3 regime: at committee scale hundreds of peer batches
arrive per round and the digest work is throughput-bound, not
latency-bound. A lone batch (or any device failure) falls back to host
hashing, so the flag can never lose digests.

Default recommendation (measured, ``benchmark.digest_bench``): keep
``device_digests=False`` unless running on real TPU hardware AND the
mempool drains tens of batches per wakeup. On the CPU platform host
hashlib wins by ~30x (``results/digest-bench-cpu.txt``: 0.89 ms host vs
27.6 ms emulated-device for 32 x 15 kB); the hardware number is captured
by ``scripts/tpu_watchdog.py`` when the TPU tunnel is up.
"""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu import telemetry
from hotstuff_tpu.crypto import Digest, sha512_digest
from hotstuff_tpu.store import Store

log = logging.getLogger("mempool")

# Bound the per-call device batch: keeps the padded transfer bounded and the
# compiled shapes few (powers of two up to this cap).
MAX_DEVICE_BATCH = 128


def _device_digest_many(batches: list[bytes]) -> list[Digest]:
    from hotstuff_tpu.ops.sha512 import sha512_32_batch

    return [Digest(d) for d in sha512_32_batch(batches)]


class Processor:
    @classmethod
    def spawn(
        cls,
        store: Store,
        rx_batch: asyncio.Queue,
        tx_digest: asyncio.Queue,
        device_digests: bool = False,
    ) -> asyncio.Task:
        async def run():
            m_batches = telemetry.counter("mempool.batches_processed")
            m_bytes = telemetry.counter("mempool.batch_bytes_stored")
            while True:
                batch: bytes = await rx_batch.get()
                batches = [batch]
                if device_digests:
                    while len(batches) < MAX_DEVICE_BATCH:
                        try:
                            batches.append(rx_batch.get_nowait())
                        except asyncio.QueueEmpty:
                            break
                if device_digests and len(batches) > 1:
                    try:
                        digests = await asyncio.to_thread(
                            _device_digest_many, batches
                        )
                    except Exception as exc:  # noqa: BLE001 — device outage
                        log.warning(
                            "device digest of %d batches failed (%r); "
                            "falling back to host hashing",
                            len(batches),
                            exc,
                        )
                        digests = [sha512_digest(b) for b in batches]
                else:
                    digests = [sha512_digest(b) for b in batches]
                for digest, b in zip(digests, batches):
                    m_batches.inc()
                    m_bytes.inc(len(b))
                    await store.write(digest.data, b)
                    await tx_digest.put(digest)

        return asyncio.create_task(run(), name="processor")
