"""BatchMaker: assemble client transactions into batches and disseminate
them (reference ``mempool/src/batch_maker.rs``).

Seals when the batch reaches ``batch_size`` bytes or after ``max_batch_delay``
ms, whichever first; reliable-broadcasts the sealed batch to all peer
mempools and hands the serialized batch plus the ACK handlers to the
QuorumWaiter (reference ``batch_maker.rs:74-155``).
"""

from __future__ import annotations

import asyncio
import logging
import time

from hotstuff_tpu import telemetry
from hotstuff_tpu.crypto import PublicKey, sha512_digest
from hotstuff_tpu.network import ReliableSender

from .messages import encode_batch
from .quorum_waiter import QuorumWaiterMessage

log = logging.getLogger("mempool")

Transaction = bytes


class BatchMaker:
    def __init__(
        self,
        batch_size: int,
        max_batch_delay: int,
        rx_transaction: asyncio.Queue,
        tx_message: asyncio.Queue,
        mempool_addresses: list[tuple[PublicKey, tuple[str, int]]],
        benchmark: bool = False,
    ) -> None:
        self.batch_size = batch_size
        self.max_batch_delay = max_batch_delay / 1000.0
        self.rx_transaction = rx_transaction
        self.tx_message = tx_message
        self.mempool_addresses = mempool_addresses
        self.benchmark = benchmark
        self.current_batch: list[Transaction] = []
        self.current_batch_size = 0
        self.network = ReliableSender()
        self._m_txs = telemetry.counter("mempool.txs_received")
        self._g_queue = telemetry.gauge("mempool.tx_queue_depth")

    @classmethod
    def spawn(cls, *args, **kwargs) -> asyncio.Task:
        self = cls(*args, **kwargs)
        return asyncio.create_task(self._run(), name="batch_maker")

    async def _run(self) -> None:
        deadline = time.monotonic() + self.max_batch_delay
        while True:
            timeout = max(deadline - time.monotonic(), 0)
            try:
                tx = await asyncio.wait_for(self.rx_transaction.get(), timeout)
                self._m_txs.inc()
                self.current_batch.append(tx)
                self.current_batch_size += len(tx)
                if self.current_batch_size >= self.batch_size:
                    await self._seal()
                    deadline = time.monotonic() + self.max_batch_delay
            except asyncio.TimeoutError:
                if self.current_batch:
                    await self._seal()
                deadline = time.monotonic() + self.max_batch_delay

    async def _seal(self) -> None:
        size = self.current_batch_size
        # Sample transactions start with byte 0 followed by a u64 id
        # (reference ``batch_maker.rs:105-115``); used for e2e latency.
        sample_ids = [
            int.from_bytes(tx[1:9], "big")
            for tx in self.current_batch
            if tx[:1] == b"\x00" and len(tx) > 8
        ]

        batch, self.current_batch, self.current_batch_size = self.current_batch, [], 0
        serialized = encode_batch(batch)

        digest = (
            sha512_digest(serialized)
            if self.benchmark or telemetry.enabled()
            else None
        )
        if telemetry.enabled():
            # Queue depth sampled at seal time (the moment of interest:
            # how far intake is running ahead of sealing) and the sealed
            # batch recorded under the same digest key the "Batch d
            # contains N B" regex contract uses.
            self._g_queue.set(self.rx_transaction.qsize())
            telemetry.record_sealed(digest.data, size)
        if self.benchmark:
            for tx_id in sample_ids:
                # NOTE: these exact log formats are the benchmark harness's
                # measurement interface (reference ``batch_maker.rs:129-139``).
                log.info("Batch %s contains sample tx %d", digest, tx_id)
            log.info("Batch %s contains %d B", digest, size)

        handlers = [
            (name, await self.network.send(address, serialized))
            for name, address in self.mempool_addresses
        ]
        await self.tx_message.put(QuorumWaiterMessage(serialized, handlers))
