"""Mempool synchronizer: fetch batches referenced by consensus that we miss
(reference ``mempool/src/synchronizer.rs``).

On ``Synchronize(digests, target)``: registers store ``notify_read`` waiters
for each missing digest and sends a ``BatchRequest`` to the block author. A
coarse timer rebroadcasts unanswered requests after ``sync_retry_delay`` to
``sync_retry_nodes`` random peers via ``lucky_broadcast``
(``synchronizer.rs:175-206``). ``Cleanup(round)`` cancels waiters older than
``gc_depth`` rounds (``synchronizer.rs:143-159``).

Retry policy matches the consensus synchronizer's: the idle tick does
zero work while nothing is outstanding (the steady state — the old loop
scanned ``pending`` every second forever), and each retry RE-ARMS its
request for a full ``sync_retry_delay`` instead of re-broadcasting on
every tick once expired (the committee-wide duplicate-request storm the
consensus side already fixed).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass

from hotstuff_tpu.crypto import Digest, PublicKey
from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store

from .config import Committee
from .messages import encode_batch_request

log = logging.getLogger("mempool")

TIMER_RESOLUTION = 1.0  # s (reference ``synchronizer.rs`` 1s-resolution timer)


@dataclass
class Synchronize:
    digests: list[Digest]
    target: PublicKey


@dataclass
class Cleanup:
    round: int


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        gc_depth: int,
        sync_retry_delay: int,
        sync_retry_nodes: int,
        rx_message: asyncio.Queue,
        clock=time.monotonic,
    ) -> None:
        self.name = name
        self.committee = committee
        self.store = store
        self.gc_depth = gc_depth
        self.sync_retry_delay = sync_retry_delay / 1000.0
        self.sync_retry_nodes = sync_retry_nodes
        self.rx_message = rx_message
        # Injectable clock (default untouched), mirroring the consensus
        # synchronizer: retry expiry must be judgeable without sleeping.
        self._clock = clock
        self.network = SimpleSender()
        self.round = 0
        # digest -> (round registered, waiter task, last request time)
        self.pending: dict[Digest, tuple[int, asyncio.Task, float]] = {}

    @classmethod
    def spawn(cls, *args, **kwargs) -> asyncio.Task:
        self = cls(*args, **kwargs)
        return asyncio.create_task(self._run(), name="mempool_synchronizer")

    async def _waiter(self, digest: Digest) -> None:
        await self.store.notify_read(digest.data)
        self.pending.pop(digest, None)

    async def _run(self) -> None:
        timer = asyncio.create_task(asyncio.sleep(TIMER_RESOLUTION))
        get_msg = asyncio.create_task(self.rx_message.get())
        while True:
            done, _ = await asyncio.wait(
                {timer, get_msg}, return_when=asyncio.FIRST_COMPLETED
            )
            if get_msg in done:
                message = get_msg.result()
                get_msg = asyncio.create_task(self.rx_message.get())
                if isinstance(message, Synchronize):
                    await self._handle_synchronize(message)
                elif isinstance(message, Cleanup):
                    self._handle_cleanup(message.round)
            if timer in done:
                timer = asyncio.create_task(asyncio.sleep(TIMER_RESOLUTION))
                # Idle fast path (PR 10's consensus-synchronizer fix): with
                # nothing outstanding — the steady state — the tick does no
                # work at all, not even a clock read.
                if self.pending:
                    self._retry_expired()

    async def _handle_synchronize(self, message: Synchronize) -> None:
        now = self._clock()
        missing = []
        for digest in message.digests:
            if digest in self.pending:
                continue  # never send the same sync request twice
            if await self.store.read(digest.data) is not None:
                continue
            log.debug("requesting sync for batch %s", digest)
            task = asyncio.create_task(self._waiter(digest))
            self.pending[digest] = (self.round, task, now)
            missing.append(digest)
        if not missing:
            return
        address = self.committee.mempool_address(message.target)
        if address is None:
            log.error("consensus asked us to sync with unknown node %s", message.target)
            return
        self.network.send(address, encode_batch_request(missing, self.name))

    def _handle_cleanup(self, round_: int) -> None:
        self.round = round_
        if self.round < self.gc_depth:
            return
        gc_round = self.round - self.gc_depth
        for digest in [d for d, (r, _, _) in self.pending.items() if r <= gc_round]:
            _, task, _ = self.pending.pop(digest)
            task.cancel()

    def _expired(self, now: float) -> list[Digest]:
        """Digests whose LAST request aged past ``sync_retry_delay``; each
        is re-armed for a full delay, so one retry per window — never one
        per poll tick (the consensus-side fix, applied here too)."""
        expired = [
            d
            for d, (_, _, ts) in self.pending.items()
            if ts + self.sync_retry_delay < now
        ]
        for d in expired:
            r, task, _ = self.pending[d]
            self.pending[d] = (r, task, now)
        return expired

    def _retry_expired(self) -> None:
        expired = self._expired(self._clock())
        if not expired:
            return
        # Best-effort gossip to a few random peers (``synchronizer.rs:190-202``).
        addresses = [a for _, a in self.committee.broadcast_addresses(self.name)]
        self.network.lucky_broadcast(
            addresses, encode_batch_request(expired, self.name), self.sync_retry_nodes
        )
