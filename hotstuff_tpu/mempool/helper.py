"""Mempool helper: serve peers' ``BatchRequest``s from the store (reference
``mempool/src/helper.rs:25-66``). The stored value is the full serialized
``Batch`` message, so it is sent back raw and flows the peer's normal
batch-reception path."""

from __future__ import annotations

import asyncio
import logging

from hotstuff_tpu.crypto import Digest, PublicKey
from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store

from .config import Committee

log = logging.getLogger("mempool")


class Helper:
    @classmethod
    def spawn(
        cls, committee: Committee, store: Store, rx_request: asyncio.Queue
    ) -> asyncio.Task:
        network = SimpleSender()

        async def run():
            while True:
                digests, origin = await rx_request.get()
                address = committee.mempool_address(origin)
                if address is None:
                    log.warning("received batch request from unknown node %s", origin)
                    continue
                for digest in digests:
                    batch = await store.read(digest.data)
                    if batch is not None:
                        network.send(address, batch)

        return asyncio.create_task(run(), name="mempool_helper")
