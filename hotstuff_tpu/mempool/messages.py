"""Mempool wire messages (reference ``mempool/src/mempool.rs:29-33``):
``Batch(Vec<Transaction>)`` and ``BatchRequest(Vec<Digest>, PublicKey)``."""

from __future__ import annotations

from hotstuff_tpu.crypto import Digest, PublicKey
from hotstuff_tpu.utils.serde import Decoder, Encoder, SerdeError

TAG_BATCH = 0
TAG_BATCH_REQUEST = 1


def encode_batch(transactions: list[bytes]) -> bytes:
    return (
        Encoder()
        .u8(TAG_BATCH)
        .seq(transactions, lambda e, tx: e.bytes(tx))
        .finish()
    )


def encode_batch_request(digests: list[Digest], requestor: PublicKey) -> bytes:
    return (
        Encoder()
        .u8(TAG_BATCH_REQUEST)
        .seq(digests, lambda e, d: e.raw(d.data))
        .raw(requestor.data)
        .finish()
    )


def decode(data: bytes):
    """Returns ("batch", [tx...]) or ("batch_request", ([digests], requestor)).

    Raises SerdeError on malformed input (byzantine peers)."""
    dec = Decoder(data)
    tag = dec.u8()
    if tag == TAG_BATCH:
        txs = dec.seq(lambda d: d.bytes())
        dec.finish()
        return ("batch", txs)
    if tag == TAG_BATCH_REQUEST:
        digests = dec.seq(lambda d: Digest(d.raw(32)))
        requestor = PublicKey(dec.raw(32))
        dec.finish()
        return ("batch_request", (digests, requestor))
    raise SerdeError(f"unknown mempool message tag {tag}")
