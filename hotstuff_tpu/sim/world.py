"""SimWorld: N sans-io consensus machines on one virtual-time event heap.

A fully simulated execution of a faultline :class:`~..faultline.policy.
Scenario`: the same compiled :class:`~..faultline.policy.Schedule`, the
same :class:`~..faultline.runtime.FaultPlane` link filters (driven by
the injected virtual clock), the same :func:`~..faultline.checker.check`
verdict — and zero real sleeping. A 20-virtual-second chaos schedule
with two view changes costs milliseconds of CPU, which is what turns the
seeded sweep from "a handful of pinned seeds" into a search
(``benchmark/sim_sweep.py``).

Determinism: every event is ``(time, seq, ...)`` with ``seq`` a
monotonic insertion counter — ties process in scheduling order, so two
runs of the same ``(scenario, n, jitter)`` produce byte-identical commit
streams. Message latency comes from per-directed-link RNG streams
derived from ``(scenario.seed, jitter)``; ``jitter`` perturbs ONLY the
latency draws, giving a cheap way to explore interleavings of one fault
schedule.

Twins support: ``twins`` maps extra node INSTANCES onto an existing
seat (same keypair, same address, separate store/machine). Frames to
that address fan out to every instance, each filtered independently by
the fault plane under its own instance name — the Twins-paper network
model of one equivocating identity living in several partitions at
once (:mod:`hotstuff_tpu.sim.twins` generates the scenarios).
"""

from __future__ import annotations

import heapq
import itertools
import logging

from hotstuff_tpu.consensus.config import Authority, Committee
from hotstuff_tpu.consensus.decode_arena import decode_shared
from hotstuff_tpu.consensus.errors import MalformedMessage
from hotstuff_tpu.consensus.messages import (
    Block,
    QC,
    Vote,
    encode_propose,
    encode_vote,
)
from hotstuff_tpu.crypto import enable_verify_memo, generate_keypair, sha512_digest
from hotstuff_tpu.faultline.checker import CommitRecord, check
from hotstuff_tpu.faultline.policy import Scenario, _seed_stream
from hotstuff_tpu.faultline.runtime import FaultPlane
from hotstuff_tpu.utils.serde import SerdeError

from .clock import VirtualClock
from .machine import CoreStateMachine, _NotifyingStore

log = logging.getLogger("sim")

__all__ = ["SimWorld", "run_sim", "EventHeap"]

#: byzantine-actor burst cadence, mirrored from faultline.byzantine.
_BYZ_PERIOD_S = 0.05

#: epsilon nudging timer checks past float-equal deadlines.
_EPS = 1e-9


def _node_name(i: int) -> str:
    return f"n{i:03d}"  # matches faultline.harness naming


# Committee keypairs are a function of (index) only — NOT of the
# scenario seed — so a sweep over thousands of seeds generates keys
# once and the decode arena can share identical frames across runs.
_KEYPAIR_CACHE: dict[int, tuple] = {}


def _keypair(i: int):
    kp = _KEYPAIR_CACHE.get(i)
    if kp is None:
        kp = _KEYPAIR_CACHE[i] = generate_keypair(
            seed=bytes([i % 251]) * 24 + b"simworld"
        )[:2]
    return kp


class EventHeap:
    """Deterministic (time, seq)-ordered event queue: same-instant events
    pop in push order, whatever their payloads hash to."""

    def __init__(self) -> None:
        self._heap: list = []
        self._seq = itertools.count()

    def push(self, t: float, item) -> None:
        heapq.heappush(self._heap, (t, next(self._seq), item))

    def pop(self):
        t, _, item = heapq.heappop(self._heap)
        return t, item

    def peek_time(self) -> float | None:
        return self._heap[0][0] if self._heap else None

    def __len__(self) -> int:
        return len(self._heap)


class _Slot:
    """One node INSTANCE: seat key + address + persistent store, plus the
    current machine incarnation (None while crashed)."""

    __slots__ = (
        "index",
        "name",
        "base",
        "pk",
        "sk",
        "address",
        "engine",
        "machine",
        "incarnation",
        "timer_gen",
        "timer_target",
        "crashed",
    )

    def __init__(self, index, name, base, pk, sk, address) -> None:
        self.index = index
        self.name = name
        self.base = base  # committee seat name (== name except twins)
        self.pk = pk
        self.sk = sk
        self.address = address
        self.engine = None  # survives restarts: the node's "disk"
        self.machine: CoreStateMachine | None = None
        self.incarnation = 0
        self.timer_gen = 0
        self.timer_target = None
        self.crashed = False


class _SimByzantine:
    """Synchronous replay of ``faultline.byzantine.ByzantineActor``'s
    attack bursts (equivocate / stale_vote_flood) on the virtual
    timeline; the same seed-derived RNG stream, the same message
    construction, one burst per scheduled tick."""

    def __init__(self, world: "SimWorld", slot: _Slot, behavior: str) -> None:
        self.world = world
        self.slot = slot
        self.behavior = behavior
        self.rng = _seed_stream(
            world.scenario.seed, "byzantine", behavior, str(slot.pk)
        )
        self.active = True
        self.sent = 0

    def burst(self) -> None:
        committee = self.world.committee
        peers = [a for _, a in committee.broadcast_addresses(self.slot.pk)]
        if self.behavior == "equivocate":
            round_ = self.world._honest_round() + 1
            parent = sha512_digest(b"equivocation-parent", self.rng.randbytes(8))
            fake_qc = QC(hash=parent, round=round_ - 1, votes=[])
            half = len(peers) // 2
            for salt, targets in ((b"a", peers[:half]), (b"b", peers[half:])):
                block = Block.new_from_key(
                    fake_qc,
                    None,
                    self.slot.pk,
                    round_,
                    [sha512_digest(b"equiv-payload-" + salt)],
                    self.slot.sk,
                )
                data = encode_propose(block)
                for addr in targets or peers:
                    self.world._transmit(self.slot, addr, data)
                self.sent += 1
        elif self.behavior == "stale_vote_flood":
            current = self.world._honest_round()
            for _ in range(8):
                stale_round = max(1, current - self.rng.randrange(1, 50))
                vote = Vote.new_from_key(
                    sha512_digest(b"stale", self.rng.randbytes(8)),
                    stale_round,
                    self.slot.pk,
                    self.slot.sk,
                )
                data = encode_vote(vote)
                for addr in peers:
                    self.world._transmit(self.slot, addr, data)
                self.sent += 1
        # silent_leader needs no actor: the plane's send filter drops the
        # node's proposals (identical to the real runtime).


class SimWorld:
    def __init__(
        self,
        scenario: Scenario,
        n: int,
        *,
        timeout_delay: int = 1_000,
        sync_retry_delay: int = 10_000,
        leader_elector: str = "",
        batch_vote_verification: bool = True,
        min_recovery_commits: int = 3,
        recovery_timeout_s: float = 30.0,
        # Per-hop latency draw: (25, 75) ms paces a simulated committee
        # at roughly the round cadence the REAL N=4 localhost plane
        # shows for the same schedules (~100 ms/round), so a scenario's
        # virtual seconds cover comparable protocol ground on both
        # planes. Lower it for more rounds per schedule, at sweep cost.
        link_delay_ms: tuple[float, float] = (25.0, 75.0),
        jitter: int = 0,
        twins: dict[str, str] | None = None,
        base_port: int = 47000,
        verify_memo: bool = True,
        # Lazarus: snapshot/truncate retention depth (0 = no compaction)
        # and the anti-entropy probe loop (opt-in: committed sweep seeds
        # keep byte-identical event streams with it off).
        retention_rounds: int = 0,
        statesync_active: bool = False,
        # Oracle: a sim.streams.StreamRecorder capturing every node's
        # round-trace marks on the virtual clock for rendering into
        # real-shape telemetry streams.
        recorder=None,
        # Twins (per-round adversary controls): a {round: seat_name}
        # leader override, a {round: [group, ...]} network partition
        # keyed on the SENDER's current round, and per-instance proposal
        # salting so a twin pair's blocks conflict by digest.
        leader_schedule: dict[int, str] | None = None,
        round_partitions: dict[int, list] | None = None,
        twin_proposal_salt: bool = False,
    ) -> None:
        self.scenario = scenario
        self.n = n
        self.min_recovery_commits = min_recovery_commits
        self.recovery_timeout_s = recovery_timeout_s
        self.link_delay = (link_delay_ms[0] / 1e3, link_delay_ms[1] / 1e3)
        self.jitter = jitter
        self._verify_memo = verify_memo
        self._mach_kwargs = dict(
            timeout_delay=timeout_delay,
            sync_retry_delay=sync_retry_delay,
            leader_elector=leader_elector,
            batch_vote_verification=batch_vote_verification,
            retention_rounds=retention_rounds,
            statesync_active=statesync_active,
        )

        base_names = [_node_name(i) for i in range(n)]
        twins = dict(twins or {})
        for inst, base in twins.items():
            if base not in base_names:
                raise ValueError(f"twin {inst!r} maps to unknown node {base!r}")
        self.twins = twins
        instance_names = base_names + sorted(twins)
        # The compiled fault schedule ranges over INSTANCES so partitions
        # can separate a twin pair sharing one committee seat.
        self.schedule = scenario.compile(instance_names)

        addresses = {
            name: ("127.0.0.1", base_port + i)
            for i, name in enumerate(base_names)
        }
        keypairs = {name: _keypair(i) for i, name in enumerate(base_names)}
        self.committee = Committee(
            authorities={
                keypairs[name][0]: Authority(stake=1, address=addresses[name])
                for name in base_names
            }
        )

        self.clock = VirtualClock()
        self._recorder = recorder
        if recorder is not None:
            recorder.bind(
                self.clock,
                {repr(keypairs[name][0]): name for name in base_names},
            )
        self._twin_salt = bool(twin_proposal_salt)
        self._round_partitions = None
        if round_partitions:
            self._round_partitions = {
                int(r): [frozenset(g) for g in groups]
                for r, groups in round_partitions.items()
            }
        self._elector_override = None
        if leader_schedule is not None:
            from hotstuff_tpu.consensus.leader import ScheduledLeaderElector

            self._elector_override = ScheduledLeaderElector(
                self.committee,
                {
                    int(r): keypairs[name][0]
                    for r, name in leader_schedule.items()
                },
            )
        self.plane = FaultPlane(
            self.schedule,
            {addresses[name]: name for name in base_names},
            clock=self.clock,
        )

        self.slots: list[_Slot] = []
        self._by_addr: dict[tuple[str, int], list[_Slot]] = {}
        for i, name in enumerate(instance_names):
            base = twins.get(name, name)
            pk, sk = keypairs[base]
            slot = _Slot(i, name, base, pk, sk, addresses[base])
            self.slots.append(slot)
            self._by_addr.setdefault(slot.address, []).append(slot)
        self._by_name = {s.name: s for s in self.slots}

        self.heap = EventHeap()
        self.commits: dict[str, list[CommitRecord]] = {
            s.name: [] for s in self.slots
        }
        self._link_rngs: dict[tuple[str, str], object] = {}
        self._byz: dict[tuple[str, str], _SimByzantine] = {}
        self.events_processed = 0
        self.decode_errors = 0
        self._recovered = False
        self._heal_t = self.schedule.last_heal_time()
        byz_nodes = {
            e.params["node"]
            for e in self.schedule.events
            if e.kind == "byzantine"
        }
        twin_bases = set(twins.values())
        self._expected = (
            {s.name for s in self.slots}
            - self.schedule.crashed_forever()
            - byz_nodes
            - twin_bases
            - set(twins)
        )

    # -- helpers -----------------------------------------------------------

    def _honest_round(self) -> int:
        rounds = [
            s.machine.round
            for s in self.slots
            if s.machine is not None and not s.crashed
        ]
        return max(rounds, default=1)

    def _link_rng(self, src: str, dst: str):
        key = (src, dst)
        rng = self._link_rngs.get(key)
        if rng is None:
            rng = self._link_rngs[key] = _seed_stream(
                self.scenario.seed, "simnet", str(self.jitter), src, dst
            )
        return rng

    def _base_delay(self, src: str, dst: str) -> float:
        lo, hi = self.link_delay
        if hi <= 0.0:
            return 0.0
        return self._link_rng(src, dst).uniform(lo, hi)

    # -- transmission ------------------------------------------------------

    def _transmit(self, src_slot: _Slot, address, data: bytes) -> None:
        """Route one unframed wire message through the fault plane to
        every instance listening on ``address``."""
        now = self.clock.now
        rp = self._round_partitions
        if rp is not None and src_slot.machine is not None:
            # Twins per-round partition: connectivity for a message is
            # decided by the SENDER's current round. Rounds without an
            # assignment are fully connected.
            groups = rp.get(src_slot.machine.round)
        else:
            groups = None
        for dst_slot in self._by_addr.get(address, ()):
            if groups is not None and not any(
                src_slot.name in g and dst_slot.name in g for g in groups
            ):
                continue
            plan = self.plane.filter_send(
                address, data, payload_off=0,
                src=src_slot.name, dst=dst_slot.name,
            )
            delay, copies = 0.0, 1
            if plan is not None:
                action, delay, copies = plan
                if action == "drop":
                    continue
            recv = self.plane.filter_recv(address, dst=dst_slot.name)
            if recv is not None:
                if recv[0] == "drop":
                    continue
                delay += recv[1]
            for _ in range(copies):
                at = now + delay + self._base_delay(src_slot.name, dst_slot.name)
                self.heap.push(
                    at, ("frame", dst_slot.index, dst_slot.incarnation, data)
                )

    def _apply_effects(self, slot: _Slot, effects: list) -> None:
        now = self.clock.now
        for eff in effects:
            tag = eff[0]
            if tag == "send":
                self._transmit(slot, eff[1], eff[2])
            elif tag == "sched":
                self.heap.push(
                    now + eff[1],
                    ("event", slot.index, slot.incarnation, eff[2]),
                )
            elif tag == "commit":
                block = eff[1]
                self.commits[slot.name].append(
                    CommitRecord(
                        block.round, block.digest().data, self.plane.vnow()
                    )
                )
            else:  # pragma: no cover - machine/world contract violation
                raise RuntimeError(f"unknown effect {tag!r}")
        self._arm_timer(slot)

    # -- timers ------------------------------------------------------------

    def _arm_timer(self, slot: _Slot) -> None:
        if slot.machine is None:
            return
        deadline = slot.machine.timer_deadline
        if slot.timer_target == deadline:
            return
        slot.timer_target = deadline
        slot.timer_gen += 1
        self.heap.push(
            max(deadline, self.clock.now),
            ("timer", slot.index, slot.incarnation, slot.timer_gen),
        )

    # -- lifecycle ---------------------------------------------------------

    def _spawn(self, slot: _Slot) -> None:
        store = _NotifyingStore(engine=slot.engine)
        machine = CoreStateMachine(
            slot.pk,
            slot.sk,
            self.committee,
            clock=self.clock,
            store=store,
            **self._mach_kwargs,
        )
        slot.engine = store._engine  # first spawn: adopt the fresh engine
        slot.machine = machine
        slot.crashed = False
        slot.timer_target = None
        if self._elector_override is not None:
            # Per-round Twins control: every instance (twins included)
            # consults the same fixed schedule. Stateless, so shared.
            machine.core.leader_elector = self._elector_override
        if self._twin_salt:
            machine.proposal_salt = slot.name.encode()
            # Every instance must treat every other instance's salted
            # payload digest as available (see _SimMempoolDriver): the
            # Twins model assumes universal batch availability; digest
            # divergence — not data withholding — is what's under test.
            machine.mempool_driver.twin_salts = tuple(
                s.name.encode() for s in self.slots
            )
        if self._recorder is not None:
            # Splice the virtual-clock trace in BEFORE init() so the
            # restored-round proposal of a restarting leader is on tape.
            self._recorder.attach(slot)
        self._apply_effects(slot, machine.init(self.clock.now))

    def _crash(self, slot: _Slot) -> None:
        if slot.crashed or slot.machine is None:
            return
        if self._recorder is not None:
            # SIGKILL semantics: close the writer epoch; events past the
            # last emit boundary die with it at render time.
            self._recorder.crashed(slot.name)
        slot.machine = None
        slot.crashed = True
        slot.incarnation += 1  # drops every in-flight frame/event/timer
        log.info("sim crashed %s at v=%.3f", slot.name, self.plane.vnow())

    def _restart(self, slot: _Slot, wipe: bool = False) -> None:
        if not slot.crashed:
            return
        slot.incarnation += 1
        if wipe:
            # Cold rejoin: the node's "disk" is lost — the next spawn
            # starts on an empty store and must recover via state sync.
            slot.engine = None
        self._spawn(slot)
        log.info(
            "sim restarted %s%s at v=%.3f",
            slot.name,
            " (wiped)" if wipe else "",
            self.plane.vnow(),
        )

    def _enact(self, action: dict) -> None:
        node = action["node"]
        slot = self._by_name.get(node)
        if slot is None:
            return
        kind = action["action"]
        if kind == "crash":
            self._crash(slot)
        elif kind == "restart":
            self._restart(slot, wipe=action.get("wipe", False))
        elif kind == "byzantine_on":
            key = (node, action["behavior"])
            if key not in self._byz and action["behavior"] != "silent_leader":
                actor = _SimByzantine(self, slot, action["behavior"])
                self._byz[key] = actor
                self.heap.push(self.clock.now, ("byz", key))
        elif kind == "byzantine_off":
            actor = self._byz.pop((node, action["behavior"]), None)
            if actor is not None:
                actor.active = False

    # -- main loop ---------------------------------------------------------

    def run(self) -> dict:
        if self._verify_memo:
            # Process-wide, pure-semantics verification memo (see
            # crypto.enable_verify_memo): simulated nodes share one
            # process, so byte-identical re-verifies across nodes — and
            # across a sweep's seeds, signatures are deterministic — are
            # wasted CPU. Left enabled afterwards on purpose: the memo
            # stays warm for the next seed of a sweep.
            enable_verify_memo()
        self.plane.start(t0=0.0)
        for slot in self.slots:
            self._spawn(slot)
        # Supervised transitions (crash/restart/byzantine) become heap
        # events at their scheduled instants; link/partition rules apply
        # lazily inside filter_send as virtual time advances.
        for at, _is_heal, _ev in self.plane._transitions:
            self.heap.push(max(at, 0.0), ("actions",))
        self.heap.push(0.0, ("actions",))

        stop_t = self.scenario.duration_s + self.recovery_timeout_s
        while len(self.heap):
            if self.heap.peek_time() > stop_t:
                break
            t, item = self.heap.pop()
            self.clock.advance_to(t)
            self.events_processed += 1
            self._dispatch(item)
            if self._recovered:
                break

        if self._recorder is not None:
            self._recorder.finish()
        verdict = check(
            self.schedule,
            self.commits,
            honest=self._honest_set(),
            min_recovery_commits=self.min_recovery_commits,
            injections=self.plane.injection_summary(),
        )
        return {
            "verdict": verdict,
            "trace": self.schedule.trace(),
            "commit_streams": {
                name: [(rec.round, rec.t) for rec in recs]
                for name, recs in self.commits.items()
            },
            "events": self.events_processed,
            "virtual_end": self.clock.now,
            "decode_errors": self.decode_errors,
        }

    def _honest_set(self) -> set[str]:
        byz = {
            e.params["node"]
            for e in self.schedule.events
            if e.kind == "byzantine"
        }
        # A twinned seat equivocates by construction: neither instance of
        # the pair is honest.
        return (
            {s.name for s in self.slots}
            - byz
            - set(self.twins)
            - set(self.twins.values())
        )

    def _dispatch(self, item) -> None:
        kind = item[0]
        if kind == "frame":
            _, idx, incarnation, data = item
            slot = self.slots[idx]
            if slot.machine is None or slot.incarnation != incarnation:
                return
            try:
                event = decode_shared(data, slot.machine.seats)
            except (SerdeError, MalformedMessage, ValueError) as e:
                self.decode_errors += 1
                log.debug("sim decode error: %s", e)
                return
            self._step(slot, event)
        elif kind == "event":
            _, idx, incarnation, event = item
            slot = self.slots[idx]
            if slot.machine is None or slot.incarnation != incarnation:
                return
            self._step(slot, event)
        elif kind == "timer":
            _, idx, incarnation, gen = item
            slot = self.slots[idx]
            if (
                slot.machine is None
                or slot.incarnation != incarnation
                or slot.timer_gen != gen
            ):
                return
            deadline = slot.machine.timer_deadline
            if deadline <= self.clock.now + _EPS:
                self._step(slot, ("timer", slot.machine.round))
            else:  # reset since armed: chase the new deadline
                slot.timer_gen += 1
                slot.timer_target = deadline
                self.heap.push(
                    deadline, ("timer", idx, slot.incarnation, slot.timer_gen)
                )
        elif kind == "actions":
            for action in self.plane.poll_actions():
                self._enact(action)
        elif kind == "byz":
            _, key = item
            actor = self._byz.get(key)
            if actor is None or not actor.active:
                return
            actor.burst()
            self.heap.push(self.clock.now + _BYZ_PERIOD_S, ("byz", key))

    def _step(self, slot: _Slot, event) -> None:
        effects = slot.machine.step(event, self.clock.now)
        self._apply_effects(slot, effects)
        if event[0] == "timer" or self._effects_had_commit(effects):
            self._check_recovery()

    @staticmethod
    def _effects_had_commit(effects) -> bool:
        return any(eff[0] == "commit" for eff in effects)

    def _check_recovery(self) -> None:
        """Early exit once every expected-alive node proved post-heal
        commit growth AND the whole schedule has been applied — mirrors
        the harness's recovery tail, minus the wall-clock waiting."""
        if self._recovered:
            return
        if self.clock.now < self.scenario.duration_s:
            return
        if not self.plane.schedule_exhausted():
            return
        for name in self._expected:
            count = 0
            for rec in self.commits[name]:
                if rec.t > self._heal_t:
                    count += 1
                    if count >= self.min_recovery_commits:
                        break
            else:
                return
        self._recovered = True


def run_sim(scenario: Scenario, n: int, **kwargs) -> dict:
    """Execute ``scenario`` on an ``n``-node simulated committee; returns
    the harness-shaped result dict (verdict / trace / commit_streams)."""
    return SimWorld(scenario, n, **kwargs).run()
