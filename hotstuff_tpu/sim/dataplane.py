"""Simulant model of the Conveyor data plane: the availability invariant
under thousands of seeded fault schedules, in milliseconds.

Per the ROADMAP's sim-first rule, the data-plane mechanism is
model-checked here BEFORE it is trusted on the real planes: N simulated
nodes seal batches on a virtual clock, disseminate them through the real
:class:`~..faultline.runtime.FaultPlane` link filters (partitions,
drops, delays, crash/restart, ``batch_withhold`` byzantine nodes), ack
what they hold, form availability certificates at 2f+1 stake, and only
then order the digest. The run's verdict is
:func:`~..faultline.checker.check_availability`: every ordered digest
must be resolvable at f+1 honest nodes.

Two protocol modes make the check falsifiable:

- ``require_certs=True`` — the Conveyor rule. The invariant holds by
  quorum intersection; a violation would mean the implementation logic
  (not the math) is wrong.
- ``require_certs=False`` — the naive pre-Conveyor rule (order the
  digest as soon as the batch is SENT, no proof anyone holds it). Under
  withholding + crash schedules the checker must FIND violations — the
  regression test pins that this harness can actually catch the bug
  class it exists for.
"""

from __future__ import annotations

import logging

from hotstuff_tpu.faultline.checker import check_availability
from hotstuff_tpu.faultline.policy import Scenario, _seed_stream
from hotstuff_tpu.faultline.runtime import FaultPlane

from .clock import VirtualClock
from .world import EventHeap

log = logging.getLogger("sim")

__all__ = ["DataPlaneSim", "run_dataplane_sim"]


def _name(i: int) -> str:
    return f"n{i:03d}"


class _SimNode:
    __slots__ = ("index", "name", "store", "acks", "ordered", "crashed", "sealed")

    def __init__(self, index: int, name: str) -> None:
        self.index = index
        self.name = name
        self.store: set[str] = set()  # digests held (persists across crash)
        self.acks: dict[str, set[str]] = {}  # own batches: digest -> ackers
        self.ordered: list[str] = []
        self.crashed = False
        self.sealed = 0


class DataPlaneSim:
    """See module docstring. ``workers`` shards only the seal cadence
    (each shard seals independently); the invariant is per-digest and
    does not depend on shard count, but sharded runs exercise
    interleaved dissemination."""

    def __init__(
        self,
        scenario: Scenario,
        n: int,
        *,
        workers: int = 1,
        seal_interval_s: float = 0.05,
        link_delay_ms: tuple[float, float] = (5.0, 20.0),
        require_certs: bool = True,
        jitter: int = 0,
    ) -> None:
        self.scenario = scenario
        self.n = n
        self.workers = workers
        self.seal_interval = seal_interval_s
        self.link_delay = (link_delay_ms[0] / 1e3, link_delay_ms[1] / 1e3)
        self.require_certs = require_certs
        self.jitter = jitter
        names = [_name(i) for i in range(n)]
        self.schedule = scenario.compile(names)
        self.clock = VirtualClock()
        addresses = {("sim", i): names[i] for i in range(n)}
        self.plane = FaultPlane(self.schedule, addresses, clock=self.clock)
        self.nodes = [_SimNode(i, names[i]) for i in range(n)]
        self._by_name = {node.name: node for node in self.nodes}
        self.heap = EventHeap()
        self.committed: set[str] = set()
        self.events_processed = 0
        self.quorum = 2 * ((n - 1) // 3) + 1
        self._link_rngs: dict[tuple[str, str], object] = {}

    # -- helpers -------------------------------------------------------------

    def _delay(self, src: str, dst: str) -> float:
        key = (src, dst)
        rng = self._link_rngs.get(key)
        if rng is None:
            rng = self._link_rngs[key] = _seed_stream(
                self.scenario.seed, "dpsim", str(self.jitter), src, dst
            )
        lo, hi = self.link_delay
        return rng.uniform(lo, hi) if hi > 0 else 0.0

    def _withholding(self, name: str) -> bool:
        return self.plane.behavior_active(name, "batch_withhold")

    def _transmit(self, src: _SimNode, dst: _SimNode, item) -> None:
        plan = self.plane.filter_send(
            ("sim", dst.index), b"\xff", src=src.name, dst=dst.name
        )
        delay = 0.0
        copies = 1
        if plan is not None:
            action, delay, copies = plan
            if action == "drop":
                return
        for _ in range(copies):
            at = self.clock.now + delay + self._delay(src.name, dst.name)
            self.heap.push(at, item)

    # -- events --------------------------------------------------------------

    def _seal(self, node: _SimNode, worker: int) -> None:
        digest = f"{node.name}/w{worker}/b{node.sealed}"
        node.sealed += 1
        node.store.add(digest)
        node.acks[digest] = {node.name}  # own stake counts toward quorum
        for peer in self.nodes:
            if peer is node:
                continue
            self._transmit(node, peer, ("batch", peer.index, digest, node.index))
        if not self.require_certs:
            # Naive rule: ordered the moment it is sent — no availability
            # proof. The checker must catch what this breaks.
            self._order(node, digest)
        elif len(node.acks[digest]) >= self.quorum:
            self._order(node, digest)  # degenerate single-node committee

    def _order(self, node: _SimNode, digest: str) -> None:
        if digest in self.committed:
            return
        node.ordered.append(digest)
        self.committed.add(digest)

    def _dispatch(self, item) -> None:
        kind = item[0]
        if kind == "seal":
            _, idx, worker = item
            node = self.nodes[idx]
            if not node.crashed:
                self._seal(node, worker)
            if self.clock.now + self.seal_interval <= self.scenario.duration_s:
                self.heap.push(
                    self.clock.now + self.seal_interval, ("seal", idx, worker)
                )
        elif kind == "batch":
            _, idx, digest, author_idx = item
            node = self.nodes[idx]
            if node.crashed:
                return  # frame lost at the dead listener
            node.store.add(digest)
            if self._withholding(node.name):
                return  # holds the bytes, withholds the attestation
            author = self.nodes[author_idx]
            self._transmit(
                node, author, ("ack", author_idx, digest, node.name)
            )
        elif kind == "ack":
            _, idx, digest, signer = item
            node = self.nodes[idx]
            if node.crashed or digest not in node.acks:
                return
            acks = node.acks[digest]
            already = len(acks) >= self.quorum
            acks.add(signer)
            if (
                self.require_certs
                and not already
                and len(acks) >= self.quorum
            ):
                self._order(node, digest)
        elif kind == "actions":
            for action in self.plane.poll_actions():
                target = self._by_name.get(action["node"])
                if target is None:
                    continue
                if action["action"] == "crash":
                    target.crashed = True
                elif action["action"] == "restart":
                    target.crashed = False

    # -- run -----------------------------------------------------------------

    def run(self) -> dict:
        self.plane.start(t0=0.0)
        for at, _is_heal, _ev in self.plane._transitions:
            self.heap.push(max(at, 0.0), ("actions",))
        self.heap.push(0.0, ("actions",))
        for node in self.nodes:
            for w in range(self.workers):
                # Stagger shards so seals interleave across the committee.
                self.heap.push(
                    (w + 1) * self.seal_interval / (self.workers + 1),
                    ("seal", node.index, w),
                )
        stop_t = self.scenario.duration_s + 5.0
        while len(self.heap):
            if self.heap.peek_time() > stop_t:
                break
            t, item = self.heap.pop()
            self.clock.advance_to(t)
            self.events_processed += 1
            self._dispatch(item)

        crashed_forever = self.schedule.crashed_forever()
        holders = {
            digest: {
                node.name
                for node in self.nodes
                if digest in node.store and node.name not in crashed_forever
            }
            for digest in self.committed
        }
        verdict = check_availability(self.schedule, self.committed, holders)
        return {
            "verdict": verdict,
            "trace": self.schedule.trace(),
            "committed": len(self.committed),
            "digests": sorted(self.committed),
            "sealed": sum(node.sealed for node in self.nodes),
            "events": self.events_processed,
            "virtual_end": self.clock.now,
        }


def run_dataplane_sim(scenario: Scenario, n: int, **kwargs) -> dict:
    return DataPlaneSim(scenario, n, **kwargs).run()
