"""Oracle's sim→stream bridge: render a Simulant run into the exact
JSON-lines telemetry streams the real emitters write.

The real observability pipeline is ``RoundTrace`` marks → ``TraceBuffer``
→ ``TelemetryEmitter`` writing ``hotstuff-meta-v1`` / snapshot /
``hotstuff-trace-v1`` lines per node, which ``Watchtower`` tails. This
module substitutes only the clock and the writer: a ``StreamRecorder``
splices a virtual-clock ``SimRoundTrace`` into every spawned
``CoreStateMachine`` (the same duck-typed mark surface ``Core`` already
calls), collects per-instance writer *epochs* (one per spawn — a restart
opens a new epoch with a new synthetic pid, exactly the mid-stream meta
boundary a real process restart produces), and renders them into stream
lines that are **byte-deterministic** in ``(scenario, seed, jitter)``.

Bridge conventions (the parts a real emitter derives from the host):

- ``anchor`` is ``{"mono": 0.0, "wall": 0.0}`` — the virtual clock IS
  both timelines, so every timestamp in the rendered streams is in
  virtual seconds and alert ``ts`` values compare directly against the
  fault schedule's virtual incident times (no ``FaultPlane.started_wall``
  needed).
- ``pid`` is synthetic and deterministic: ``40000 + 100*slot_index +
  incarnation``. Distinct per epoch, stable across runs.
- A crash drops the unflushed tail of the victim's stream (everything
  after the last emit boundary), the same loss a SIGKILL inflicts on a
  real buffered writer. A clean scenario end flushes everything behind a
  ``final: true`` snapshot.

``Watchtower.feed`` replays the merged timeline in milliseconds; see
``benchmark/detector_sweep.py`` for the labeled-incident scoring loop
built on top.
"""

from __future__ import annotations

import json
import os
from collections import OrderedDict

from hotstuff_tpu.telemetry.dtrace import DTRACE_SCHEMA
from hotstuff_tpu.telemetry.emitter import META_SCHEMA, SCHEMA
from hotstuff_tpu.telemetry.profiler import PROFILE_SCHEMA
from hotstuff_tpu.telemetry.trace import TRACE_SCHEMA
from hotstuff_tpu.telemetry.watchtower import ALERT_SCHEMA, Watchtower

__all__ = [
    "SimRoundTrace",
    "StreamRecorder",
    "replay_watchtower",
]

# Deterministic stand-in for os.getpid(): per-slot, bumped per
# incarnation so a restart is a visible pid change in the meta record.
SIM_PID_BASE = 40000

# Mirrors telemetry.spans first-mark-wins slots.
_PROPOSE, _VOTE, _QC = 0, 1, 2


class SimRoundTrace:
    """``RoundTrace`` duck-type on the virtual clock.

    Emits the same stage names with the same first-mark-wins semantics
    (``propose`` / ``first_vote`` / ``qc`` emit once per round) and the
    same commit-driven GC, but stamps ``clock.now`` instead of
    ``time.perf_counter()`` and appends events to the recorder's current
    epoch instead of a ``TraceBuffer``. Author labels inside
    ``"<author>|<digest>"`` details arrive as ``repr(PublicKey)`` from
    the core; they are translated to committee seat names here so the
    rendered streams (and every alert accusing from them) speak
    ``n000``-style names end to end.
    """

    __slots__ = ("node", "_clock", "_events", "_alias", "_rounds", "_max_rounds")

    def __init__(self, node, clock, events, alias, max_rounds=512):
        self.node = node
        self._clock = clock
        self._events = events  # the owning epoch's event list
        self._alias = alias
        self._rounds: OrderedDict[int, list] = OrderedDict()
        self._max_rounds = max_rounds

    def _translate(self, detail):
        if detail is None:
            return None
        head, sep, tail = detail.partition("|")
        if not sep:
            return detail
        return self._alias.get(head, head) + sep + tail

    def _emit(self, round_, stage, detail=None):
        self._events.append(
            (int(round_), stage, self._clock.now, self._translate(detail))
        )

    def _marks(self, round_):
        marks = self._rounds.get(round_)
        if marks is None:
            if len(self._rounds) >= self._max_rounds:
                self._rounds.popitem(last=False)
            marks = self._rounds[round_] = [None, None, None]
        return marks

    # -- the mark surface Core calls ---------------------------------------

    def mark_propose(self, round_: int, detail: str | None = None) -> None:
        marks = self._marks(round_)
        if marks[_PROPOSE] is None:
            marks[_PROPOSE] = self._clock.now
            self._emit(round_, "propose", detail)

    def mark_verified(self, round_: int) -> None:
        self._emit(round_, "verified")

    def mark_vote_send(self, round_: int) -> None:
        self._emit(round_, "vote_send")

    def mark_vote(self, round_: int) -> None:
        marks = self._marks(round_)
        if marks[_VOTE] is None:
            marks[_VOTE] = self._clock.now
            self._emit(round_, "first_vote")

    def mark_vote_rx(self, round_: int, detail: str) -> None:
        self._emit(round_, "vote_rx", detail)

    def mark_timeout(self, round_: int) -> None:
        self._emit(round_, "timeout")

    def mark_qc(self, round_: int) -> None:
        marks = self._marks(round_)
        if marks[_QC] is None:
            marks[_QC] = self._clock.now
            self._emit(round_, "qc")

    def mark_commit(self, round_: int, detail: str | None = None) -> None:
        self._emit(round_, "commit", detail)
        for r in [r for r in self._rounds if r <= round_]:
            del self._rounds[r]

    # The leader-side broadcast mark the real plane emits from the
    # Proposer actor (``trace_event(..., "propose_send", ...)``), not
    # through RoundTrace; the sim machine calls it from ``_make_block``.
    def propose_send(self, round_: int, detail: str | None = None) -> None:
        self._emit(round_, "propose_send", detail)


class _Epoch:
    """One writer lifetime of one instance: spawn → crash/scenario end."""

    __slots__ = ("node", "pid", "start", "end", "crashed", "events")

    def __init__(self, node, pid, start):
        self.node = node
        self.pid = pid
        self.start = start
        self.end: float | None = None
        self.crashed = False
        self.events: list[tuple] = []


class StreamRecorder:
    """Collects per-instance trace epochs from a ``SimWorld`` run and
    renders them as telemetry stream lines.

    Usage::

        rec = StreamRecorder(interval_s=0.5)
        world = SimWorld(scenario, 4, recorder=rec)
        result = world.run()
        streams = rec.render()            # {instance: [json line, ...]}
        watch, alerts = replay_watchtower(rec)

    The world calls ``bind`` (clock + pk→name alias) at construction,
    ``attach`` per spawn/restart, ``crashed`` per crash, and ``finish``
    when the run ends.
    """

    def __init__(self, interval_s: float = 0.5):
        self.interval_s = float(interval_s)
        self._epochs: dict[str, list[_Epoch]] = {}
        self._alias: dict[str, str] = {}
        self._clock = None
        self.virtual_end: float | None = None

    # -- SimWorld hooks ----------------------------------------------------

    def bind(self, clock, alias: dict[str, str]) -> None:
        self._clock = clock
        self._alias = dict(alias)

    def attach(self, slot) -> None:
        epochs = self._epochs.setdefault(slot.name, [])
        if epochs and epochs[-1].end is None:
            epochs[-1].end = self._clock.now
        epoch = _Epoch(
            node=slot.name,
            pid=SIM_PID_BASE + 100 * slot.index + slot.incarnation,
            start=self._clock.now,
        )
        epochs.append(epoch)
        trace = SimRoundTrace(slot.name, self._clock, epoch.events, self._alias)
        slot.machine.core._trace = trace
        slot.machine.trace = trace

    def crashed(self, name: str) -> None:
        epochs = self._epochs.get(name)
        if epochs and epochs[-1].end is None:
            epochs[-1].end = self._clock.now
            epochs[-1].crashed = True

    def finish(self) -> None:
        self.virtual_end = self._clock.now
        for epochs in self._epochs.values():
            if epochs and epochs[-1].end is None:
                epochs[-1].end = self._clock.now

    # -- rendering ---------------------------------------------------------

    def render(self) -> dict[str, list[str]]:
        """Stream lines per instance, byte-deterministic. Key order in
        the records is fixed at construction and ``json.dumps`` with the
        emitter's separators preserves it, so identical runs render
        identical bytes."""
        return {
            name: [
                json.dumps(obj, separators=(",", ":"))
                for _, obj in self._render_node(name)
            ]
            for name in sorted(self._epochs)
        }

    def timeline(self) -> list[tuple[float, str, dict]]:
        """The merged replay order: ``(emit_ts, instance, record)``
        sorted by emit time (ties broken by instance name, then by
        per-stream line order) — the order a tailing Watchtower would
        observe the lines appear across all per-node files. Records are
        the structured objects (no JSON round-trip: this is the sweep's
        hot path)."""
        merged = []
        for name in sorted(self._epochs):
            for i, (ts, obj) in enumerate(self._render_node(name)):
                merged.append((ts, name, i, obj))
        merged.sort(key=lambda item: (item[0], item[1], item[2]))
        return [(ts, name, obj) for ts, name, _, obj in merged]

    def write(self, directory: str) -> list[str]:
        """Write ``telemetry-<instance>.jsonl`` files (the layout
        ``DirectoryWatch`` and ``telemetry.validate`` expect)."""
        os.makedirs(directory, exist_ok=True)
        paths = []
        for name, lines in self.render().items():
            path = os.path.join(
                directory, f"telemetry-{name.replace('+', '_')}.jsonl"
            )
            with open(path, "w") as f:
                for line in lines:
                    f.write(line + "\n")
            paths.append(path)
        return paths

    def _render_node(self, name: str) -> list[tuple[float, dict]]:
        out: list[tuple[float, dict]] = []
        for epoch in self._epochs[name]:
            self._render_epoch(epoch, out)
        return out

    def _render_epoch(self, epoch: _Epoch, out: list[tuple[float, dict]]) -> None:
        anchor = {"mono": 0.0, "wall": 0.0}
        out.append(
            (
                epoch.start,
                {
                    "schema": META_SCHEMA,
                    "schemas": [
                        SCHEMA, TRACE_SCHEMA, DTRACE_SCHEMA,
                        PROFILE_SCHEMA, ALERT_SCHEMA,
                    ],
                    "node": epoch.node,
                    "pid": epoch.pid,
                    "ts": epoch.start,
                    "anchor": anchor,
                    "interval_s": self.interval_s,
                },
            )
        )
        end = epoch.end if epoch.end is not None else epoch.start
        # Emit boundaries: spawn, every interval after it, and — for a
        # clean shutdown only — a final flush at the epoch end. A crash
        # never reaches its next boundary, so the tail events are lost
        # with the writer (the detectors must work from what was durable,
        # exactly as on the real plane).
        boundaries = [epoch.start]
        k = 1
        while epoch.start + k * self.interval_s < end:
            boundaries.append(epoch.start + k * self.interval_s)
            k += 1
        if not epoch.crashed:
            boundaries.append(end)
        counters = {
            "consensus.commits_total": 0,
            "consensus.proposals_total": 0,
            "consensus.timeouts_total": 0,
            "consensus.votes_total": 0,
        }
        round_hi = 0
        height = 0
        idx = 0
        ev_seq = 0
        events = epoch.events
        for seq, bound in enumerate(boundaries):
            final = (not epoch.crashed) and bound is boundaries[-1] and seq > 0
            delta: list[list] = []
            while idx < len(events) and events[idx][2] <= bound:
                round_, stage, t, detail = events[idx]
                idx += 1
                ev = [ev_seq, epoch.node, round_, stage, t]
                if detail is not None:
                    ev.append(detail)
                delta.append(ev)
                ev_seq += 1
                round_hi = max(round_hi, round_)
                if stage == "commit":
                    counters["consensus.commits_total"] += 1
                    if isinstance(detail, str) and detail.startswith("h"):
                        try:
                            height = max(height, int(detail[1:]))
                        except ValueError:
                            pass
                elif stage == "propose_send":
                    counters["consensus.proposals_total"] += 1
                elif stage == "timeout":
                    counters["consensus.timeouts_total"] += 1
                elif stage == "vote_rx":
                    counters["consensus.votes_total"] += 1
            out.append(
                (
                    bound,
                    {
                        "schema": SCHEMA,
                        "node": epoch.node,
                        "pid": epoch.pid,
                        "seq": seq,
                        "ts": bound,
                        "final": final,
                        "counters": dict(counters),
                        "gauges": {
                            "consensus.last_committed_round": height,
                            "consensus.round": round_hi,
                        },
                        "histograms": {},
                    },
                )
            )
            if delta:
                out.append(
                    (
                        bound,
                        {
                            "schema": TRACE_SCHEMA,
                            "node": epoch.node,
                            "pid": epoch.pid,
                            "anchor": anchor,
                            "evicted": 0,
                            "events": delta,
                        },
                    )
                )


def replay_watchtower(
    recorder: StreamRecorder,
    config=None,
    *,
    label: str = "oracle",
):
    """Feed a recorded run's merged timeline through a fresh
    ``Watchtower`` on the virtual clock. Returns ``(watch, alerts)``;
    alert ``ts`` values are virtual seconds, directly comparable to the
    fault schedule's incident times."""
    watch = Watchtower(config, label=label)
    alerts = watch.feed(
        (obj, name) for _, name, obj in recorder.timeline()
    )
    alerts += watch.flush()
    return watch, alerts
