"""Lazarus simulation model: seeded replica join/truncate schedules.

The sim-first half of the state-sync subsystem (see
``docs/statesync.md``): every seed is a full replica-lifecycle schedule
— one victim crashes early, the committee keeps committing (and, with
retention armed, SNAPSHOTS + TRUNCATES its logs past the victim's last
known round), then the victim comes back — half the seeds with a wiped
store (cold join), half with its stale one (warm lag below the quorum's
truncation horizon). Some seeds add link impairment during catch-up to
stress the retry/rotation path. The schedule executes on the sans-io
plane (:mod:`hotstuff_tpu.sim.world`) in virtual time through the real
:class:`~hotstuff_tpu.faultline.runtime.FaultPlane`, with the Lazarus
machinery live: ``retention_rounds > 0`` arms the Compactor on every
node and ``statesync_active=True`` arms the anti-entropy probe loop.

Each run is judged by three machine-checked invariants:

- **safety** / **liveness** — the standard faultline checker verdict;
  cross-node agreement doubles as the rejoin-prefix check (a recovered
  victim's commit stream is compared round-by-round against the
  quorum's — a snapshot install that adopted a wrong chain shows up as
  a ``conflicting_commit``);
- **frontier availability** — post-run, every committed ``(round,
  digest)`` must still be servable at f+1 honest live nodes, where a
  node serves a block either from its store or by covering it with its
  snapshot floor (:func:`~hotstuff_tpu.faultline.checker.
  check_frontier_availability`). Truncation may bound disk, never
  recoverability.

Sweep CLI (the CI leg; artifact schema ``statesync-sweep-v1``)::

    python -m hotstuff_tpu.sim.statesync --seeds 0:200 --gate \
        --out results/statesync-sweep.json
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time

from hotstuff_tpu.consensus.statesync import SNAPSHOT_KEY, peek_frontier
from hotstuff_tpu.faultline.checker import check_frontier_availability
from hotstuff_tpu.faultline.policy import Scenario, _seed_stream

from .world import SimWorld

__all__ = [
    "rejoin_scenario",
    "run_rejoin",
    "probe_frontier_availability",
    "SCHEMA",
]

SCHEMA = "statesync-sweep-v1"


def rejoin_scenario(seed: int, duration_s: float = 12.0) -> Scenario:
    """One seeded replica-lifecycle schedule. All free choices (victim,
    crash/rejoin instants, wipe-or-stale, whether catch-up happens under
    link noise) are drawn from streams keyed only by ``seed``, so the
    schedule — like every faultline scenario — replays byte-identically.
    """
    rng = _seed_stream(seed, "lazarus")
    victim = rng.randrange(1 << 16)  # compile maps modulo committee size
    t_crash = round(rng.uniform(0.8, 0.2 * duration_s), 3)
    # Rejoin late enough that (at the sim's ~10 rounds/virtual-second
    # pacing) the survivors' compaction hysteresis has fired at least
    # once and the victim is below every peer's truncation horizon.
    t_rejoin = round(rng.uniform(0.55 * duration_s, 0.75 * duration_s), 3)
    restart: dict = {"kind": "restart", "node": victim, "at": t_rejoin}
    if rng.random() < 0.5:
        restart["wipe"] = True  # cold join: empty store
    events = [
        {"kind": "crash", "node": victim, "at": t_crash},
        restart,
    ]
    if rng.random() < 0.3:
        # Impaired catch-up: drop/delay a seeded link while the victim
        # is syncing, exercising retry + per-peer rotation.
        at = round(rng.uniform(t_rejoin, 0.85 * duration_s), 3)
        events.append(
            {
                "kind": "link",
                "src": "?",
                "dst": "*",
                "at": at,
                "until": round(min(at + 0.1 * duration_s, 0.9 * duration_s), 3),
                "drop": round(rng.uniform(0.05, 0.25), 3),
                "delay_ms": [5.0, round(rng.uniform(20.0, 60.0), 1)],
            }
        )
    return Scenario(
        name=f"rejoin-{seed}",
        seed=seed,
        duration_s=duration_s,
        events=events,
    )


def probe_frontier_availability(world: SimWorld) -> dict:
    """Post-run audit over the sim stores (mirrors the real harness's
    ``_probe_frontier_availability``): collect every committed
    ``(round, digest)``, each live node's resolvable set and snapshot
    floor, and hand them to the checker invariant."""
    committed: set = set()
    for recs in world.commits.values():
        for rec in recs:
            committed.add((rec.round, rec.digest))
    resolvers: dict = {}
    floors: dict[str, int] = {}
    for slot in world.slots:
        if slot.crashed or slot.engine is None:
            continue
        snap = slot.engine.get_meta(SNAPSHOT_KEY)
        if snap is not None:
            floors[slot.name] = peek_frontier(snap)[0]
        for _round, digest in committed:
            if slot.engine.get(digest) is not None:
                resolvers.setdefault(digest, set()).add(slot.name)
    return check_frontier_availability(
        world.schedule, committed, resolvers, floors
    )


def _rejoin_metrics(world: SimWorld) -> dict:
    """Per-run recovery numbers for the sweep artifact: how long after
    the rejoin the victim's first commit landed, and where its committed
    round ended relative to the quorum's."""
    restarts = [e for e in world.schedule.events if e.kind == "restart"]
    if not restarts:
        return {}
    ev = restarts[-1]
    victim = ev.params["node"]
    post = [rec for rec in world.commits.get(victim, ()) if rec.t > ev.at]
    victim_max = max(
        (rec.round for rec in world.commits.get(victim, ())), default=0
    )
    quorum_max = max(
        (
            rec.round
            for name, recs in world.commits.items()
            if name != victim
            for rec in recs
        ),
        default=0,
    )
    floor = None
    slot = world._by_name.get(victim)
    if slot is not None and slot.engine is not None:
        snap = slot.engine.get_meta(SNAPSHOT_KEY)
        if snap is not None:
            floor = peek_frontier(snap)[0]
    return {
        "victim": victim,
        "wipe": bool(ev.params.get("wipe")),
        "rejoin_t": ev.at,
        "first_commit_after_s": round(post[0].t - ev.at, 3) if post else None,
        "post_rejoin_commits": len(post),
        "victim_max_round": victim_max,
        "quorum_max_round": quorum_max,
        "victim_snapshot_round": floor,
    }


def run_rejoin(
    seed: int,
    n: int = 4,
    *,
    duration_s: float = 12.0,
    retention_rounds: int = 16,
    sync_retry_delay: int = 1_000,
    **world_kwargs,
) -> dict:
    """Execute one seeded rejoin schedule with the Lazarus machinery
    armed; returns the harness-shaped result with the verdict extended
    by ``frontier_availability`` and a ``rejoin`` metrics section."""
    scenario = rejoin_scenario(seed, duration_s=duration_s)
    world = SimWorld(
        scenario,
        n,
        retention_rounds=retention_rounds,
        statesync_active=True,
        sync_retry_delay=sync_retry_delay,
        **world_kwargs,
    )
    result = world.run()
    result["verdict"]["frontier_availability"] = probe_frontier_availability(
        world
    )
    result["rejoin"] = _rejoin_metrics(world)
    return result


def _violation(verdict: dict) -> str | None:
    if not verdict["safety"]["ok"]:
        return "safety"
    if not verdict["liveness"]["recovered"]:
        return "liveness"
    fa = verdict.get("frontier_availability")
    if fa is not None and not fa["ok"]:
        return "frontier_availability"
    return None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seeds", default="0:200",
                   help="seed range lo:hi (half-open) for rejoin schedules")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--duration", type=float, default=12.0,
                   help="virtual seconds per schedule")
    p.add_argument("--retention", type=int, default=16,
                   help="snapshot/truncate retention depth in rounds")
    p.add_argument("--timeout-delay", type=int, default=1_000, help="ms")
    p.add_argument("--sync-retry-delay", type=int, default=1_000,
                   help="ms; also the statesync probe cadence")
    p.add_argument("--link-delay", default="25:75",
                   help="per-hop latency draw lo:hi in ms")
    p.add_argument("--out", default=None, help="summary JSON path")
    p.add_argument("--gate", action="store_true",
                   help="exit non-zero on any checker violation")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args(argv)

    if not args.verbose:
        for name in ("consensus", "network", "faultline", "sim"):
            logging.getLogger(name).setLevel(logging.ERROR)

    lo, hi = (int(x) for x in args.seeds.split(":"))
    dlo, dhi = (float(x) for x in args.link_delay.split(":"))

    runs = []
    failures = []
    t0 = time.perf_counter()
    events_total = 0
    cold = warm = 0
    recoveries = []
    for seed in range(lo, hi):
        result = run_rejoin(
            seed,
            args.nodes,
            duration_s=args.duration,
            retention_rounds=args.retention,
            sync_retry_delay=args.sync_retry_delay,
            timeout_delay=args.timeout_delay,
            link_delay_ms=(dlo, dhi),
        )
        verdict = result["verdict"]
        violation = _violation(verdict)
        rejoin = result["rejoin"]
        events_total += result["events"]
        if rejoin.get("wipe"):
            cold += 1
        else:
            warm += 1
        if rejoin.get("first_commit_after_s") is not None:
            recoveries.append(rejoin["first_commit_after_s"])
        runs.append(
            {
                "seed": seed,
                "violation": violation,
                "rejoin": rejoin,
                "commits": verdict["commits"],
                "recovery_s": verdict["liveness"]["recovery_s"],
                "floors": verdict["frontier_availability"]["floors"],
            }
        )
        if violation is not None:
            failures.append(
                {"seed": seed, "violation": violation, "rejoin": rejoin}
            )
            print(f"  VIOLATION {violation}: rejoin-{seed} "
                  f"(wipe={rejoin.get('wipe')})")

    wall = time.perf_counter() - t0
    n_runs = len(runs)
    summary = {
        "schema": SCHEMA,
        "config": {
            "seeds": [lo, hi],
            "nodes": args.nodes,
            "duration_s": args.duration,
            "retention_rounds": args.retention,
            "timeout_delay_ms": args.timeout_delay,
            "sync_retry_delay_ms": args.sync_retry_delay,
            "link_delay_ms": [dlo, dhi],
        },
        "totals": {
            "runs": n_runs,
            "cold_joins": cold,
            "warm_rejoins": warm,
            "ok": n_runs - len(failures),
            "violations": len(failures),
            "events_simulated": events_total,
            "wall_s": round(wall, 3),
            "schedules_per_min": round(n_runs / wall * 60.0, 1)
            if wall > 0
            else 0.0,
            "rejoin_first_commit_s": {
                "min": min(recoveries) if recoveries else None,
                "max": max(recoveries) if recoveries else None,
                "mean": round(sum(recoveries) / len(recoveries), 3)
                if recoveries
                else None,
            },
        },
        "failures": failures,
        "runs": runs,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
            f.write("\n")
    print(
        f"statesync-sweep: {n_runs} schedules ({cold} cold / {warm} warm) "
        f"in {wall:.1f}s; {len(failures)} violations"
    )
    if args.gate and failures:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
